"""Cross-request prefix cache: a copy-on-write radix tree over the paged
KV pool.

Production traffic is dominated by shared system prompts and few-shot
templates, yet a paged engine without this module re-prefills every request
from token 0 — including preemption victims re-prefilling their OWN prompt
(RESULTS.md §5 r10). The fix needs no device-side machinery at all: the
page table is already a plain jit input (sampling/serve.py), so two slots
whose page-table rows contain the same physical page READ the same K/V.
Sharing is therefore purely a host-allocator question — which this trie
answers — and the compiled program set does not change by construction
(pinned by tests/test_recompile_pins.py).

Structure. A compressed radix (Patricia) trie at PAGE granularity: one
`_Entry` per physical page, keyed by the `page_size`-token content that was
written into it; consecutive single-child entries are stored as one
`_Node`'s entry chain, and divergence points split the chain into children
keyed by their first page's tokens. Each entry carries a refcount (live
slot readers) and an LRU stamp.

Sharing rules — why readers can never observe a torn page:

  * Only FULL, FINISHED pages enter the trie: `insert_live` shares a
    prompt's `len(prompt) // page_size` complete pages at prefill
    completion, and `release` absorbs a departing slot's complete committed
    pages. The engine never writes a position below its committed length,
    so a trie page is immutable from the moment it becomes shareable.
  * `match` hands out at most `(len(prompt) - 1) // page_size` pages (the
    engine passes `max_tokens = len(prompt) - 1`), so every request
    re-prefills at least its final prompt token — the logits that seed the
    first generated token always come from a live prefill chunk.
  * The copy-on-write tail is REPREFILL, not memcpy: a page the matcher had
    to stop short of (cap hit or the prompt ends mid-page while a trie page
    carries the same leading tokens) is recomputed into a freshly allocated
    private page through the existing scatter write path
    (GPT.prefill_paged_chunk). `MatchResult.cow_truncated` marks exactly
    those admissions; nothing ever copies pool bytes host-side.
  * In int8 pool mode the per-page absmax scales are indexed by PHYSICAL
    page alongside the int8 columns (models/gpt.py PagedKVCache), so
    sharing a page shares its quantization scales with zero extra
    bookkeeping (pinned by tests/test_prefix_cache.py).

Lifecycle. `match` (admission) takes a reference on every handed-out page;
`release` (finish/cancel/timeout/preemption) drops them, donates the
departing slot's private complete pages to the trie with refcount 0, and
returns the pages that go back to the allocator (partial tails, and pages
whose content already lives in the trie under a different physical page).
A preempted slot therefore leaves its history IN the trie and re-matches
it on readmission — resume re-prefills only the sub-page tail instead of
the whole folded prompt (the r10 self-re-prefill fix, regression-pinned by
tests/test_prefix_cache.py).

Eviction. `evict` frees only refcount-0 entries, deepest-first within a
branch (a page cannot leave while pages that extend it remain) and
globally least-recently-used first — so a hot shared node is reclaimed
LRU-last and a referenced one never. The engine calls it when the
allocator runs dry, BEFORE considering slot preemption; the
`evict_shared_prefix` chaos fault (robustness/faults.py) calls it with
`force_all=True` to prove a forced flush never corrupts a live reader.
"""

from __future__ import annotations

import dataclasses
import typing as tp


@dataclasses.dataclass
class MatchResult:
    """`match` outcome: `pages` map into the new slot's page table verbatim
    (prefill skipped for `tokens = len(pages) * page_size` positions);
    `cow_truncated` flags that a trie page carrying the same leading tokens
    existed past the match end — the admission's tail re-prefill is a
    copy-on-write event, not a plain miss."""

    pages: tp.List[int]
    tokens: int
    cow_truncated: bool


class _Entry:
    """One shareable physical page: `key` is the page_size-token content
    written into it, `refs` counts live slot readers, `last_use` is the
    trie-clock LRU stamp."""

    __slots__ = ("key", "page", "refs", "last_use")

    def __init__(self, key: tp.Tuple[int, ...], page: int, refs: int, tick: int):
        self.key = key
        self.page = page
        self.refs = refs
        self.last_use = tick

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"_Entry(page={self.page}, refs={self.refs})"


class _Node:
    """A run of single-successor entries (path compression) plus children
    keyed by their first entry's token tuple. The root holds no entries."""

    __slots__ = ("entries", "children", "parent")

    def __init__(self, entries: tp.List[_Entry], parent: tp.Optional["_Node"]):
        self.entries = entries
        self.children: tp.Dict[tp.Tuple[int, ...], "_Node"] = {}
        self.parent = parent


class PrefixCache:
    """Host-side page-granular radix trie (module docstring). Pure host
    code: it deals in physical page INDICES only and never touches device
    memory — the engine moves the returned indices between its allocator
    and its page tables."""

    def __init__(self, page_size: int):
        assert page_size > 0
        self.page_size = page_size
        self._root = _Node([], None)
        self._tick = 0  # monotonic LRU clock (bumped per trie operation)
        self._n_pages = 0  # entries currently held (refs 0 included)
        # Spill hook (sampling/fleet.py SpillTier): called per evicted entry
        # with (full_prefix_tokens, page) BEFORE the page returns to the
        # allocator, where full_prefix_tokens is the entry's complete token
        # prefix from the root (the spill tier's lookup key must be
        # position-dependent — the same page content at a different depth is
        # different KV). Host-only; the page's device bytes are still intact
        # when the hook runs because the allocator hasn't reissued the page.
        self.on_evict: tp.Optional[
            tp.Callable[[tp.Tuple[int, ...], int], None]
        ] = None

    # -- keys ----------------------------------------------------------

    def _key_at(self, tokens, d: int) -> tp.Tuple[int, ...]:
        ps = self.page_size
        return tuple(int(t) for t in tokens[d * ps : (d + 1) * ps])

    # -- read side -----------------------------------------------------

    def match(self, tokens, *, max_tokens: tp.Optional[int] = None) -> MatchResult:
        """Greedy longest-prefix walk; every returned page is referenced
        (the caller OWNS one ref per page until the paired `release`).
        `max_tokens` caps the match so the caller always re-prefills the
        positions past it (the engine passes len(prompt) - 1)."""
        ps = self.page_size
        limit = len(tokens) if max_tokens is None else min(max_tokens, len(tokens))
        max_full = limit // ps
        self._tick += 1
        pages: tp.List[int] = []
        node, idx, d = self._root, 0, 0
        while d < max_full:
            key = self._key_at(tokens, d)
            if idx < len(node.entries):
                e = node.entries[idx]
                if e.key != key:
                    break
                e.refs += 1
                e.last_use = self._tick
                pages.append(e.page)
                idx += 1
                d += 1
            else:
                child = node.children.get(key)
                if child is None:
                    break
                node, idx = child, 0
        # COW detection: does a trie page's content extend past where we
        # stopped, matching everything we still have to place in the next
        # page? Then the tail re-prefill recomputes (part of) a shared page
        # into a private one — the copy-on-write event the stats report.
        rest = tuple(int(t) for t in tokens[d * ps : min(len(tokens), (d + 1) * ps)])
        cow = False
        if rest:
            if idx < len(node.entries):
                cow = node.entries[idx].key[: len(rest)] == rest
            else:
                cow = any(k[: len(rest)] == rest for k in node.children)
        return MatchResult(pages=pages, tokens=len(pages) * ps, cow_truncated=cow)

    def peek(self, tokens, *, max_tokens: tp.Optional[int] = None) -> int:
        """Side-effect-free match probe: how many pages WOULD match. Feeds
        the engine's refcount-aware backpressure accounting
        (`ServeEngine._backlog_pages`); takes no references, moves no LRU
        stamps."""
        ps = self.page_size
        limit = len(tokens) if max_tokens is None else min(max_tokens, len(tokens))
        max_full = limit // ps
        n = 0
        node, idx = self._root, 0
        while n < max_full:
            key = self._key_at(tokens, n)
            if idx < len(node.entries):
                if node.entries[idx].key != key:
                    break
                idx += 1
                n += 1
            else:
                child = node.children.get(key)
                if child is None:
                    break
                node, idx = child, 0
        return n

    # -- write side ----------------------------------------------------

    def insert_live(self, tokens, pages: tp.List[int], n_shared: int) -> int:
        """Share a live slot's complete prompt pages at prefill completion
        (they are immutable from here on — the engine only writes positions
        >= len(prompt)). `pages[:n_shared]` are already trie entries the
        slot references; the remainder is offered. Newly inserted entries
        start at refcount 1 — the inserting slot reads them. Returns the
        slot's new n_shared: the insert stops early when the trie already
        holds the same content under a DIFFERENT physical page (the slot
        keeps reading its private copy; `release` reconciles later)."""
        ps = self.page_size
        full = len(tokens) // ps
        self._tick += 1
        node, idx, d = self._root, 0, 0
        while d < full:
            key = self._key_at(tokens, d)
            if idx < len(node.entries):
                e = node.entries[idx]
                if e.key == key:
                    if d < n_shared:
                        assert e.page == pages[d], "shared prefix diverged"
                    elif e.page != pages[d]:
                        # duplicate content raced in (a sibling slot finished
                        # the same prefix first): stop sharing here
                        return d
                    e.last_use = self._tick
                    idx += 1
                    d += 1
                    continue
                assert d >= n_shared, "shared prefix diverged"
                self._split(node, idx)
            child = node.children.get(key)
            if child is not None:
                node, idx = child, 0
                continue
            self._attach(node, tokens, pages, d, full, refs=1)
            return full
        return full

    def release(self, tokens, pages: tp.List[int], n_shared: int) -> tp.List[int]:
        """A slot departs (finish/cancel/timeout/preemption): drop its refs
        on `pages[:n_shared]`, donate its private COMPLETE pages to the trie
        at refcount 0 (so an identical or resumed request re-matches them),
        and return the pages the allocator gets back — partial tails,
        overallocated growth, and content-duplicates the trie already holds
        under another physical page. `tokens` is the slot's COMMITTED
        content (concat(prompt, generated)[:length])."""
        ps = self.page_size
        full = len(tokens) // ps
        assert n_shared <= full <= len(pages)
        self._tick += 1
        freed: tp.List[int] = []
        node, idx, d = self._root, 0, 0
        while d < full:
            key = self._key_at(tokens, d)
            if idx < len(node.entries):
                e = node.entries[idx]
                if e.key == key:
                    if d < n_shared:
                        assert e.page == pages[d], "shared prefix diverged"
                        e.refs -= 1
                        assert e.refs >= 0, "refcount underflow"
                    else:
                        assert e.page != pages[d], "page owned twice"
                        freed.append(pages[d])  # content-duplicate
                    e.last_use = self._tick
                    idx += 1
                    d += 1
                    continue
                assert d >= n_shared, "shared prefix diverged"
                self._split(node, idx)
            child = node.children.get(key)
            if child is not None:
                node, idx = child, 0
                continue
            self._attach(node, tokens, pages, d, full, refs=0)
            d = full
        freed.extend(pages[full:])
        return freed

    def evict(self, n_wanted: int, *, force_all: bool = False) -> tp.List[int]:
        """Reclaim up to `n_wanted` refcount-0 pages (every one of them
        with `force_all=True` — the evict_shared_prefix chaos fault).
        Order: deepest entry of a leaf branch first (a page never leaves
        while pages extending it remain) and least-recently-used across
        leaves — a hot shared node goes LRU-last, a referenced node never
        goes at all. Returns the freed physical pages."""
        freed: tp.List[int] = []
        while force_all or len(freed) < n_wanted:
            best: tp.Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node.children or not node.entries:
                    continue  # interior node, or the (empty) root
                e = node.entries[-1]
                if e.refs == 0 and (
                    best is None or e.last_use < best.entries[-1].last_use
                ):
                    best = node
            if best is None:
                break
            if self.on_evict is not None:
                self.on_evict(self._full_prefix(best), best.entries[-1].page)
            e = best.entries.pop()
            freed.append(e.page)
            self._n_pages -= 1
            if not best.entries:
                self._detach(best)
        return freed

    def _full_prefix(self, node: _Node) -> tp.Tuple[int, ...]:
        """The complete token prefix of `node`'s LAST entry, reconstructed
        by walking the parent chain — the position-dependent identity a
        spill tier must key on (module docstring: a page's KV depends on
        every token before it, not just the page_size tokens inside it)."""
        chain: tp.List[_Node] = []
        n: tp.Optional[_Node] = node
        while n is not None and n is not self._root:
            chain.append(n)
            n = n.parent
        toks: tp.List[int] = []
        for anc in reversed(chain):
            for e in anc.entries:
                toks.extend(e.key)
        return tuple(toks)

    # -- accounting (tests, chaos conservation, backpressure) ----------

    def page_count(self) -> int:
        """Entries currently held, referenced or not. The chaos/page
        conservation invariant with the cache enabled is
        `allocator.free_count + page_count() == num_pages - 1` once the
        engine drains (tests/test_prefix_cache.py, chaos_serve.py)."""
        return self._n_pages

    def referenced_page_count(self) -> int:
        """Entries with at least one live reader — the unreclaimable part
        of the trie's footprint, charged once (not per reader) by the
        engine's backpressure accounting."""
        return sum(1 for e in self._iter_entries() if e.refs > 0)

    def pages_held(self) -> tp.Set[int]:
        return {e.page for e in self._iter_entries()}

    def referenced_pages(self) -> tp.Set[int]:
        """Physical pages with at least one live reader — the part of the
        trie's footprint a live pool resize must carry over (resident
        working set, sampling/ops.py resize_pool)."""
        return {e.page for e in self._iter_entries() if e.refs > 0}

    def remap_pages(self, mapping: tp.Mapping[int, int]) -> int:
        """Rewrite every entry's physical page id through `mapping` — the
        trie re-seed step of a live pool resize (sampling/ops.py): the
        token->content structure and all refcounts survive; only the
        physical addressing changes, in lockstep with the slot page lists
        and the migrated pool. Every held page must be in `mapping`
        (resize migrates the full resident set). Returns entries remapped."""
        n = 0
        for e in self._iter_entries():
            e.page = mapping[e.page]
            n += 1
        return n

    def stats(self) -> tp.Dict[str, int]:
        ents = list(self._iter_entries())
        return {
            "pages": len(ents),
            "referenced": sum(1 for e in ents if e.refs > 0),
            "refs": sum(e.refs for e in ents),
        }

    def _iter_entries(self) -> tp.Iterator[_Entry]:
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield from node.entries

    # -- structure -----------------------------------------------------

    def _split(self, node: _Node, idx: int) -> None:
        """Divergence inside a compressed chain: entries[idx:] (and the
        node's children) move under a new child so a sibling branch can
        attach at depth idx. idx >= 1 always — a walk only enters a node
        after matching its first entry."""
        assert 0 < idx < len(node.entries)
        tail = _Node(node.entries[idx:], node)
        tail.children = node.children
        for c in tail.children.values():
            c.parent = tail
        node.entries = node.entries[:idx]
        node.children = {tail.entries[0].key: tail}

    def _attach(
        self, node: _Node, tokens, pages: tp.List[int], d: int, full: int, refs: int
    ) -> None:
        entries = [
            _Entry(self._key_at(tokens, i), pages[i], refs, self._tick)
            for i in range(d, full)
        ]
        if not entries:
            return
        assert entries[0].key not in node.children
        node.children[entries[0].key] = _Node(entries, node)
        self._n_pages += full - d

    def _detach(self, node: _Node) -> None:
        parent = node.parent
        for key, child in list(parent.children.items()):
            if child is node:
                del parent.children[key]
                return
        raise AssertionError("orphan trie node")  # pragma: no cover
