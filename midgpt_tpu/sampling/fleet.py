"""Fleet serving: N ServeEngine replicas behind a prefix-affinity router,
with health-checked failover and a verified host-RAM KV spill tier
(docs/ROBUSTNESS.md "Fleet serving & failover").

One engine in one process is a single point of failure: an engine crash
drops every accepted stream, and every trie eviction discards KV that cost
real prefill FLOPs to build. This module extends the single-engine
robustness machinery (supervisor/chaos faults/backoff, PRs 3/6/12) from
*one engine surviving faults* to *a fleet surviving the loss of an engine*,
with three cooperating pieces:

  * `FleetRouter` — schedules arrivals by TRIE AFFINITY: the first
    page_size tokens of the prompt (the only shareable granule, see
    prefix_cache.py) rendezvous-hash over the alive replicas, so requests
    sharing a system prompt land on the replica already holding its pages
    and the fleet-wide prefix hit rate does not dilute toward 1/N.
    Rendezvous (highest-random-weight) hashing keeps the mapping stable
    when a replica dies: only the dead replica's keys move.
  * Health-checked FAILOVER — the router steps each replica inside a
    try/except with clock-injected heartbeats; a replica whose step raises
    `max_consecutive_failures` times in a row, or whose heartbeat goes
    stale past `heartbeat_timeout_s`, is marked dead. Its already-finished
    results are harvested, and its accepted-but-unfinished streams are
    resubmitted to survivors through the bounded `PageHandoffQueue`
    retry path (sampling/disagg.py — the general page-transport
    primitive). Resubmission replays the ORIGINAL prompt with the FULL
    budget: greedy streams are batch-composition-independent (the
    engine's founding parity invariant, tests/test_serving.py), so a
    failed-over stream reproduces the exact tokens the dead replica would
    have served — the chaos gate parity-checks every stream, survivors
    AND failovers, against a fault-free single-engine pass. Delivery on
    the `on_token` hook is therefore at-least-once across a failover
    (already-streamed tokens replay); terminal results in `finished` are
    exactly-once.
  * `SpillTier` — a host-RAM tier under every replica's trie: refcount-0
    pages spill their content to host memory on eviction (int8 pages
    travel quantized with their scales — 2x cheaper) instead of being
    discarded, keyed by the page's FULL token prefix (KV is
    position-dependent: the same page content at a different depth is
    different KV). Each spilled page carries a crc32 checksum verified on
    re-adoption and the weights_version it was computed under: a corrupt
    or stale page is discarded and the tokens re-prefill — the PR 3
    verified-checkpoint discipline applied to KV, so a flipped bit can
    never poison a decode. Re-adoption rides the pow2-bucketed adoption
    scatter (`disagg._adopt_pages`). The tier is SHARED fleet-wide: KV
    content depends only on tokens and weights, not on which replica
    computed it, so a failed-over stream re-prefills from pages its dead
    replica spilled.

Graceful degradation, never a crash: when every surviving replica sheds an
admission the router raises an aggregated, retryable `BackpressureError`
(`submit_retry` wraps it in the shared bounded backoff schedule,
robustness/backoff.py), and a failover the survivors refuse past the
queue's retry budget becomes a terminal "shed" finish — structured
outcomes at every exhaustion point.

Conservation extends across tiers (`assert_fleet_conserved`): every alive
replica obeys the single-engine pool law (ops.assert_conserved), and the
spill tier's ledger closes — resident + readopted + corrupt_discarded +
capacity_dropped + stale_discarded == total_spilled. The fleet chaos
scenarios (robustness/chaos_serve.py: engine_crash / handoff_stall /
spill_corrupt) assert both after every drain.

Cross-process fleets (sampling/fleet_proc.py, docs/ROBUSTNESS.md
"Cross-process fleet"): a replica may be a `ProcReplica` — a proxy for a
worker PROCESS hosting the engine behind the framed socket transport.
The router drives it through the same duck-typed surface, so everything
above holds unchanged; what this module adds for that mode is (a) the
wire-level fault kinds (`proc_kill9` / `conn_drop` / `wire_corrupt` /
`wire_stall`) fired from `step()` against proc replicas — kill -9
detection deliberately flows through the SAME consecutive-failure health
path as an in-process engine death, fed by `ReplicaGoneError` off the
wire; (b) spill-page transfer (`SpillTier.export_entries` /
`import_entries`) whose `transferred`/`received` buckets keep the ledger
law closing when pages cross a process boundary; and (c) per-replica
dispatch in `assert_fleet_conserved`, which runs the pool law INSIDE the
worker (over the `conserve` RPC) for proc replicas.
"""

from __future__ import annotations

import dataclasses
import time
import typing as tp
import zlib

import numpy as np

from midgpt_tpu.robustness import faults
from midgpt_tpu.robustness.backoff import retry_with_backoff
from midgpt_tpu.sampling.disagg import (
    HandoffRetryExhausted,
    PageHandoffQueue,
)
from midgpt_tpu.sampling.serve import (
    BackpressureError,
    FinishedRequest,
    ServeEngine,
)


class _SpillEntry:
    """One spilled page: single-page host blocks ('k'/'v' (L, H, ps, C)
    and, int8 pools, 'k_scale'/'v_scale' (L, H, ps)), the crc32 of their
    bytes, the weights_version the KV was computed under, and an LRU
    stamp."""

    __slots__ = ("blocks", "checksum", "weights_version", "stamp", "nbytes")

    def __init__(self, blocks, checksum, weights_version, stamp):
        self.blocks = blocks
        self.checksum = checksum
        self.weights_version = weights_version
        self.stamp = stamp
        self.nbytes = sum(b.nbytes for b in blocks.values())


def _blocks_crc(blocks: tp.Dict[str, np.ndarray]) -> int:
    crc = 0
    for key in sorted(blocks):
        crc = zlib.crc32(blocks[key].tobytes(), crc)
    return crc


class SpillTier:
    """Host-RAM spill tier for evicted trie pages (module docstring).

    Entries key on the page's full token prefix, so `peek_run`/`take_run`
    walk exactly the pages an admission's trie match stopped short of.
    Checksums are verified at TAKE (the moment the bytes would enter a
    decode), never at peek — a corrupt entry truncates the run, is counted
    `corrupt_discarded`, and the affected tokens re-prefill. The ledger
    `total_spilled == resident + readopted + corrupt_discarded +
    capacity_dropped + stale_discarded` is the cross-tier half of the
    fleet conservation invariant (`assert_fleet_conserved`).

    Chaos hooks (robustness/faults.py): `arm_stall` models a wedged
    host transport — the NEXT consult that would return pages refuses
    instead (counted `stall_fallbacks`; the caller re-prefills, correct
    but slower); `corrupt_one` flips a byte in the most recently spilled
    resident entry so the checksum discipline is exercised end to end."""

    def __init__(
        self,
        *,
        capacity_bytes: tp.Optional[int] = None,
        clock: tp.Callable[[], float] = time.perf_counter,
    ):
        self._entries: tp.Dict[tp.Tuple[int, ...], _SpillEntry] = {}
        self.capacity_bytes = capacity_bytes
        self._clock = clock
        self._tick = 0
        self._stall_armed = False
        # ledger counters (every spilled page ends in exactly one bucket)
        self.total_spilled = 0
        self.readopted = 0
        self.corrupt_discarded = 0
        self.capacity_dropped = 0
        self.stale_discarded = 0
        # cross-process transfer buckets (fleet_proc.py): pages that
        # entered/left this tier over the wire rather than via spill/take
        self.received = 0
        self.transferred = 0
        # non-ledger visibility counters
        self.duplicate_skips = 0
        self.stall_fallbacks = 0
        self.spilled_bytes = 0
        self.readopted_bytes = 0

    # -- spill side (prefix_cache.on_evict) ----------------------------

    def spill(self, cache, prefix: tp.Tuple[int, ...], page: int,
              weights_version: str) -> bool:
        """Land `page`'s pool content on the host under `prefix` (the
        page's full token prefix from PrefixCache.on_evict). Called while
        the page's device bytes are still intact — eviction frees the page
        AFTER the hook returns. int8 pools spill quantized: the int8
        columns plus their per-page scales, half the bytes of a bf16
        page."""
        import jax.numpy as jnp

        key = tuple(int(t) for t in prefix)
        existing = self._entries.get(key)
        if existing is not None:
            if existing.weights_version == weights_version:
                # same tokens + same weights => same KV; keep the resident
                self.duplicate_skips += 1
                return False
            # stale duplicate from before a hot swap: replace it
            del self._entries[key]
            self.stale_discarded += 1
        # (1,)-shaped take keeps ONE cached gather program for every page
        # index (a python-int slice would compile per index).
        idx = jnp.asarray([page], jnp.int32)
        blocks: tp.Dict[str, np.ndarray] = {
            "k": np.asarray(jnp.take(cache.k, idx, axis=2))[:, :, 0],
            "v": np.asarray(jnp.take(cache.v, idx, axis=2))[:, :, 0],
        }
        if cache.k_scale is not None:
            blocks["k_scale"] = np.asarray(
                jnp.take(cache.k_scale, idx, axis=1)
            )[:, 0]
            blocks["v_scale"] = np.asarray(
                jnp.take(cache.v_scale, idx, axis=1)
            )[:, 0]
        self._tick += 1
        entry = _SpillEntry(
            blocks, _blocks_crc(blocks), weights_version, self._tick
        )
        self._entries[key] = entry
        self.total_spilled += 1
        self.spilled_bytes += entry.nbytes
        self._enforce_capacity()
        return True

    def _enforce_capacity(self) -> None:
        if self.capacity_bytes is None:
            return
        while (
            len(self._entries) > 1
            and sum(e.nbytes for e in self._entries.values())
            > self.capacity_bytes
        ):
            key = min(self._entries, key=lambda k: self._entries[k].stamp)
            del self._entries[key]
            self.capacity_dropped += 1

    # -- re-adopt side (ServeEngine._readopt_from_spill) ---------------

    def peek_run(self, prompt, start_page: int, limit: int,
                 weights_version: str) -> int:
        """How many consecutive pages starting at page depth `start_page`
        of `prompt` are resident under `weights_version` (checksums NOT
        verified — that happens at take). An armed stall refuses the first
        consult that would return pages, then clears: the caller falls
        back to plain re-prefill, which is the stall's whole failure
        mode — slower, never wrong."""
        ps = self._require_ps()
        n = 0
        for j in range(limit):
            key = tuple(int(t) for t in prompt[: (start_page + j + 1) * ps])
            e = self._entries.get(key)
            if e is None or e.weights_version != weights_version:
                break
            n += 1
        if n and self._stall_armed:
            self._stall_armed = False
            self.stall_fallbacks += 1
            return 0
        return n

    def take_run(self, prompt, start_page: int, n: int,
                 weights_version: str) -> tp.List[tp.Dict[str, np.ndarray]]:
        """Move up to `n` consecutive pages out of the tier (move-on-take:
        the caller owns them; re-eviction re-spills). Each page's crc32 is
        verified here — a mismatch discards THAT entry, truncates the run,
        and counts `corrupt_discarded`: corrupt bytes never reach a
        decode, the tokens simply re-prefill."""
        ps = self._require_ps()
        out: tp.List[tp.Dict[str, np.ndarray]] = []
        for j in range(n):
            key = tuple(int(t) for t in prompt[: (start_page + j + 1) * ps])
            e = self._entries.pop(key, None)
            if e is None:
                break
            if e.weights_version != weights_version:
                self.stale_discarded += 1
                break
            if _blocks_crc(e.blocks) != e.checksum:
                self.corrupt_discarded += 1
                break
            self.readopted += 1
            self.readopted_bytes += e.nbytes
            out.append(e.blocks)
        return out

    # page_size is bound once, at the first attach (ServeEngine
    # attach_spill): spill keys are exact multiples of it, and a tier
    # shared across replicas requires them to agree.
    _ps: int = 0

    def set_page_size(self, ps: int) -> None:
        if self._ps and self._ps != ps:
            raise ValueError(
                f"spill tier already bound to page_size={self._ps}, "
                f"got {ps}"
            )
        self._ps = ps

    def _require_ps(self) -> int:
        if not self._ps:
            raise RuntimeError(
                "spill tier consulted before any engine attached it "
                "(ServeEngine.attach_spill binds page_size)"
            )
        return self._ps

    # -- chaos hooks ---------------------------------------------------

    def arm_stall(self) -> None:
        self._stall_armed = True

    def corrupt_one(self) -> bool:
        """Flip a byte in the most recently spilled resident entry's K
        block WITHOUT updating its checksum — the take-side verification
        must catch it. Returns False when nothing is resident (the fault
        stays armed until something is)."""
        if not self._entries:
            return False
        key = max(self._entries, key=lambda k: self._entries[k].stamp)
        e = self._entries[key]
        k = e.blocks["k"].copy()
        flat = k.view(np.uint8).reshape(-1)
        flat[0] ^= 0xFF
        e.blocks["k"] = k
        return True

    # -- cross-process transfer (fleet_proc.py) ------------------------

    def export_entries(self):
        """Move every resident entry out of this tier for wire transfer
        (typically a draining worker handing its spilled KV to survivors).
        Move-on-export like take_run: the pages leave this ledger through
        the `transferred` bucket and re-enter the receiver's through
        `received` — both sides' conservation laws keep closing. Checksums
        travel UNVERIFIED and UNCHANGED: the receiver's take-side check
        then covers transit and residence with one number."""
        from midgpt_tpu.sampling.fleet_proc import SpillTransferItem

        items = [
            SpillTransferItem(
                key=key,
                blocks=e.blocks,
                checksum=e.checksum,
                weights_version=e.weights_version,
            )
            for key, e in sorted(
                self._entries.items(), key=lambda kv: kv[1].stamp
            )
        ]
        self._entries.clear()
        self.transferred += len(items)
        return items

    def import_entries(self, items) -> int:
        """Land wire-transferred entries in this tier, preserving each
        page's ORIGINAL spill-time checksum (a bit flipped in transit is
        caught by the normal take_run verification — corrupt KV degrades
        to re-prefill, never poisons a decode). A resident duplicate under
        the same weights_version wins (`duplicate_skips`); a stale one is
        replaced (`stale_discarded`). Returns the number imported."""
        imported = 0
        for it in items:
            key = tuple(int(t) for t in it.key)
            self.received += 1
            imported += 1
            existing = self._entries.get(key)
            if existing is not None:
                if existing.weights_version == it.weights_version:
                    # resident copy is equivalent: the incoming page goes
                    # straight to the discard bucket it would reach anyway
                    self.duplicate_skips += 1
                    self.stale_discarded += 1
                    continue
                del self._entries[key]
                self.stale_discarded += 1
            self._tick += 1
            self._entries[key] = _SpillEntry(
                dict(it.blocks), int(it.checksum), it.weights_version,
                self._tick,
            )
        self._enforce_capacity()
        return imported

    # -- accounting ----------------------------------------------------

    def resident_count(self) -> int:
        return len(self._entries)

    def resident_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def ledger(self) -> tp.Dict[str, int]:
        return {
            "total_spilled": self.total_spilled,
            "resident": len(self._entries),
            "readopted": self.readopted,
            "corrupt_discarded": self.corrupt_discarded,
            "capacity_dropped": self.capacity_dropped,
            "stale_discarded": self.stale_discarded,
            "received": self.received,
            "transferred": self.transferred,
        }

    def assert_ledger(self, where: str = "") -> None:
        """Pages in == pages accounted for. Sources: spilled locally or
        received over the wire. Sinks: resident, readopted, one of the
        discard buckets, or transferred away. Identical to the pre-proc
        law when both transfer buckets are zero."""
        led = self.ledger()
        total = (
            led["resident"]
            + led["readopted"]
            + led["corrupt_discarded"]
            + led["capacity_dropped"]
            + led["stale_discarded"]
            + led["transferred"]
        )
        assert total == led["total_spilled"] + led["received"], (
            f"spill ledger violated {where}: {led} "
            f"(buckets sum to {total})"
        )

    def stats(self) -> tp.Dict[str, int]:
        return {
            **self.ledger(),
            "resident_bytes": self.resident_bytes(),
            "spilled_bytes": self.spilled_bytes,
            "readopted_bytes": self.readopted_bytes,
            "duplicate_skips": self.duplicate_skips,
            "stall_fallbacks": self.stall_fallbacks,
        }


@dataclasses.dataclass
class FailoverItem:
    """One accepted stream crossing replicas after a crash: the ORIGINAL
    prompt and FULL budget (greedy batch-independence makes the replay
    bit-identical). Rides PageHandoffQueue with empty blocks — the pages
    re-prefill from the shared spill tier / survivor trie at the
    destination, so nothing is gathered from the dead replica."""

    uid: int  # fleet uid
    prompt: np.ndarray  # (T0,) int32
    max_new_tokens: int
    eos_id: tp.Optional[int]
    deadline: tp.Optional[float]
    blocks: tp.Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    n_pages: int = 0


@dataclasses.dataclass
class _Stream:
    """Router-side record of an accepted stream: everything needed to
    replay it on a survivor if its replica dies."""

    uid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: tp.Optional[int]
    deadline: tp.Optional[float]
    replica: int
    replica_uid: int


class FleetRouter:
    """N ServeEngine replicas behind prefix-affinity routing with
    health-checked failover (module docstring).

    The router OWNS its engines: it overwrites their `on_token` hooks (to
    translate replica uids to fleet uids) and attaches the shared spill
    tier to each. Engines must be greedy (temperature 0 — failover parity
    is the contract), prefix-cached (the trie is both the affinity target
    and the spill source), and agree on page_size."""

    def __init__(
        self,
        engines: tp.Sequence[ServeEngine],
        *,
        clock: tp.Callable[[], float] = time.perf_counter,
        spill: tp.Optional[SpillTier] = None,
        heartbeat_timeout_s: tp.Optional[float] = None,
        max_consecutive_failures: int = 3,
        failover_retries: int = 512,
        on_token: tp.Optional[tp.Callable[[int, int, float], None]] = None,
        on_finish: tp.Optional[tp.Callable[[FinishedRequest], None]] = None,
    ):
        engines = list(engines)
        if not engines:
            raise ValueError("a fleet needs at least one replica")
        for i, eng in enumerate(engines):
            if eng.prefix_cache is None:
                raise ValueError(
                    f"replica {i} has no prefix cache — the trie is the "
                    "router's affinity target and the spill tier's source"
                )
            if eng.temperature != 0.0:
                raise ValueError(
                    "FleetRouter is greedy-only: failover replays a stream "
                    "on a survivor and bit-parity is the contract"
                )
        ps = engines[0].page_size
        if any(e.page_size != ps for e in engines):
            raise ValueError("replicas must agree on page_size")
        self.engines = engines
        self.page_size = ps
        self.alive = [True] * len(engines)
        self._clock = clock
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.max_consecutive_failures = max_consecutive_failures
        self.on_token = on_token
        self.on_finish = on_finish
        self.spill = spill if spill is not None else SpillTier(clock=clock)
        for i, eng in enumerate(engines):
            eng.attach_spill(self.spill)
            eng.on_token = self._make_token_relay(i)
        # failover transport: same bounded-retry page queue as disagg —
        # blocks are empty, so only the retry discipline rides (base_s=0:
        # the router tick is the pacing, like the disagg pipeline tick).
        self.failover_queue = PageHandoffQueue(
            retries=failover_retries, base_s=0.0, clock=clock
        )
        self.finished: tp.Dict[int, FinishedRequest] = {}
        self._pending: tp.Dict[int, _Stream] = {}
        self._by_replica: tp.Dict[tp.Tuple[int, int], int] = {}
        self._uid = 0
        self.rounds = 0
        now = clock()
        self._heartbeat = [now] * len(engines)
        self._failures = [0] * len(engines)
        # counters
        self.failovers = 0  # replica deaths
        self.failed_over_streams = 0
        self.router_shed = 0  # submit-time total refusals (all replicas)
        self.shed_streams = 0  # failovers terminally shed past the budget
        self.crash_log: tp.List[tp.Dict[str, tp.Any]] = []
        # cross-process replicas (fleet_proc.ProcReplica marks itself):
        # the wire-level fault kinds in step() only target these, and
        # their deaths are counted separately for the serve_fleet profile
        self._proc_idx = [
            i
            for i, eng in enumerate(engines)
            if getattr(eng, "is_proc", False)
        ]
        self.proc_failovers = 0

    # -- admission -----------------------------------------------------

    def submit(
        self,
        prompt: tp.Sequence[int],
        max_new_tokens: int,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
    ) -> int:
        """Place a request on the affinity replica, spilling over to the
        other survivors least-loaded-first. When EVERY survivor sheds,
        raises one aggregated BackpressureError (retryable iff any
        replica's shed was) — the fleet's graceful-degradation front
        door."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        deadline = None if ttl_s is None else self._clock() + ttl_s
        uid = self._uid
        self._place(uid, prompt, max_new_tokens, eos_id, deadline)
        self._uid += 1
        return uid

    def submit_retry(
        self,
        prompt: tp.Sequence[int],
        max_new_tokens: int,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
        *,
        retries: int = 8,
        base_s: float = 0.0,
    ) -> int:
        """`submit` under the shared bounded backoff schedule
        (robustness/backoff.py). The "sleep" between attempts steps the
        fleet once — capacity frees as replicas finish work, so waiting
        IS progress. Non-retryable sheds propagate immediately; the final
        failure re-raises the aggregated BackpressureError."""
        return retry_with_backoff(
            lambda: self.submit(prompt, max_new_tokens, eos_id, ttl_s),
            retries=retries,
            base_s=base_s,
            retry_on=(BackpressureError,),
            sleep=lambda _delay: self.step(),
            should_retry=lambda e: getattr(e, "retryable", False),
        )

    def _place(self, uid, prompt, max_new_tokens, eos_id, deadline) -> None:
        now = self._clock()
        ttl = None if deadline is None else max(deadline - now, 0.0)
        errs: tp.List[BackpressureError] = []
        for i in self._route_order(prompt):
            try:
                ruid = self.engines[i].submit(
                    prompt, max_new_tokens, eos_id, ttl_s=ttl
                )
            except BackpressureError as e:
                errs.append(e)
                continue
            self._pending[uid] = _Stream(
                uid, prompt, max_new_tokens, eos_id, deadline, i, ruid
            )
            self._by_replica[(i, ruid)] = uid
            return
        self.router_shed += 1
        retryable = any(e.retryable for e in errs) if errs else False
        first = errs[0] if errs else None
        raise BackpressureError(
            f"all {sum(self.alive)} surviving replicas shed the request"
            + (f" (affinity replica: {errs[0]})" if errs else ""),
            needed_pages=getattr(first, "needed_pages", None),
            backlog_pages=getattr(first, "backlog_pages", None),
            budget_pages=getattr(first, "budget_pages", None),
            retryable=retryable,
        )

    def _route_order(self, prompt) -> tp.List[int]:
        """Affinity replica first (rendezvous hash of the first full page
        — the only granule the trie can share), then the remaining
        survivors least-loaded first. Prompts without a full shareable
        page have no affinity and go least-loaded."""
        alive = [i for i, a in enumerate(self.alive) if a]
        if not alive:
            raise RuntimeError("no alive replicas in the fleet")
        load = {i: 0 for i in alive}
        for st in self._pending.values():
            if st.replica in load:
                load[st.replica] += 1
        rest = sorted(alive, key=lambda i: (load[i], i))
        aff = self._affinity(prompt, alive)
        if aff is None:
            return rest
        return [aff] + [i for i in rest if i != aff]

    def _affinity(self, prompt, alive: tp.List[int]) -> tp.Optional[int]:
        ps = self.page_size
        if len(prompt) < ps + 1:  # match caps at len(prompt) - 1 tokens
            return None
        key = np.asarray(prompt[:ps], np.int64).tobytes()
        return max(
            alive,
            key=lambda i: zlib.crc32(key + i.to_bytes(4, "little")),
        )

    # -- the fleet round -----------------------------------------------

    @property
    def idle(self) -> bool:
        return (
            not self._pending
            and not len(self.failover_queue)
            and all(
                eng.idle
                for i, eng in enumerate(self.engines)
                if self.alive[i]
            )
        )

    def run(self, max_rounds: int = 100_000) -> tp.Dict[int, FinishedRequest]:
        start = self.rounds
        while not self.idle:
            if self.rounds - start >= max_rounds:
                raise RuntimeError(
                    f"fleet failed to drain within {max_rounds} rounds"
                )
            self.step()
        return self.finished

    def step(self) -> None:
        """One fleet round: fire fleet-level chaos faults, step every
        alive replica under the health checks, harvest finishes, drain
        the failover queue onto survivors."""
        self.rounds += 1
        if sum(self.alive) > 1 and faults.should_fire(
            "engine_crash", step=self.rounds
        ):
            self._crash(self._crash_victim(), reason="fault")
        if faults.should_fire("handoff_stall", step=self.rounds):
            self.spill.arm_stall()
        if self.spill.resident_count() > 0 and faults.should_fire(
            "spill_corrupt", step=self.rounds
        ):
            self.spill.corrupt_one()
        self._fire_proc_faults()
        for i, eng in enumerate(self.engines):
            if not self.alive[i]:
                continue
            now = self._clock()
            if eng.idle:
                self._heartbeat[i] = now
                continue
            try:
                eng.step()
            except Exception:
                self._failures[i] += 1
                if self._failures[i] >= self.max_consecutive_failures:
                    self._crash(i, reason="consecutive_failures")
                continue
            self._heartbeat[i] = now
            self._failures[i] = 0
            if (
                self.heartbeat_timeout_s is not None
                and self._clock() - self._heartbeat[i]
                > self.heartbeat_timeout_s
            ):
                self._crash(i, reason="heartbeat_stale")
        self._harvest()
        self._drain_failover()

    def _fire_proc_faults(self) -> None:
        """The wire-level fault kinds (robustness/faults.py "cross-process
        fleet" section), targeting the busiest alive proc replica so the
        fault lands under real traffic. `proc_kill9` SIGKILLs the worker
        and deliberately does NOT mark it dead here: detection must flow
        through the same health checks as any other replica death — step
        RPCs fail with ReplicaGoneError until the consecutive-failure
        threshold fires `_crash`. The other three arm transport-level
        chaos the RPC retry path must absorb transparently."""
        procs = [i for i in self._proc_idx if self.alive[i]]
        if not procs:
            return
        victim = self._busiest(procs)
        if sum(self.alive) > 1 and faults.should_fire(
            "proc_kill9", step=self.rounds
        ):
            self.engines[victim].kill9()
        if faults.should_fire("conn_drop", step=self.rounds):
            self.engines[victim].drop_conn()
        if faults.should_fire("wire_corrupt", step=self.rounds):
            self.engines[victim].arm_wire_corrupt()
        if faults.should_fire("wire_stall", step=self.rounds):
            self.engines[victim].arm_wire_stall()

    def _busiest(self, candidates: tp.List[int]) -> int:
        load = {i: 0 for i in candidates}
        for st in self._pending.values():
            if st.replica in load:
                load[st.replica] += 1
        return max(sorted(load), key=lambda i: load[i])

    def _crash_victim(self) -> int:
        """The engine_crash fault's target: the alive replica holding the
        most accepted streams (maximal failover work; deterministic
        low-index tie-break)."""
        return self._busiest([i for i, a in enumerate(self.alive) if a])

    def _crash(self, i: int, *, reason: str) -> None:
        """Mark replica `i` dead and fail its streams over: harvest what
        it already finished (those results are durable), push every
        accepted-but-unfinished stream onto the failover queue for
        resubmission to survivors. The dead replica's pool dies with it —
        conservation is per-ALIVE-replica — but its spilled pages live on
        in the shared tier, so the replays re-prefill cheaper."""
        if not self.alive[i]:
            return
        self.alive[i] = False
        self.failovers += 1
        if getattr(self.engines[i], "is_proc", False):
            self.proc_failovers += 1
        self.crash_log.append(
            {"replica": i, "round": self.rounds, "reason": reason}
        )
        # proc replicas: tear the transport down and make sure the worker
        # process is gone — a half-alive worker must not keep serving a
        # router that already failed its streams over
        closer = getattr(self.engines[i], "on_router_crash", None)
        if closer is not None:
            closer()
        self._harvest_engine(i)
        moved = sorted(
            (st for st in self._pending.values() if st.replica == i),
            key=lambda st: st.uid,
        )
        for st in moved:
            del self._pending[st.uid]
            del self._by_replica[(i, st.replica_uid)]
            self.failover_queue.push(
                FailoverItem(
                    uid=st.uid,
                    prompt=st.prompt,
                    max_new_tokens=st.max_new_tokens,
                    eos_id=st.eos_id,
                    deadline=st.deadline,
                )
            )
            self.failed_over_streams += 1

    def _harvest(self) -> None:
        for i in range(len(self.engines)):
            if self.alive[i]:
                self._harvest_engine(i)

    def _harvest_engine(self, i: int) -> None:
        eng = self.engines[i]
        done = [
            st
            for st in self._pending.values()
            if st.replica == i and st.replica_uid in eng.finished
        ]
        for st in done:
            fr = eng.finished[st.replica_uid]
            out = FinishedRequest(st.uid, fr.tokens, fr.token_times, fr.status)
            self.finished[st.uid] = out
            del self._pending[st.uid]
            del self._by_replica[(i, st.replica_uid)]
            if self.on_finish is not None:
                self.on_finish(out)

    def _drain_failover(self) -> None:
        while True:
            item = self.failover_queue.pop()
            if item is None:
                break
            if item.deadline is not None and (
                item.deadline - self._clock() <= 0
            ):
                self._terminal(item, "timeout")
                continue
            try:
                self._place(
                    item.uid, item.prompt, item.max_new_tokens,
                    item.eos_id, item.deadline,
                )
            except BackpressureError:
                try:
                    self.failover_queue.requeue(item)
                except HandoffRetryExhausted:
                    # survivors refused past the bounded budget: terminal
                    # structured shed, never a silent drop or a spin
                    self._terminal(item, "shed")
                    self.shed_streams += 1
                break

    def _terminal(self, item: FailoverItem, status: str) -> None:
        out = FinishedRequest(item.uid, item.prompt, [], status)
        self.finished[item.uid] = out
        if self.on_finish is not None:
            self.on_finish(out)

    def _make_token_relay(self, i: int):
        def relay(ruid: int, tok: int, t: float) -> None:
            uid = self._by_replica.get((i, ruid))
            if uid is not None and self.on_token is not None:
                self.on_token(uid, tok, t)

        return relay

    # -- reporting -----------------------------------------------------

    def prefix_hit_rate(self) -> float:
        """Fleet-wide trie hit rate: Σ matched / Σ matchable tokens over
        EVERY replica (dead ones served real traffic before dying). The
        number affinity routing exists to protect — random routing over N
        replicas dilutes a template workload toward 1/N of the
        single-engine rate."""
        matched = sum(e._prefix_matched_tokens for e in self.engines)
        matchable = sum(e._prefix_matchable_tokens for e in self.engines)
        return matched / matchable if matchable else 0.0

    def transport_stats(self) -> tp.Optional[tp.Dict[str, tp.Any]]:
        """Wire-level rollup over the proc replicas (None for a pure
        in-process fleet): summed volume/recovery counters, mean p50 and
        worst p95 latency — the serve_fleet profile's transport fields."""
        if not self._proc_idx:
            return None
        per = [self.engines[i].transport.stats() for i in self._proc_idx]
        out: tp.Dict[str, tp.Any] = {
            k: sum(s[k] for s in per)
            for k in (
                "rpc_count", "wire_bytes", "connects", "reconnects",
                "retries", "corrupt_frames", "deadline_expiries",
                "forced_drops",
            )
        }
        out["rpc_p50_ms"] = round(
            sum(s["rpc_p50_ms"] for s in per) / len(per), 3
        )
        out["rpc_p95_ms"] = max(s["rpc_p95_ms"] for s in per)
        return out

    def stats(self) -> tp.Dict[str, tp.Any]:
        return {
            "fleet_size": len(self.engines),
            "alive": sum(self.alive),
            "rounds": self.rounds,
            "failovers": self.failovers,
            "proc_failovers": self.proc_failovers,
            "failed_over_streams": self.failed_over_streams,
            "router_shed": self.router_shed,
            "shed_streams": self.shed_streams,
            "prefix_hit_rate": self.prefix_hit_rate(),
            "failover_queue": self.failover_queue.stats(),
            "spill": self.spill.stats(),
            "transport": self.transport_stats(),
            "crash_log": list(self.crash_log),
            "replicas": [
                {
                    "alive": self.alive[i],
                    "rounds": eng.rounds,
                    "preemptions": eng.preemptions,
                    "shed": eng.shed,
                    "spill_readopted_pages": eng.spill_readopted_pages,
                    "prefix_hit_rate": eng.prefix_stats()["hit_rate"],
                }
                for i, eng in enumerate(self.engines)
            ],
        }


def assert_fleet_conserved(router: FleetRouter, where: str = "") -> None:
    """The cross-tier conservation law (ISSUE 14): every ALIVE replica
    obeys the single-engine pool law (free + trie-held + live-slot-only
    == num_pages - 1, ops.assert_conserved — a dead replica's pool died
    with it), and the shared spill tier's ledger closes (every page ever
    spilled is resident, readopted, or accounted discarded). Chaos
    scenarios assert this after every drain, including the spill-corrupt
    discard paths.

    Cross-process replicas run the pool law INSIDE the worker (the pages
    live there) over the `conserve` RPC — the law closes ACROSS the
    process boundary, with the worker-side verdict surfacing as the same
    AssertionError the in-process path raises."""
    from midgpt_tpu.sampling import ops

    for i, eng in enumerate(router.engines):
        if not router.alive[i]:
            continue
        if getattr(eng, "is_proc", False):
            eng.assert_conserved(f"{where} fleet replica {i}")
        else:
            ops.assert_conserved(eng, f"{where} fleet replica {i}")
    router.spill.assert_ledger(where)
