"""Zero-downtime model ops: blue/green weight hot-swap, elastic KV pool
resize, and the SLO-driven policy controller (docs/ROBUSTNESS.md
"Zero-downtime model ops").

Production serving means deploys under traffic. This module is the first
subsystem allowed to mutate a live engine's *identity* — its weights, its
pool geometry, its role — so every operation is built around the existing
invariants rather than around speed:

  * **Hot-swap** (`stage_hot_swap` / `maybe_flip_swap`) is blue/green at
    round granularity: the new params are validated (tree structure, leaf
    shapes/dtypes, config) against the live ones and re-homed onto the
    live params' shardings BEFORE staging, so a same-shape swap is a pure
    pointer flip — zero new compiled programs (params are *traced* args
    of the serving jits; only shapes/dtypes/shardings are compile keys,
    tests/test_recompile_pins.py). While a swap is staged, admissions
    pause; in-flight streams finish on the old weights; the flip happens
    at the first round boundary with no live slot. The KV pool and the
    prefix trie survive untouched (their content keys on prompt tokens,
    which are weight-independent; post-flip hits replay old-weight K/V —
    exactly the pages a restarted engine would recompute, see
    docs/ROBUSTNESS.md for the staleness contract).
  * **Resize** (`resize_pool`) moves the resident working set — live slot
    pages plus every referenced trie page — into a freshly allocated pool
    through the same pow2-bucketed gather/adoption scatter that the
    disagg handoff uses (sampling/disagg.py `_adopt_pages`), then remaps
    slot page lists and trie entries onto the new physical ids. Shrink
    REFUSES with a structured, retryable `PoolResizeError` rather than
    evicting below the resident working set (the backpressure discipline,
    serve.py `BackpressureError`); unreferenced trie pages are LRU-evicted
    to fit. Page conservation (free + trie + live-only == num_pages - 1)
    is asserted before and after the migration.
  * **ModelOps** is a clock-injected controller (GC012: no wall-clock
    reads outside the injected callable) that consumes the signals the
    obs layer already surfaces — free-page fraction, backlog pages,
    shed_frac, p95 TTFT when the caller has one (tools/loadgen.py) — and
    emits grow/shrink/re-role/shed-threshold decisions, observable as
    `ops.decision` tracer instants and Prometheus gauges.

Chaos gates: robustness/chaos_serve.py `hot_swap_mid_decode` (verified
checkpoint flipped mid-trace, zero drops, bit-parity on both sides of the
flip) and `pool_resize` (grow-then-shrink mid-trace, conservation at every
boundary, parity vs a no-resize pass, int8 scales migrating with pages).
"""
from __future__ import annotations

import dataclasses
import math
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.models.gpt import PagedKVCache
from midgpt_tpu.sampling.disagg import _adopt_pages
from midgpt_tpu.sampling.serve import PageAllocator, ServeEngine


class HotSwapError(RuntimeError):
    """A staged weight swap was rejected BEFORE touching the live engine.

    Structured fields (callers never string-parse):

      reason     "tree_structure" | "shape" | "dtype" | "config" |
                 "draft_missing" | "draft_unexpected" | "swap_pending"
      path       offending leaf path ("" when not leaf-specific)
      expected   live engine's value for the mismatched property
      got        candidate's value
    """

    def __init__(
        self,
        message: str,
        *,
        reason: str,
        path: str = "",
        expected: tp.Any = None,
        got: tp.Any = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.path = path
        self.expected = expected
        self.got = got


class PoolResizeError(RuntimeError):
    """A live pool resize was refused — shrinking below the resident
    working set would have to drop referenced pages, which is a data-loss
    decision the caller must make (finish/evict streams), not the resizer.

    Structured fields (the BackpressureError discipline, serve.py):

      requested_pages   the num_pages the caller asked for
      resident_pages    distinct pages that MUST survive (live slots +
                        referenced trie entries), i.e. the floor is
                        resident_pages + 1 (sink)
      num_pages         the pool's current num_pages
      requested_slots / live_slots   set for slot-count refusals
      retryable         True — retry after streams drain or evictions
    """

    def __init__(
        self,
        message: str,
        *,
        requested_pages: int,
        resident_pages: int,
        num_pages: int,
        requested_slots: tp.Optional[int] = None,
        live_slots: tp.Optional[int] = None,
        retryable: bool = True,
    ):
        super().__init__(message)
        self.requested_pages = requested_pages
        self.resident_pages = resident_pages
        self.num_pages = num_pages
        self.requested_slots = requested_slots
        self.live_slots = live_slots
        self.retryable = retryable


# ---------------------------------------------------------------------------
# Blue/green weight hot-swap
# ---------------------------------------------------------------------------


def _leaf_paths(tree) -> tp.List[tp.Tuple[str, tp.Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _validate_swap_tree(old, new, *, what: str) -> None:
    if jax.tree_util.tree_structure(old) != jax.tree_util.tree_structure(new):
        raise HotSwapError(
            f"hot-swap rejected: {what} tree structure differs from the "
            "live engine's (different model family / qkv layout?)",
            reason="tree_structure",
            expected=str(jax.tree_util.tree_structure(old)),
            got=str(jax.tree_util.tree_structure(new)),
        )
    for (path, o), (_, n) in zip(_leaf_paths(old), _leaf_paths(new)):
        if tuple(o.shape) != tuple(np.shape(n)):
            raise HotSwapError(
                f"hot-swap rejected: {what} leaf {path} has shape "
                f"{tuple(np.shape(n))}, live engine has {tuple(o.shape)} — "
                "same-shape swaps only (a different architecture is a new "
                "engine, not a swap)",
                reason="shape",
                path=path,
                expected=tuple(o.shape),
                got=tuple(np.shape(n)),
            )
        n_dtype = jnp.asarray(n).dtype if not hasattr(n, "dtype") else n.dtype
        if jnp.dtype(o.dtype) != jnp.dtype(n_dtype):
            raise HotSwapError(
                f"hot-swap rejected: {what} leaf {path} has dtype {n_dtype}, "
                f"live engine has {o.dtype} — a dtype change is a recompile, "
                "not a zero-downtime swap",
                reason="dtype",
                path=path,
                expected=str(o.dtype),
                got=str(n_dtype),
            )


def stage_hot_swap(
    engine: ServeEngine,
    params,
    *,
    draft_params=None,
    version: str = "inline",
    config=None,
) -> tp.Dict[str, tp.Any]:
    """Validate + stage a blue/green weight swap on `engine`.

    Rejections raise `HotSwapError` without perturbing the engine. On
    success the candidate params are device_put onto the live params'
    shardings (the sharding is a compile key of the serving jits — this is
    what makes the flip zero-recompile on both single-chip and mesh
    engines) and staged; `maybe_flip_swap` flips at the first round
    boundary with no live slot (immediately, for an idle engine). While
    staged, `_admit` pauses so queued arrivals deterministically take the
    NEW weights.
    """
    if engine._staged_swap is not None:
        raise HotSwapError(
            "hot-swap rejected: a swap is already staged "
            f"(version {engine._staged_swap['version']!r}) and has not "
            "flipped yet",
            reason="swap_pending",
            expected=None,
            got=version,
        )
    if config is not None:
        live_cfg = engine.config
        cand = config
        # Mesh engines rewrite qkv_proj to "split3" at construction
        # (serve.py); accept the pre-rewrite spelling of the same config.
        if getattr(cand, "qkv_proj", None) != getattr(live_cfg, "qkv_proj", None):
            cand = dataclasses.replace(cand, qkv_proj=live_cfg.qkv_proj)
        if cand != live_cfg:
            raise HotSwapError(
                "hot-swap rejected: model config differs from the live "
                "engine's — a config change is a new engine, not a swap",
                reason="config",
                expected=live_cfg,
                got=config,
            )
    _validate_swap_tree(engine.params, params, what="params")
    if draft_params is not None and engine.draft_params is None:
        raise HotSwapError(
            "hot-swap rejected: draft params offered but the live engine "
            "has no draft model configured",
            reason="draft_unexpected",
        )
    if draft_params is None and engine.draft_params is not None:
        # Target-only swap on a speculative engine is legal — the draft
        # only PROPOSES; the rejection sampler guarantees the committed
        # distribution is the (new) target's regardless of draft staleness.
        pass
    if draft_params is not None:
        _validate_swap_tree(engine.draft_params, draft_params, what="draft_params")

    params = jax.tree.map(
        lambda o, n: jax.device_put(n, o.sharding), engine.params, params
    )
    if draft_params is not None:
        draft_params = jax.tree.map(
            lambda o, n: jax.device_put(n, o.sharding),
            engine.draft_params,
            draft_params,
        )
    engine._staged_swap = {
        "params": params,
        "draft_params": draft_params,
        "version": version,
        "staged_round": engine.rounds,
        "staged_at": engine._clock(),
        "in_flight_at_stage": sorted(
            s.request.uid for s in engine.slots if s is not None
        ),
    }
    engine._trace.instant(
        "ops.hot_swap_staged",
        "ops",
        engine._obs_tid,
        args={
            "version": version,
            "in_flight": len(engine._staged_swap["in_flight_at_stage"]),
        },
    )
    summary = {
        "staged": True,
        "version": version,
        "staged_round": engine.rounds,
        "in_flight_at_stage": list(engine._staged_swap["in_flight_at_stage"]),
    }
    # Idle engines flip immediately — nothing to drain.
    summary["flipped"] = maybe_flip_swap(engine)
    return summary


def maybe_flip_swap(engine: ServeEngine) -> bool:
    """Flip a staged swap iff no old-side stream remains in flight: no
    slot live AND no recompute-preempted stream waiting in the queue (its
    committed tokens came from the old weights — resuming it on the new
    ones would hand back a stream that matches neither version). That is
    the round boundary where blue/green is a pure pointer exchange.
    Called by `ServeEngine.step` between expiry and admission; returns
    True when the flip happened."""
    st = engine._staged_swap
    if st is None:
        return False
    if any(s is not None for s in engine.slots):
        return False
    if any(q.uid in engine._resumed_uids for q in engine.queue):
        return False
    old_version = engine.weights_version
    engine.params = st["params"]
    if st["draft_params"] is not None:
        engine.draft_params = st["draft_params"]
    engine.weights_version = st["version"]
    engine._staged_swap = None
    engine.hot_swaps += 1
    record = {
        "staged_round": st["staged_round"],
        "flip_round": engine.rounds,
        "swap_latency_s": engine._clock() - st["staged_at"],
        "in_flight_at_stage": st["in_flight_at_stage"],
        "served_uids_at_flip": sorted(engine.finished),
        "from_version": old_version,
        "version": st["version"],
    }
    engine.swap_history.append(record)
    engine._trace.instant(
        "ops.hot_swap",
        "ops",
        engine._obs_tid,
        args={
            "version": st["version"],
            "from_version": old_version,
            "flip_round": engine.rounds,
        },
    )
    if engine.obs is not None:
        engine.obs.metrics.counter(
            "ops_hot_swaps", "completed blue/green weight flips"
        ).inc()
    return True


# ---------------------------------------------------------------------------
# Elastic pool resize
# ---------------------------------------------------------------------------


def assert_conserved(engine: ServeEngine, where: str) -> None:
    """The serving-wide page conservation law (chaos_serve.py invariant):
    free + trie-held + live-slot-only == num_pages - 1 (page 0 is the
    sink). Resize asserts it on BOTH sides of a migration."""
    pc = engine.prefix_cache
    held = set() if pc is None else pc.pages_held()
    # -1 entries are window-reclaimed placeholders (serve.py
    # _reclaim_window) — already back on the free list, not live.
    live = {p for s in engine.slots if s is not None for p in s.pages if p >= 0}
    total = engine.allocator.free_count + len(held) + len(live - held)
    assert total == engine.allocator.num_pages - 1, (
        f"page conservation violated {where}: free={engine.allocator.free_count} "
        f"trie={len(held)} live_only={len(live - held)} "
        f"!= num_pages-1={engine.allocator.num_pages - 1}"
    )


def _pow2_bucket(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _gather_resident(cache, old_ids: tp.List[int], pad_to: int):
    """Host-gather the resident pages (padded to the pow2 bucket with the
    sink page 0, so the gather's compile key is the bucket, not the exact
    resident count — the same bucket discipline as the serving jits)."""
    idx = jnp.asarray(old_ids + [0] * (pad_to - len(old_ids)), jnp.int32)
    blocks = {
        "k": np.asarray(jnp.take(cache.k, idx, axis=2)),
        "v": np.asarray(jnp.take(cache.v, idx, axis=2)),
    }
    if cache.k_scale is not None:
        blocks["k_scale"] = np.asarray(jnp.take(cache.k_scale, idx, axis=1))
        blocks["v_scale"] = np.asarray(jnp.take(cache.v_scale, idx, axis=1))
    return blocks


def _migrate_cache(engine, cache, old_ids, new_ids, num_pages, config):
    """Copy resident pages of one pool (target or draft) into a freshly
    allocated `num_pages` pool via the disagg adoption scatter — int8
    scales travel with their pages ('k_scale'/'v_scale' blocks)."""
    bucket = _pow2_bucket(len(old_ids))
    blocks = _gather_resident(cache, old_ids, bucket)
    # Pad destinations with `num_pages`: XLA oob-scatter drops the pad
    # writes (disagg.py _adopt_pages contract).
    dst = jnp.asarray(new_ids + [num_pages] * (bucket - len(new_ids)), jnp.int32)
    new_cache = PagedKVCache.init(
        config, num_pages=num_pages, page_size=engine.page_size,
        dtype=engine.cache_dtype,
    )
    if engine.mesh is not None:
        from midgpt_tpu.parallel import serve_tp as _stp

        new_cache = _stp.put_sharded(
            new_cache, _stp.serve_cache_specs(new_cache), engine.mesh
        )
    if not old_ids:
        return new_cache
    return _adopt_pages(engine.mesh, new_cache, dst, blocks)


def resize_pool(
    engine: ServeEngine,
    num_pages: tp.Optional[int] = None,
    *,
    max_slots: tp.Optional[int] = None,
) -> tp.Dict[str, tp.Any]:
    """Grow/shrink the live pool to `num_pages` (and/or the slot count to
    `max_slots`) by migrating the resident working set into a new pool.

    Runs between rounds on the engine thread (the async front door routes
    it through the driver loop, server.py). Protocol:

      1. Refuse (PoolResizeError, retryable) if the resident working set —
         live slot pages + referenced trie pages — cannot fit, or if live
         slots exceed the requested slot count.
      2. LRU-evict unreferenced trie pages that no longer fit.
      3. Gather resident pages (pow2 bucket, sink-padded), scatter into
         the new pool with the disagg adoption jit (int8 scales ride
         along), remap slot page lists + trie entries to the new ids.
      4. Install pool + allocator; conservation asserted on both sides.

    The new pool's first decode/prefill round compiles the page-bucket
    programs for the new num_pages (a program key); an identical resize
    replays from the jit cache — pinned in tests/test_recompile_pins.py.
    """
    old_total = engine.allocator.num_pages
    if num_pages is None:
        num_pages = old_total
    if num_pages < 2:
        raise ValueError(f"num_pages must be >= 2 (sink + 1), got {num_pages}")
    live_slots = [s for s in engine.slots if s is not None]
    if max_slots is not None and max_slots < 1:
        raise ValueError(f"max_slots must be >= 1, got {max_slots}")
    if max_slots is not None and len(live_slots) > max_slots:
        raise PoolResizeError(
            f"resize refused: {len(live_slots)} live slots exceed the "
            f"requested max_slots={max_slots} — drain or cancel streams "
            "first (retryable)",
            requested_pages=num_pages,
            resident_pages=0,
            num_pages=old_total,
            requested_slots=max_slots,
            live_slots=len(live_slots),
        )

    pc = engine.prefix_cache
    live = {p for s in live_slots for p in s.pages if p >= 0}
    referenced = set() if pc is None else pc.referenced_pages()
    # Slot-shared pages (pages[:n_shared]) are referenced trie entries by
    # construction, so |live ∪ referenced| = |live − held| + |referenced|.
    resident = live | referenced
    if num_pages - 1 < len(resident):
        raise PoolResizeError(
            f"resize refused: requested num_pages={num_pages} holds "
            f"{num_pages - 1} pages but the resident working set is "
            f"{len(resident)} pages (live slots + referenced trie entries) "
            "— shrinking would drop live data; drain streams or evict "
            "first (retryable)",
            requested_pages=num_pages,
            resident_pages=len(resident),
            num_pages=old_total,
        )
    assert_conserved(engine, "before resize")

    trie_evicted = 0
    if pc is not None:
        held = pc.pages_held()
        overflow = len(live | held) - (num_pages - 1)
        if overflow > 0:
            # Only unreferenced entries are evictable; the resident check
            # above guarantees there are at least `overflow` of them.
            freed = engine.prefix_cache.evict(overflow)
            engine.allocator.free(freed)
            trie_evicted = len(freed)
            assert trie_evicted == overflow, (
                f"resize eviction shortfall: wanted {overflow}, "
                f"evicted {trie_evicted}"
            )

    held = set() if pc is None else pc.pages_held()
    old_ids = sorted(live | held)
    n_migrate = len(old_ids)
    allocator = PageAllocator(num_pages)
    new_ids: tp.List[int] = []
    if n_migrate:
        got = allocator.alloc(n_migrate)
        assert got is not None  # n_migrate <= num_pages - 1 checked above
        new_ids.extend(got)
    mapping = dict(zip(old_ids, new_ids))

    engine.cache = _migrate_cache(
        engine, engine.cache, old_ids, new_ids, num_pages, engine.config
    )
    if engine.draft_cache is not None:
        engine.draft_cache = _migrate_cache(
            engine, engine.draft_cache, old_ids, new_ids, num_pages,
            engine.draft_config,
        )
    for s in live_slots:
        s.pages[:] = [mapping[p] if p >= 0 else -1 for p in s.pages]
    if pc is not None:
        pc.remap_pages(mapping)
    engine.allocator = allocator
    if max_slots is not None and max_slots != engine.max_slots:
        # Live slots keep their _Slot objects; the page table is rebuilt
        # from engine.slots every round, so compaction is free. A new
        # max_slots is a program shape key — bounded, caller-chosen.
        engine.slots = live_slots + [None] * (max_slots - len(live_slots))
        engine.max_slots = max_slots
    assert_conserved(engine, "after resize")

    engine.resizes += 1
    record = {
        "round": engine.rounds,
        "from_pages": old_total,
        "to_pages": num_pages,
        "pages_migrated": n_migrate,
        "trie_pages_evicted": trie_evicted,
        "max_slots": engine.max_slots,
        "gather_bucket": _pow2_bucket(n_migrate) if n_migrate else 0,
    }
    engine.resize_history.append(record)
    engine._trace.instant(
        "ops.resize", "ops", engine._obs_tid,
        args={k: v for k, v in record.items()},
    )
    if engine.obs is not None:
        engine.obs.metrics.counter(
            "ops_resizes", "completed live pool resizes"
        ).inc()
        engine.obs.metrics.gauge(
            "ops_pool_pages", "current pool num_pages"
        ).set(float(num_pages))
    return record


# ---------------------------------------------------------------------------
# SLO-driven policy controller
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OpsDecision:
    """One controller tick's outcome. kind is one of "none" | "grow" |
    "shrink" | "shed_threshold" | "re_role"; `applied` is False when the
    target refused (e.g. PoolResizeError on a shrink — recorded in
    `error`, retryable next tick) or when the controller runs advisory
    (`apply=False`)."""

    kind: str
    reason: str
    args: tp.Dict[str, tp.Any] = dataclasses.field(default_factory=dict)
    applied: bool = False
    error: tp.Optional[str] = None


class ModelOps:
    """Clock-injected SLO policy loop over a ServeEngine or a DisaggServe.

    Consumes only signals the engine already exposes (free-page fraction,
    backlog pages, shed fraction, handoff queue depth) plus an optional
    caller-measured `ttft_p95_ms` (tools/loadgen.py feeds its own window),
    and emits at most ONE decision per tick:

      grow            free pages below `low_free_frac`, TTFT over budget,
                      or shed fraction over budget -> resize the pool up
                      by `grow_frac`.
      shrink          free pages above `high_free_frac` with an idle
                      backlog -> resize down by `shrink_frac` (refusals
                      are recorded, not raised — retryable next tick).
      shed_threshold  persistent shedding with a healthy pool -> loosen
                      `max_backlog_pages` (scheduler.set_backlog_budget).
      re_role         disagg targets: deep handoff backlog -> move pool
                      pages prefill->decode (DisaggServe.rebalance);
                      starved prefill with an idle queue -> the reverse.

    A "none" tick touches no device state and dispatches no program —
    obs-on controller ticks are zero-recompile-pinned
    (tests/test_recompile_pins.py). Decisions surface as `ops.decision`
    tracer instants and `ops_*` Prometheus gauges.
    """

    def __init__(
        self,
        target,
        *,
        clock: tp.Callable[[], float] = time.perf_counter,
        obs=None,
        low_free_frac: float = 0.15,
        high_free_frac: float = 0.85,
        grow_frac: float = 0.5,
        shrink_frac: float = 0.25,
        min_interval_s: float = 0.0,
        ttft_budget_ms: tp.Optional[float] = None,
        shed_budget_frac: float = 0.25,
        handoff_backlog_high: int = 4,
        rebalance_pages: int = 4,
        apply: bool = True,
    ):
        self.target = target
        self._clock = clock
        self._disagg = hasattr(target, "prefill") and hasattr(target, "decode")
        if obs is None:
            obs = getattr(target, "obs", None)
        self.obs = obs
        self.low_free_frac = low_free_frac
        self.high_free_frac = high_free_frac
        self.grow_frac = grow_frac
        self.shrink_frac = shrink_frac
        self.min_interval_s = min_interval_s
        self.ttft_budget_ms = ttft_budget_ms
        self.shed_budget_frac = shed_budget_frac
        self.handoff_backlog_high = handoff_backlog_high
        self.rebalance_pages = rebalance_pages
        self.apply = apply
        self._last_tick: tp.Optional[float] = None
        self.decisions: tp.List[OpsDecision] = []

    # -- signal helpers --------------------------------------------------

    @staticmethod
    def _free_frac(eng) -> float:
        cap = eng.allocator.num_pages - 1
        return eng.allocator.free_count / max(1, cap)

    @staticmethod
    def _shed_frac(eng) -> float:
        return eng.shed / max(1, eng.shed + eng._uid)

    def _gauges(self, prefix: str, eng) -> None:
        if self.obs is None:
            return
        m = self.obs.metrics
        m.gauge(
            f"ops_{prefix}free_page_frac", "free pages / allocatable pages"
        ).set(self._free_frac(eng))
        m.gauge(
            f"ops_{prefix}backlog_pages", "worst-case page demand of live work"
        ).set(float(eng._backlog_pages()))
        m.gauge(
            f"ops_{prefix}shed_frac", "shed submits / total submits"
        ).set(self._shed_frac(eng))

    def _record(self, decision: OpsDecision) -> OpsDecision:
        self.decisions.append(decision)
        if self.obs is not None and decision.kind != "none":
            self.obs.tracer.instant(
                "ops.decision", "ops", "ops",
                args={
                    "kind": decision.kind,
                    "reason": decision.reason,
                    "applied": decision.applied,
                    **{k: v for k, v in decision.args.items()
                       if isinstance(v, (int, float, str, bool))},
                },
            )
            self.obs.metrics.counter(
                f"ops_decisions_{decision.kind}",
                f"controller '{decision.kind}' decisions",
            ).inc()
        return decision

    # -- tick ------------------------------------------------------------

    def tick(self, *, ttft_p95_ms: tp.Optional[float] = None) -> OpsDecision:
        now = self._clock()
        if (
            self._last_tick is not None
            and now - self._last_tick < self.min_interval_s
        ):
            return self._record(OpsDecision(kind="none", reason="interval"))
        self._last_tick = now
        if self._disagg:
            return self._tick_disagg()
        return self._tick_engine(self.target, ttft_p95_ms)

    def _tick_engine(self, eng, ttft_p95_ms) -> OpsDecision:
        self._gauges("", eng)
        cap = eng.allocator.num_pages - 1
        free_frac = self._free_frac(eng)
        shed_frac = self._shed_frac(eng)
        ttft_over = (
            self.ttft_budget_ms is not None
            and ttft_p95_ms is not None
            and ttft_p95_ms > self.ttft_budget_ms
        )
        if free_frac < self.low_free_frac or ttft_over:
            new_pages = 1 + int(math.ceil(cap * (1.0 + self.grow_frac)))
            reason = "ttft_over_budget" if ttft_over else "free_pages_low"
            d = OpsDecision(
                kind="grow", reason=reason,
                args={"from_pages": cap + 1, "to_pages": new_pages,
                      "free_frac": free_frac},
            )
            if self.apply:
                eng.resize(new_pages)
                d.applied = True
            return self._record(d)
        if shed_frac > self.shed_budget_frac and eng.max_backlog_pages is not None:
            from midgpt_tpu.sampling.scheduler import set_backlog_budget

            new_budget = int(eng.max_backlog_pages * 1.5) + 1
            d = OpsDecision(
                kind="shed_threshold", reason="shed_frac_over_budget",
                args={"from_budget": eng.max_backlog_pages,
                      "to_budget": new_budget, "shed_frac": shed_frac},
            )
            if self.apply:
                set_backlog_budget(eng, new_budget)
                d.applied = True
            return self._record(d)
        if free_frac > self.high_free_frac and eng._backlog_pages() == 0:
            new_pages = 1 + max(1, int(math.ceil(cap * (1.0 - self.shrink_frac))))
            if new_pages < cap + 1:
                d = OpsDecision(
                    kind="shrink", reason="free_pages_high",
                    args={"from_pages": cap + 1, "to_pages": new_pages,
                          "free_frac": free_frac},
                )
                if self.apply:
                    try:
                        eng.resize(new_pages)
                        d.applied = True
                    except PoolResizeError as e:
                        d.error = str(e)
                return self._record(d)
        return self._record(OpsDecision(kind="none", reason="in_band"))

    def _tick_disagg(self) -> OpsDecision:
        d = self.target
        self._gauges("prefill_", d.prefill)
        self._gauges("decode_", d.decode)
        depth = d.queue.stats()["depth"]
        if self.obs is not None:
            self.obs.metrics.gauge(
                "ops_handoff_depth", "prefill->decode handoff queue depth"
            ).set(float(depth))
        if depth > self.handoff_backlog_high:
            dec = OpsDecision(
                kind="re_role", reason="handoff_backlog_deep",
                args={"src": "prefill", "dst": "decode",
                      "pages": self.rebalance_pages, "depth": depth},
            )
            if self.apply:
                try:
                    d.rebalance(self.rebalance_pages, src="prefill", dst="decode")
                    dec.applied = True
                except PoolResizeError as e:
                    dec.error = str(e)
            return self._record(dec)
        if (
            depth == 0
            and self._free_frac(d.prefill) < self.low_free_frac
            and self._free_frac(d.decode) > self.high_free_frac
        ):
            dec = OpsDecision(
                kind="re_role", reason="prefill_starved",
                args={"src": "decode", "dst": "prefill",
                      "pages": self.rebalance_pages, "depth": depth},
            )
            if self.apply:
                try:
                    d.rebalance(self.rebalance_pages, src="decode", dst="prefill")
                    dec.applied = True
                except PoolResizeError as e:
                    dec.error = str(e)
            return self._record(dec)
        return self._record(OpsDecision(kind="none", reason="in_band"))
