"""Cross-process fleet serving: the replica socket transport and worker
protocol (docs/ROBUSTNESS.md "Cross-process fleet").

ROADMAP item 4a promotes the fleet's replica boundary (sampling/fleet.py)
from an object boundary to a real OS process boundary: each replica is a
worker process hosting one ServeEngine — its own CPU mesh, its own jit
cache, its own host-RAM SpillTier — and the FleetRouter drives it through
`ProcReplica`, which implements the exact duck-typed engine surface the
router already speaks (`submit`/`step`/`idle`/`finished`/counter attrs),
so the in-process path stays bit-identical and every r18 fleet test passes
unchanged. Deliberately NO `jax.distributed`: replicas share no arrays and
no collectives — everything that crosses the boundary is plain host data
over a socket (the GC015 wire contract, now literal), which is why this
works on jax 0.4.37 where multi-process CPU collectives do not
(tests/test_multiprocess.py pins that env gap).

Wire format — length-prefixed, crc32-framed JSON + binary blobs:

    header:  magic "MGW1" | u32 payload_len | u32 crc32(payload)
    payload: u32 json_len | JSON bytes | blob bytes (concatenated)

ndarrays anywhere in a message tree are replaced by ``{"__blob__": i}``
descriptors (dtype/shape in the JSON header) and travel as raw bytes —
never pickled, never a live device array. The crc32 is verified BEFORE the
JSON is decoded: a truncated or bit-flipped frame raises `WireFrameError`
and is dropped with the connection, mirroring the SpillTier rule — a bad
frame degrades to a retried RPC (harvest marks make retries idempotent),
never into a decode.

Robustness weight lives in `ReplicaTransport`: per-RPC deadlines
(socket timeouts -> structured `TransportError`), connect/call retry on
the shared `robustness/backoff.py` schedule, a wire heartbeat (`last_ok`
on the injected clock) feeding the router's existing clock-injected health
checks, and chaos hooks (`arm_wire_corrupt` / `arm_wire_stall` /
`drop_conn`) for the `wire_corrupt` / `wire_stall` / `conn_drop` fault
kinds. A worker that stays unreachable past the retry budget raises
`ReplicaGoneError`; the router's consecutive-failure health check then
fires the same `_crash` failover path as an in-process engine death — a
`kill -9` of a worker looks exactly like r18's `engine_crash`, proven
token-for-token by the `proc_kill9` chaos gate.

Retry idempotence, per verb: `submit` carries a router-side `seq` the
worker dedups on (a retried admit never double-admits); `harvest` is a
high-water-mark read (`events_from` + `known_uids` — the request is the
ack); a retried `step` just runs an extra engine round, which greedy
batch-composition independence makes parity-neutral; `stats`/`conserve`
are pure reads. SIGTERM drains gracefully through the existing preempt
flag (robustness/preempt.py): the handler only flips the flag, the worker
loop notices it between RPCs, refuses new admissions with a non-retryable
backpressure reply, finishes its in-flight streams, and exits once idle
and disconnected. Spilled KV survives a drain — `spill_export` /
`spill_import` move `SpillTransferItem`s (checksums travel with their
pages, so take-side verification still covers the bytes end to end) and
the tier ledger extends with `received`/`transferred` buckets that keep
the conservation law closing across the boundary.

This module is import-light (no jax, no engine imports at module scope):
the frame codec, errors, and transport are unit-testable with nothing but
numpy + sockets; ProcReplica lazy-imports the engine types it mirrors.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import time
import typing as tp
import zlib

import numpy as np

from midgpt_tpu.robustness.backoff import retry_with_backoff

# -- structured errors (analysis/error_contracts.py registers the field
# contracts; GC016 enforces them at every raise site) -----------------------


class TransportError(ConnectionError):
    """One RPC attempt failed at the transport layer — connect refused,
    send/recv error, or the response did not land inside the per-RPC
    deadline. Retryable by construction: `ReplicaTransport.call` absorbs
    these on the shared backoff schedule and only escalates to
    `ReplicaGoneError` when the budget is spent."""

    def __init__(
        self,
        message: str,
        *,
        host: str,
        port: int,
        rpc: str,
        deadline_s: tp.Optional[float] = None,
    ):
        super().__init__(message)
        self.host = host
        self.port = port
        self.rpc = rpc
        self.deadline_s = deadline_s


class WireFrameError(ValueError):
    """A frame failed validation BEFORE its JSON was decoded — bad magic,
    truncated read, length overflow, or crc32 mismatch. The connection is
    dropped (a desynced stream cannot be trusted for the next frame) and
    the RPC retries on a fresh one; corrupt bytes never reach a decode."""

    def __init__(self, message: str, *, reason: str, nbytes: int = 0):
        super().__init__(message)
        self.reason = reason
        self.nbytes = nbytes


class ReplicaGoneError(ConnectionError):
    """The worker stayed unreachable past the transport's full retry
    budget. This is the wire's verdict that the replica is dead; the
    router's consecutive-failure health check turns it into the same
    failover `_crash` path an in-process engine death takes."""

    def __init__(
        self,
        message: str,
        *,
        host: str,
        port: int,
        rpc: str,
        attempts: int,
    ):
        super().__init__(message)
        self.host = host
        self.port = port
        self.rpc = rpc
        self.attempts = attempts


# -- frame codec ------------------------------------------------------------

_MAGIC = b"MGW1"
_HEADER = struct.Struct("<4sII")  # magic | payload_len | crc32(payload)
_JLEN = struct.Struct("<I")
# Sanity bound, not a resource budget: tiny-model KV pages are KBs; a
# length field past this is a desynced/corrupt stream, not a big message.
MAX_FRAME_BYTES = 1 << 28


def _pack_tree(obj: tp.Any, blobs: tp.List[np.ndarray]) -> tp.Any:
    """JSON-ify a message tree, lifting ndarrays out as indexed blobs."""
    if isinstance(obj, np.ndarray):
        # reshape back: ascontiguousarray promotes 0-d to 1-d, which would
        # silently change the shape a 0-d scalar lands with on the far side
        blobs.append(np.ascontiguousarray(obj).reshape(obj.shape))
        return {"__blob__": len(blobs) - 1}
    if isinstance(obj, dict):
        return {str(k): _pack_tree(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_pack_tree(v, blobs) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _unpack_tree(obj: tp.Any, blobs: tp.List[np.ndarray]) -> tp.Any:
    if isinstance(obj, dict):
        if set(obj) == {"__blob__"}:
            return blobs[obj["__blob__"]]
        return {k: _unpack_tree(v, blobs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack_tree(v, blobs) for v in obj]
    return obj


def encode_frame(obj: tp.Any) -> bytes:
    """Message tree -> one framed byte string (module docstring layout)."""
    blobs: tp.List[np.ndarray] = []
    tree = _pack_tree(obj, blobs)
    head = json.dumps(
        {
            "tree": tree,
            "blobs": [
                {"dtype": str(b.dtype), "shape": list(b.shape)} for b in blobs
            ],
        },
        separators=(",", ":"),
    ).encode("utf-8")
    payload = b"".join(
        [_JLEN.pack(len(head)), head] + [b.tobytes() for b in blobs]
    )
    return _HEADER.pack(_MAGIC, len(payload), zlib.crc32(payload)) + payload


def decode_frame(data: bytes) -> tp.Any:
    """One framed byte string -> message tree. Magic, length, and crc32
    are all verified before a single byte of JSON is parsed."""
    if len(data) < _HEADER.size:
        raise WireFrameError(
            f"frame truncated at {len(data)} bytes (header is "
            f"{_HEADER.size})",
            reason="truncated",
            nbytes=len(data),
        )
    magic, plen, crc = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise WireFrameError(
            f"bad frame magic {magic!r}", reason="bad_magic", nbytes=len(data)
        )
    if plen > MAX_FRAME_BYTES:
        raise WireFrameError(
            f"frame length {plen} exceeds {MAX_FRAME_BYTES} — desynced or "
            "corrupt stream",
            reason="length",
            nbytes=len(data),
        )
    payload = data[_HEADER.size:]
    if len(payload) != plen:
        raise WireFrameError(
            f"frame payload truncated: {len(payload)} of {plen} bytes",
            reason="truncated",
            nbytes=len(data),
        )
    if zlib.crc32(payload) != crc:
        raise WireFrameError(
            "frame checksum mismatch — rejecting before decode",
            reason="checksum",
            nbytes=len(data),
        )
    (jlen,) = _JLEN.unpack_from(payload)
    if _JLEN.size + jlen > plen:
        raise WireFrameError(
            f"frame JSON header overruns payload ({jlen} bytes declared)",
            reason="length",
            nbytes=len(data),
        )
    head = json.loads(payload[_JLEN.size:_JLEN.size + jlen])
    blobs: tp.List[np.ndarray] = []
    off = _JLEN.size + jlen
    for desc in head.get("blobs", ()):
        dt = np.dtype(desc["dtype"])
        shape = tuple(int(s) for s in desc["shape"])
        count = int(np.prod(shape)) if shape else 1
        end = off + count * dt.itemsize
        if end > plen:
            raise WireFrameError(
                "frame blob section truncated", reason="truncated",
                nbytes=len(data),
            )
        # copy(): frombuffer views are read-only and entries may be
        # mutated after landing (e.g. SpillTier.corrupt_one)
        blobs.append(
            np.frombuffer(payload, dt, count, off).reshape(shape).copy()
        )
        off = end
    return _unpack_tree(head["tree"], blobs)


def _recv_exact(sock: socket.socket, n: int, what: str) -> bytes:
    """Read exactly n bytes. EOF before the first byte is a clean peer
    close (ConnectionError); EOF mid-read is a truncated frame."""
    chunks: tp.List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0 and what == "header":
                raise ConnectionError("peer closed the connection")
            raise WireFrameError(
                f"connection closed mid-{what}: {got} of {n} bytes",
                reason="truncated",
                nbytes=got,
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame_bytes(sock: socket.socket) -> bytes:
    """Read one raw frame off the socket (header validated enough to size
    the read; full verification happens in decode_frame)."""
    head = _recv_exact(sock, _HEADER.size, "header")
    magic, plen, _ = _HEADER.unpack(head)
    if magic != _MAGIC:
        raise WireFrameError(
            f"bad frame magic {magic!r}", reason="bad_magic",
            nbytes=len(head),
        )
    if plen > MAX_FRAME_BYTES:
        raise WireFrameError(
            f"frame length {plen} exceeds {MAX_FRAME_BYTES}",
            reason="length",
            nbytes=len(head),
        )
    return head + _recv_exact(sock, plen, "payload")


def read_frame(sock: socket.socket) -> tp.Any:
    return decode_frame(read_frame_bytes(sock))


def write_frame(sock: socket.socket, obj: tp.Any) -> int:
    data = encode_frame(obj)
    sock.sendall(data)
    return len(data)


# -- spill transfer payload (GC015 wire item) -------------------------------


@dataclasses.dataclass
class SpillTransferItem:
    """One spilled page crossing the process boundary: its full-prefix
    key, host-landed blocks (the blessed {k, v, k_scale, v_scale} shape),
    the ORIGINAL spill-time crc32 — preserved end to end so the take-side
    verification still covers transit AND residence — and the
    weights_version the KV was computed under."""

    key: tp.Tuple[int, ...]
    blocks: tp.Dict[str, np.ndarray]
    checksum: int
    weights_version: str


# -- router-side transport --------------------------------------------------


class ReplicaTransport:
    """One worker's socket endpoint: framed request/response RPCs with
    per-call deadlines, bounded reconnect/retry on the shared backoff
    schedule, a wire heartbeat, and the wire-level chaos hooks (module
    docstring)."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        rpc_deadline_s: float = 120.0,
        call_retries: int = 3,
        retry_base_s: float = 0.05,
        clock: tp.Callable[[], float] = time.perf_counter,
        sleep: tp.Callable[[float], None] = time.sleep,
        obs=None,
        obs_tid: str = "transport",
    ):
        if call_retries < 1:
            raise ValueError(f"call_retries must be >= 1, got {call_retries}")
        self.host = host
        self.port = port
        self.rpc_deadline_s = rpc_deadline_s
        self.call_retries = call_retries
        self.retry_base_s = retry_base_s
        self._clock = clock
        self._sleep = sleep
        self._sock: tp.Optional[socket.socket] = None
        self._seq = 0
        # wire heartbeat: injected-clock stamp of the last successful RPC
        # (FleetRouter's staleness check reads the same clock family)
        self.last_ok: tp.Optional[float] = None
        # counters
        self.rpc_count = 0
        self.wire_bytes = 0
        self.connects = 0
        self.retries = 0
        self.corrupt_frames = 0
        self.deadline_expiries = 0
        self.forced_drops = 0
        self._lat_s: tp.List[float] = []
        # chaos arms (wire_corrupt / wire_stall fault kinds)
        self._corrupt_next = False
        self._stall_next = False
        self._obs = obs
        self._obs_tid = obs_tid
        self._h_rpc = (
            None
            if obs is None
            else obs.metrics.histogram(
                "transport_rpc_s",
                "round-trip latency per fleet-transport RPC",
            )
        )

    # -- connection lifecycle ------------------------------------------

    def _ensure_conn(self, rpc: str, deadline_s: float) -> socket.socket:
        if self._sock is not None:
            return self._sock
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=deadline_s
            )
        except OSError as e:
            raise TransportError(
                f"connect to {self.host}:{self.port} failed for rpc "
                f"{rpc!r}: {e}",
                host=self.host,
                port=self.port,
                rpc=rpc,
                deadline_s=deadline_s,
            ) from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self.connects += 1
        return sock

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._drop_sock()

    @property
    def reconnects(self) -> int:
        return max(self.connects - 1, 0)

    # -- chaos hooks (robustness/faults.py kinds) ----------------------

    def drop_conn(self) -> None:
        """`conn_drop`: abruptly close the live connection; the next RPC
        must reconnect transparently (counted in `reconnects`)."""
        self.forced_drops += 1
        self._drop_sock()

    def arm_wire_corrupt(self) -> None:
        """`wire_corrupt`: flip a byte in the NEXT received frame before
        verification — the checksum must reject it pre-decode and the RPC
        must recover by retrying on a fresh connection."""
        self._corrupt_next = True

    def arm_wire_stall(self) -> None:
        """`wire_stall`: the NEXT RPC's response never lands inside its
        deadline (the request is sent, the read abandoned, the connection
        dropped — exactly what a deadline expiry leaves behind)."""
        self._stall_next = True

    # -- the RPC -------------------------------------------------------

    def call(
        self,
        op: str,
        payload: tp.Optional[tp.Dict[str, tp.Any]] = None,
        *,
        deadline_s: tp.Optional[float] = None,
        retries: tp.Optional[int] = None,
    ) -> tp.Dict[str, tp.Any]:
        """One request/response RPC. Transient transport failures retry on
        the shared backoff schedule (`robustness/backoff.py`); the `seq`
        assigned here is stable across those retries so side-effectful
        verbs dedup worker-side. Exhausting the budget raises
        `ReplicaGoneError`."""
        dl = self.rpc_deadline_s if deadline_s is None else deadline_s
        budget = self.call_retries if retries is None else retries
        self._seq += 1
        seq = self._seq
        self.rpc_count += 1
        t_start = self._clock()

        def attempt() -> tp.Dict[str, tp.Any]:
            sock = self._ensure_conn(op, dl)
            sock.settimeout(dl)
            req = dict(payload or {})
            req["op"] = op
            req["seq"] = seq
            try:
                self.wire_bytes += write_frame(sock, req)
                if self._stall_next:
                    self._stall_next = False
                    self.deadline_expiries += 1
                    self._drop_sock()
                    raise TransportError(
                        f"rpc {op!r} response did not land within {dl}s "
                        f"(wire stall)",
                        host=self.host,
                        port=self.port,
                        rpc=op,
                        deadline_s=dl,
                    )
                raw = read_frame_bytes(sock)
            except socket.timeout as e:
                self.deadline_expiries += 1
                self._drop_sock()
                raise TransportError(
                    f"rpc {op!r} exceeded its {dl}s deadline",
                    host=self.host,
                    port=self.port,
                    rpc=op,
                    deadline_s=dl,
                ) from e
            except WireFrameError:
                self.corrupt_frames += 1
                self._drop_sock()
                raise
            except OSError as e:
                self._drop_sock()
                raise TransportError(
                    f"rpc {op!r} transport failure: {e}",
                    host=self.host,
                    port=self.port,
                    rpc=op,
                    deadline_s=dl,
                ) from e
            self.wire_bytes += len(raw)
            if self._corrupt_next:
                self._corrupt_next = False
                flipped = bytearray(raw)
                flipped[-1] ^= 0xFF
                raw = bytes(flipped)
            try:
                reply = decode_frame(raw)
            except WireFrameError:
                # checksum/shape rejection AFTER a full read: the stream
                # itself is suspect — drop it and retry on a fresh one
                self.corrupt_frames += 1
                self._drop_sock()
                raise
            return reply

        def on_backoff(delay: float) -> None:
            self.retries += 1
            self._sleep(delay)

        try:
            reply = retry_with_backoff(
                attempt,
                retries=budget,
                base_s=self.retry_base_s,
                retry_on=(TransportError, WireFrameError),
                sleep=on_backoff,
            )
        except (TransportError, WireFrameError) as e:
            raise ReplicaGoneError(
                f"replica {self.host}:{self.port} unreachable after "
                f"{budget} attempt(s) on rpc {op!r}: {e}",
                host=self.host,
                port=self.port,
                rpc=op,
                attempts=budget,
            ) from e
        now = self._clock()
        self.last_ok = now
        self._lat_s.append(now - t_start)
        if self._h_rpc is not None:
            self._h_rpc.observe(now - t_start)
            self._obs.tracer.complete(
                f"transport.{op}", "rpc", self._obs_tid, t_start,
                now - t_start,
            )
        return reply

    # -- heartbeat + reporting -----------------------------------------

    def heartbeat_age(self, now: float) -> tp.Optional[float]:
        """Seconds since the last successful RPC on the injected clock
        (None before the first) — the wire heartbeat the router's
        staleness check consumes."""
        return None if self.last_ok is None else now - self.last_ok

    def _lat_pct(self, q: float) -> float:
        if not self._lat_s:
            return 0.0
        return float(np.percentile(np.asarray(self._lat_s), q))

    def stats(self) -> tp.Dict[str, tp.Any]:
        return {
            "rpc_count": self.rpc_count,
            "wire_bytes": self.wire_bytes,
            "connects": self.connects,
            "reconnects": self.reconnects,
            "retries": self.retries,
            "corrupt_frames": self.corrupt_frames,
            "deadline_expiries": self.deadline_expiries,
            "forced_drops": self.forced_drops,
            "rpc_p50_ms": round(self._lat_pct(50) * 1e3, 3),
            "rpc_p95_ms": round(self._lat_pct(95) * 1e3, 3),
        }


# -- router-side replica proxy ----------------------------------------------


class ProcReplica:
    """FleetRouter-facing proxy for one worker process. Implements the
    duck-typed engine surface the router drives (submit / step / idle /
    finished / counters), so `FleetRouter([ProcReplica(...), ...])` is the
    in-process fleet with the object boundary promoted to a process
    boundary — and nothing else changed.

    `step()` is one worker engine round plus a harvest: the worker's
    token events replay through the router's `on_token` relay and its
    durable finishes land in `self.finished`, both under high-water-mark
    idempotence so a retried RPC never duplicates either. RPC failures
    propagate as exceptions, which is exactly what the router's
    consecutive-failure health check counts — kill -9 detection IS the
    existing health machinery, fed by the wire."""

    is_proc = True

    def __init__(self, transport: ReplicaTransport):
        self.transport = transport
        hello = transport.call("hello")
        self.pid = int(hello["pid"])
        self.page_size = int(hello["page_size"])
        self.max_pages_per_slot = int(hello.get("max_pages_per_slot", 0))
        self.temperature = float(hello.get("temperature", 0.0))
        self.weights_version = str(hello.get("weights_version", "inline"))
        # truthy sentinel iff the worker engine runs its prefix trie — the
        # router validates `prefix_cache is None`, never dereferences it
        self.prefix_cache = True if hello.get("prefix_cache") else None
        self.on_token: tp.Optional[tp.Callable[[int, int, float], None]] = None
        self.finished: tp.Dict[int, tp.Any] = {}
        self._idle = True
        self._events_seen = 0
        # counters mirrored from the worker at every harvest (FleetRouter
        # stats/chaos summaries read these attribute names off engines)
        self.rounds = 0
        self.preemptions = 0
        self.shed = 0
        self.timeouts = 0
        self.cancelled = 0
        self.decode_kills = 0
        self.prefix_evictions = 0
        self.spill_readopted_pages = 0
        self._prefix_matched_tokens = 0
        self._prefix_matchable_tokens = 0
        self._hit_rate = 0.0
        self._spill_ledger: tp.Dict[str, int] = {}

    # -- engine surface the router drives ------------------------------

    def attach_spill(self, tier) -> None:
        """The worker owns its OWN tier (host RAM is per-process); the
        router-side shared tier only binds page_size here so replicas
        keep agreeing on the spill granule."""
        tier.set_page_size(self.page_size)
        self._router_spill = tier

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
    ) -> int:
        reply = self.transport.call(
            "submit",
            {
                "prompt": np.asarray(prompt, np.int32).reshape(-1),
                "max_new_tokens": int(max_new_tokens),
                "eos_id": None if eos_id is None else int(eos_id),
                "ttl_s": None if ttl_s is None else float(ttl_s),
            },
        )
        if reply.get("error") == "backpressure":
            from midgpt_tpu.sampling.serve import BackpressureError

            raise BackpressureError(
                str(reply.get("message", "replica shed the request")),
                needed_pages=reply.get("needed_pages"),
                backlog_pages=reply.get("backlog_pages"),
                budget_pages=reply.get("budget_pages"),
                retryable=bool(reply.get("retryable", False)),
            )
        self._raise_remote(reply, "submit")
        self._idle = False
        return int(reply["uid"])

    @property
    def idle(self) -> bool:
        return self._idle

    def step(self) -> None:
        reply = self.transport.call("step")
        self._raise_remote(reply, "step")
        self._apply_counters(reply)
        self._harvest()

    def run(self) -> None:
        """Drive the worker to idle — the ServeEngine.run() shape, for
        solo warm passes and reference drives outside a FleetRouter."""
        r = 0
        while not self.idle:
            self.step()
            r += 1
            if r >= 100_000:
                raise RuntimeError("proc replica run() did not converge")

    def _harvest(self) -> None:
        reply = self.transport.call(
            "harvest",
            {
                "events_from": self._events_seen,
                "known_uids": list(self.finished),
            },
        )
        self._raise_remote(reply, "harvest")
        for ruid, tok, t in reply.get("events", ()):
            self._events_seen += 1
            if self.on_token is not None:
                self.on_token(int(ruid), int(tok), float(t))
        if reply.get("finished"):
            from midgpt_tpu.sampling.serve import FinishedRequest

            for fin in reply["finished"]:
                uid = int(fin["uid"])
                self.finished[uid] = FinishedRequest(
                    uid,
                    np.asarray(fin["tokens"]),
                    [float(t) for t in fin.get("token_times", ())],
                    str(fin["status"]),
                )
        self._apply_counters(reply)

    def _apply_counters(self, reply: tp.Dict[str, tp.Any]) -> None:
        if "idle" in reply:
            self._idle = bool(reply["idle"])
        c = reply.get("counters")
        if not c:
            return
        self.rounds = int(c.get("rounds", self.rounds))
        self.preemptions = int(c.get("preemptions", self.preemptions))
        self.shed = int(c.get("shed", self.shed))
        self.timeouts = int(c.get("timeouts", self.timeouts))
        self.cancelled = int(c.get("cancelled", self.cancelled))
        self.decode_kills = int(c.get("decode_kills", self.decode_kills))
        self.prefix_evictions = int(
            c.get("prefix_evictions", self.prefix_evictions)
        )
        self.spill_readopted_pages = int(
            c.get("spill_readopted_pages", self.spill_readopted_pages)
        )
        self._prefix_matched_tokens = int(
            c.get("prefix_matched", self._prefix_matched_tokens)
        )
        self._prefix_matchable_tokens = int(
            c.get("prefix_matchable", self._prefix_matchable_tokens)
        )
        self._hit_rate = float(c.get("hit_rate", self._hit_rate))
        if "spill_ledger" in c:
            self._spill_ledger = dict(c["spill_ledger"])

    def prefix_stats(self) -> tp.Dict[str, float]:
        return {"hit_rate": self._hit_rate}

    def _raise_remote(self, reply: tp.Dict[str, tp.Any], op: str) -> None:
        if reply.get("error"):
            raise RuntimeError(
                f"worker pid {self.pid} rpc {op!r} failed remotely: "
                f"{reply.get('message', reply['error'])}"
            )

    # -- conservation across the boundary ------------------------------

    def assert_conserved(self, where: str = "") -> None:
        """Run the single-engine pool law AND the worker tier's ledger
        check IN the worker (the pool lives there), surfacing a violation
        as the same AssertionError the in-process path raises."""
        reply = self.transport.call("conserve", {"where": where})
        if not reply.get("ok"):
            raise AssertionError(
                f"worker pid {self.pid} conservation failed {where}: "
                f"{reply.get('error', 'unknown')}"
            )
        self._spill_ledger = dict(reply.get("spill_ledger", {}))

    def spill_ledger(self) -> tp.Dict[str, int]:
        return dict(self._spill_ledger)

    # -- spill-page transfer -------------------------------------------

    def export_spill(self) -> tp.List[SpillTransferItem]:
        """Pull every resident spilled page out of the worker's tier
        (counted `transferred` there); typically after a graceful drain,
        so surviving replicas can re-adopt the KV the drained worker
        paid to prefill."""
        reply = self.transport.call("spill_export")
        self._raise_remote(reply, "spill_export")
        return [
            SpillTransferItem(
                key=tuple(int(t) for t in d["key"]),
                blocks={k: np.asarray(v) for k, v in d["blocks"].items()},
                checksum=int(d["checksum"]),
                weights_version=str(d["weights_version"]),
            )
            for d in reply.get("items", ())
        ]

    def import_spill(self, items: tp.Sequence[SpillTransferItem]) -> int:
        reply = self.transport.call(
            "spill_import",
            {
                "items": [
                    {
                        "key": list(it.key),
                        "blocks": it.blocks,
                        "checksum": it.checksum,
                        "weights_version": it.weights_version,
                    }
                    for it in items
                ]
            },
        )
        self._raise_remote(reply, "spill_import")
        return int(reply.get("imported", 0))

    # -- lifecycle / chaos ---------------------------------------------

    def drain(self) -> tp.Dict[str, tp.Any]:
        """Graceful drain: the worker stops admitting (non-retryable
        backpressure on new submits), keeps serving step/harvest until
        its in-flight streams finish, and exits once idle after the
        router disconnects — the SIGTERM path, driven explicitly."""
        return self.transport.call("drain")

    def kill9(self) -> None:
        """`proc_kill9`: SIGKILL the worker process — no drain, no flush,
        no goodbye. Detection and failover must come entirely from the
        health checks riding the wire."""
        os.kill(self.pid, signal.SIGKILL)

    def drop_conn(self) -> None:
        self.transport.drop_conn()

    def arm_wire_corrupt(self) -> None:
        self.transport.arm_wire_corrupt()

    def arm_wire_stall(self) -> None:
        self.transport.arm_wire_stall()

    def _evict_shared_prefix_fault(self) -> None:
        reply = self.transport.call("evict_prefix")
        self._raise_remote(reply, "evict_prefix")

    def stats(self) -> tp.Dict[str, tp.Any]:
        reply = self.transport.call("stats")
        self._raise_remote(reply, "stats")
        out = dict(reply.get("stats", {}))
        out["spill"] = reply.get("spill", {})
        out["compile_counts"] = reply.get("compile_counts", {})
        out["transport"] = self.transport.stats()
        return out

    def compile_counts(self) -> tp.Dict[str, tp.Any]:
        reply = self.transport.call("stats")
        self._raise_remote(reply, "stats")
        return dict(reply.get("compile_counts", {}))

    def close(self, kill: bool = False) -> None:
        try:
            self.transport.call("bye", retries=1, deadline_s=5.0)
        except (ReplicaGoneError, OSError):
            pass
        self.transport.close()
        if kill:
            try:
                os.kill(self.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass

    def on_router_crash(self) -> None:
        """FleetRouter._crash hook: a replica the health checks declared
        dead gets its transport torn down and — belt and braces — its
        process SIGKILLed, so a half-alive worker cannot keep serving a
        router that already failed its streams over."""
        self.transport.close()
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass


# -- worker side ------------------------------------------------------------


def build_worker_engine(spec: tp.Dict[str, tp.Any]):
    """Spec -> (ServeEngine, SpillTier). Same-seed workers build
    bit-identical params (GPT.init under the spec's PRNG seed), which is
    what makes cross-process failover replays token-for-token exact."""
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.models.gpt import GPT, GPTConfig
    from midgpt_tpu.sampling.fleet import SpillTier
    from midgpt_tpu.sampling.serve import ServeEngine

    cfg = GPTConfig(**spec["model"])
    params = GPT.init(cfg, jax.random.PRNGKey(int(spec.get("seed", 0))))
    kw = dict(spec.get("engine", {}))
    dtype_name = kw.pop("cache_dtype", "float32")
    dtypes = {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "int8": jnp.int8,
    }
    eng = ServeEngine(
        cfg,
        params,
        temperature=0.0,
        prefix_cache=True,
        cache_dtype=dtypes[dtype_name],
        **kw,
    )
    tier = SpillTier()
    eng.attach_spill(tier)
    return eng, tier


def parent_jax_config() -> tp.Dict[str, tp.Any]:
    """The parent-process jax config knobs that change numerics, to mirror
    into worker specs: params init (threefry) and matmul precision must
    agree across the boundary or greedy parity is fiction (pinned by the
    cross-process parity gate in tests/test_fleet_proc.py)."""
    import jax

    out: tp.Dict[str, tp.Any] = {
        "jax_threefry_partitionable": bool(
            jax.config.jax_threefry_partitionable
        ),
    }
    prec = jax.config.jax_default_matmul_precision
    if prec is not None:
        out["jax_default_matmul_precision"] = prec
    return out


class _WorkerState:
    """Everything one worker process serves RPCs against."""

    def __init__(self, eng, tier):
        self.eng = eng
        self.tier = tier
        self.events: tp.List[tp.Tuple[int, int, float]] = []
        self.submit_replies: tp.Dict[int, tp.Dict[str, tp.Any]] = {}
        self.draining = False
        eng.on_token = self._on_token

    def _on_token(self, uid: int, tok: int, t: float) -> None:
        self.events.append((int(uid), int(tok), float(t)))

    def counters(self) -> tp.Dict[str, tp.Any]:
        eng = self.eng
        return {
            "rounds": eng.rounds,
            "preemptions": eng.preemptions,
            "shed": eng.shed,
            "timeouts": eng.timeouts,
            "cancelled": eng.cancelled,
            "decode_kills": eng.decode_kills,
            "prefix_evictions": eng.prefix_evictions,
            "spill_readopted_pages": eng.spill_readopted_pages,
            "prefix_matched": eng._prefix_matched_tokens,
            "prefix_matchable": eng._prefix_matchable_tokens,
            "hit_rate": eng.prefix_stats()["hit_rate"],
            "spill_ledger": self.tier.ledger(),
        }

    def handle(self, req: tp.Dict[str, tp.Any]) -> tp.Dict[str, tp.Any]:
        op = req.get("op", "")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            return {"error": "exception", "message": f"unknown op {op!r}"}
        try:
            return fn(req)
        except Exception as e:  # structured remote error, never a hang
            return {"error": "exception", "message": f"{type(e).__name__}: {e}"}

    # -- verbs ---------------------------------------------------------

    def _op_hello(self, req) -> tp.Dict[str, tp.Any]:
        return {
            "pid": os.getpid(),
            "page_size": self.eng.page_size,
            "max_pages_per_slot": self.eng.max_pages_per_slot,
            "temperature": self.eng.temperature,
            "prefix_cache": self.eng.prefix_cache is not None,
            "weights_version": getattr(self.eng, "weights_version", "inline"),
        }

    def _op_submit(self, req) -> tp.Dict[str, tp.Any]:
        from midgpt_tpu.sampling.serve import BackpressureError

        seq = req.get("seq")
        if seq in self.submit_replies:  # retried RPC: never double-admit
            return self.submit_replies[seq]
        if self.draining:
            reply: tp.Dict[str, tp.Any] = {
                "error": "backpressure",
                "message": "worker is draining (SIGTERM) — not admitting",
                "needed_pages": None,
                "backlog_pages": None,
                "budget_pages": None,
                "retryable": False,
            }
        else:
            try:
                uid = self.eng.submit(
                    np.asarray(req["prompt"], np.int32),
                    int(req["max_new_tokens"]),
                    req.get("eos_id"),
                    ttl_s=req.get("ttl_s"),
                )
                reply = {"uid": int(uid), "idle": self.eng.idle}
            except BackpressureError as e:
                reply = {
                    "error": "backpressure",
                    "message": str(e),
                    "needed_pages": e.needed_pages,
                    "backlog_pages": e.backlog_pages,
                    "budget_pages": e.budget_pages,
                    "retryable": e.retryable,
                }
        self.submit_replies[seq] = reply
        return reply

    def _op_step(self, req) -> tp.Dict[str, tp.Any]:
        if not self.eng.idle:
            self.eng.step()
        return {"idle": self.eng.idle, "counters": self.counters()}

    def _op_harvest(self, req) -> tp.Dict[str, tp.Any]:
        known = set(req.get("known_uids", ()))
        fins = []
        for uid, fr in self.eng.finished.items():
            if uid in known:
                continue
            fins.append(
                {
                    "uid": int(uid),
                    "tokens": np.asarray(fr.tokens),
                    "token_times": [float(t) for t in fr.token_times],
                    "status": fr.status,
                }
            )
        start = int(req.get("events_from", 0))
        return {
            "events": [list(e) for e in self.events[start:]],
            "finished": fins,
            "idle": self.eng.idle,
            "counters": self.counters(),
        }

    def _op_stats(self, req) -> tp.Dict[str, tp.Any]:
        return {
            "stats": _jsonable(self.eng.stats()),
            "spill": self.tier.stats(),
            "compile_counts": self.eng.compile_stats(),
            "counters": self.counters(),
        }

    def _op_conserve(self, req) -> tp.Dict[str, tp.Any]:
        from midgpt_tpu.sampling import ops

        where = str(req.get("where", ""))
        try:
            ops.assert_conserved(self.eng, where)
            self.tier.assert_ledger(where)
        except AssertionError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "spill_ledger": self.tier.ledger()}

    def _op_spill_export(self, req) -> tp.Dict[str, tp.Any]:
        items = self.tier.export_entries()
        return {
            "items": [
                {
                    "key": list(it.key),
                    "blocks": it.blocks,
                    "checksum": it.checksum,
                    "weights_version": it.weights_version,
                }
                for it in items
            ]
        }

    def _op_spill_import(self, req) -> tp.Dict[str, tp.Any]:
        items = [
            SpillTransferItem(
                key=tuple(int(t) for t in d["key"]),
                blocks={k: np.asarray(v) for k, v in d["blocks"].items()},
                checksum=int(d["checksum"]),
                weights_version=str(d["weights_version"]),
            )
            for d in req.get("items", ())
        ]
        return {"imported": self.tier.import_entries(items)}

    def _op_evict_prefix(self, req) -> tp.Dict[str, tp.Any]:
        self.eng._evict_shared_prefix_fault()
        return {"idle": self.eng.idle}

    def _op_drain(self, req) -> tp.Dict[str, tp.Any]:
        self.draining = True
        return {"draining": True, "idle": self.eng.idle}

    def _op_bye(self, req) -> tp.Dict[str, tp.Any]:
        return {"bye": True}


def _jsonable(obj: tp.Any) -> tp.Any:
    """Engine stats() dicts hold numpy scalars/arrays and arbitrary
    nesting; coerce to the frame codec's tree shape."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def run_worker(
    spec: tp.Dict[str, tp.Any],
    *,
    port: int = 0,
    announce: tp.Optional[tp.Callable[[int], None]] = None,
) -> None:
    """Worker process main loop (tools/fleet_worker.py calls this after
    pinning the jax platform). Binds, announces the port, then serves one
    router connection at a time. SIGTERM routes through the preempt flag
    (the handler only flips it — GC014); the loop notices between RPCs,
    stops admitting, and exits once drained and disconnected. Exits too
    when the parent process disappears — an orphaned worker must not
    squat on a CPU forever."""
    from midgpt_tpu.robustness import preempt

    eng, tier = build_worker_engine(spec)
    preempt.install_handlers()
    state = _WorkerState(eng, tier)
    parent = os.getppid()
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)
    srv.settimeout(0.25)
    if announce is not None:
        announce(srv.getsockname()[1])
    try:
        while True:
            if preempt.requested():
                state.draining = True
            if state.draining and eng.idle:
                return
            if os.getppid() != parent:
                return  # orphaned: the router process is gone
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            with conn:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(None)
                saw_bye = _serve_conn(conn, state, preempt)
            if saw_bye and state.draining and eng.idle:
                return
    finally:
        srv.close()


def _serve_conn(conn: socket.socket, state: _WorkerState, preempt) -> bool:
    """Serve frames on one connection until the peer disconnects or says
    bye. A corrupt inbound frame drops the connection (the router's
    transport retries on a fresh one). Returns True on explicit bye."""
    while True:
        if preempt.requested():
            state.draining = True
        try:
            req = read_frame(conn)
        except (ConnectionError, OSError):
            return False
        except WireFrameError:
            return False
        reply = state.handle(req)
        reply["seq"] = req.get("seq")
        try:
            write_frame(conn, reply)
        except (OSError, ConnectionError):
            return False
        if req.get("op") == "bye":
            return True


# -- spawning helpers (chaos/bench/tests) -----------------------------------


def worker_script_path() -> str:
    root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )
    return os.path.join(root, "tools", "fleet_worker.py")


def _popen_worker(spec: tp.Dict[str, tp.Any]):
    root = os.path.dirname(os.path.dirname(worker_script_path()))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, worker_script_path(), "--spec-json",
         json.dumps(spec)],
        stdout=subprocess.PIPE,
        env=env,
        text=True,
    )


def spawn_worker(spec: tp.Dict[str, tp.Any]) -> tp.Tuple[tp.Any, int]:
    """Popen a worker with `spec`, block until it announces its port on
    stdout ("PORT <n>"), return (Popen, port). Stderr passes through so
    worker tracebacks land in the driver's log, never on the one-line
    JSON stdout contract (the worker's stdout is a pipe)."""
    proc = _popen_worker(spec)
    return proc, _await_port(proc)


def spawn_workers(
    spec: tp.Dict[str, tp.Any], n: int
) -> tp.List[tp.Tuple[tp.Any, int]]:
    """Spawn `n` workers CONCURRENTLY: all Popens first, then collect the
    port announcements — the expensive part of worker startup (jax import
    + engine build) overlaps instead of serializing."""
    procs = [_popen_worker(spec) for _ in range(n)]
    return [(p, _await_port(p)) for p in procs]


def _await_port(proc) -> int:
    while True:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"fleet worker exited (rc={proc.poll()}) before announcing "
                "its port"
            )
        if line.startswith("PORT "):
            return int(line.split()[1])


def connect_replica(port: int, **transport_kw) -> ProcReplica:
    return ProcReplica(
        ReplicaTransport("127.0.0.1", port, **transport_kw)
    )
