"""Disaggregated prefill/decode serving: two ServeEngine roles bridged by a
page-handoff queue (docs/SERVING.md "Mesh-sharded serving").

Prefill and decode want opposite machines: prefill is compute-bound batch
work (long chunks, few slots), decode is HBM-bound latency work (many
slots, short chunks). A monolithic engine time-slices both on one set of
chips and each interferes with the other's SLO (FastUSP's multi-level
split, PAPERS.md). Disaggregation runs a prefill-heavy engine instance and
a decode-heavy one — on the two rows of a (data=2, tp) serving mesh
(parallel/serve_tp.role_submeshes), or unsharded side by side on the CPU
test mesh — and moves each request between them exactly once, at the
prefill/decode boundary.

The handoff rides machinery previous PRs already built, which is why it is
small:

  * chunked prefill makes the prefill role preemptible (a request never
    holds the engine longer than one chunk), and `max_new_tokens=1` makes
    "prefill + first token" a complete ServeEngine request — the prefill
    role needs no new scheduler states;
  * the prefix-cache trie already expresses "these pages hold tokens
    0..n": at prefill finish the request's complete prompt pages sit in
    the trie, `match` hands them (referenced) to the handoff, and on the
    decode side `release(..., n_shared=0)` donates the adopted copies back
    into the DECODE trie, so the decode engine's ordinary admission path
    re-matches them and skips prompt re-prefill — the decode role needs no
    new admission states either;
  * page content moves as a host-gathered block and lands through one
    jitted scatter (`_adopt_pages`, donated pool, oob-padded page indices
    like every engine scatter), so the adopt is one compiled program per
    (page-count bucket, dtype) — the same bucketing discipline that keeps
    the serving jits' compile set mix-independent.

Greedy parity: the decode role's prompt is `prompt + [first_token]`; its
prefill recomputes exactly the positions the handoff did not ship and its
first host-side argmax reproduces the monolithic engine's second token
(prefill-logits/decode-step parity is the engine's founding invariant,
tests/test_sampling.py), so a disaggregated greedy stream is token-for-
token the monolithic stream (pinned by tests/test_tp_serving.py). The
queue is lossy-safe in both directions: a handoff that cannot get decode
pool pages degrades to plain re-prefill on the decode side (correct, just
slower), and a timed-out request propagates its timeout status.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.models.gpt import GPTConfig, GPTParams, PagedKVCache
from midgpt_tpu.obs import DISABLED_SNAPSHOT, Observability
from midgpt_tpu.robustness.backoff import backoff_delays
from midgpt_tpu.obs.trace import NULL_TRACER
from midgpt_tpu.sampling.serve import (
    BackpressureError,
    FinishedRequest,
    ServeEngine,
)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def _adopt_pages(mesh, cache, dst, blocks):
    """Scatter handed-off page blocks into the decode pool at physical
    pages `dst` ((n,) int32, padded to a power-of-two bucket with
    `num_pages` so pad writes drop under XLA oob-scatter semantics — the
    same funnel shape as the engine's K/V column writes). `blocks` carries
    'k'/'v' (L, H, n, ps, C) and, int8 pools, 'k_scale'/'v_scale'
    (L, n, H, ps); its key set and the dst bucket are the compile keys.
    The pool is donated: an adopt is an in-place page write, not a pool
    copy. `mesh` is static like the serving jits' trailing mesh arg and
    pins the sharded pool's out-sharding (serve._maybe_constrain)."""
    k = cache.k.at[:, :, dst].set(blocks["k"].astype(cache.k.dtype))
    v = cache.v.at[:, :, dst].set(blocks["v"].astype(cache.v.dtype))
    ks, vs = cache.k_scale, cache.v_scale
    if "k_scale" in blocks:
        ks = ks.at[:, dst].set(blocks["k_scale"])
        vs = vs.at[:, dst].set(blocks["v_scale"])
    new = PagedKVCache(k=k, v=v, k_scale=ks, v_scale=vs)
    if mesh is not None:
        from midgpt_tpu.parallel.serve_tp import constrain_cache

        new = constrain_cache(new, mesh)
    return new


@dataclasses.dataclass
class HandoffItem:
    """One request crossing the prefill->decode boundary: identity and
    budget, the prefill role's first token (with its wall-clock time, so
    TTFT survives the handoff), and the host-gathered content of its
    complete prompt pages."""

    uid: int  # DisaggServe uid
    prompt: np.ndarray  # (T0,) int32
    first_token: int
    first_time: float
    max_new_tokens: int  # ORIGINAL budget (decode role gets it minus 1)
    eos_id: tp.Optional[int]
    deadline: tp.Optional[float]
    blocks: tp.Dict[str, np.ndarray]  # page content, keys as _adopt_pages
    n_pages: int


class HandoffRetryExhausted(RuntimeError):
    """A queued page-transport item was refused by its destination more
    times than the queue's bounded retry budget allows. Structured like
    BackpressureError: `uid` identifies the stream, `attempts` the spent
    budget, so a router can convert it into a terminal shed instead of
    retrying forever (graceful degradation, never a silent drop)."""

    def __init__(self, message: str, *, uid: int, attempts: int):
        super().__init__(message)
        self.uid = uid
        self.attempts = attempts


class PageHandoffQueue:
    """FIFO of page-transport items with transfer accounting and a bounded
    retry-with-backoff schedule — the general page-transport primitive:
    disagg's prefill->decode handoff and the fleet router's failover
    resubmission (sampling/fleet.py) both ride it. Host-side and
    process-local here (all roles live in one process on the test mesh);
    the counters are the interface a cross-host transport would have to
    honor — bytes_copied is the KV traffic the transport actually moves,
    the number to weigh against the prompt re-prefill FLOPs it saves.

    Items are duck-typed: anything with `uid`, `n_pages`, and `blocks`
    queues (HandoffItem, fleet.FailoverItem). Retry state lives ON the
    item (`_handoff_attempts`, `_not_before`), so requeue backs an item
    off on the SAME exponential schedule every transient-failure path in
    the repo uses (robustness/backoff.py: base_s * 2**attempt), and a
    destination that keeps refusing raises the structured
    HandoffRetryExhausted instead of spinning — ad-hoc unbounded
    front-requeue loops are gone."""

    def __init__(
        self,
        *,
        retries: int = 32,
        base_s: float = 0.0,
        clock: tp.Callable[[], float] = time.perf_counter,
    ):
        if retries < 1:
            raise ValueError(f"retries must be >= 1, got {retries}")
        self._q: tp.Deque[tp.Any] = collections.deque()
        self.retries = retries
        self.base_s = base_s
        self._clock = clock
        self.enqueued = 0
        self.dequeued = 0
        self.pages_copied = 0
        self.bytes_copied = 0
        self.retry_exhausted = 0

    def __len__(self) -> int:
        return len(self._q)

    def push(self, item) -> None:
        self.enqueued += 1
        self.pages_copied += item.n_pages
        self.bytes_copied += sum(b.nbytes for b in item.blocks.values())
        item._handoff_attempts = 0
        item._not_before = 0.0
        self._q.append(item)

    def pop(self, now: tp.Optional[float] = None):
        """The next ready item, or None when the queue is empty or its head
        is still inside a backoff window (FIFO order is preserved — a
        backed-off head shields the items behind it, which would only be
        refused by the same full destination)."""
        if not self._q:
            return None
        item = self._q[0]
        if getattr(item, "_not_before", 0.0) > (
            self._clock() if now is None else now
        ):
            return None
        self.dequeued += 1
        return self._q.popleft()

    def requeue(self, item) -> None:
        """Return a refused item to the FRONT (it keeps its place) with the
        next exponential delay stamped on it. Raises HandoffRetryExhausted
        once the item has been refused `retries` times — the caller owns
        the terminal disposition (disagg: fallback re-prefill happened
        earlier; fleet: terminal shed)."""
        self.dequeued -= 1
        attempts = getattr(item, "_handoff_attempts", 0) + 1
        item._handoff_attempts = attempts
        if attempts >= self.retries:
            self.retry_exhausted += 1
            raise HandoffRetryExhausted(
                f"handoff uid={item.uid} refused {attempts} times "
                f"(budget {self.retries})",
                uid=item.uid,
                attempts=attempts,
            )
        # attempts-th delay of the shared schedule: base_s * 2**(attempts-1)
        delay = next(
            itertools.islice(
                backoff_delays(self.retries, self.base_s), attempts - 1, None
            ),
            0.0,
        )
        item._not_before = self._clock() + delay
        self._q.appendleft(item)

    def stats(self) -> tp.Dict[str, int]:
        return {
            "depth": len(self._q),
            "enqueued": self.enqueued,
            "dequeued": self.dequeued,
            "pages_copied": self.pages_copied,
            "bytes_copied": self.bytes_copied,
            "retry_exhausted": self.retry_exhausted,
        }


class DisaggServe:
    """A prefill-role ServeEngine and a decode-role ServeEngine joined by a
    PageHandoffQueue (module docstring).

    `mesh`, when given, must carry data >= 2: role r lives on
    `role_submeshes(mesh)[r]` — row 0 prefill, row 1 decode — so the two
    roles occupy disjoint devices and each is tp-sharded across its row.
    With mesh=None both roles run unsharded (the CPU parity
    configuration). `engine_kw` is shared by both roles;
    `prefill_kw`/`decode_kw` override per role (the point of
    disaggregation: e.g. a long prefill_chunk on the prefill role, more
    slots on the decode role). Greedy only (temperature=0): the handoff
    carries no RNG stream, and parity with a monolithic engine is the
    contract."""

    def __init__(
        self,
        config: GPTConfig,
        params: GPTParams,
        *,
        mesh=None,
        prefill_kw: tp.Optional[tp.Dict[str, tp.Any]] = None,
        decode_kw: tp.Optional[tp.Dict[str, tp.Any]] = None,
        clock: tp.Callable[[], float] = time.perf_counter,
        obs: tp.Optional[Observability] = None,
        **engine_kw,
    ):
        if engine_kw.get("temperature", 0.0) != 0.0:
            raise ValueError("DisaggServe is greedy-only (module docstring)")
        if engine_kw.pop("prefix_cache", True) is not True:
            raise ValueError(
                "DisaggServe requires the prefix cache: the trie IS the "
                "handoff's page-ownership ledger"
            )
        pf_mesh = dec_mesh = None
        if mesh is not None:
            from midgpt_tpu.parallel.serve_tp import role_submeshes

            roles = role_submeshes(mesh)
            if len(roles) < 2:
                raise ValueError(
                    "disaggregation needs a mesh with data >= 2 (one row "
                    "per role); got data="
                    f"{int(mesh.shape['data'])}"
                )
            pf_mesh, dec_mesh = roles[0], roles[1]
        self._clock = clock
        # One shared Observability, two tid lanes: both roles' round spans
        # land in the same flight recorder under "prefill"/"decode" thread
        # names, with the handoff spans on a third "disagg" lane — the
        # Perfetto view IS the pipeline diagram.
        self.obs = obs
        self._trace = obs.tracer if obs is not None else NULL_TRACER
        self.prefill = ServeEngine(
            config, params, prefix_cache=True, clock=clock, mesh=pf_mesh,
            obs=obs, obs_tid="prefill",
            **{**engine_kw, **(prefill_kw or {})},
        )
        self.decode = ServeEngine(
            config, params, prefix_cache=True, clock=clock, mesh=dec_mesh,
            obs=obs, obs_tid="decode",
            **{**engine_kw, **(decode_kw or {})},
        )
        # Bounded transport: a decode role that refuses the same item 512
        # ticks in a row is wedged, and the structured exhaustion below
        # converts the stream to a terminal shed instead of spinning the
        # pipeline forever (base_s=0: the pipeline tick IS the pacing).
        self.queue = PageHandoffQueue(retries=512, base_s=0.0, clock=clock)
        self.finished: tp.Dict[int, FinishedRequest] = {}
        # disagg uid -> (prompt, max_new, eos, deadline), keyed twice over
        # the role engines' own uid spaces while a request is inside one.
        self._pf_pending: tp.Dict[int, tp.Tuple[int, np.ndarray, int,
                                                tp.Optional[int],
                                                tp.Optional[float]]] = {}
        self._dec_pending: tp.Dict[int, HandoffItem] = {}
        self._uid = 0
        # Handoffs that could not get decode-pool pages and fell back to
        # plain re-prefill on the decode role (correct, just slower).
        self.fallback_reprefills = 0
        # prefill<->decode pool-capacity moves (ops.py re-role decisions)
        self.re_roles = 0

    # -- public surface ------------------------------------------------

    def submit(
        self,
        prompt: tp.Sequence[int],
        max_new_tokens: int,
        eos_id: tp.Optional[int] = None,
        ttl_s: tp.Optional[float] = None,
    ) -> int:
        """Queue a request on the PREFILL role (budget 1: prefill + first
        token is a complete request there). Backpressure propagates —
        shedding happens at the front door, not mid-pipeline."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        deadline = None if ttl_s is None else self._clock() + ttl_s
        pf_uid = self.prefill.submit(prompt, 1, eos_id=None, ttl_s=ttl_s)
        uid = self._uid
        self._uid += 1
        self._pf_pending[pf_uid] = (uid, prompt, max_new_tokens, eos_id, deadline)
        return uid

    @property
    def idle(self) -> bool:
        return (
            not self._pf_pending
            and not self._dec_pending
            and not len(self.queue)
            and self.prefill.idle
            and self.decode.idle
        )

    def run(self) -> tp.Dict[int, FinishedRequest]:
        while not self.idle:
            self.step()
        return self.finished

    def step(self) -> None:
        """One pipeline tick: advance prefill, drain its finishes into the
        handoff queue, adopt queued handoffs into the decode role, advance
        decode, drain its finishes. The two engine step()s are independent
        device programs on disjoint (sub)meshes — a real deployment
        overlaps them; the host loop here interleaves them, which is
        enough for every invariant the tests pin."""
        if not self.prefill.idle:
            self.prefill.step()
        self._drain_prefill()
        self._drain_queue()
        if not self.decode.idle:
            self.decode.step()
        self._drain_decode()

    def stats(self) -> tp.Dict[str, tp.Any]:
        return {
            "queue": self.queue.stats(),
            "fallback_reprefills": self.fallback_reprefills,
            "re_roles": self.re_roles,
            "prefill": self.prefill.stats(),
            "decode": self.decode.stats(),
            # shared across both roles (one Observability, two tid lanes)
            "obs": (
                DISABLED_SNAPSHOT if self.obs is None else self.obs.snapshot()
            ),
        }

    def rebalance(self, n_pages: int, *, src: str = "prefill",
                  dst: str = "decode") -> tp.Dict[str, tp.Any]:
        """Move `n_pages` of pool capacity from the `src` role to the
        `dst` role via two live resizes (sampling/ops.py resize_pool) —
        the re-role actuator of the model-ops policy loop. Shrink-first:
        if the src role cannot give the pages up without dropping its
        resident working set, the retryable PoolResizeError propagates
        BEFORE anything changed; the dst grow that follows cannot fail.
        Each role keeps its own pool and devices — re-roling moves page
        BUDGET, not pages in flight (those still cross on the handoff
        queue's adoption scatter)."""
        roles = {"prefill": self.prefill, "decode": self.decode}
        if src not in roles or dst not in roles or src == dst:
            raise ValueError(f"rebalance src/dst must be distinct roles "
                             f"from {sorted(roles)}, got {src!r}->{dst!r}")
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        shrink = roles[src].resize(roles[src].allocator.num_pages - n_pages)
        grow = roles[dst].resize(roles[dst].allocator.num_pages + n_pages)
        self.re_roles += 1
        self._trace.instant(
            "ops.re_role", "ops", "disagg",
            args={"src": src, "dst": dst, "pages": n_pages},
        )
        return {"src": src, "dst": dst, "pages": n_pages,
                "src_resize": shrink, "dst_resize": grow}

    # -- internals -----------------------------------------------------

    def _finish(self, fr: FinishedRequest) -> None:
        self.finished[fr.uid] = fr

    def _drain_prefill(self) -> None:
        done = [u for u in self._pf_pending if u in self.prefill.finished]
        for pf_uid in done:
            uid, prompt, max_new, eos_id, deadline = self._pf_pending.pop(pf_uid)
            fr = self.prefill.finished[pf_uid]
            if fr.status != "ok":
                self._finish(
                    FinishedRequest(uid, fr.tokens, fr.token_times, fr.status)
                )
                continue
            first = int(fr.tokens[len(prompt)])
            first_time = fr.token_times[0]
            if max_new == 1 or (eos_id is not None and first == eos_id):
                self._finish(
                    FinishedRequest(
                        uid,
                        np.append(prompt, np.int32(first)),
                        [first_time],
                        "ok",
                    )
                )
                continue
            item = self._gather_pages(
                uid, prompt, first, first_time, max_new, eos_id, deadline
            )
            self.queue.push(item)
            self._trace.instant(
                "handoff.push", "disagg", "disagg",
                args={
                    "uid": uid,
                    "n_pages": item.n_pages,
                    "bytes": sum(b.nbytes for b in item.blocks.values()),
                },
            )

    def _gather_pages(
        self, uid, prompt, first, first_time, max_new, eos_id, deadline
    ) -> HandoffItem:
        """Reference the request's complete prompt pages out of the
        prefill trie, land their content on the host, and drop the refs
        (the entries stay in the PREFILL trie for future shared-template
        hits — the handoff copies, it does not steal)."""
        with self._trace.span("handoff.gather", "disagg", "disagg"):
            pc = self.prefill.prefix_cache
            mr = pc.match(prompt, max_tokens=len(prompt) - 1)
            n = len(mr.pages)
            blocks: tp.Dict[str, np.ndarray] = {}
            if n:
                idx = jnp.asarray(mr.pages, jnp.int32)
                cache = self.prefill.cache
                blocks["k"] = np.asarray(jnp.take(cache.k, idx, axis=2))
                blocks["v"] = np.asarray(jnp.take(cache.v, idx, axis=2))
                if cache.k_scale is not None:
                    blocks["k_scale"] = np.asarray(
                        jnp.take(cache.k_scale, idx, axis=1)
                    )
                    blocks["v_scale"] = np.asarray(
                        jnp.take(cache.v_scale, idx, axis=1)
                    )
                ps = self.prefill.page_size
                self.prefill.allocator.free(
                    pc.release(prompt[: n * ps], mr.pages, n)
                )
        return HandoffItem(
            uid=uid, prompt=prompt, first_token=first, first_time=first_time,
            max_new_tokens=max_new, eos_id=eos_id, deadline=deadline,
            blocks=blocks, n_pages=n,
        )

    def _drain_queue(self) -> None:
        while True:
            item = self.queue.pop()
            if item is None:
                break
            if item.deadline is not None:
                remaining = item.deadline - self._clock()
                if remaining <= 0:
                    self._finish(
                        FinishedRequest(
                            item.uid,
                            np.append(item.prompt, np.int32(item.first_token)),
                            [item.first_time],
                            "timeout",
                        )
                    )
                    continue
            else:
                remaining = None
            dec_prompt = np.append(item.prompt, np.int32(item.first_token))
            try:
                dec_uid = self.decode.submit(
                    dec_prompt, item.max_new_tokens - 1, item.eos_id,
                    ttl_s=remaining,
                )
            except BackpressureError:
                try:
                    self.queue.requeue(item)
                except HandoffRetryExhausted:
                    # wedged decode role: terminal shed, never a spin
                    self._finish(
                        FinishedRequest(
                            item.uid,
                            np.append(item.prompt, np.int32(item.first_token)),
                            [item.first_time],
                            "shed",
                        )
                    )
                break  # decode role is full; retry next tick
            with self._trace.span("handoff.adopt", "disagg", "disagg"):
                self._adopt(item)
            self._dec_pending[dec_uid] = item

    def _adopt(self, item: HandoffItem) -> None:
        """Allocate decode-pool pages, scatter the handed-off content into
        them, and donate them to the DECODE trie at refcount 0 — from here
        the decode engine's ordinary admission match finds them and skips
        the prompt prefill. Falls back to nothing (plain re-prefill) when
        the decode pool cannot free enough pages."""
        n = item.n_pages
        if n == 0:
            return
        eng = self.decode
        dst = eng.allocator.alloc(n)
        if dst is None:
            # Reclaim unreferenced trie pages, the engine's own pressure
            # valve, then retry once.
            eng.allocator.free(
                eng.prefix_cache.evict(n - eng.allocator.free_count)
            )
            dst = eng.allocator.alloc(n)
        if dst is None:
            self.fallback_reprefills += 1
            self._trace.instant(
                "handoff.fallback_reprefill", "disagg", "disagg",
                args={"uid": item.uid},
            )
            return
        bucket = 1
        while bucket < n:
            bucket *= 2
        pad = bucket - n
        dst_j = jnp.asarray(
            np.asarray(dst + [eng.cache.num_pages] * pad, np.int32)
        )
        def _pad(blk: np.ndarray, axis: int):
            if pad == 0:
                return jnp.asarray(blk)
            shape = list(blk.shape)
            shape[axis] = pad
            return jnp.asarray(
                np.concatenate([blk, np.zeros(shape, blk.dtype)], axis=axis)
            )

        blocks = {
            key: _pad(blk, 1 if key.endswith("scale") else 2)
            for key, blk in item.blocks.items()
        }
        eng.cache = _adopt_pages(eng.mesh, eng.cache, dst_j, blocks)
        ps = eng.page_size
        eng.allocator.free(
            eng.prefix_cache.release(item.prompt[: n * ps], dst, 0)
        )

    def _drain_decode(self) -> None:
        done = [u for u in self._dec_pending if u in self.decode.finished]
        for dec_uid in done:
            item = self._dec_pending.pop(dec_uid)
            fr = self.decode.finished[dec_uid]
            # fr.tokens is (prompt + first) + the decode role's generation —
            # exactly the monolithic stream.
            self._finish(
                FinishedRequest(
                    item.uid,
                    fr.tokens,
                    [item.first_time] + list(fr.token_times),
                    fr.status,
                )
            )
