from midgpt_tpu.sampling.engine import generate

__all__ = ["generate"]
