from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.prefix_cache import MatchResult, PrefixCache
from midgpt_tpu.sampling.scheduler import FCFSScheduler, Scheduler, SLOScheduler
from midgpt_tpu.sampling.serve import BackpressureError, ServeEngine
from midgpt_tpu.sampling.server import AsyncServeServer, ServerDraining

__all__ = [
    "generate",
    "ServeEngine",
    "BackpressureError",
    "AsyncServeServer",
    "ServerDraining",
    "Scheduler",
    "FCFSScheduler",
    "SLOScheduler",
    "PrefixCache",
    "MatchResult",
]
