from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.serve import ServeEngine

__all__ = ["generate", "ServeEngine"]
