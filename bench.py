"""Single-chip training benchmark. Prints ONE JSON line for the driver.

Measures the full compiled training step (fwd + bwd + optimizer, bf16
compute / fp32 params, remat) on the GPT-2-small 124M `openwebtext` shape and
reports MFU. Baseline for `vs_baseline` is the reference's published 47.8%
MFU on its headline 1.5B run (reference README; BASELINE.md) — MFU is the
hardware-normalized metric that is comparable across chip counts.

Usage: python bench.py [--steps N] [--batch B] [--attn naive|flash|blockwise]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax

if os.environ.get("MIDGPT_PLATFORM"):
    # Same opt-in as launch.py: the axon TPU plugin ignores JAX_PLATFORMS,
    # so off-TPU runs (tests/test_bench_contract.py validates the JSON
    # contract on the CPU mesh) must select the platform via the config API
    # before backend init.
    jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])
    if os.environ.get("MIDGPT_CPU_DEVICES"):
        from midgpt_tpu.utils.compat import set_cpu_device_count

        set_cpu_device_count(int(os.environ["MIDGPT_CPU_DEVICES"]))

import numpy as np

BASELINE_MFU = 0.478  # reference 1.5B on v3-128 (BASELINE.md)

# The dead-tunnel probe runs in a CHILD PROCESS. r19's in-process watchdog
# ran the trivial dispatch on a worker thread with a timed join — but a
# backend init that hangs in native code HOLDING THE GIL (verified r20: the
# axon plugin's first contact wedges inside C++ before any Python bytecode
# can run again) starves the watchdog thread itself, so the deadline never
# fired and the bench still hung to the driver's timeout. A subprocess is
# immune: the parent's timed wait() needs nothing from the child's
# interpreter, and SIGKILL ends a native-code hang that no in-process
# mechanism can. The child honors the same MIDGPT_PLATFORM /
# MIDGPT_CPU_DEVICES selection and the MIDGPT_FAULTS `hang_step` hook the
# in-process probe did (the contract test models the dead tunnel with it).
_PROBE_CHILD_SRC = """
import os
import jax
if os.environ.get("MIDGPT_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])
    if os.environ.get("MIDGPT_CPU_DEVICES"):
        from midgpt_tpu.utils.compat import set_cpu_device_count
        set_cpu_device_count(int(os.environ["MIDGPT_CPU_DEVICES"]))
from midgpt_tpu.robustness import faults
if os.environ.get("MIDGPT_FAULTS"):
    faults.activate_plan(os.environ["MIDGPT_FAULTS"])
if faults.should_fire("hang_step"):
    import threading
    threading.Event().wait()  # the dead tunnel, modeled: never returns
import jax.numpy as jnp
# Touch the backend end to end: placement + compute + host sync.
assert float(jnp.zeros((8, 128)).sum()) == 0.0
"""


def _backend_reachable(deadline_s: float) -> bool:
    """Fork a child, dispatch a trivial op there, bounded join.

    True only when the child lands the dispatch inside the budget; a
    timeout (child killed) or a crashed child both report unreachable —
    either way the real bench would not have produced a number."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CHILD_SRC],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=deadline_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--batch", type=int, default=None,
                        help="per-microbatch per-device batch size (default: "
                        "the shape config's measured optimum — 16 for 124m, "
                        "12 for wide)")
    parser.add_argument("--accum", type=int, default=1,
                        help="g_accum_iters: microbatches per step (the "
                        "production 124M recipe uses 16 — reference "
                        "configs/openwebtext.py:18)")
    parser.add_argument("--attn", type=str, default=None, choices=["naive", "flash", "blockwise"])
    parser.add_argument("--remat", type=str, default="off",
                        choices=["off", "none", "dots", "dots_attn", "flash"],
                        help="off = no per-block checkpoint; else checkpoint policy")
    parser.add_argument("--attn-block", type=int, default=1024, help="flash/blockwise tile size")
    parser.add_argument("--unroll", type=int, default=12, help="layer-scan unroll factor")
    parser.add_argument("--profile", type=str, default=None, help="capture a trace to this dir")
    parser.add_argument("--loss-chunk", type=int, default=None, help="fused CE chunk tokens")
    parser.add_argument("--seq", type=int, default=None, help="override sequence length (long-context bench)")
    parser.add_argument(
        "--shape", type=str, default="124m", choices=["124m", "wide"],
        help="model shape: '124m' = GPT-2-small (C=64); 'wide' = C=128 "
        "wide-head slice (n_embd=2048, n_head=16, reduced depth) — doubles "
        "attention MXU utilization to probe the >=55%% MFU target",
    )
    parser.add_argument("--layers", type=int, default=None, help="override n_layer")
    parser.add_argument("--vocab", type=int, default=None,
                        help="override vocab_size (contract tests shrink the "
                        "embedding to run the full bench path off-TPU; the "
                        "default keeps the shape config's padded vocab)")
    parser.add_argument("--rope", type=str, default=None,
                        choices=["interleaved", "split"],
                        help="RoPE lowering override (default: the shape "
                        "config's setting)")
    parser.add_argument("--attn-layout", type=str, default=None,
                        choices=["seq", "head"],
                        help="attention activation layout override")
    parser.add_argument("--probe-deadline", type=float, default=60.0,
                        help="backend reachability budget in seconds (0 "
                        "disables): a trivial dispatch must land within it, "
                        "else one {'error': 'backend_unreachable'} JSON line "
                        "comes out instead of a silent hang")
    args = parser.parse_args()

    if args.probe_deadline > 0 and not _backend_reachable(args.probe_deadline):
        print(json.dumps({
            "error": "backend_unreachable",
            "metric": "train_mfu",
            "value": None,
            "detail": {
                "probe_deadline_s": args.probe_deadline,
                "platform_requested": os.environ.get(
                    "MIDGPT_PLATFORM", "(default: tpu tunnel)"
                ),
                "hint": "the device backend did not answer a trivial "
                "dispatch inside the probe budget — dead axon tunnel or "
                "wedged runtime; restart the tunnel and re-run",
            },
        }))
        return 1

    from midgpt_tpu.config import MeshConfig

    # One source of truth per shape: '124m' is the openwebtext recipe
    # (reference configs/openwebtext.py), 'wide' is the shipped
    # configs/wide610m.py — the same file launch.py trains, so the bench
    # number is reproducible through the normal CLI too.
    if args.shape == "wide":
        from midgpt_tpu.configs.wide610m import config as base_config
    else:
        from midgpt_tpu.configs.openwebtext import config as base_config
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.metrics import device_peak_flops, flops_per_token
    from midgpt_tpu.training.train import init_state, make_train_step

    n_dev = jax.device_count()
    model_cfg = base_config.model_config
    # Pallas flash kernel on TPU; naive elsewhere (interpret mode is too slow
    # for a benchmark).
    attn = args.attn or ("flash" if jax.default_backend() == "tpu" else "naive")
    import dataclasses

    shape_overrides = {"n_layer": args.layers} if args.layers else {}
    # wide610m is a single-chip config, so its batch_size IS the per-device
    # optimum; the 124m shape keeps the bench's historical default (the
    # openwebtext preset's global batch is a multi-chip recipe value).
    per_dev_batch = args.batch or (
        base_config.batch_size if args.shape == "wide" else 16
    )
    model_cfg = dataclasses.replace(
        model_cfg,
        **shape_overrides,
        **({"vocab_size": args.vocab} if args.vocab else {}),
        **({"block_size": args.seq} if args.seq else {}),
        attn_impl=attn,
        remat=args.remat != "off",
        remat_policy=args.remat if args.remat != "off" else "none",
        scan_unroll=args.unroll,
        **({"attn_block_size": args.attn_block} if args.attn_block else {}),
        **({"rope_style": args.rope} if args.rope else {}),
        **({"attn_layout": args.attn_layout} if args.attn_layout else {}),
    )
    config = base_config.replace(
        **({"loss_chunk_tokens": args.loss_chunk} if args.loss_chunk else {}),
        batch_size=per_dev_batch * n_dev,
        g_accum_iters=args.accum,
        shard_model=n_dev > 1,
        mesh=MeshConfig(data=1, fsdp=n_dev, sp=1),
        model_config=model_cfg,
        debug=True,
    )

    mesh = make_mesh(config.mesh)
    params, opt_state, specs, optimizer = init_state(config, mesh)
    step, *_ = make_train_step(config, optimizer, mesh, specs)

    T = model_cfg.block_size
    B = config.batch_size
    rng = np.random.default_rng(0)
    x = rng.integers(0, model_cfg.vocab_size, (args.accum, B, T), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec())
    yg = make_global_batch(y, mesh, batch_spec())

    key = jax.random.PRNGKey(0)
    loss = None
    for i in range(args.warmup):
        key, k = jax.random.split(key)
        params, opt_state, loss = step(params, opt_state, xg, yg, k)
    float(loss)  # device_get: hard host sync (block_until_ready is not
    # sufficient under the axon remote-TPU tunnel)

    if args.profile:
        jax.profiler.start_trace(args.profile)
    t0 = time.perf_counter()
    for i in range(args.steps):
        key, k = jax.random.split(key)
        params, opt_state, loss = step(params, opt_state, xg, yg, k)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    if args.profile:
        jax.profiler.stop_trace()

    tokens_per_sec = args.steps * args.accum * B * T / dt
    fpt = flops_per_token(model_cfg)
    peak = device_peak_flops()
    achieved = tokens_per_sec * fpt / n_dev
    mfu = achieved / peak if peak else None

    result = {
        "metric": f"train_mfu_{args.shape}_{attn}_{jax.devices()[0].platform}"
        + (f"_accum{args.accum}" if args.accum > 1 else ""),
        "value": round(mfu * 100, 2) if mfu is not None else round(tokens_per_sec, 0),
        "unit": "% MFU" if mfu is not None else "tokens/sec",
        "vs_baseline": round(mfu / BASELINE_MFU, 3) if mfu is not None else None,
        "detail": {
            "tokens_per_sec": round(tokens_per_sec, 0),
            "step_ms": round(1000 * dt / args.steps, 2),
            "batch": B,
            "g_accum_iters": args.accum,
            "seq_len": T,
            "n_devices": n_dev,
            "device": getattr(jax.devices()[0], "device_kind", "?"),
            "final_loss": final_loss,
            # vs_baseline compares this 124M single-chip MFU against the
            # reference's published 47.8% MFU from a 1.5B v3-128 run — a
            # cross-scale, cross-topology ratio (MFU is hardware-normalized
            # but model shape still matters), not an apples-to-apples speedup.
            "baseline": "reference 1.5B openwebtext_xl on v3-128, 47.8% MFU (cross-scale)",
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
