"""Training launcher CLI (the reference's primary entry point, launch.py:15-72).

    python launch.py --config=shakespeare_char [--rundir=...] [--debug] \
        [--multihost] [--set key=value ...]

Behavior parity: dynamic config import by name, timestamped rundir default,
config.json persisted to the rundir (local or gs://) for sample-time
reconstruction, wandb-id persistence for resume (when wandb is installed),
cross-host barrier after proc-0 setup, then the supervised train loop
(robustness/supervisor.py: restart-on-divergence + SIGTERM/SIGINT emergency
checkpointing). `--set` dotted overrides (e.g. --set max_steps=100 --set
model_config.n_layer=4) are an addition the reference lacks.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from datetime import datetime


def apply_overrides(config, pairs):
    """Apply all `--set dotted.key=value` overrides in ONE rebuild.

    Each touched dataclass is replaced exactly once with every override it
    receives, so cross-field validation (__post_init__) sees the final
    state — `--set model_config.attn_impl=flash --set
    model_config.dropout=0.0` works in either order."""
    tree: dict = {}
    for dotted_key, raw_value in pairs:
        parts = dotted_key.split(".")
        target = config
        for p in parts[:-1]:
            target = getattr(target, p)
        current = getattr(target, parts[-1])
        # Optional fields default to None, so the current value's type can't
        # drive parsing — consult the declared annotation (a string under
        # `from __future__ import annotations`) so `--set loss_remat_chunks=0`
        # parses as bool False, not the truthy string '0'.
        fields = getattr(target, "__dataclass_fields__", {})
        ann = str(fields[parts[-1]].type) if parts[-1] in fields else ""
        if raw_value.lower() in ("none", "null"):
            value = None  # tri-state fields (e.g. loss_remat_chunks)
        elif isinstance(current, bool) or "bool" in ann:
            value = raw_value.lower() in ("1", "true", "yes")
        elif raw_value.lower() in ("true", "false"):
            value = raw_value.lower() == "true"
        elif current is not None:
            value = type(current)(raw_value)
        elif "int" in ann:
            value = int(raw_value)  # Optional[int] fields (e.g. n_kv_heads)
        elif "float" in ann:
            value = float(raw_value)
        else:
            value = raw_value
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def rebuild(obj, node):
        kwargs = {
            k: rebuild(getattr(obj, k), v) if isinstance(v, dict) else v
            for k, v in node.items()
        }
        return dataclasses.replace(obj, **kwargs)

    return rebuild(config, tree)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=str, required=True)
    parser.add_argument("--rundir", type=str)
    parser.add_argument("--debug", action="store_true")
    parser.add_argument("--multihost", action="store_true")
    parser.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="dotted config override, e.g. --set model_config.n_layer=4",
    )
    args = parser.parse_args()

    import jax

    # Platform override for dev boxes/CI (the axon TPU plugin ignores the
    # JAX_PLATFORMS env var, so route through the config API).
    if os.environ.get("MIDGPT_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])
        if os.environ.get("MIDGPT_CPU_DEVICES"):
            from midgpt_tpu.utils.compat import set_cpu_device_count

            set_cpu_device_count(int(os.environ["MIDGPT_CPU_DEVICES"]))

    if args.multihost:
        jax.distributed.initialize()

    from midgpt_tpu.config import load_config, to_json
    from midgpt_tpu.robustness import preempt
    from midgpt_tpu.robustness.supervisor import supervise

    config = load_config(args.config)
    if args.set:
        config = apply_overrides(
            config, [kv.partition("=")[::2] for kv in args.set]
        )

    if args.rundir is not None:
        config = config.replace(rundir=args.rundir)
    elif not args.debug:
        assert not args.multihost, "multihost runs must prespecify --rundir"
        config = config.replace(
            rundir=os.path.abspath(
                os.path.join("outputs", datetime.now().strftime("%Y-%m-%d-%H-%M-%S"))
            )
        )
    if args.debug:
        config = config.replace(debug=True)

    if jax.process_index() == 0 and not config.debug and config.rundir:
        if config.rundir.startswith("gs://"):
            import gcsfs

            fs = gcsfs.GCSFileSystem()
            fs.makedirs(config.rundir, exist_ok=True)
            with fs.open(os.path.join(config.rundir, "config.json"), "w") as f:
                f.write(to_json(config))
        else:
            os.makedirs(config.rundir, exist_ok=True)
            with open(os.path.join(config.rundir, "config.json"), "w") as f:
                f.write(to_json(config))
        print(f"Writing to {config.rundir}")

    if args.multihost:
        from jax.experimental.multihost_utils import sync_global_devices

        sync_global_devices("end_setup")

    print(config)
    # SIGTERM/SIGINT -> emergency checkpoint at the next step boundary, then
    # a clean exit (a second signal hard-kills). The supervisor adds
    # restart-on-divergence with data-window skip (docs/ROBUSTNESS.md).
    preempt.install_handlers()
    runtime = None
    mesh_product = config.mesh.data * config.mesh.fsdp * config.mesh.sp
    if (
        config.on_resume_mesh == "any"
        and config.mesh.data != -1
        and mesh_product != jax.device_count()
    ):
        # Elastic resume surface (docs/ROBUSTNESS.md "Elastic resume &
        # watchdog"): the configured mesh doesn't fit what the scheduler
        # handed us, and the config opted into topology changes — build the
        # runtime with the data axis re-derived for the ACTUAL device count
        # (the supervisor then reshard-restores the checkpoint through the
        # new mesh's shardings).
        from midgpt_tpu.training.train import make_runtime

        print(
            f"elastic resume: configured mesh wants {mesh_product} device(s), "
            f"found {jax.device_count()}; re-deriving the data axis "
            "(on_resume_mesh='any')"
        )
        runtime = make_runtime(config, devices=list(jax.devices()))
    supervise(config, runtime=runtime)


if __name__ == "__main__":
    main()
