#!/usr/bin/env bash
# Bootstrap every host of a TPU slice for training (capability parity with
# reference scripts/setup.sh:9-19, adapted to this package layout).
#
#   scripts/tpu setup <name> [data-disk]
#
# Steps, on every host:
#   1. rsync this repo
#   2. install jax[tpu] from Google's libtpu release index + requirements
#   3. optionally attach a read-only persistent disk holding train.bin/val.bin
#      and mount it at /mnt/disks/persist
#
# Requires MIDGPT_TPU_PROJECT / MIDGPT_TPU_ZONE (see scripts/tpu).

set -euo pipefail

NAME="${1:?usage: setup_hosts.sh <tpu-name> [data-disk]}"
DISK="${2:-}"
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
TPU="$SCRIPT_DIR/tpu"

# Stale host keys accumulate as slices are recreated with recycled IPs.
while IFS= read -r ip; do
    [[ -n "$ip" ]] && ssh-keygen -R "$ip" >/dev/null 2>&1 || true
done < <("$TPU" ips "$NAME")

"$TPU" copy "$NAME"
"$TPU" ssh "$NAME" "pip install -q 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html"
# `tpu copy` rsyncs the repo to ~/<basename of the local checkout>
REPO_DIR="$(basename "$(cd "$SCRIPT_DIR/.." && pwd)")"
"$TPU" ssh "$NAME" "cd '$REPO_DIR' && pip install -q -r requirements.txt"

if [[ -n "$DISK" ]]; then
    gcloud compute tpus tpu-vm attach-disk "$NAME" \
        --project "${MIDGPT_TPU_PROJECT:?}" --zone "${MIDGPT_TPU_ZONE:?}" \
        --disk "$DISK" --mode=read-only
    "$TPU" ssh "$NAME" "sudo mkdir -p /mnt/disks/persist && sudo mount -o discard,defaults,ro /dev/sdb /mnt/disks/persist || true"
fi

echo "setup complete: $NAME"
