"""Offline prep for openwebtext: HF dataset → GPT-2 BPE → uint16 memmap streams.

Produces `train.bin` (~9B tokens, ~17GB) and `val.bin` in the flat uint16
format `midgpt_tpu.data.TokenDataset` samples from. Capability parity with
reference data/openwebtext/prepare.py:21-76 (load_dataset → 0.05% val split
→ tiktoken encode + end-of-text sentinel per document → parallel map →
memmap concat), redesigned around a chunked stream writer: token counts are
precomputed per split, each split is written through a bounded-size buffer
(constant RAM regardless of dataset size), and both deps are import-gated
with actionable errors for air-gapped hosts.

Tokenization is identical to the reference recipe so checkpoints/losses are
comparable: `encode_ordinary` (no special-token splitting) with the GPT-2
end-of-text id appended to every document. Run on a beefy CPU host, not the
TPU VM, if you can — this is pure preprocessing.

Usage:
    python data/openwebtext/prepare.py [--num-proc N] [--out-dir DIR]
    python data/openwebtext/prepare.py --dataset stas/openwebtext-10k  # smoke
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

try:
    from datasets import load_dataset
except ImportError:
    sys.exit("pip install datasets  (HF hub access required; run on a host with egress)")
try:
    import tiktoken
except ImportError:
    sys.exit("pip install tiktoken")

VAL_FRACTION = 0.0005
SPLIT_SEED = 2357  # same split seed as the reference recipe → same val set


def tokenize_split(dataset, num_proc: int):
    enc = tiktoken.get_encoding("gpt2")

    def encode_doc(example):
        ids = enc.encode_ordinary(example["text"])
        ids.append(enc.eot_token)
        return {"ids": ids, "n": len(ids)}

    return dataset.map(
        encode_doc,
        remove_columns=["text"],
        desc="tokenizing",
        num_proc=num_proc,
    )


def write_split(tokenized, path: str, buffer_tokens: int = 16 * 1024 * 1024) -> int:
    """Stream `ids` lists into a uint16 memmap through a bounded buffer.

    Iterates the dataset in batches (never materializing the full `ids`
    column — at openwebtext scale that would be hundreds of GB of Python
    lists) and flushes through a fixed-size staging buffer."""
    total = int(np.sum(tokenized["n"], dtype=np.uint64))
    out = np.memmap(path, dtype=np.uint16, mode="w+", shape=(total,))
    buf = np.empty(buffer_tokens, dtype=np.uint16)
    fill = 0
    cursor = 0
    for batch in tokenized.select_columns(["ids"]).iter(batch_size=1024):
        for ids in batch["ids"]:
            n = len(ids)
            if fill + n > buffer_tokens:
                out[cursor : cursor + fill] = buf[:fill]
                cursor += fill
                fill = 0
            if n > buffer_tokens:  # pathological mega-document: bypass buffer
                out[cursor : cursor + n] = np.asarray(ids, dtype=np.uint16)
                cursor += n
                continue
            buf[fill : fill + n] = np.asarray(ids, dtype=np.uint16)
            fill += n
    out[cursor : cursor + fill] = buf[:fill]
    cursor += fill
    assert cursor == total, f"wrote {cursor} of {total} tokens"
    out.flush()
    return total


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dataset", type=str, default="openwebtext",
                        help="HF dataset name (use a small one to smoke-test)")
    parser.add_argument("--num-proc", type=int, default=max(1, (os.cpu_count() or 2) // 2))
    parser.add_argument("--out-dir", type=str, default=os.path.dirname(os.path.abspath(__file__)))
    args = parser.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    raw = load_dataset(args.dataset, split="train", num_proc=args.num_proc)
    parts = raw.train_test_split(test_size=VAL_FRACTION, seed=SPLIT_SEED, shuffle=True)
    splits = {"train": parts["train"], "val": parts["test"]}

    for name, ds in splits.items():
        tokenized = tokenize_split(ds, args.num_proc)
        path = os.path.join(args.out_dir, f"{name}.bin")
        total = write_split(tokenized, path)
        print(f"{name}: {total:,} tokens -> {path}")


if __name__ == "__main__":
    main()
