"""Offline prep for the char-level tiny-shakespeare dataset.

Produces the token-stream format `midgpt_tpu.data.TokenDataset` reads:
`train.bin` / `val.bin` flat uint16 streams plus `meta.pkl` holding the char
codec (vocab_size, stoi, itos) that `sample.py` uses to encode prompts and
decode samples.

Capability parity with reference data/shakespeare_char/prepare.py:12-61
(download → char vocab → 90/10 split → uint16 bins + meta.pkl), redesigned
for this repo: stdlib-only download with an explicit offline story (pass
--input to use any local text file — air-gapped TPU pods rarely have
egress), deterministic output, and a printed token count per split.

Usage:
    python data/shakespeare_char/prepare.py               # download + build
    python data/shakespeare_char/prepare.py --input my.txt  # offline
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import urllib.request

import numpy as np

URL = (
    "https://raw.githubusercontent.com/karpathy/char-rnn/master/"
    "data/tinyshakespeare/input.txt"
)


def fetch_text(out_dir: str, input_path: str | None) -> str:
    if input_path:
        with open(input_path, "r", encoding="utf-8") as f:
            return f.read()
    cached = os.path.join(out_dir, "input.txt")
    if os.path.exists(cached):
        with open(cached, "r", encoding="utf-8") as f:
            return f.read()
    try:
        with urllib.request.urlopen(URL, timeout=30) as r:
            text = r.read().decode("utf-8")
    except OSError as e:
        sys.exit(
            f"download failed ({e}); no network? Pass --input <file.txt> "
            f"or place input.txt next to this script."
        )
    with open(cached, "w", encoding="utf-8") as f:
        f.write(text)
    return text


def build(text: str, out_dir: str, val_fraction: float = 0.1) -> None:
    # Vectorized char codec: utf-32 round-trip puts one codepoint per uint32
    # lane, np.unique builds the vocab, searchsorted maps to ids — no
    # per-character Python loop, so hundred-MB offline corpora (the air-gap
    # path) prep in seconds instead of minutes.
    codes = np.frombuffer(text.encode("utf-32-le"), dtype=np.uint32)
    uniq = np.unique(codes)
    if len(uniq) > 65536:  # ids 0..65535 fit uint16 exactly
        sys.exit(
            f"char vocab {len(uniq):,} exceeds the uint16 token format; "
            "filter the input (e.g. tools/make_offline_corpus.py strips "
            "non-ASCII) before preparing"
        )
    chars = [chr(c) for c in uniq]
    stoi = {ch: i for i, ch in enumerate(chars)}
    itos = {i: ch for i, ch in enumerate(chars)}
    ids = np.searchsorted(uniq, codes).astype(np.uint16)

    n_val = int(len(ids) * val_fraction)
    splits = {"train": ids[: len(ids) - n_val], "val": ids[len(ids) - n_val :]}
    for name, arr in splits.items():
        path = os.path.join(out_dir, f"{name}.bin")
        arr.tofile(path)
        print(f"{name}: {len(arr):,} tokens -> {path}")

    meta = {"vocab_size": len(chars), "stoi": stoi, "itos": itos}
    with open(os.path.join(out_dir, "meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    print(f"vocab: {len(chars)} chars -> meta.pkl")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=str, default=None, help="local text file (skip download)")
    parser.add_argument("--out-dir", type=str, default=os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument("--val-fraction", type=float, default=0.1)
    args = parser.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    build(fetch_text(args.out_dir, args.input), args.out_dir, args.val_fraction)


if __name__ == "__main__":
    main()
