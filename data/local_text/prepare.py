"""Fully-offline BPE data prep: local text trees → trained byte-level BPE → uint16 bins.

The openwebtext recipe (data/openwebtext/prepare.py) needs HF hub + tiktoken
egress. Air-gapped TPU pods often have neither, but they do have large local
text trees (source checkouts, docs, mounted corpora). This pipeline produces
a training-ready dataset with the SAME on-disk contract (`train.bin`/`val.bin`
flat uint16 + `meta.pkl`) from purely local files:

  1. walk --roots collecting text files by extension, dedup by content hash
  2. train a byte-level BPE (HF `tokenizers`, Rust) of --vocab-size on the
     corpus itself — no downloaded vocab needed
  3. encode every document, append <|endoftext|> (id matching the trained
     vocab), stream the ids into uint16 bins with the reference's 0.05% val
     split discipline (shuffle seed 2357; reference data/openwebtext/
     prepare.py:21-30 uses the same fraction/seed on HF splits)

`meta.pkl` records {"kind": "hf_bpe", "tokenizer_file", "vocab_size",
"tokenizer_sha256", "split_tokens"}: the codec pointer for sample.py plus a
staleness fingerprint — TokenDataset refuses bins whose token counts
disagree with `split_tokens`, and sample.py refuses a tokenizer.json whose
hash disagrees with `tokenizer_sha256` (bins/tokenizer/meta are only
coherent as a set from one prepare run).

Usage:
    python data/local_text/prepare.py --roots DIR [DIR ...] [--vocab-size N]
"""

from __future__ import annotations

import argparse
import hashlib
import os
import pickle
import sys

import numpy as np

try:
    from tokenizers import ByteLevelBPETokenizer, Tokenizer
except ImportError:
    sys.exit("pip install tokenizers  (ships with transformers)")

VAL_FRACTION = 0.0005
SPLIT_SEED = 2357  # same split discipline as the openwebtext recipe
EOT = "<|endoftext|>"


def collect_documents(roots, exts, max_bytes, min_bytes=256):
    """Unique (by content hash) utf-8 decodable files under roots."""
    seen, docs = set(), []
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for fn in sorted(filenames):
                if not any(fn.endswith(e) for e in exts):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    size = os.path.getsize(path)
                    if not (min_bytes <= size <= max_bytes):
                        continue
                    with open(path, "rb") as f:
                        raw = f.read()
                except OSError:
                    continue
                digest = hashlib.sha1(raw).digest()
                if digest in seen:
                    continue
                seen.add(digest)
                try:
                    text = raw.decode("utf-8")
                except UnicodeDecodeError:
                    continue
                docs.append(text)
    return docs


def encode_to_bin(tokenizer, docs, eot_id, path, batch=512):
    """Stream-encode docs into a flat uint16 file; returns token count."""
    total = 0
    with open(path, "wb") as f:
        for lo in range(0, len(docs), batch):
            encs = tokenizer.encode_batch(docs[lo : lo + batch])
            for e in encs:
                ids = np.asarray(e.ids + [eot_id], dtype=np.uint16)
                ids.tofile(f)
                total += ids.size
    return total


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--roots", nargs="+", required=True)
    parser.add_argument("--out-dir", default=os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument("--vocab-size", type=int, default=50257)
    parser.add_argument("--exts", default=".py,.md,.rst,.txt")
    parser.add_argument("--max-file-mb", type=float, default=2.0)
    parser.add_argument("--val-fraction", type=float, default=VAL_FRACTION)
    parser.add_argument(
        "--train-sample-mb", type=float, default=0.0,
        help="cap BPE *training* to a seeded random sample of this many MB "
        "of text (0 = train on everything). Encoding always covers the full "
        "corpus — merges learned from a large sample are near-identical, and "
        "BPE training is the single-core-hostile part of the pipeline.",
    )
    args = parser.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    exts = tuple(args.exts.split(","))

    docs = collect_documents(args.roots, exts, int(args.max_file_mb * 1e6))
    n_chars = sum(len(d) for d in docs)
    print(f"collected {len(docs):,} unique documents, {n_chars:,} chars")
    if not docs:
        sys.exit("no documents found under the given roots")

    rng = np.random.default_rng(SPLIT_SEED)
    order = rng.permutation(len(docs))
    n_val = max(1, int(len(docs) * args.val_fraction))
    val_docs = [docs[i] for i in order[:n_val]]
    train_docs = [docs[i] for i in order[n_val:]]

    # BPE merges are learned from the TRAIN split only — val tokens must not
    # leak into the vocabulary statistics (mild train/val contamination
    # otherwise; the reference's GPT-2 vocab is likewise fixed independently
    # of its val split).
    trainer_docs = train_docs
    if args.train_sample_mb > 0:
        budget = int(args.train_sample_mb * 1e6)
        sample_order = np.random.default_rng(SPLIT_SEED + 1).permutation(
            len(train_docs)
        )
        trainer_docs, used = [], 0
        for i in sample_order:
            trainer_docs.append(train_docs[i])
            used += len(train_docs[i])
            if used >= budget:
                break
        print(f"BPE trainer sample: {len(trainer_docs):,} docs, {used:,} chars")

    tok_path = os.path.join(args.out_dir, "tokenizer.json")
    tokenizer = ByteLevelBPETokenizer()
    tokenizer.train_from_iterator(
        iter(trainer_docs), vocab_size=args.vocab_size, special_tokens=[EOT],
        show_progress=False,
    )
    tokenizer.save(tok_path)
    tokenizer = Tokenizer.from_file(tok_path)
    eot_id = tokenizer.token_to_id(EOT)
    vocab_size = tokenizer.get_vocab_size()
    assert vocab_size <= np.iinfo(np.uint16).max, "uint16 stream format"
    print(f"trained BPE: vocab {vocab_size}, eot id {eot_id} -> {tok_path}")

    counts = {}
    for name, split in (("train", train_docs), ("val", val_docs)):
        path = os.path.join(args.out_dir, f"{name}.bin")
        counts[name] = encode_to_bin(tokenizer, split, eot_id, path)
        print(f"{name}: {counts[name]:,} tokens -> {path}")

    with open(tok_path, "rb") as f:
        tok_sha = hashlib.sha256(f.read()).hexdigest()
    with open(os.path.join(args.out_dir, "meta.pkl"), "wb") as f:
        # Staleness fingerprint: bins, tokenizer and meta are only coherent
        # as a set from ONE prepare run. The token counts let TokenDataset
        # detect bins from an older run (e.g. tracked tokenizer.json updated
        # by git while untracked *.bin stayed behind) and fail loudly
        # instead of training on re-interpreted ids.
        pickle.dump(
            {
                "kind": "hf_bpe",
                "tokenizer_file": "tokenizer.json",
                "vocab_size": vocab_size,
                "tokenizer_sha256": tok_sha,
                "split_tokens": counts,
            },
            f,
        )


if __name__ == "__main__":
    main()
