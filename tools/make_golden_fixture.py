"""Regenerate tests/golden/tiny_fp32.json (the golden-loss fixture).

Run this ONLY when GOLDEN_SPEC legitimately changes (never to paper over an
unexplained trajectory shift — that is the regression the fixture exists to
catch). Must run on the same 8-device virtual CPU mesh the tests use:

    python tools/make_golden_fixture.py
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import conftest  # noqa: E402,F401  — THE jax config the tests run under
import jax  # noqa: E402

import golden_runner  # noqa: E402


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        golden_runner.make_stream(d)
        losses = golden_runner.run_trajectory(d)
    out = os.path.join(
        os.path.dirname(__file__), "..", "tests", "golden", "tiny_fp32.json"
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        import numpy
        import optax

        import platform

        json.dump(
            {
                "spec": golden_runner.GOLDEN_SPEC,
                # The trajectory depends on all three stacks: jax (compiled
                # math + threefry), numpy (Generator method streams are NOT
                # guaranteed stable across feature releases, NEP 19), optax
                # (chain internals) — AND on the host platform: XLA:CPU
                # emits different vector code per ISA (AVX-512 vs AVX2 vs
                # aarch64 NEON), so f32 reduction shapes can differ across
                # machines even on identical software (ADVICE r5).
                "versions": {
                    "jax": jax.__version__,
                    "numpy": numpy.__version__,
                    "optax": optax.__version__,
                    "platform": platform.platform(),
                    "machine": platform.machine(),
                    "processor": platform.processor() or "unknown",
                },
                "losses": losses,
            },
            f,
            indent=1,
        )
    print(f"wrote {out}: {losses[:3]} ... {losses[-3:]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
