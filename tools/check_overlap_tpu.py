"""Assert the ZeRO-3 gather/compute overlap on the REAL TPU backend.

tests/test_shard_map_fsdp.py::test_zero3_gathers_schedulable_ahead_of_compute
pins the dataflow property (weight gathers independent of layer compute) on
the CPU mesh; this tool pins the other half of the claim in
parallel/shard_map_fsdp.py — that the TPU compiler actually exploits that
freedom. The CPU backend emits synchronous all-gathers, so this can only be
shown against the TPU compiler; a v5e:2x4 topology is AOT-compiled (no
8-chip hardware needed — works through the single-chip axon tunnel) and the
post-optimization HLO is checked structurally. This XLA/libtpu build does
not split async gathers into `all-gather-start`/`-done` instruction pairs in
that text; overlap shows up in two forms, both detected:

  * gathers ANNOTATED `frontend_attributes={async_collective_name=
    "all-gather-start*"}` + a CUSTOM barrier_config (the start/done split
    happens in the backend scheduler), and
  * collective-continuation fusions: block matmul kernels that carry the
    NEXT layer's gather windows as aliased outputs (`continuation_config`,
    `calls=%async_collective_fusion.*`) — the gather is streamed INSIDE the
    compute kernel. The strongest overlap form.

Exit 0 iff EVERY gather-bearing scan body (forward and backward) has at
least one async/fused gather. Run: `python tools/check_overlap_tpu.py` (on
the TPU host). Measured result recorded in RESULTS.md §3a.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_step_lowered(mesh):
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPTConfig
    from midgpt_tpu.utils.hlo import lower_abstract_train_step

    config = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=16,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        eval_interval=5,
        beta2=0.95,
        weight_decay=1e-4,
        param_dtype="float32",
        compute_dtype="bfloat16",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=1, fsdp=8, sp=1),
        model_config=GPTConfig(
            # Real-ish shapes so the scheduler has matmuls worth hiding
            # gathers behind (tiny dims would be all overhead).
            block_size=512, vocab_size=8192, n_layer=4, n_head=8, n_embd=512,
            attn_impl="naive", scan_unroll=2,
        ),
    )
    return lower_abstract_train_step(config, mesh=mesh)


def analyze(txt: str) -> int:
    """Return 0 iff every gather-bearing scan body overlaps its gathers."""
    from midgpt_tpu.utils.hlo import (
        hlo_computations,
        is_forward_body,
        while_body_names,
    )

    def is_async(l):
        return (
            "all-gather-start(" in l
            or 'async_collective_name="all-gather-start' in l
        )

    bodies = while_body_names(txt)
    bodies_ok, bodies_bad = [], []
    for n, lines in hlo_computations(txt).items():
        # Structural body detection (referenced as body=%n from a while op),
        # not metadata: leaf fusions inherit the body's op_name metadata and
        # must not be graded as bodies — nor may a real body with ONE
        # (combined) serialized gather be skipped.
        if n not in bodies or not any("shard_map/while" in l for l in lines):
            continue
        n_sync = sum(
            1 for l in lines if " all-gather(" in l and not is_async(l)
        )
        n_annot = sum(1 for l in lines if is_async(l))
        cont_lines = [l for l in lines if "calls=%async_collective_fusion" in l]
        # op_name labels feed the DISPLAY only — the count must not depend
        # on metadata naming (it drifts across XLA versions).
        cont_ops = [
            m.group(1)
            for m in (
                re.search(r'op_name="[^"]*?/(block/[\w,>-]+(?:/[\w,>-]+)?)', l)
                for l in cont_lines
            )
            if m
        ]
        if n_sync + n_annot + len(cont_lines) == 0:
            continue  # gather-free body (not a ZeRO-3 layer scan)
        kind = "forward" if is_forward_body(lines) else "backward"
        print(
            f"{kind} scan body {n}: {n_annot} annotated-async gathers, "
            f"{len(cont_lines)} gathers fused into compute kernels "
            f"(continuation fusions on: {sorted(set(cont_ops))}), "
            f"{n_sync} plain"
        )
        (bodies_ok if n_annot + len(cont_lines) > 0 else bodies_bad).append(
            (kind, n)
        )
    if not bodies_ok and not bodies_bad:
        print("FAIL: no gather-bearing scan body found — did lowering change?")
        return 1
    if bodies_bad:
        print(
            "FAIL: scan bodies with fully-serialized gathers: "
            f"{bodies_bad} — the ZeRO-3 weight stream there runs behind "
            "compute instead of overlapping it"
        )
        return 1
    print(
        f"OK: the ZeRO-3 weight stream overlaps compute in all "
        f"{len(bodies_ok)} gather-bearing scan bodies {bodies_ok} — via "
        "async annotation and collective-continuation fusion into the "
        "block matmul kernels"
    )
    return 0


def main() -> int:
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from midgpt_tpu.parallel.mesh import AXES

    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    mesh = Mesh(
        np.asarray(topo.devices).reshape(1, 8, 1, 1, 1), axis_names=AXES
    )
    lowered = build_step_lowered(mesh)
    # NOT default-on in this toolchain's compile path (measured: without the
    # flag, zero gathers are async-ified). Real-pod launches must set it —
    # see docs/PARALLELISM.md "Overlap".
    opts = {"xla_tpu_enable_latency_hiding_scheduler": "true"}
    txt = lowered.compile(compiler_options=opts).as_text()
    return analyze(txt)


if __name__ == "__main__":
    sys.exit(main())
