"""Arrival-process load harness for the serving front door: one JSON line.

bench_serve replays a fixed trace to completion — a throughput number.
Production serving is governed by DIFFERENT numbers: time-to-first-token
and time-per-output-token percentiles under an offered load, and what
fraction of traffic had to be shed to hold them (the error budget). This
harness generates a seeded arrival process (Poisson or bursty), a
prompt/output-length mixture (short chat-y requests vs long-document
requests, optionally a `--template-frac` share of template-headed
system-prompt traffic), drives the asyncio front door
(sampling/server.py) over a fresh `ServeEngine` at each offered-load
point, and emits ONE JSON line (driver contract, `serve_slo` profile in
analysis/bench_contract.py). With `--prefix-cache` the engines run with
the cross-request prefix cache on and per-point/headline
`prefix_hit_rate` fields report how much prefill the trie absorbed. With
`--fleet N` each point instead drives N replica engines behind the
prefix-affinity FleetRouter with its shared host-RAM KV spill tier
(sampling/fleet.py; docs/ROBUSTNESS.md "Fleet serving & failover") through
a synchronous step loop, and points + headline carry fleet_size /
failovers / fleet-wide prefix_hit_rate / spill_hits. Adding `--procs`
promotes every replica to a worker PROCESS behind the framed socket
transport (sampling/fleet_proc.py; docs/ROBUSTNESS.md "Cross-process
fleet") — the parent builds no engine and compiles nothing, and points +
headline add rpc_p50_ms / rpc_p95_ms / wire_bytes:

    python tools/loadgen.py --process poisson --rates 20,60 \
        [--scheduler slo] [--ttl-s 2.0] [--slo-ttft-ms 500 --slo-tpot-ms 50] \
        [--error-budget 0.2] [--cpu-devices 8] [--trace-out /tmp/traces]

Every engine runs under a per-point flight recorder (midgpt_tpu/obs/):
each point (and the headline, from the hottest point) carries
`round_host_ms`/`round_device_ms` p50/p95 — the decode-round split into
host work (batch assembly + jit enqueue + token commit) vs device wait
(docs/OBSERVABILITY.md) — plus `overlap_mode`/`round_group`/
`overlap_hidden_ms`, the round-overlap dispatch A/B identity driven by
`--overlap {off,double,group:k}` (docs/SERVING.md "Round-overlap
dispatch"; the TPOT-vs-mode comparison is THE acceptance A/B for ROADMAP
item 3). `--trace-out DIR` additionally dumps one Chrome-trace JSON
(+ .prom metrics) per point for Perfetto / tools/trace_view.py.

Client-perceived metrics: TTFT is measured from the client's submit
attempt (admission retries and queueing included — that is what a user
waits through), TPOT from first to last streamed token. `shed_frac`
counts requests refused by backpressure/SLO admission after the bounded
retry budget; `timeout_frac` counts TTL expiries. A point is `slo_ok`
when its p95s meet the (optional) SLO targets AND shed+timeout stays
inside the error budget.

Compile time is not a latency claim: every jit shape the workload can
touch is warmed by a synchronous pre-pass before the first timed point
(module-level jits — warm shapes are shared by every engine after it).
Arrivals, mixtures, and scheduling are all seeded/deterministic; the
measured times are wall-clock, so on the CPU test mesh treat percentiles
as scheduling-structure signal (CLAUDE.md), not kernel-speed signal.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
import typing as tp

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _percentile_ms(xs: tp.List[float], q: float) -> float:
    """Percentile of a list of seconds, in ms; 0.0 for an empty list (a
    degenerate point — visible as completed == 0, never NaN: the JSON
    contract rejects non-finite constants)."""
    if not xs:
        return 0.0
    return round(float(np.percentile(np.asarray(xs), q)) * 1e3, 3)


def _arrivals(process: str, rate: float, n: int, rng, burst_size: int):
    """Seeded arrival offsets (seconds from point start) at offered rate
    `rate` req/s: exponential inter-arrivals (poisson) or bursts of
    `burst_size` simultaneous arrivals with exponential gaps sized so the
    long-run offered rate matches (bursty — the pathological shape
    continuous batching exists to absorb)."""
    t, out = 0.0, []
    if process == "poisson":
        for _ in range(n):
            t += float(rng.exponential(1.0 / rate))
            out.append(t)
    else:  # bursty
        while len(out) < n:
            t += float(rng.exponential(burst_size / rate))
            out.extend([t] * min(burst_size, n - len(out)))
    return out


def _mixture(
    rng, n: int, block_size: int, vocab: int, long_frac: float,
    templates: tp.Sequence[np.ndarray] = (), template_frac: float = 0.0,
):
    """Prompt/output-length mixture: mostly short interactive requests, a
    `long_frac` tail of long-document prompts with bigger budgets. With
    `template_frac` > 0, that fraction of requests instead share one of
    `templates` as a common prompt head (system-prompt traffic) with a
    short unique tail — the workload the cross-request prefix cache
    (sampling/prefix_cache.py) exists for. Templates are built once per
    SEED, not per point, so every offered-load point measures the same
    shared heads — points stay comparable even though each point's fresh
    engine starts with a cold trie."""
    reqs = []
    for _ in range(n):
        if templates and rng.random() < template_frac:
            head = templates[int(rng.integers(0, len(templates)))]
            tail = rng.integers(
                0, vocab, int(rng.integers(2, 8)), dtype=np.int64
            )
            prompt = np.concatenate([head, tail])
            m = min(int(rng.integers(6, 14)), block_size - len(prompt) - 1)
            reqs.append((prompt, m))
            continue
        if rng.random() < long_frac:
            if block_size >= 2048:
                # long-context regime (the split-K bucket rule's territory,
                # sampling/serve.py `_split_bucket`): near-context document
                # prompts with bigger output budgets, so the serve_slo line
                # tracks p95 TPOT with auto-split decode in the mix. The
                # small-block branch below is untouched — the default
                # harness geometry (and its pinned program census) draws
                # the exact same stream it always did.
                t0 = int(rng.integers(block_size // 2, block_size * 7 // 8))
                m = int(rng.integers(24, 48))
            else:
                t0 = int(rng.integers(block_size // 4, block_size // 2))
                m = int(rng.integers(12, 24))
        else:
            t0 = int(rng.integers(4, max(5, block_size // 8)))
            m = int(rng.integers(6, 14))
        m = min(m, block_size - t0 - 1)
        reqs.append((rng.integers(0, vocab, t0, dtype=np.int64), m))
    return reqs


def _warm_compile_grid(engine, cfg, decode_chunk, page_size, seed):
    """Compile the full reachable serving program set: for each pow2 page
    bucket and each pow2 decode-chunk tail, run one solo request whose
    prompt pins the bucket and whose budget pins the tail width (the
    bucket/tail scheme: sampling/serve.py `_page_bucket`/`_decode_round`).
    Sequential solo runs also sweep every prefill bucket on the way."""
    rng = np.random.default_rng(seed + 7919)
    S = cfg.block_size
    max_bucket = engine.max_pages_per_slot
    tails = []
    n = decode_chunk
    while n >= 1:
        tails.append(n)
        n //= 2
    b = 1
    while b <= max_bucket:
        # mid-page prompt: bucket stays pinned at b while the tail decodes
        prompt_len = max(2, (b - 1) * page_size + 2)
        for tail in tails:
            if prompt_len + 1 + tail >= S:
                continue
            engine.submit(
                rng.integers(0, cfg.vocab_size, prompt_len, np.int64),
                tail + 1,  # first token rides prefill; `tail` decode steps
            )
            engine.run()
        b *= 2


async def _drive_point(server, reqs, arrivals, ttl_s):
    """One offered-load point: a client task per request (sleep to its
    arrival, submit with the server's bounded backpressure retry, consume
    the stream). Returns per-request client-side records."""
    from midgpt_tpu.sampling.serve import BackpressureError
    from midgpt_tpu.sampling.server import ServerDraining

    t0 = time.perf_counter()
    records = []

    async def client(i, prompt, m, at):
        delay = at - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        rec = {"i": i, "status": "shed", "ttft_s": None, "tpot_s": None}
        records.append(rec)
        t_submit = time.perf_counter()
        try:
            uid = await server.submit(prompt, m, ttl_s=ttl_s)
        except (BackpressureError, ServerDraining):
            return
        times = []
        async for _tok in server.stream(uid):
            times.append(time.perf_counter())
        fr = server.result(uid)
        rec["status"] = fr.status if fr is not None else "lost"
        if times:
            rec["ttft_s"] = times[0] - t_submit
            if len(times) > 1:
                rec["tpot_s"] = (times[-1] - times[0]) / (len(times) - 1)

    await asyncio.gather(
        *(client(i, p, m, at)
          for i, ((p, m), at) in enumerate(zip(reqs, arrivals)))
    )
    return records


def _drive_fleet_point(router, reqs, arrivals, ttl_s, submit_retries=8):
    """One offered-load point against a FleetRouter, driven synchronously:
    the router's step loop IS the clock (sampling/fleet.py — replicas are
    in-process engines, so an asyncio front door would add nothing but
    scheduling noise). Arrivals submit when their offset passes, under a
    bounded per-request retry budget — a request still refused after
    `submit_retries` attempts stays a shed, mirroring the async path's
    bounded-retry front door. TTFT runs from the FIRST submit attempt
    (admission retries and queueing included, same client-perceived
    definition as _drive_point); token times ride the router's on_token
    relay, so across a failover the replayed stream's delivery is
    at-least-once and TPOT is measured over everything the client saw."""
    from midgpt_tpu.sampling.serve import BackpressureError

    t0 = time.perf_counter()
    records = [
        {"i": i, "status": "shed", "ttft_s": None, "tpot_s": None}
        for i in range(len(reqs))
    ]
    first_attempt: tp.Dict[int, float] = {}
    token_times: tp.Dict[int, tp.List[float]] = {}
    uid_to_i: tp.Dict[int, int] = {}

    def on_token(uid, tok, t):
        token_times.setdefault(uid, []).append(time.perf_counter())

    router.on_token = on_token
    order = sorted(range(len(reqs)), key=lambda i: arrivals[i])
    qi = 0
    waiting: tp.List[tp.List[int]] = []  # [request index, attempts so far]
    guard = 0
    while qi < len(order) or waiting or not router.idle:
        guard += 1
        if guard >= 1_000_000:
            raise SystemExit("fleet point did not converge")
        now = time.perf_counter() - t0
        while qi < len(order) and arrivals[order[qi]] <= now:
            waiting.append([order[qi], 0])
            qi += 1
        still: tp.List[tp.List[int]] = []
        for item in waiting:
            i = item[0]
            first_attempt.setdefault(i, time.perf_counter())
            try:
                uid = router.submit(reqs[i][0], reqs[i][1], ttl_s=ttl_s)
            except BackpressureError as e:
                item[1] += 1
                if item[1] < submit_retries and getattr(e, "retryable", False):
                    still.append(item)
                continue  # budget exhausted / terminal: stays "shed"
            uid_to_i[uid] = i
        waiting = still
        if qi < len(order) and router.idle and not waiting:
            # quiet fleet, next arrival in the future: sleep up to it
            delay = arrivals[order[qi]] - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            continue
        router.step()
    for uid, i in uid_to_i.items():
        fr = router.finished.get(uid)
        rec = records[i]
        rec["status"] = fr.status if fr is not None else "lost"
        times = token_times.get(uid, [])
        if times:
            rec["ttft_s"] = times[0] - first_attempt[i]
            if len(times) > 1:
                rec["tpot_s"] = (times[-1] - times[0]) / (len(times) - 1)
    return records


def _point_stats(rate, records, error_budget, slo_ttft_ms, slo_tpot_ms):
    n = len(records)
    shed = sum(1 for r in records if r["status"] == "shed")
    timeouts = sum(1 for r in records if r["status"] == "timeout")
    completed = sum(1 for r in records if r["status"] == "ok")
    ttfts = [r["ttft_s"] for r in records if r["ttft_s"] is not None]
    tpots = [r["tpot_s"] for r in records if r["tpot_s"] is not None]
    stats = {
        "offered_rps": rate,
        "n_offered": n,
        "completed": completed,
        "shed": shed,
        "timeouts": timeouts,
        "shed_frac": round(shed / max(n, 1), 4),
        "timeout_frac": round(timeouts / max(n, 1), 4),
        "ttft_p50_ms": _percentile_ms(ttfts, 50),
        "ttft_p95_ms": _percentile_ms(ttfts, 95),
        "tpot_p50_ms": _percentile_ms(tpots, 50),
        "tpot_p95_ms": _percentile_ms(tpots, 95),
    }
    ok = (shed + timeouts) / max(n, 1) <= error_budget
    if slo_ttft_ms:
        ok = ok and stats["ttft_p95_ms"] <= slo_ttft_ms
    if slo_tpot_ms:
        ok = ok and stats["tpot_p95_ms"] <= slo_tpot_ms
    stats["slo_ok"] = bool(ok and completed > 0)
    return stats


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--process", choices=("poisson", "bursty"), default="poisson")
    ap.add_argument("--rates", type=str, default="20,60",
                    help="comma-separated offered loads (req/s), one timed "
                    "point each — >= 2 points make the SLO curve the "
                    "serve_slo contract expects")
    ap.add_argument("--n-requests", type=int, default=8,
                    help="requests offered per point")
    ap.add_argument("--burst-size", type=int, default=4,
                    help="--process bursty: simultaneous arrivals per burst")
    ap.add_argument("--long-frac", type=float, default=0.25,
                    help="fraction of long-document requests in the mixture. "
                    "At --block-size >= 2048 the long draws move to the "
                    "long-context regime (prompts of S/2..7S/8 tokens, "
                    "24-48 token budgets) so p95 TPOT under mixed load "
                    "exercises the auto split-K buckets (docs/SERVING.md "
                    "'Split-K decode'); smaller block sizes keep the "
                    "original S/4..S/2 draws")
    ap.add_argument("--template-frac", type=float, default=0.0,
                    help="fraction of requests sharing a template prompt "
                    "head (system-prompt traffic); pair with "
                    "--prefix-cache to measure cross-request reuse")
    ap.add_argument("--n-templates", type=int, default=2,
                    help="distinct shared prompt heads in the template mix")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the cross-request prefix cache "
                    "(sampling/prefix_cache.py) in every engine; per-point "
                    "and headline prefix_hit_rate fields are emitted")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scheduler", choices=("fcfs", "slo"), default="fcfs")
    ap.add_argument("--min-headroom-s", type=float, default=0.0,
                    help="--scheduler slo: shed requests whose deadline is "
                    "nearer than this at submit")
    ap.add_argument("--ttl-s", type=float, default=0.0,
                    help="per-request TTL (0 = none): expiries count "
                    "against the error budget as timeouts")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="p95 TTFT target (0 = unset)")
    ap.add_argument("--slo-tpot-ms", type=float, default=0.0,
                    help="p95 TPOT target (0 = unset)")
    ap.add_argument("--error-budget", type=float, default=0.2,
                    help="max shed+timeout fraction for a point to be slo_ok")
    ap.add_argument("--trace-out", type=str, default=None,
                    help="directory to dump one Chrome-trace flight "
                    "recorder (+ .prom metrics) per offered-load point — "
                    "open in Perfetto or roll up with tools/trace_view.py")
    ap.add_argument("--hot-swap", action="store_true",
                    help="zero-downtime ops under load: at each point, a "
                    "verified-checkpoint blue/green weight swap is staged "
                    "through the async front door mid-arrival-window "
                    "(sampling/ops.py; docs/ROBUSTNESS.md 'Zero-downtime "
                    "model ops'). Points and headline carry the "
                    "weights_version transition; the SLO acceptance is the "
                    "curve staying inside the error budget THROUGH the "
                    "swap — same slo_ok computation, no special-casing")
    ap.add_argument("--fleet", type=int, default=0,
                    help=">= 2 runs every point against that many replica "
                    "engines behind the prefix-affinity FleetRouter "
                    "(sampling/fleet.py) with its shared host-RAM spill "
                    "tier, driven synchronously (the router step loop is "
                    "the clock). Implies --prefix-cache (the trie is the "
                    "affinity target). Points and headline carry "
                    "fleet_size / failovers / fleet-wide prefix_hit_rate "
                    "/ spill_hits (docs/ROBUSTNESS.md 'Fleet serving & "
                    "failover'). Incompatible with --hot-swap and --tp")
    ap.add_argument("--procs", action="store_true",
                    help="--fleet: replicas are separate worker PROCESSES "
                    "(sampling/fleet_proc.py) behind the framed socket "
                    "transport — the parent builds no engine and compiles "
                    "nothing; every point drives the same worker fleet. "
                    "Points and headline add rpc_p50_ms / rpc_p95_ms / "
                    "wire_bytes (docs/ROBUSTNESS.md 'Cross-process "
                    "fleet'). Round decomposition reads zero (the rounds "
                    "run in the workers); fcfs scheduler and --overlap "
                    "off only")
    ap.add_argument("--overlap", type=str, default="off",
                    help="round-overlap dispatch mode for every engine "
                    "(docs/SERVING.md 'Round-overlap dispatch'): 'off', "
                    "'double' (dispatch round N+1 before round N's host "
                    "phase), or 'group:k' (fuse k rounds per dispatch). "
                    "Fixed offered load + --overlap off vs double is the "
                    "TPOT A/B; points and headline carry overlap_mode / "
                    "round_group / overlap_hidden_ms either way")
    # engine/model shape (tiny defaults: the CPU-mesh scheduling testbed)
    ap.add_argument("--max-slots", type=int, default=3)
    ap.add_argument("--page-size", type=int, default=8)
    # 27, not 25: pool size is a jit program-key dim, and the tier-1
    # recompile pins (tests/test_recompile_pins.py) count compiles of the
    # 25-page f32 geometry from a pristine baseline — the in-process
    # bench-contract loadgen run must not pre-warm that program set.
    # 0 = auto: 27 below the long-context regime; at --block-size >= 2048
    # a 27-page pool cannot hold ONE long-mixture prompt, so auto sizes a
    # fully-resident pool (every slot can pin its largest bucket).
    ap.add_argument("--num-pages", type=int, default=0)
    ap.add_argument("--max-backlog-pages", type=int, default=0,
                    help="backpressure budget (0 = unbounded)")
    ap.add_argument("--prefill-chunk", type=int, default=16)
    ap.add_argument("--decode-chunk", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=64)
    ap.add_argument("--vocab-size", type=int, default=96)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=2)
    ap.add_argument("--n-embd", type=int, default=32)
    ap.add_argument("--n-kv-heads", type=int, default=0,
                    help="GQA/MQA: KV heads shared by n_head/n_kv_heads "
                    "query-head groups (0 = MHA; docs/SERVING.md "
                    "'Attention variants'). Shrinks KV page bytes by the "
                    "group factor; the serve_slo model block carries the "
                    "variant knobs so GQA curves are not comparable-by-"
                    "accident with MHA ones")
    ap.add_argument("--sliding-window", type=int, default=0,
                    help="sliding-window attention: decode attends to the "
                    "last N positions only and the engine reclaims pages "
                    "behind the window (0 = full context)")
    ap.add_argument("--attn-sinks", type=int, default=0,
                    help="with --sliding-window: the first N positions "
                    "stay visible (and their pages resident) beyond the "
                    "window")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force CPU with this many virtual devices (0 = native)")
    ap.add_argument("--tp", type=int, default=0,
                    help="> 0 runs every engine tensor-parallel on a "
                    "(data=1, tp=N) serve mesh (parallel/serve_tp.py): "
                    "params sharded by the megatron tp rules, KV pool on "
                    "the head axis. The serve_slo line carries tp/mesh "
                    "fields so sharded and single-chip curves are "
                    "distinguishable. Pair with --cpu-devices >= N")
    args = ap.parse_args()
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if args.fleet:
        if args.fleet < 2:
            ap.error("--fleet needs >= 2 replicas (one cannot fail over)")
        if args.hot_swap or args.tp:
            ap.error("--fleet is incompatible with --hot-swap and --tp")
        args.prefix_cache = True  # the router's affinity target
    if args.procs:
        if not args.fleet:
            ap.error("--procs requires --fleet N (it spawns the replicas)")
        if args.scheduler != "fcfs":
            ap.error("--procs workers run the default fcfs scheduler")
        if args.overlap != "off":
            ap.error("--procs workers run with --overlap off")
        if args.max_backlog_pages:
            ap.error("--procs workers run with an unbounded backlog")
    if not args.num_pages:
        pages_per_slot = -(-args.block_size // args.page_size)
        args.num_pages = (
            27 if args.block_size < 2048
            else 1 + args.max_slots * pages_per_slot
        )

    import jax

    if args.cpu_devices:
        from midgpt_tpu.utils.compat import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)

    import jax.numpy as jnp

    from midgpt_tpu.models.gpt import GPT, GPTConfig
    from midgpt_tpu.obs import Observability
    from midgpt_tpu.sampling.scheduler import FCFSScheduler, SLOScheduler
    from midgpt_tpu.sampling.serve import ServeEngine, parse_overlap
    from midgpt_tpu.sampling.server import AsyncServeServer

    overlap_mode, overlap_group = parse_overlap(args.overlap)

    cfg = GPTConfig(
        block_size=args.block_size,
        vocab_size=args.vocab_size,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
        n_kv_heads=args.n_kv_heads or None,
        sliding_window=args.sliding_window,
        attn_sinks=args.attn_sinks,
    )
    worker_procs: tp.List[tp.Any] = []
    proc_replicas: tp.List[tp.Any] = []
    if args.procs:
        # The parent builds no params and no engine: the replicas are
        # worker processes (own CPU mesh, own jit cache, same-seed
        # params), reused across every offered-load point. Warm each
        # worker's full compile grid over the wire so the first point's
        # percentiles measure scheduling, not worker-side compiles.
        import dataclasses as _dc

        from midgpt_tpu.sampling.fleet_proc import (
            connect_replica,
            parent_jax_config,
            spawn_workers,
        )

        spec = {
            "model": _dc.asdict(cfg),
            "seed": args.seed,
            "engine": {
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "num_pages": args.num_pages,
                "prefill_chunk": args.prefill_chunk,
                "decode_chunk": args.decode_chunk,
                "cache_dtype": "float32",
            },
            "cpu_devices": args.cpu_devices or 1,
            "jax_config": parent_jax_config(),
        }
        worker_procs = spawn_workers(spec, args.fleet)
        proc_replicas = [
            connect_replica(port, retry_base_s=0.05)
            for _, port in worker_procs
        ]
        for rep in proc_replicas:
            _warm_compile_grid(
                rep, cfg, args.decode_chunk, args.page_size, args.seed
            )
    else:
        params = GPT.init(cfg, jax.random.PRNGKey(args.seed))
    on_tpu = jax.default_backend() == "tpu"
    cache_dtype = jnp.bfloat16 if on_tpu else jnp.float32

    mesh = None
    if args.tp:
        from midgpt_tpu.parallel.serve_tp import make_serve_mesh

        if args.tp < 2 or args.tp > len(jax.devices()):
            raise SystemExit(
                f"--tp {args.tp} needs 2 <= tp <= {len(jax.devices())} devices"
            )
        if cfg.n_head % args.tp:
            raise SystemExit(f"--tp {args.tp} must divide n_head {cfg.n_head}")
        mesh = make_serve_mesh(tp_size=args.tp)

    def make_engine(obs=None, obs_tid="engine"):
        sched = (
            SLOScheduler(min_headroom_s=args.min_headroom_s)
            if args.scheduler == "slo"
            else FCFSScheduler()
        )
        return ServeEngine(
            cfg,
            params,
            obs_tid=obs_tid,
            max_slots=args.max_slots,
            page_size=args.page_size,
            num_pages=args.num_pages,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=cache_dtype,
            max_backlog_pages=args.max_backlog_pages or None,
            scheduler=sched,
            prefix_cache=bool(args.prefix_cache),
            mesh=mesh,
            obs=obs,
            overlap=overlap_mode,
            round_group=overlap_group,
        )

    # Warm EVERY (decode-chunk tail x page bucket) program the workload
    # can reach, plus all prefill buckets — solo requests crafted per
    # combo. This matters more here than in bench_serve: arrivals are
    # sparse, so a request often decodes alone at a SMALL page bucket that
    # a concurrent warm trace would never touch, and one cold combo costs
    # ~1s on this host — enough to swamp a timed point's percentiles. The
    # jits are module-level, so every per-point engine dispatches warm.
    S = cfg.block_size
    # The warm engine runs prefix-enabled too (make_engine): the cache is
    # page-table indirection over the SAME program set — the grid below
    # stays exhaustive over the prefix-cache path with zero extra shapes,
    # and a warm run proving that is cheaper than trusting it.
    warm = None
    if not args.procs:
        warm = make_engine()
        _warm_compile_grid(
            warm, cfg, args.decode_chunk, args.page_size, args.seed
        )

    # --hot-swap: one verified checkpoint (training/checkpoint.py sha256
    # manifest) restored once; every point stages the same candidate, so
    # points stay comparable. Same shapes as the live params — the swap
    # must not compile anything (tests/test_recompile_pins.py pins it).
    swap_payload = None
    if args.hot_swap:
        import tempfile
        import types

        from midgpt_tpu.sampling.engine import restore_for_sampling
        from midgpt_tpu.training.checkpoint import CheckpointManager

        ckpt_dir = os.path.join(
            tempfile.mkdtemp(prefix="midgpt_loadgen_swap_"), "ckpt"
        )
        mgr = CheckpointManager(ckpt_dir, save_interval_steps=1)
        mgr.save(
            3, {"params": GPT.init(cfg, jax.random.PRNGKey(args.seed + 101))},
            force=True,
        )
        mgr.wait()
        swap_version = mgr.weights_version(3)
        mgr.close()
        shim = types.SimpleNamespace(
            model_config=cfg, fsdp_min_size=1 << 60, param_dtype="float32"
        )
        # Replicated restore (mesh=None — restore_for_sampling's mesh arg
        # wants a training fsdp mesh, not a serve mesh): stage_hot_swap
        # device_puts the candidate onto the LIVE params' shardings, which
        # re-shards it correctly for tp engines too.
        swap_params, _ = restore_for_sampling(ckpt_dir, shim)
        swap_payload = (swap_params, swap_version)

    # Shared prompt heads for the template mixture: ~3 pages each, built
    # once per seed (see _mixture on why once-per-seed matters).
    template_rng = np.random.default_rng(args.seed + 31)
    templates = [
        template_rng.integers(0, cfg.vocab_size, 3 * args.page_size, np.int64)
        for _ in range(args.n_templates)
    ] if args.template_frac > 0.0 else []

    points = []
    for pi, rate in enumerate(rates):
        point_rng = np.random.default_rng(args.seed + 1000 * pi)
        reqs = _mixture(
            point_rng, args.n_requests, S, cfg.vocab_size, args.long_frac,
            templates=templates, template_frac=args.template_frac,
        )
        arrivals = _arrivals(
            args.process, rate, args.n_requests, point_rng, args.burst_size
        )
        # One flight recorder per point: round decomposition percentiles
        # (dispatch / device_wait / host_post — docs/OBSERVABILITY.md) are
        # per-offered-load numbers, and a dumped trace must cover exactly
        # one point to be readable.
        obs = Observability()
        if args.fleet:
            from midgpt_tpu.sampling.fleet import (
                FleetRouter,
                assert_fleet_conserved,
            )

            if args.procs:
                # Fresh router per point (per-point ledger/counters) over
                # the PERSISTENT worker fleet: the workers' jit caches and
                # tries stay warm across points, like module-level jits do
                # for in-process replicas. Hit rate and wire bytes are
                # deltas over this point's drive; rpc percentiles are
                # transport-lifetime distributions.
                pm0 = sum(r._prefix_matched_tokens for r in proc_replicas)
                pa0 = sum(r._prefix_matchable_tokens for r in proc_replicas)
                router = FleetRouter(proc_replicas)
                wire0 = router.transport_stats()["wire_bytes"]
            else:
                # One recorder across the replicas (distinct tids): the
                # decomposition is a fleet-wide round picture for this
                # point.
                router = FleetRouter(
                    [
                        make_engine(obs, obs_tid=f"replica{k}")
                        for k in range(args.fleet)
                    ]
                )
            records = _drive_fleet_point(
                router, reqs, arrivals, args.ttl_s or None
            )
            assert_fleet_conserved(router, f"loadgen point {pi}")
            stats = _point_stats(
                rate, records, args.error_budget,
                args.slo_ttft_ms, args.slo_tpot_ms,
            )
            stats["fleet_size"] = args.fleet
            stats["failovers"] = router.failovers
            stats["spill_hits"] = router.spill.readopted
            if args.procs:
                pm1 = sum(r._prefix_matched_tokens for r in proc_replicas)
                pa1 = sum(r._prefix_matchable_tokens for r in proc_replicas)
                stats["prefix_hit_rate"] = round(
                    (pm1 - pm0) / max(pa1 - pa0, 1), 4
                )
                transport = router.transport_stats()
                stats["rpc_p50_ms"] = transport["rpc_p50_ms"]
                stats["rpc_p95_ms"] = transport["rpc_p95_ms"]
                stats["wire_bytes"] = transport["wire_bytes"] - wire0
                stats["proc_failovers"] = router.proc_failovers
            else:
                stats["prefix_hit_rate"] = round(router.prefix_hit_rate(), 4)
            decomp = obs.round_decomp()
            stats["rounds"] = decomp["rounds"]
            stats["round_host_ms"] = {
                "p50": round(
                    decomp["dispatch"]["p50_ms"]
                    + decomp["host_post"]["p50_ms"], 3
                ),
                "p95": round(
                    decomp["dispatch"]["p95_ms"]
                    + decomp["host_post"]["p95_ms"], 3
                ),
            }
            stats["round_device_ms"] = {
                "p50": decomp["device_wait"]["p50_ms"],
                "p95": decomp["device_wait"]["p95_ms"],
            }
            stats["overlap_mode"] = warm.overlap if warm else "off"
            stats["round_group"] = warm.round_group if warm else 1
            stats["overlap_hidden_ms"] = {
                "p50": decomp["overlap_hidden"]["p50_ms"],
                "p95": decomp["overlap_hidden"]["p95_ms"],
            }
            if args.trace_out:
                obs.dump(
                    args.trace_out,
                    filename=f"loadgen_point{pi}_r{rate:g}.json",
                )
            points.append(stats)
            continue
        engine = make_engine(obs)
        server = AsyncServeServer(engine, idle_poll_s=0.001)

        async def run_point():
            driver = asyncio.create_task(server.run())
            swapper = None
            if swap_payload is not None:
                # Stage mid-arrival-window (the median arrival): traffic
                # lands on both sides of the flip, so the point's
                # percentiles measure the swap's SLO cost, not a quiet
                # engine's.
                async def do_swap():
                    await asyncio.sleep(arrivals[len(arrivals) // 2])
                    await server.hot_swap(
                        swap_payload[0], version=swap_payload[1], config=cfg
                    )

                swapper = asyncio.create_task(do_swap())
            records = await _drive_point(
                server, reqs, arrivals, args.ttl_s or None
            )
            if swapper is not None:
                await swapper
            await server.drain()
            await driver
            return records

        records = asyncio.run(run_point())
        stats = _point_stats(
            rate, records, args.error_budget,
            args.slo_ttft_ms, args.slo_tpot_ms,
        )
        if swap_payload is not None:
            # The transition a metrics scrape would see on this point.
            stats["weights_version"] = engine.weights_version
            stats["hot_swaps"] = engine.hot_swaps
            stats["swap_flip_round"] = (
                engine.swap_history[-1]["flip_round"]
                if engine.swap_history else None
            )
        if args.prefix_cache:
            # Engine-side observability through the front door's stats()
            # passthrough — what a deployment's metrics scrape would read.
            stats["prefix_hit_rate"] = round(
                server.stats()["prefix"]["hit_rate"], 4
            )
        # Round timing decomposition, read the same way a deployment
        # would: through the stats() obs payload. host = dispatch (batch
        # assembly + jit enqueue) + host_post (token commit); device =
        # device_wait (enqueue -> array landed, the only tunnel-safe sync
        # point). Percentile sums are a summary convenience, not a joint
        # distribution claim.
        decomp = server.stats()["obs"]["round_decomp"]
        stats["rounds"] = decomp["rounds"]
        stats["round_host_ms"] = {
            "p50": round(
                decomp["dispatch"]["p50_ms"] + decomp["host_post"]["p50_ms"], 3
            ),
            "p95": round(
                decomp["dispatch"]["p95_ms"] + decomp["host_post"]["p95_ms"], 3
            ),
        }
        stats["round_device_ms"] = {
            "p50": decomp["device_wait"]["p50_ms"],
            "p95": decomp["device_wait"]["p95_ms"],
        }
        # round-overlap A/B identity (engine.round_group is the bucketed
        # value that actually ran) + the host time the overlap hid
        stats["overlap_mode"] = engine.overlap
        stats["round_group"] = engine.round_group
        stats["overlap_hidden_ms"] = {
            "p50": decomp["overlap_hidden"]["p50_ms"],
            "p95": decomp["overlap_hidden"]["p95_ms"],
        }
        if args.trace_out:
            obs.dump(args.trace_out, filename=f"loadgen_point{pi}_r{rate:g}.json")
        points.append(stats)

    worst = points[-1]  # rates ascending by convention: report the hottest
    print(
        json.dumps(
            {
                "bench": "serve_slo",
                # --procs: the workers' backend (the parent runs no engine)
                "backend": "cpu" if args.procs else jax.default_backend(),
                "process": args.process,
                "scheduler": args.scheduler,
                "seed": args.seed,
                "n_requests": args.n_requests,
                "long_frac": args.long_frac,
                "template_frac": args.template_frac or None,
                "prefix_cache": bool(args.prefix_cache),
                "ttl_s": args.ttl_s or None,
                "error_budget": args.error_budget,
                "slo_ttft_ms": args.slo_ttft_ms or None,
                "slo_tpot_ms": args.slo_tpot_ms or None,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": S,
                    # attention-variant provenance (docs/SERVING.md
                    # 'Attention variants'): a GQA or windowed curve has a
                    # different KV byte budget per slot than an MHA one
                    "n_kv_heads": cfg.kv_heads,
                    "kv_groups": cfg.kv_groups,
                    "sliding_window": cfg.sliding_window,
                    "attn_sinks": cfg.attn_sinks,
                },
                "max_slots": args.max_slots,
                "num_pages": args.num_pages,
                # sharding provenance: serve_slo lines from a tp-sharded
                # engine must not be comparable-by-accident with
                # single-chip curves (ServeEngine.stats() carries the same)
                "tp": args.tp or None,
                "mesh": warm.mesh_shape() if warm else None,
                "max_backlog_pages": args.max_backlog_pages or None,
                "points": points,
                # hottest-point headline numbers (driver contract fields)
                "ttft_p50_ms": worst["ttft_p50_ms"],
                "ttft_p95_ms": worst["ttft_p95_ms"],
                "tpot_p50_ms": worst["tpot_p50_ms"],
                "tpot_p95_ms": worst["tpot_p95_ms"],
                "shed_frac": worst["shed_frac"],
                "timeout_frac": worst["timeout_frac"],
                "round_host_ms": worst["round_host_ms"],
                "round_device_ms": worst["round_device_ms"],
                "overlap_mode": worst["overlap_mode"],
                "round_group": worst["round_group"],
                "overlap_hidden_ms": worst["overlap_hidden_ms"],
                "prefix_hit_rate": worst.get("prefix_hit_rate"),
                # --fleet: availability/affinity headline from the hottest
                # point (docs/ROBUSTNESS.md "Fleet serving & failover");
                # prefix_hit_rate above is then the FLEET-wide rate, the
                # number affinity routing exists to protect
                "fleet_size": args.fleet or None,
                "failovers": worst.get("failovers") if args.fleet else None,
                "spill_hits": worst.get("spill_hits") if args.fleet else None,
                # --procs: cross-process transport headline, hottest point
                # (docs/ROBUSTNESS.md "Cross-process fleet")
                "procs": bool(args.procs),
                "rpc_p50_ms": worst.get("rpc_p50_ms") if args.procs else None,
                "rpc_p95_ms": worst.get("rpc_p95_ms") if args.procs else None,
                "wire_bytes": worst.get("wire_bytes") if args.procs else None,
                # --hot-swap: the version transition every point rode
                # (docs/ROBUSTNESS.md 'Zero-downtime model ops'); slo_ok
                # below is then the "curve stays flat through the swap"
                # acceptance, with no special-casing.
                "weights_versions": (
                    ["inline", swap_payload[1]] if swap_payload else None
                ),
                "hot_swaps": (
                    sum(p.get("hot_swaps", 0) for p in points)
                    if swap_payload else None
                ),
                "slo_ok": bool(all(p["slo_ok"] for p in points)),
            }
        )
    )
    # --procs: explicit teardown of the worker fleet. Error paths need no
    # handling here — workers watch os.getppid() and self-exit when this
    # process dies (fleet_proc.run_worker's orphan check).
    if args.procs:
        import subprocess

        for rep in proc_replicas:
            rep.close()
        for proc, _port in worker_procs:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
