"""Fleet replica worker process (docs/ROBUSTNESS.md "Cross-process
fleet"). Spawned by `midgpt_tpu.sampling.fleet_proc.spawn_worker`: builds
one ServeEngine on its OWN CPU mesh (own jax backend, own jit cache, own
host-RAM SpillTier) and serves the framed socket protocol until drained
(SIGTERM -> preempt flag), told bye, orphaned, or SIGKILLed — the last
being the `proc_kill9` chaos gate's whole point.

Deliberately no `jax.distributed`: nothing here is a collective. Replicas
share no arrays; the only thing crossing the process boundary is plain
host data inside crc32-verified frames (tests/test_multiprocess.py pins
the env gap that makes real multi-process CPU collectives unavailable on
jax 0.4.37 — this worker is how the fleet scales out without them).

Stdout carries exactly one line ("PORT <n>") for the spawner; everything
diagnostic goes to stderr so a worker under a bench driver can never
pollute a one-line JSON stdout contract.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--spec-json",
        required=True,
        help="JSON spec: {model, seed, engine, cpu_devices, jax_config}",
    )
    ap.add_argument(
        "--port",
        type=int,
        default=0,
        help="listen port (default 0: ephemeral, announced on stdout)",
    )
    args = ap.parse_args()
    spec = json.loads(args.spec_json)

    # Platform pin BEFORE backend init (CLAUDE.md: JAX_PLATFORMS env is
    # ignored behind the axon tunnel — config.update is the only lever),
    # plus the parent's numerics knobs (fleet_proc.parent_jax_config) so
    # same-seed params match the router-side reference bit for bit.
    import jax

    from midgpt_tpu.utils.compat import set_cpu_device_count

    jax.config.update("jax_platforms", "cpu")
    set_cpu_device_count(int(spec.get("cpu_devices", 1)))
    for knob, value in spec.get("jax_config", {}).items():
        jax.config.update(knob, value)

    from midgpt_tpu.sampling.fleet_proc import run_worker

    def announce(port: int) -> None:
        print(f"PORT {port}", flush=True)

    run_worker(spec, port=args.port, announce=announce)


if __name__ == "__main__":
    main()
