"""Wrapper for `python -m midgpt_tpu.analysis` runnable straight from a
checkout (adds the repo root to sys.path, same convention as the other
tools/ entry points). All arguments pass through; see docs/ANALYSIS.md.

    python tools/graftcheck.py [paths...] [--json] [--audit] [--rules ...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from midgpt_tpu.analysis.__main__ import main

if __name__ == "__main__":
    sys.exit(main())
