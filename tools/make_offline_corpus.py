"""Assemble an offline text corpus from files already on the machine.

Air-gapped TPU hosts (zero egress — this environment) cannot download
tiny-shakespeare or openwebtext, but they carry hundreds of MB of
English-adjacent text: package documentation, READMEs, and source code.
This tool walks a set of roots, concatenates every text-like file (sorted
paths — deterministic), and writes one UTF-8 corpus file that
`data/shakespeare_char/prepare.py --input` can tokenize.

Not a replacement for a real web corpus — a way to exercise the full
prepare → train → eval → sample pipeline at scale with genuinely
non-random data when the canonical datasets are unreachable.

Usage:
    python tools/make_offline_corpus.py --out outputs/corpus.txt \
        [--roots DIR ...] [--max-mb 400]
"""

from __future__ import annotations

import argparse
import os
import sys

TEXT_EXTS = (".py", ".md", ".rst", ".txt")
SEP = "\n\n"


def default_roots() -> list[str]:
    roots = []
    try:
        import site

        roots += site.getsitepackages()
    except Exception:
        pass
    for r in ("/usr/share/doc",):
        if os.path.isdir(r):
            roots.append(r)
    return roots


def iter_files(roots: list[str]):
    for root in roots:
        for dirpath, dirs, files in os.walk(root):
            dirs.sort()
            for f in sorted(files):
                if f.endswith(TEXT_EXTS):
                    yield os.path.join(dirpath, f)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", type=str, required=True)
    parser.add_argument("--roots", type=str, nargs="*", default=None)
    parser.add_argument("--max-mb", type=float, default=400.0)
    parser.add_argument(
        "--max-file-kb", type=float, default=1024.0,
        help="skip files bigger than this (generated/bundled blobs)",
    )
    args = parser.parse_args()

    roots = args.roots or default_roots()
    budget = int(args.max_mb * 1e6)
    written = 0
    n_files = 0
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as out:
        for path in iter_files(roots):
            if written >= budget:
                break
            try:
                if os.path.getsize(path) > args.max_file_kb * 1024:
                    continue
                with open(path, "r", encoding="utf-8", errors="ignore") as f:
                    text = f.read()
            except OSError:
                continue
            # Char-level models want a small vocab: bundled docs carry long
            # tails of CJK/symbol codepoints that would explode it (and the
            # uint16 token format caps vocab at 65536).
            text = text.encode("ascii", errors="ignore").decode("ascii")
            if not text.strip():
                continue
            out.write(text)
            out.write(SEP)
            written += len(text) + len(SEP)
            n_files += 1
    print(f"{args.out}: {written / 1e6:.1f} MB from {n_files} files ({len(roots)} roots)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
