"""Decode throughput bench: KV-cached generation on the real chip.

Measures ms/token of the sampling engine's chunked decode
(sampling/engine.py) on the 124M shape with random bf16 weights —
the RESULTS.md inference table's methodology — plus an estimated
KV-cache HBM bytes/token column so cache-dtype wins are attributable:
decode is HBM-bandwidth-bound, and the cache read is the dominant stream,
so ms/token should track this column across dtypes far more closely than
it tracks FLOPs.

Two cache paths:

  * contiguous (default, `--kv_dtype bf16`): the fixed-batch engine's
    (L, B, H, S, C) cache — its attention reads the FULL block_size of
    keys per token (masked), so the traffic estimate uses S, not the
    used length.
  * paged (`--paged`, implied by `--kv_dtype int8` — the quantized mode
    exists only in the paged pool): B slots decoding through
    `sampling/serve._serve_decode_chunk` against a dedicated page table,
    bf16 or int8 pages. Reads are O(used length) through the page table.

Usage: python tools/bench_decode.py [--batch 8] [--tokens 512]
           [--prompt 128] [--kv_dtype bf16|int8] [--paged]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

PAGE_SIZE = 8


def est_kv_bytes_per_token(cfg, kv_dtype: str, read_len: int) -> int:
    """Estimated KV-cache HBM traffic per generated token: read `read_len`
    cached K+V positions + write one, all layers/heads; int8 adds the f32
    scale side-buffer stream (4 bytes per position per head per K/V —
    4/head_dim of the int8 page bytes, ops/quant.py)."""
    per_pos = 2 * cfg.n_layer * cfg.n_head * cfg.head_dim  # K+V elements
    item = 1 if kv_dtype == "int8" else 2
    traffic = per_pos * (read_len + 1) * item
    if kv_dtype == "int8":
        traffic += 2 * cfg.n_layer * cfg.n_head * 4 * (read_len + 1)
    return traffic


def _paged_bench(args, cfg, params, kv_dtype: str) -> float:
    """ms/token of the serve engine's batched paged decode chunk with every
    slot active — decode-loop cost only (no prefill: the pages hold zeros,
    which is fine for a throughput bench; values don't change the math's
    cost, and sampling is greedy so the token stream is just replayed
    through the embedding)."""
    from midgpt_tpu.models.gpt import PagedKVCache
    from midgpt_tpu.sampling.serve import _serve_decode_chunk

    B, ps = args.batch, PAGE_SIZE
    total = args.prompt + args.tokens
    pages_per_slot = -(-total // ps)
    num_pages = 1 + B * pages_per_slot
    dtype = jnp.int8 if kv_dtype == "int8" else jnp.bfloat16
    cache = PagedKVCache.init(cfg, num_pages, ps, dtype=dtype)
    table = np.zeros((B, pages_per_slot), np.int32)
    for b in range(B):
        table[b] = 1 + b * pages_per_slot + np.arange(pages_per_slot)
    table = jnp.asarray(table)
    active = jnp.ones((B,), bool)
    chunk = 8

    def run(n_tokens, cache, start_len):
        tok = jnp.zeros((B,), jnp.int32)
        lengths = start_len
        for _ in range(n_tokens // chunk):
            cache, toks = _serve_decode_chunk(
                cfg, params, tok, cache, table,  # graftcheck: disable=GC011 — bench CLI: geometry is fixed per process by argparse; one compile per run is the measured artifact
                jnp.full((B,), lengths, jnp.int32), active,
                chunk, 0.0, None, None, "auto", None, None, args.split_k,  # graftcheck: disable=GC011 — bench CLI: split_k is the swept argparse knob; each value compiles once by design
            )
            tok = toks[-1]
            lengths += chunk
        float(tok.ravel()[0].astype(jnp.float32))  # force (CLAUDE.md sync)
        return cache

    cache = run(min(64, args.tokens), cache, args.prompt)  # warm compile
    t0 = time.perf_counter()
    run(args.tokens, cache, args.prompt)
    dt = time.perf_counter() - t0
    return 1000 * dt / args.tokens


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--tokens", type=int, default=512)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--top-k", type=int, default=50)
    p.add_argument("--kv_dtype", choices=("bf16", "int8"), default="bf16",
                   help="KV cache storage dtype; int8 implies --paged "
                   "(the contiguous cache has no quantized mode)")
    p.add_argument("--paged", action="store_true",
                   help="bench the paged serve decode chunk instead of the "
                   "contiguous engine (required to compare dtypes on the "
                   "same code path)")
    p.add_argument("--split-k", type=int, default=1,
                   help="key-sequence partitions per attention call (paged "
                   "path only; normalized to a pow2 divisor of the table "
                   "width — docs/SERVING.md 'Split-K decode')")
    args = p.parse_args()
    if args.split_k != 1:
        args.paged = True
    if args.kv_dtype == "int8":
        args.paged = True

    from midgpt_tpu.configs.openwebtext import config as base
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.sampling.engine import generate

    cfg = base.model_config
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )

    if args.paged:
        ms_tok = _paged_bench(args, cfg, params, args.kv_dtype)
        # paged attention reads O(used length): mean over the run
        read_len = args.prompt + args.tokens // 2
        est = est_kv_bytes_per_token(cfg, args.kv_dtype, read_len)
        tag = f",split{args.split_k}" if args.split_k != 1 else ""
        print(
            f"decode[paged,{args.kv_dtype}{tag}]: {ms_tok:.2f} ms/token  "
            f"({1000 * args.batch / ms_tok:,.0f} tok/s total, batch "
            f"{args.batch}, prompt {args.prompt}, {args.tokens} new)  "
            f"est_kv_bytes/token={est:,} (per slot, mean len {read_len})"
        )
        return 0

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (args.batch, args.prompt), dtype=np.int32)

    # warmup: 128 new tokens decompose as 1 (prefill) + 64+32+16+8+4+2+1 —
    # every power-of-two chunk length the engine can dispatch, so no XLA
    # compile can land inside the timed region below.
    out = generate(
        cfg, params, prompt, 128, top_k=args.top_k, key=jax.random.PRNGKey(1)
    )
    float(out.ravel()[0].astype(jnp.float32))

    t0 = time.perf_counter()
    out = generate(
        cfg, params, prompt, args.tokens, top_k=args.top_k,
        key=jax.random.PRNGKey(2),
    )
    float(out.ravel()[0].astype(jnp.float32))
    dt = time.perf_counter() - t0
    ms_tok = 1000 * dt / args.tokens
    # the contiguous cache's attention reads the FULL (masked) block_size
    est = est_kv_bytes_per_token(cfg, args.kv_dtype, cfg.block_size)
    print(
        f"decode[contiguous,{args.kv_dtype}]: {ms_tok:.2f} ms/token  "
        f"({args.batch * args.tokens / dt:,.0f} tok/s total, batch "
        f"{args.batch}, prompt {args.prompt}, {args.tokens} new)  "
        f"est_kv_bytes/token={est:,} (per slot, full S={cfg.block_size})"
    )
    return 0


if __name__ == "__main__":
    main()
