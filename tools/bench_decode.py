"""Decode throughput bench: KV-cached generation on the real chip.

Measures ms/token of the sampling engine's chunked decode
(sampling/engine.py) on the 124M shape with random bf16 weights —
the RESULTS.md inference table's methodology.

Usage: python tools/bench_decode.py [--batch 8] [--tokens 512] [--prompt 128]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--tokens", type=int, default=512)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--top-k", type=int, default=50)
    args = p.parse_args()

    from midgpt_tpu.configs.openwebtext import config as base
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.sampling.engine import generate

    cfg = base.model_config
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (args.batch, args.prompt), dtype=np.int32)

    # warmup: 128 new tokens decompose as 1 (prefill) + 64+32+16+8+4+2+1 —
    # every power-of-two chunk length the engine can dispatch, so no XLA
    # compile can land inside the timed region below.
    out = generate(
        cfg, params, prompt, 128, top_k=args.top_k, key=jax.random.PRNGKey(1)
    )
    float(out.ravel()[0].astype(jnp.float32))

    t0 = time.perf_counter()
    out = generate(
        cfg, params, prompt, args.tokens, top_k=args.top_k,
        key=jax.random.PRNGKey(2),
    )
    float(out.ravel()[0].astype(jnp.float32))
    dt = time.perf_counter() - t0
    ms_tok = 1000 * dt / args.tokens
    print(
        f"decode: {ms_tok:.2f} ms/token  "
        f"({args.batch * args.tokens / dt:,.0f} tok/s total, batch "
        f"{args.batch}, prompt {args.prompt}, {args.tokens} new)"
    )
    return 0


if __name__ == "__main__":
    main()
