"""Continuous-batching serving bench: one JSON line (driver contract).

Runs a seeded synthetic mixed-length request trace twice through each mode —
the first pass warms every jit shape (compile time is not a serving-rate
claim), the second is timed:

  * continuous — sampling/serve.py ServeEngine: paged KV cache, chunked
    prefill interleaved with batched decode, admission the moment a slot
    frees.
  * sequential — the fixed-batch engine.generate, one request at a time
    (what the pre-serving repo could do for a stream of arriving requests).

Reported: aggregate tokens/sec for both modes (the ISSUE acceptance is
continuous > sequential), p50/p99 per-token latency and mean TTFT for the
continuous run (chunk-granular: a decode chunk's n tokens each count
gap/n), and the HBM high-water of each mode's cache (analytic bytes — the
paged pool vs the per-request contiguous cache — plus the device allocator
peak when the backend exposes one; per CLAUDE.md, wall-clock through the
TPU tunnel is untrustworthy below many iterations, so treat the CPU-mesh
numbers as scheduling-structure signal, not kernel-speed signal).

    python tools/bench_serve.py [--n-requests 12] [--max-slots 4] ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-requests", type=int, default=12)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--decode-chunk", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=256)
    ap.add_argument("--vocab-size", type=int, default=512)
    ap.add_argument("--n-layer", type=int, default=4)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--n-embd", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force CPU with this many virtual devices (0 = native backend)")
    args = ap.parse_args()

    import jax

    if args.cpu_devices:
        from midgpt_tpu.utils.compat import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)

    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.models.gpt import GPT, GPTConfig, KVCache
    from midgpt_tpu.sampling.engine import generate
    from midgpt_tpu.sampling.serve import ServeEngine

    on_tpu = jax.default_backend() == "tpu"
    cfg = GPTConfig(
        block_size=args.block_size,
        vocab_size=args.vocab_size,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
    )
    params = GPT.init(cfg, jax.random.PRNGKey(args.seed))
    if on_tpu:
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    cache_dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # Mixed-length trace: short chat-y prompts to near-context documents.
    rng = np.random.default_rng(args.seed)
    S = cfg.block_size
    trace = []
    for _ in range(args.n_requests):
        t0 = int(rng.integers(4, max(5, S // 2)))
        m = int(rng.integers(8, max(9, min(64, S - t0))))
        trace.append((rng.integers(0, cfg.vocab_size, t0, dtype=np.int64), m))
    total_new = sum(m for _, m in trace)

    def run_continuous():
        eng = ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=cache_dtype,
        )
        for prompt, m in trace:
            eng.submit(prompt, m)
        t0 = time.perf_counter()
        done = eng.run()
        # Force everything to host (np conversion happened per chunk already).
        dt = time.perf_counter() - t0
        return eng, done, dt, t0

    def run_sequential():
        t0 = time.perf_counter()
        outs = [
            generate(cfg, params, jnp.asarray(p, jnp.int32)[None], m, temperature=0.0)
            for p, m in trace
        ]
        outs = [np.asarray(o) for o in outs]  # force
        return time.perf_counter() - t0

    run_continuous()  # warm every prefill/decode-chunk shape
    eng, done, dt_cont, t_start = run_continuous()
    run_sequential()  # warm per-prompt-length prefills + decode chunks
    dt_seq = run_sequential()

    # Per-token latency at chunk granularity: a chunk of n tokens landing
    # gap seconds after the previous event costs gap/n per token. TTFT is
    # the first token's time after engine start.
    lat, ttft = [], []
    for fr in done.values():
        ts = np.asarray(fr.token_times)
        ttft.append(ts[0] - t_start)
        edges = np.flatnonzero(np.diff(ts) > 0) + 1
        groups = np.split(ts, edges)
        prev = ts[0]
        for g in groups[1:]:
            lat.extend([(g[0] - prev) / len(g)] * len(g))
            prev = g[0]
    lat = np.asarray(lat) if lat else np.zeros(1)

    # HBM high-water of the caches (analytic; allocator peak if exposed).
    paged_bytes = eng.cache_hbm_bytes()
    itemsize = jnp.dtype(cache_dtype).itemsize
    contiguous_bytes = (
        2 * cfg.n_layer * cfg.n_head * S * cfg.head_dim * itemsize
    )  # per-request KVCache the sequential engine allocates
    try:
        peak = jax.local_devices()[0].memory_stats().get("peak_bytes_in_use")
    except Exception:
        peak = None

    print(
        json.dumps(
            {
                "bench": "serve",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "num_pages": eng.allocator.num_pages,
                "prefill_chunk": args.prefill_chunk,
                "decode_chunk": args.decode_chunk,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": S,
                },
                "continuous_tok_s": round(total_new / dt_cont, 2),
                "sequential_tok_s": round(total_new / dt_seq, 2),
                "speedup": round(dt_seq / dt_cont, 3),
                "p50_token_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                "p99_token_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
                "ttft_ms_mean": round(float(np.mean(ttft)) * 1e3, 3),
                "hbm_paged_cache_bytes": int(paged_bytes),
                "hbm_sequential_cache_bytes": int(contiguous_bytes),
                "device_peak_bytes_in_use": peak,
                # Compiled-program census (ServeEngine.compile_stats): the
                # "request churn never recompiles" claim as a number drivers
                # can watch for drift (schema: analysis/bench_contract.py).
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
