"""Continuous-batching serving bench: one JSON line (driver contract).

Runs a seeded synthetic mixed-length request trace twice through each mode —
the first pass warms every jit shape (compile time is not a serving-rate
claim), the second is timed:

  * continuous — sampling/serve.py ServeEngine: paged KV cache, chunked
    prefill interleaved with batched decode, admission the moment a slot
    frees.
  * sequential — the fixed-batch engine.generate, one request at a time
    (what the pre-serving repo could do for a stream of arriving requests).

Reported: aggregate tokens/sec for both modes (the ISSUE acceptance is
continuous > sequential), p50/p99 per-token latency and mean TTFT for the
continuous run (chunk-granular: a decode chunk's n tokens each count
gap/n), and the HBM high-water of each mode's cache (analytic bytes — the
paged pool vs the per-request contiguous cache — plus the device allocator
peak when the backend exposes one; per CLAUDE.md, wall-clock through the
TPU tunnel is untrustworthy below many iterations, so treat the CPU-mesh
numbers as scheduling-structure signal, not kernel-speed signal). The
timed continuous run carries a flight recorder (midgpt_tpu/obs/): the
line reports `round_host_ms`/`round_device_ms` p50/p95 — the decode-round
host-vs-device split — plus `overlap_mode`/`round_group`/
`overlap_hidden_ms` (the round-overlap dispatch A/B identity, driven by
`--overlap {off,double,group:k}`), and `--trace-out DIR` dumps the
Chrome trace.

    python tools/bench_serve.py [--n-requests 12] [--max-slots 4] ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _quick_train(cfg, params, steps: int, seed: int):
    """Fit the synthetic model to a noisy Markov stream (x_{t+1} =
    perm[x_t] with prob 0.85, else uniform) for a handful of Adam steps.

    The spec bench needs a model whose early layers AGREE with its full
    stack — on random init the self-draft's greedy agreement is ~40%
    (measured, RESULTS.md §5), an artifact of the init, not a property of
    speculation. A lightly-fitted model is the honest testbed: draft and
    target both approximate the data distribution, which is exactly the
    regime speculative decoding is built for."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from midgpt_tpu.models.gpt import GPT

    V = cfg.vocab_size
    rng = np.random.default_rng(seed + 1)
    perm = rng.permutation(V)

    def batch(n, T):
        x = np.zeros((n, T + 1), np.int64)
        x[:, 0] = rng.integers(0, V, n)
        for t in range(T):
            nxt = perm[x[:, t]]
            noise = rng.random(n) < 0.15
            x[:, t + 1] = np.where(noise, rng.integers(0, V, n), nxt)
        return jnp.asarray(x[:, :-1], jnp.int32), jnp.asarray(x[:, 1:], jnp.int32)

    opt = optax.adam(3e-3)
    ostate = opt.init(params)

    @jax.jit
    def step(params, ostate, x, y):
        def loss_fn(p):
            logits = GPT.apply(cfg, p, x, inference=True).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        up, ostate = opt.update(g, ostate)
        return optax.apply_updates(params, up), ostate, loss

    T = min(64, cfg.block_size)
    loss = None
    for _ in range(steps):
        x, y = batch(8, T)
        params, ostate, loss = step(params, ostate, x, y)
    return params, (0.0 if loss is None else float(loss))


def _latency_stats(done, t_start):
    """Per-token latency (chunk-granular), per-request TTFT and per-request
    tok/s from the finished map. A chunk of n tokens landing gap seconds
    after the previous event costs gap/n per token; a request's tok/s is
    its generated tokens over its total residency (queueing included — the
    user-visible rate)."""
    import numpy as np

    lat, ttft, req_rate = [], [], []
    for fr in done.values():
        ts = np.asarray(fr.token_times)
        if ts.size == 0:
            continue
        ttft.append(ts[0] - t_start)
        span = max(ts[-1] - t_start, 1e-9)
        req_rate.append(len(ts) / span)
        edges = np.flatnonzero(np.diff(ts) > 0) + 1
        groups = np.split(ts, edges)
        prev = ts[0]
        for g in groups[1:]:
            lat.extend([(g[0] - prev) / len(g)] * len(g))
            prev = g[0]
    lat = np.asarray(lat) if lat else np.zeros(1)
    ttft = np.asarray(ttft) if ttft else np.zeros(1)
    req_rate = np.asarray(req_rate) if req_rate else np.zeros(1)
    return lat, ttft, req_rate


def _greedy_match_frac(done_a, done_b, trace_uids) -> float:
    """Fraction of generated-token positions where two greedy runs of the
    same trace agree — the int8-vs-bf16 accuracy number (docs/SERVING.md
    'Quantized KV cache': on the quick-fitted bench model expect >= 0.99;
    on an UNTRAINED model near-uniform logits make argmax fragile under
    any perturbation, so a raw-init match fraction is meaningless)."""
    import numpy as np

    match = total = 0
    for uid, prompt_len in trace_uids:
        a = np.asarray(done_a[uid].tokens)[prompt_len:]
        b = np.asarray(done_b[uid].tokens)[prompt_len:]
        n = min(len(a), len(b))
        match += int(np.sum(a[:n] == b[:n]))
        total += max(len(a), len(b))
    return match / max(total, 1)


def _spec_bench(args, cfg, params, cache_dtype, trace, total_new) -> int:
    """--spec mode: speculative vs plain continuous engine, one JSON line
    ('serve_spec' profile, analysis/bench_contract.py)."""
    import jax

    from midgpt_tpu.sampling.serve import ServeEngine
    from midgpt_tpu.sampling.spec import self_draft

    draft_layers = args.spec_draft_layers or max(1, cfg.n_layer // 3)
    params, final_loss = _quick_train(cfg, params, args.train_steps, args.seed)
    draft_cfg, draft_params = self_draft(cfg, params, draft_layers)

    def run(draft):
        eng = ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=cache_dtype,
            draft_params=draft_params if draft else None,
            draft_config=draft_cfg if draft else None,
            draft_shares_cache=draft,  # self-draft: prefix layers share the pool
            spec_k_max=args.spec_k,
        )
        for prompt, m in trace:
            eng.submit(prompt, m)
        t0 = time.perf_counter()
        eng.run()
        return eng, time.perf_counter() - t0

    run(draft=False)  # warm the plain prefill/decode shapes
    _, dt_base = run(draft=False)
    run(draft=True)  # warm draft prefill + each (k, page) bucket
    eng_spec, dt_spec = run(draft=True)
    stats = eng_spec.spec_stats()

    print(
        json.dumps(
            {
                "bench": "serve_spec",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "draft_layers": draft_layers,
                "spec_k_max": args.spec_k,
                "train_steps": args.train_steps,
                "train_loss": round(final_loss, 3),
                "baseline_tok_s": round(total_new / dt_base, 2),
                "spec_tok_s": round(total_new / dt_spec, 2),
                "speedup_spec": round(dt_base / dt_spec, 3),
                "accept_rate": round(stats["accept_rate"], 4),
                "tokens_per_verify": round(stats["tokens_per_verify"], 3),
                "kv_dtype": args.kv_dtype,
                "cache_hbm_bytes": int(eng_spec.cache_hbm_bytes()),
                "hbm_target_cache_bytes": int(eng_spec.cache_hbm_bytes()),
                # 0: the prefix self-draft rides the target pool's first
                # n_draft layers — speculation costs no extra cache HBM
                "hbm_draft_cache_bytes": 0
                if eng_spec.draft_cache is None
                else int(
                    eng_spec.draft_cache.k.nbytes + eng_spec.draft_cache.v.nbytes
                ),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _tp_bench(args, cfg, params, trace, total_new) -> int:
    """--tp mode: single-chip vs tensor-parallel mesh-sharded engine on the
    same greedy trace, one pass per cache mode — base dtype, int8, and
    self-draft speculation ('serve_tp' profile, analysis/bench_contract.py).

    The headline numbers are match_f32/match_int8/match_spec, each required
    EXACTLY 1.0: the tp engine shards head-aligned einsums whose megatron
    all-reduce restores the same f32 partials the single chip computes, so
    sharding must be bit-invisible to the token streams, the invariant
    tests/test_tp_serving.py pins per mode (the quick fit is belt-and-braces
    — parity holds on raw init too, but a fitted model makes the match
    robust to any future near-tie in the argmax). Per-shard HBM
    is reported because the pool is sharded on the head axis: each of the
    tp shards holds cache_hbm_bytes / tp, which is THE capacity lever tp
    serving buys (docs/SERVING.md 'Mesh-sharded serving')."""
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.parallel.serve_tp import make_serve_mesh
    from midgpt_tpu.sampling.serve import ServeEngine
    from midgpt_tpu.sampling.spec import self_draft

    n_dev = len(jax.devices())
    if args.tp < 2 or args.tp > n_dev:
        raise SystemExit(f"--tp {args.tp} needs 2 <= tp <= {n_dev} devices")
    if cfg.n_head % args.tp:
        raise SystemExit(f"--tp {args.tp} must divide n_head {cfg.n_head}")
    params, final_loss = _quick_train(cfg, params, args.train_steps, args.seed)
    mesh = make_serve_mesh(tp_size=args.tp)
    base_dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    draft_layers = args.spec_draft_layers or max(1, cfg.n_layer // 3)
    draft_cfg, draft_params = self_draft(cfg, params, draft_layers)

    def run(mesh_arg, mode):
        kw = {}
        if mode == "spec":
            kw = dict(
                draft_params=draft_params,
                draft_config=draft_cfg,
                draft_shares_cache=True,
                spec_k_max=args.spec_k,
            )
        eng = ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype="int8" if mode == "int8" else base_dtype,
            mesh=mesh_arg,
            **kw,
        )
        uids = [(eng.submit(p, m), len(p)) for p, m in trace]
        t0 = time.perf_counter()
        done = eng.run()
        return eng, done, time.perf_counter() - t0, uids

    fields = {}
    engines = {}
    for mode in ("f32", "int8", "spec"):
        run(None, mode)  # warm the single-chip shapes for this mode
        _, done_s, dt_s, uids = run(None, mode)
        run(mesh, mode)  # warm the tp-sharded shapes
        eng_tp, done_t, dt_t, _ = run(mesh, mode)
        engines[mode] = eng_tp
        fields[f"match_{mode}"] = round(
            _greedy_match_frac(done_s, done_t, uids), 4
        )
        fields[f"single_tok_s_{mode}"] = round(total_new / dt_s, 2)
        fields[f"tp_tok_s_{mode}"] = round(total_new / dt_t, 2)

    eng = engines["f32"]
    shard = int(eng.cache_hbm_bytes_per_shard())
    print(
        json.dumps(
            {
                "bench": "serve_tp",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "tp": args.tp,
                "n_devices": n_dev,
                "mesh": eng.mesh_shape(),
                "base_dtype": str(jnp.dtype(base_dtype)),
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "train_steps": args.train_steps,
                "train_loss": round(final_loss, 3),
                "draft_layers": draft_layers,
                "spec_k_max": args.spec_k,
                **fields,
                "num_pages": eng.allocator.num_pages,
                "int8_num_pages": engines["int8"].allocator.num_pages,
                # head-axis sharding: each shard holds exactly total/tp —
                # the contract checker re-derives both from the totals
                "cache_hbm_bytes": int(eng.cache_hbm_bytes()),
                "cache_hbm_bytes_per_shard": shard,
                "hbm_per_slot_per_shard_bytes": shard // args.max_slots,
                "int8_cache_hbm_bytes_per_shard": int(
                    engines["int8"].cache_hbm_bytes_per_shard()
                ),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _prefix_bench(args, cfg, params, cache_dtype) -> int:
    """--shared-prefix-frac mode: template-heavy workload (N shared system
    prompts x unique tails, plus exact-duplicate resubmissions that
    exercise the copy-on-write truncation path) through the SAME engine
    twice — prefix cache off, then on, at the same page budget. Emits the
    'serve_prefix' JSON profile (analysis/bench_contract.py): the headline
    numbers are prefix_hit_rate, the TTFT collapse (template prefill
    skipped), and greedy_match_frac, which must be EXACTLY 1.0 — shared
    pages hold bit-identical K/V to privately prefilled ones, so sharing
    is invisible to the streams (tests/test_prefix_cache.py pins this per
    cache mode)."""
    import jax
    import numpy as np

    from midgpt_tpu.sampling.serve import ServeEngine

    rng = np.random.default_rng(args.seed)
    V = cfg.vocab_size
    n_templates = args.prefix_templates
    t_len = args.template_tokens or 5 * args.page_size
    if t_len + 16 + 12 > cfg.block_size:
        raise SystemExit(
            f"--template-tokens {t_len} leaves no room for tails in "
            f"block_size {cfg.block_size}"
        )
    templates = [
        rng.integers(0, V, t_len, dtype=np.int64) for _ in range(n_templates)
    ]
    trace = []
    for i in range(args.n_requests):
        m = int(rng.integers(8, 13))
        if rng.random() < args.shared_prefix_frac:
            if trace and rng.random() < 0.25:
                # exact duplicate of an earlier templated prompt (a retried
                # query): its first post-template page prefix-matches a trie
                # page, so the capped match reports a COW truncation
                prompt = trace[rng.integers(0, len(trace))][0]
                while len(prompt) <= t_len:  # ensure it IS a templated one
                    prompt = trace[rng.integers(0, len(trace))][0]
            else:
                tail = rng.integers(
                    0, V, int(rng.integers(3, 9)), dtype=np.int64
                )
                prompt = np.concatenate([templates[i % n_templates], tail])
        else:
            prompt = rng.integers(
                0, V, int(rng.integers(4, 11)), dtype=np.int64
            )
        trace.append((prompt, m))
    total_new = sum(m for _, m in trace)
    pool_kw = (
        {"pool_hbm_bytes": args.pool_hbm_bytes} if args.pool_hbm_bytes else {}
    )

    def run(prefix_on):
        eng = ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=cache_dtype,
            prefix_cache=prefix_on,
            **pool_kw,
        )
        uids = [(eng.submit(p, m), len(p)) for p, m in trace]
        t0 = time.perf_counter()
        done = eng.run()
        return eng, done, time.perf_counter() - t0, t0, uids

    run(False)  # warm every jit shape (a fresh engine per run: cold trie)
    eng_off, done_off, dt_off, t0_off, uids = run(False)
    eng_on, done_on, dt_on, t0_on, _ = run(True)
    _, ttft_off, _ = _latency_stats(done_off, t0_off)
    _, ttft_on, _ = _latency_stats(done_on, t0_on)
    st = eng_on.prefix_stats()

    print(
        json.dumps(
            {
                "bench": "serve_prefix",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "kv_dtype": args.kv_dtype,
                "num_pages": eng_on.allocator.num_pages,
                "pool_hbm_bytes": args.pool_hbm_bytes or None,
                "shared_prefix_frac": args.shared_prefix_frac,
                "n_templates": n_templates,
                "template_tokens": t_len,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "baseline_tok_s": round(total_new / dt_off, 2),
                "prefix_tok_s": round(total_new / dt_on, 2),
                "speedup_prefix": round(dt_off / dt_on, 3),
                "baseline_ttft_ms_p50": round(
                    float(np.percentile(ttft_off, 50)) * 1e3, 3
                ),
                "baseline_ttft_ms_p95": round(
                    float(np.percentile(ttft_off, 95)) * 1e3, 3
                ),
                "prefix_ttft_ms_p50": round(
                    float(np.percentile(ttft_on, 50)) * 1e3, 3
                ),
                "prefix_ttft_ms_p95": round(
                    float(np.percentile(ttft_on, 95)) * 1e3, 3
                ),
                "prefix_hit_rate": round(st["hit_rate"], 4),
                "cow_pages": st["cow_pages"],
                "baseline_prefill_tokens": eng_off.prefilled_tokens,
                "prefix_prefill_tokens": eng_on.prefilled_tokens,
                "baseline_preemptions": eng_off.preemptions,
                "prefix_preemptions": eng_on.preemptions,
                "trie_pages": st["trie_pages"],
                "reclaimed_pages": st["reclaimed_pages"],
                # exact by construction: shared pages ARE the pages a
                # private prefill of the same tokens would have written
                "greedy_match_frac": round(
                    _greedy_match_frac(done_off, done_on, uids), 4
                ),
                "cache_hbm_bytes": int(eng_on.cache_hbm_bytes()),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _gqa_bench(args, cfg, cache_dtype) -> int:
    """--gqa mode: KV-bytes capacity A/B ('serve_gqa' profile,
    analysis/bench_contract.py; docs/SERVING.md 'Attention variants').

    The same mixed-length greedy trace runs through an MHA engine and a
    GQA engine (n_kv_heads = n_head / G, optionally + sliding window) at
    the SAME fixed pool_hbm_bytes. A GQA page is G-fold smaller
    (PagedKVCache.page_bytes), so the byte budget admits G-fold more
    pages — which converts into admissible slots and strictly fewer
    recompute preemptions on an oversubscribed trace. Each variant's
    streams are compared against engine.generate on its OWN params
    (different projection layouts are different models — cross-variant
    token equality would be meaningless); both match fractions must be
    EXACTLY 1.0: paged reads are bit-identical to dense-cache reads per
    variant, so capacity is the only thing the A/B varies."""
    import collections
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
    from midgpt_tpu.sampling.engine import generate
    from midgpt_tpu.sampling.serve import ServeEngine

    G = args.gqa
    if cfg.n_head % G:
        raise SystemExit(f"--gqa {G} does not divide n_head={cfg.n_head}")
    gqa_cfg = _dc.replace(
        cfg,
        n_kv_heads=cfg.n_head // G,
        sliding_window=args.sliding_window,
        attn_sinks=args.attn_sinks,
    )

    rng = np.random.default_rng(args.seed)
    S = cfg.block_size
    trace = []
    for _ in range(args.n_requests):
        t0 = int(rng.integers(4, max(5, S // 2)))
        m = int(rng.integers(8, max(9, min(64, S - t0))))
        trace.append((rng.integers(0, cfg.vocab_size, t0, dtype=np.int64), m))
    total_new = sum(m for _, m in trace)
    ps = args.page_size
    req_pages = [-(-(len(p) + m) // ps) for p, m in trace]

    # Fixed byte budget, the independent variable: default sizes the MHA
    # pool to ~1/3 of the trace's worst-case page demand (but always at
    # least the largest single request), so the MHA side oversubscribes
    # and preempts while GQA's G-fold page count absorbs the same trace.
    mha_page_bytes = PagedKVCache.page_bytes(cfg, ps, cache_dtype)
    pool_hbm_bytes = args.pool_hbm_bytes or mha_page_bytes * (
        1 + max(max(req_pages), sum(req_pages) // 3)
    )

    Ref = collections.namedtuple("Ref", "tokens")

    def run(vcfg):
        params = GPT.init(vcfg, jax.random.PRNGKey(args.seed))
        if jax.default_backend() == "tpu":
            params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)

        def once():
            eng = ServeEngine(
                vcfg,
                params,
                max_slots=args.max_slots,
                page_size=ps,
                prefill_chunk=args.prefill_chunk,
                decode_chunk=args.decode_chunk,
                temperature=0.0,
                cache_dtype=cache_dtype,
                pool_hbm_bytes=pool_hbm_bytes,
            )
            uids = [(eng.submit(p, m), len(p)) for p, m in trace]
            t0 = time.perf_counter()
            done = eng.run()
            return eng, done, time.perf_counter() - t0, uids

        once()  # warm the variant's jit shapes
        eng, done, dt, uids = once()
        refs = {
            uid: Ref(
                np.asarray(
                    generate(
                        vcfg, params, jnp.asarray(p, jnp.int32)[None], m,
                        temperature=0.0,
                    )[0]
                )
            )
            for (uid, _), (p, m) in zip(uids, trace)
        }
        return eng, done, refs, dt, uids

    eng_mha, done_mha, refs_mha, dt_mha, uids_mha = run(cfg)
    eng_gqa, done_gqa, refs_gqa, dt_gqa, uids_gqa = run(gqa_cfg)

    mean_req_pages = sum(req_pages) / len(req_pages)
    slots = lambda eng: int((eng.allocator.num_pages - 1) // mean_req_pages)
    print(
        json.dumps(
            {
                "bench": "serve_gqa",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": ps,
                "kv_dtype": args.kv_dtype,
                "pool_hbm_bytes": pool_hbm_bytes,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "kv_groups": G,
                "n_kv_heads": gqa_cfg.kv_heads,
                "sliding_window": args.sliding_window,
                "attn_sinks": args.attn_sinks,
                "mha_page_bytes": mha_page_bytes,
                "gqa_page_bytes": PagedKVCache.page_bytes(
                    gqa_cfg, ps, cache_dtype
                ),
                "mha_num_pages": eng_mha.allocator.num_pages,
                "gqa_num_pages": eng_gqa.allocator.num_pages,
                # the headline slots-per-HBM-byte win: pages (and mean-
                # request slots) admitted by the SAME byte budget
                "pages_ratio": round(
                    eng_gqa.allocator.num_pages / eng_mha.allocator.num_pages,
                    3,
                ),
                "mha_slots_capacity": slots(eng_mha),
                "gqa_slots_capacity": slots(eng_gqa),
                "mha_preemptions": eng_mha.preemptions,
                "gqa_preemptions": eng_gqa.preemptions,
                "mha_tok_s": round(total_new / dt_mha, 2),
                "gqa_tok_s": round(total_new / dt_gqa, 2),
                "window_reclaimed_pages": eng_gqa.window_reclaimed_pages,
                "greedy_match_frac_mha": round(
                    _greedy_match_frac(done_mha, refs_mha, uids_mha), 4
                ),
                "greedy_match_frac_gqa": round(
                    _greedy_match_frac(done_gqa, refs_gqa, uids_gqa), 4
                ),
                "mha_cache_hbm_bytes": int(eng_mha.cache_hbm_bytes()),
                "gqa_cache_hbm_bytes": int(eng_gqa.cache_hbm_bytes()),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _fleet_bench(args, cfg, params, cache_dtype) -> int:
    """--fleet mode: availability A/B ('serve_fleet' profile,
    analysis/bench_contract.py; docs/ROBUSTNESS.md 'Fleet serving &
    failover'). The same template-heavy trace runs through one
    prefix-cached engine, then through an N-replica FleetRouter with an
    engine_crash armed mid-trace. Both passes take an identical mid-trace
    trie flush (a pressure spike force-reclaiming unreferenced pages) at
    the half-way drain: the single engine loses that KV and re-prefills,
    while the fleet's replicas spill it to the shared host-RAM tier and
    the second half re-adopts — which is the tier's throughput story, and
    puts the checksum/adoption path inside the parity gate. Structural
    gates: the crash drops zero accepted streams, every fleet stream
    (survivors and failover replays) bit-matches the single-engine pass,
    and affinity routing keeps the fleet trie hit rate >= the single
    engine's instead of diluting toward 1/N (pinned:
    tests/test_bench_contract.py serve_fleet runner + checker-drift, and
    the fleet chaos gates in tests/test_chaos_serve.py)."""
    import jax
    import numpy as np

    from midgpt_tpu.robustness import faults
    from midgpt_tpu.sampling.fleet import FleetRouter, assert_fleet_conserved
    from midgpt_tpu.sampling.serve import ServeEngine

    if args.fleet < 2:
        raise SystemExit("--fleet needs >= 2 replicas (one cannot fail over)")
    if args.procs:
        return _proc_fleet_bench(args, cfg)

    rng = np.random.default_rng(args.seed)
    V = cfg.vocab_size
    n_templates = args.prefix_templates
    t_len = args.template_tokens or 5 * args.page_size
    templates = [
        rng.integers(0, V, t_len, dtype=np.int64) for _ in range(n_templates)
    ]
    trace = []
    for i in range(args.n_requests):
        tail = rng.integers(0, V, int(rng.integers(3, 9)), dtype=np.int64)
        prompt = np.concatenate([templates[i % n_templates], tail])
        trace.append((prompt, int(rng.integers(8, 13))))
    total_new = sum(m for _, m in trace)
    half = len(trace) // 2
    # 41: a fresh program-key pool geometry (see chaos_serve._engine's pin
    # note), roomy enough that max_slots full requests fit without
    # thrashing while the trie still feels pressure across the trace
    num_pages = 41

    def mk_engine(**kw):
        return ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            num_pages=num_pages,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=cache_dtype,
            prefix_cache=True,
            **kw,
        )

    def run_single():
        faults.clear()
        eng = mk_engine()
        t0 = time.perf_counter()
        uids = [eng.submit(p, m) for p, m in trace[:half]]
        eng.run()
        eng._evict_shared_prefix_fault()  # the shared mid-trace flush
        uids += [eng.submit(p, m) for p, m in trace[half:]]
        eng.run()
        return eng, uids, time.perf_counter() - t0

    run_single()  # warm every jit shape at this geometry
    eng_single, single_uids, dt_single = run_single()
    single_tokens = {
        idx: np.asarray(eng_single.finished[uid].tokens)
        for idx, uid in enumerate(single_uids)
    }
    single_hit = eng_single.prefix_stats()["hit_rate"]

    faults.clear()
    faults.activate("engine_crash", step=args.fleet_crash_round)
    router = FleetRouter(
        [mk_engine(obs_tid=f"replica{i}") for i in range(args.fleet)]
    )

    def drive(pending, r):
        # trickled one per round so the crash finds streams in flight
        while pending or not router.idle:
            if pending:
                idx, (p, m) = pending.pop(0)
                uid_to_idx[router.submit_retry(p, m)] = idx
            router.step()
            r += 1
            if r >= 100_000:
                raise SystemExit("fleet drive did not converge")
        return r

    uid_to_idx: dict = {}
    t0 = time.perf_counter()
    r = drive(list(enumerate(trace[:half])), 0)
    for i, rep in enumerate(router.engines):
        if router.alive[i]:
            rep._evict_shared_prefix_fault()  # same flush — but spilled
    drive(list(enumerate(trace[half:], start=half)), r)
    dt_fleet = time.perf_counter() - t0
    faults.clear()
    assert_fleet_conserved(router, "fleet bench")

    match = total = dropped = parity_checked = 0
    for uid, idx in uid_to_idx.items():
        fr = router.finished.get(uid)
        if fr is None or fr.status != "ok":
            dropped += 1
            continue
        parity_checked += 1
        a = np.asarray(fr.tokens)
        b = single_tokens[idx]
        n = min(len(a), len(b))
        match += int(np.sum(a[:n] == b[:n]))
        total += max(len(a), len(b))

    print(
        json.dumps(
            {
                "bench": "serve_fleet",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "fleet_size": args.fleet,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "kv_dtype": args.kv_dtype,
                "num_pages": num_pages,
                "n_templates": n_templates,
                "template_tokens": t_len,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "single_tok_s": round(total_new / dt_single, 2),
                "fleet_tok_s": round(total_new / dt_fleet, 2),
                "single_hit_rate": round(single_hit, 4),
                "fleet_hit_rate": round(router.prefix_hit_rate(), 4),
                "failovers": router.failovers,
                "failed_over_streams": router.failed_over_streams,
                "crash_round": args.fleet_crash_round,
                "alive": sum(router.alive),
                "dropped": dropped,
                "parity_checked": parity_checked,
                "greedy_match_frac": round(match / max(total, 1), 4),
                "spill_readopted_pages": sum(
                    e.spill_readopted_pages for e in router.engines
                ),
                "spill": router.spill.stats(),
                "pages_conserved": True,
                "procs": False,
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _proc_fleet_bench(args, cfg) -> int:
    """--fleet --procs: the fleet availability A/B with every replica a
    separate worker PROCESS (sampling/fleet_proc.py) behind the framed
    socket transport, and the mid-trace fault a real kill -9
    (docs/ROBUSTNESS.md 'Cross-process fleet'). Two differences from the
    in-process A/B, both forced by real process death:

      * the single-engine reference runs in its OWN worker process (same
        spec, same pinned CPU backend as the fleet workers) — an
        in-parent reference would compare across backends whenever the
        parent sits on the real TPU, and the parent must compile NOTHING
        (its jit census is snapshotted up front and pinned unchanged);
      * there is no fleet_hit_rate >= single_hit_rate gate: a SIGKILLed
        worker takes its per-process host-RAM tier with it, so the KV
        the in-process crash path spills and re-adopts is simply gone —
        the survivor re-prefills the failed-over streams (bit-exactly;
        the parity gate still covers every stream), which is honest
        misses. bench_contract.check_serve_fleet_bench branches on the
        `procs` field for exactly this reason.

    Both sides are timed with warm worker jit caches (one untimed pass
    each, like the in-process warm run) and hit rates are deltas over
    the timed window only. The line carries the transport A/B fields —
    rpc_p50_ms / rpc_p95_ms / wire_bytes / proc_failovers — pinned by
    tests/test_bench_contract.py."""
    import dataclasses as _dc
    import subprocess

    import numpy as np

    from midgpt_tpu.robustness import faults
    from midgpt_tpu.sampling.fleet import FleetRouter, assert_fleet_conserved
    from midgpt_tpu.sampling.fleet_proc import (
        connect_replica,
        parent_jax_config,
        spawn_workers,
    )
    from midgpt_tpu.sampling.serve import ServeEngine

    rng = np.random.default_rng(args.seed)
    V = cfg.vocab_size
    n_templates = args.prefix_templates
    t_len = args.template_tokens or 5 * args.page_size
    templates = [
        rng.integers(0, V, t_len, dtype=np.int64) for _ in range(n_templates)
    ]
    trace = []
    for i in range(args.n_requests):
        tail = rng.integers(0, V, int(rng.integers(3, 9)), dtype=np.int64)
        prompt = np.concatenate([templates[i % n_templates], tail])
        trace.append((prompt, int(rng.integers(8, 13))))
    total_new = sum(m for _, m in trace)
    half = len(trace) // 2
    num_pages = 41  # the in-process fleet-bench geometry; workers own
    # their jit caches, so the program-key ledger concern is per-process

    compiles_before = ServeEngine.compile_stats()
    spec = {
        "model": _dc.asdict(cfg),
        "seed": args.seed,
        "engine": {
            "max_slots": args.max_slots,
            "page_size": args.page_size,
            "num_pages": num_pages,
            "prefill_chunk": args.prefill_chunk,
            "decode_chunk": args.decode_chunk,
            "cache_dtype": "int8" if args.kv_dtype == "int8" else "bfloat16",
        },
        "cpu_devices": args.cpu_devices or 1,
        "jax_config": parent_jax_config(),
    }

    def prefix_counts(reps):
        return (
            sum(r._prefix_matched_tokens for r in reps),
            sum(r._prefix_matchable_tokens for r in reps),
        )

    def ref_pass(rep):
        # the run_single procedure over the wire: half, flush, half
        t0 = time.perf_counter()
        uids = [rep.submit(p, m) for p, m in trace[:half]]
        rep.run()
        rep._evict_shared_prefix_fault()
        uids += [rep.submit(p, m) for p, m in trace[half:]]
        rep.run()
        return uids, time.perf_counter() - t0

    procs = []
    try:
        # reference worker + N fleet workers, spawned concurrently
        procs = spawn_workers(spec, args.fleet + 1)
        ref = connect_replica(procs[0][1], retry_base_s=0.05)
        ref_pass(ref)  # warm the reference worker's jit cache
        m0, a0 = prefix_counts([ref])
        ref_uids, dt_single = ref_pass(ref)
        m1, a1 = prefix_counts([ref])
        single_hit = (m1 - m0) / max(a1 - a0, 1)
        single_tokens = {
            idx: np.asarray(ref.finished[uid].tokens)
            for idx, uid in enumerate(ref_uids)
        }
        ref.close()
        procs[0][0].kill()

        replicas = [
            connect_replica(port, retry_base_s=0.05) for _, port in procs[1:]
        ]
        for rep in replicas:
            ref_pass(rep)  # warm each fleet worker's jit cache
        wm, wa = prefix_counts(replicas)
        faults.clear()
        faults.activate("proc_kill9", step=args.fleet_crash_round)
        router = FleetRouter(replicas)

        uid_to_idx: dict = {}

        def drive(pending, r):
            # trickled one per round so the kill finds streams in flight
            while pending or not router.idle:
                if pending:
                    idx, (p, m) = pending.pop(0)
                    uid_to_idx[router.submit_retry(p, m)] = idx
                router.step()
                r += 1
                if r >= 100_000:
                    raise SystemExit("proc fleet drive did not converge")
            return r

        t0 = time.perf_counter()
        r = drive(list(enumerate(trace[:half])), 0)
        for i, rep in enumerate(router.engines):
            if router.alive[i]:
                rep._evict_shared_prefix_fault()  # same flush, over the wire
        drive(list(enumerate(trace[half:], start=half)), r)
        dt_fleet = time.perf_counter() - t0
        faults.clear()
        assert_fleet_conserved(router, "proc fleet bench")
        fm, fa = prefix_counts(replicas)
        fleet_hit = (fm - wm) / max(fa - wa, 1)

        match = total = dropped = parity_checked = 0
        for uid, idx in uid_to_idx.items():
            fr = router.finished.get(uid)
            if fr is None or fr.status != "ok":
                dropped += 1
                continue
            parity_checked += 1
            a = np.asarray(fr.tokens)
            b = single_tokens[idx]
            n = min(len(a), len(b))
            match += int(np.sum(a[:n] == b[:n]))
            total += max(len(a), len(b))

        transport = router.transport_stats()
        compiles_after = ServeEngine.compile_stats()
        assert compiles_after == compiles_before, (
            f"router process compiled programs for proc replicas: "
            f"{compiles_before} -> {compiles_after}"
        )

        print(
            json.dumps(
                {
                    "bench": "serve_fleet",
                    # the workers' backend — the parent dispatches nothing
                    "backend": "cpu",
                    "n_requests": args.n_requests,
                    "total_new_tokens": total_new,
                    "fleet_size": args.fleet,
                    "max_slots": args.max_slots,
                    "page_size": args.page_size,
                    "kv_dtype": args.kv_dtype,
                    "num_pages": num_pages,
                    "n_templates": n_templates,
                    "template_tokens": t_len,
                    "model": {
                        "n_layer": cfg.n_layer,
                        "n_head": cfg.n_head,
                        "n_embd": cfg.n_embd,
                        "block_size": cfg.block_size,
                    },
                    "single_tok_s": round(total_new / dt_single, 2),
                    "fleet_tok_s": round(total_new / dt_fleet, 2),
                    "single_hit_rate": round(single_hit, 4),
                    "fleet_hit_rate": round(fleet_hit, 4),
                    "failovers": router.failovers,
                    "failed_over_streams": router.failed_over_streams,
                    "crash_round": args.fleet_crash_round,
                    "alive": sum(router.alive),
                    "dropped": dropped,
                    "parity_checked": parity_checked,
                    "greedy_match_frac": round(match / max(total, 1), 4),
                    "spill_readopted_pages": sum(
                        e.spill_readopted_pages for e in router.engines
                    ),
                    "spill": router.spill.stats(),
                    "pages_conserved": True,
                    "procs": True,
                    "proc_failovers": router.proc_failovers,
                    "worker_pids": [rep.pid for rep in replicas],
                    "transport": transport,
                    "rpc_p50_ms": transport["rpc_p50_ms"],
                    "rpc_p95_ms": transport["rpc_p95_ms"],
                    "wire_bytes": transport["wire_bytes"],
                    "router_compiles_delta": 0,
                    "compile_counts": ServeEngine.compile_stats(),
                }
            )
        )
        return 0
    finally:
        faults.clear()
        for proc, _port in procs:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass


def _longctx_bench(args) -> int:
    """--long-ctx mode: the split-K decode A/B ('serve_longctx' profile,
    analysis/bench_contract.py).

    Three measurements, all through the real serve dispatch:

      * long point — decode-round latency of ONE active slot whose visible
        length ends at --t-long, unsplit vs the engine's auto split
        (docs/SERVING.md 'Split-K decode': the single-long-request regime
        is where an unsplit sweep serializes the whole key sequence);
      * short point — the same at --t-short. The no-regression guarantee
        at short T is STRUCTURAL: the auto bucket rule picks split 1 there
        (reported as split_k_short), so the engine runs the byte-identical
        pre-split-K program. The forced-split short latency is also
        reported as diagnostic context for the bucket threshold.
      * parity — the same greedy trace through two engines (forced split 4
        vs unsplit) on a quick-fitted model at a 1024-token block; the
        reported greedy_match_frac must be EXACTLY 1.0 (split-K reorders
        f32 reductions, so this pins that the margins survive — the same
        matrix tests/test_split_k.py locks per mode).

    Latency harness: raw `_serve_decode_chunk` calls (the engine's decode
    program), B=1, page table width rounded UP to a pow2 so the requested
    split divides it (a 513-page natural width would normalize every split
    back to 1 — the same rounding the engine's page buckets guarantee).
    Median of --rounds timed rounds after one warm round; sync per round
    via float() (CLAUDE.md: block_until_ready does not cross the tunnel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
    from midgpt_tpu.sampling.serve import ServeEngine, _serve_decode_chunk

    ps, chunk, rounds = args.page_size, args.decode_chunk, args.rounds
    on_tpu = jax.default_backend() == "tpu"
    baseline_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    pool_dtype = jnp.int8 if args.kv_dtype == "int8" else baseline_dtype
    cache_dtype = "int8" if args.kv_dtype == "int8" else baseline_dtype
    if args.t_long < 2 * (rounds + 1) * chunk:
        raise SystemExit(f"--t-long {args.t_long} too short for "
                         f"{rounds} rounds of {chunk}-token chunks")

    cfg = GPTConfig(
        block_size=args.t_long,
        vocab_size=args.vocab_size,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
    )
    params = GPT.init(cfg, jax.random.PRNGKey(args.seed))
    if on_tpu:
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)

    # The engine's own bucket rule decides the splits under test — the
    # bench measures what serving will actually dispatch, not a hand-picked
    # split (sampling/serve.py ServeEngine._split_bucket).
    eng = ServeEngine(cfg, params, max_slots=1, page_size=ps,
                      decode_chunk=chunk, temperature=0.0,
                      cache_dtype=cache_dtype)
    split_long = eng._split_bucket(args.t_long)
    split_short = eng._split_bucket(args.t_short)
    del eng

    def round_ms(t_total, split_k):
        pages = -(-t_total // ps)
        width = 1 << max(0, pages - 1).bit_length()  # pow2 ceil
        cache = PagedKVCache.init(cfg, 1 + width, ps, dtype=pool_dtype)
        table = jnp.asarray(1 + np.arange(width, dtype=np.int32))[None]
        active = jnp.ones((1,), bool)
        tok = jnp.zeros((1,), jnp.int32)
        lengths = t_total - (rounds + 1) * chunk
        times = []
        for r in range(rounds + 1):  # round 0 warms the compile
            t0 = time.perf_counter()
            cache, toks = _serve_decode_chunk(
                cfg, params, tok, cache, table,  # graftcheck: disable=GC011 — bench CLI: cfg is built once from argparse; one compile per A/B arm is the measured artifact
                jnp.full((1,), lengths, jnp.int32), active,
                chunk, 0.0, None, None, "auto", None, None, split_k,  # graftcheck: disable=GC011 — bench CLI: decode_chunk is a process-constant argparse knob
            )
            tok = toks[-1]
            float(tok.ravel()[0].astype(jnp.float32))  # force (CLAUDE.md)
            if r:
                times.append(time.perf_counter() - t0)
            lengths += chunk
        return 1000 * float(np.median(times))

    ms_long_1 = round_ms(args.t_long, 1)
    ms_long_s = round_ms(args.t_long, split_long)
    ms_short_1 = round_ms(args.t_short, 1)
    ms_short_4 = round_ms(args.t_short, 4)  # forced: auto stays unsplit

    # Exact greedy parity, split vs unsplit, on a model with real argmax
    # margins (the _quick_train rationale — raw-init near-ties make any
    # f32 reduction reorder look like corruption when it is not).
    match_bs = min(1024, args.t_long)
    mcfg = GPTConfig(
        block_size=match_bs,
        vocab_size=args.vocab_size,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
    )
    mparams = GPT.init(mcfg, jax.random.PRNGKey(args.seed))
    if on_tpu:
        mparams = jax.tree.map(lambda p: p.astype(jnp.bfloat16), mparams)
    mparams, train_loss = _quick_train(mcfg, mparams, args.train_steps, args.seed)
    rng = np.random.default_rng(args.seed)
    mtrace = [
        (
            rng.integers(
                0, args.vocab_size,
                int(rng.integers(5 * match_bs // 8, 3 * match_bs // 4)),
                dtype=np.int64,
            ),
            24,
        )
        for _ in range(3)
    ]

    def run_match(split):
        m_eng = ServeEngine(mcfg, mparams, max_slots=2, page_size=ps,
                            prefill_chunk=args.prefill_chunk,
                            decode_chunk=chunk, temperature=0.0,
                            cache_dtype=cache_dtype, split_k=split)
        uids = [(m_eng.submit(p, m), len(p)) for p, m in mtrace]
        done = m_eng.run()
        return done, uids

    done_1, uids = run_match(1)
    done_s, _ = run_match(4)
    gmf = _greedy_match_frac(done_1, done_s, uids)

    print(
        json.dumps(
            {
                "bench": "serve_longctx",
                "backend": jax.default_backend(),
                "t_long": args.t_long,
                "t_short": args.t_short,
                "page_size": ps,
                "decode_chunk": chunk,
                "rounds": rounds,
                "kv_dtype": args.kv_dtype,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "split_k_long": split_long,
                "split_k_short": split_short,
                "ms_round_long_unsplit": round(ms_long_1, 3),
                "ms_round_long_split": round(ms_long_s, 3),
                "long_speedup": round(ms_long_1 / ms_long_s, 3),
                "ms_round_short_unsplit": round(ms_short_1, 3),
                "ms_round_short_forced_split": round(ms_short_4, 3),
                "short_ratio": round(ms_short_4 / ms_short_1, 3),
                "match_block_size": match_bs,
                "greedy_match_frac": round(gmf, 4),
                "train_steps": args.train_steps,
                "train_loss": round(train_loss, 3),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def _ops_bench(args, cfg, params, cache_dtype, trace, total_new) -> int:
    """--hot-swap mode: zero-downtime model ops ('serve_ops' profile,
    analysis/bench_contract.py; protocol: docs/ROBUSTNESS.md 'Zero-downtime
    model ops').

    One trickle-arrival pass through a live engine with two ops landing
    mid-trace: a blue/green weight swap from a sha256-verified checkpoint
    (staged at --swap-round, flipped at the drain boundary), then a live
    pool grow three rounds after the flip, while the new-weights side is
    still decoding. Two upfront reference passes (old weights / new
    weights, same trace, same geometry) provide the bit-exact parity
    oracles — greedy streams are batch-composition independent, the same
    property the preemption and disagg gates lean on (schema + parity
    split enforced in tests/test_bench_contract.py::
    test_bench_serve_ops_emits_conformant_json_line) — and double as the
    compile warm-up, so the swap window's jit-cache delta is the headline
    swap_recompiles == 0 claim: a same-shape swap device_puts onto the
    live shardings and must reuse every compiled program. The resize leg
    compiles its gather/adopt programs AFTER the window closes, which is
    why it runs second."""
    import tempfile
    import types

    import jax
    import numpy as np

    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.sampling import ops as mops
    from midgpt_tpu.sampling.engine import restore_for_sampling
    from midgpt_tpu.sampling.serve import ServeEngine
    from midgpt_tpu.training.checkpoint import CheckpointManager

    num_pages, grow_pages = 21, 23  # fresh geometries (program-key dims)

    ckpt_dir = os.path.join(
        tempfile.mkdtemp(prefix="midgpt_ops_bench_"), "ckpt"
    )
    mgr = CheckpointManager(ckpt_dir, save_interval_steps=1)
    mgr.save(
        7, {"params": GPT.init(cfg, jax.random.PRNGKey(args.seed + 101))},
        force=True,
    )
    mgr.wait()
    version = mgr.weights_version(7)
    mgr.close()
    shim = types.SimpleNamespace(
        model_config=cfg, fsdp_min_size=1 << 60, param_dtype="float32"
    )
    params_new, ckpt_step = restore_for_sampling(ckpt_dir, shim)

    def engine(p):
        return ServeEngine(
            cfg, p, max_slots=args.max_slots, page_size=args.page_size,
            prefill_chunk=args.prefill_chunk, decode_chunk=args.decode_chunk,
            temperature=0.0, cache_dtype=cache_dtype, num_pages=num_pages,
        )

    def reference(p):
        eng = engine(p)
        uids = [eng.submit(pr, m) for pr, m in trace]
        done = eng.run()
        return {u: np.asarray(done[u].tokens) for u in uids}

    def jit_total():
        return sum(v or 0 for v in ServeEngine.compile_stats().values())

    ref_old = reference(params)  # warms every shape at this geometry
    ref_new = reference(params_new)

    def drive():
        """One trickle pass with the swap staged at --swap-round and the
        pool grow landing three rounds after the flip. Run twice: the
        first pass warms every shape the ops schedule touches (incl. the
        resize's gather/adopt programs), so the second pass's jit-cache
        delta over [stage .. 3 post-flip rounds] isolates what the SWAP
        ITSELF compiles — the warm-then-count discipline the recompile
        pins use (tests/test_recompile_pins.py)."""
        eng = engine(params)
        pending = list(trace)
        jit0 = swap_recompiles = None
        post_flip = r = 0
        t0 = time.perf_counter()
        while pending or not eng.idle:
            if pending and r % 2 == 0:
                p, m = pending.pop(0)
                eng.submit(p, m)
            if r == args.swap_round:
                jit0 = jit_total()
                eng.hot_swap(params_new, version=version, config=cfg)
            eng.step()
            if eng.hot_swaps and swap_recompiles is None:
                post_flip += 1
                if post_flip == 3:  # 3 new-weights decode rounds in window
                    swap_recompiles = jit_total() - jit0
                    eng.resize(grow_pages)
            r += 1
            assert r < 10_000, "ops bench failed to drain"
        return eng, swap_recompiles, time.perf_counter() - t0

    drive()  # warm the trickle schedule's shapes end to end
    eng, swap_recompiles, dt = drive()
    done = eng.finished

    swap = eng.swap_history[0]
    rz = eng.resize_history[-1]
    old_uids = set(swap["served_uids_at_flip"])
    po = sum(
        1 for u in old_uids
        if np.array_equal(np.asarray(done[u].tokens), ref_old[u])
    )
    pn = sum(
        1 for u in done if u not in old_uids
        and np.array_equal(np.asarray(done[u].tokens), ref_new[u])
    )
    try:
        mops.assert_conserved(eng, "ops bench drain")
        conserved = True
    except AssertionError:
        conserved = False

    print(
        json.dumps(
            {
                "bench": "serve_ops",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "kv_dtype": args.kv_dtype,
                "num_pages": num_pages,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": cfg.block_size,
                },
                "checkpoint_step": ckpt_step,
                "weights_version_before": "inline",
                "weights_version_after": eng.weights_version,
                "swap_latency_ms": round(swap["swap_latency_s"] * 1e3, 3),
                "streams_in_flight_at_flip": len(swap["in_flight_at_stage"]),
                "staged_round": swap["staged_round"],
                "flip_round": swap["flip_round"],
                "dropped": sum(
                    1 for fr in done.values() if fr.status != "ok"
                ),
                "parity_old_side": po,
                "parity_new_side": pn,
                "swap_recompiles": swap_recompiles,
                "resize_from_pages": rz["from_pages"],
                "resize_to_pages": rz["to_pages"],
                "pages_migrated": rz["pages_migrated"],
                "pages_conserved": conserved,
                "fault_pass_tok_s": round(total_new / dt, 2),
                "compile_counts": ServeEngine.compile_stats(),
            }
        )
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-requests", type=int, default=12)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--decode-chunk", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=256)
    ap.add_argument("--vocab-size", type=int, default=512)
    # model shape: None resolves per mode below — the plain serve bench
    # keeps its r6 4L/128D shape; --spec defaults to 6L/384D, a shape where
    # the batched verify's GEMM efficiency (vs per-token GEMV decode) is
    # measurable even on the CPU mesh (RESULTS.md §5)
    ap.add_argument("--n-layer", type=int, default=None)
    ap.add_argument("--n-head", type=int, default=None)
    ap.add_argument("--n-embd", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kv_dtype", choices=("bf16", "int8"), default="bf16",
                    help="paged KV cache storage dtype. int8 stores pages "
                    "quantized (f32 absmax scales in a side buffer, "
                    "docs/SERVING.md 'Quantized KV cache'): the model is "
                    "quick-fitted first (--train-steps) so the reported "
                    "greedy_match_frac vs a bf16-cache run is meaningful, "
                    "and a bf16 engine at the SAME pool budget runs for "
                    "comparison (bf16_* fields)")
    ap.add_argument("--pool_hbm_bytes", type=int, default=0,
                    help="byte budget for the paged pool (0 = the default "
                    "half-of-dedicated sizing): num_pages is derived from "
                    "the cache dtype, so int8 admits 2x the pages of bf16 "
                    "at the same spend — THE lever the oversubscription "
                    "comparison measures")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force CPU with this many virtual devices (0 = native backend)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding bench: quick-train the model "
                    "on a synthetic Markov stream (an UNTRAINED model has "
                    "arbitrary draft agreement — speculation claims on it "
                    "are meaningless), then compare the continuous engine "
                    "with and without a self-draft on the same trace. Emits "
                    "the 'serve_spec' JSON profile instead of 'serve'")
    ap.add_argument("--spec-draft-layers", type=int, default=0,
                    help="self-draft depth (0 = max(1, n_layer // 3))")
    ap.add_argument("--spec-k", type=int, default=8,
                    help="spec_k_max for the speculative engine (pow2)")
    ap.add_argument("--train-steps", type=int, default=60,
                    help="--spec: quick-train steps before benchmarking")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0,
                    help="> 0 selects the prefix-cache bench: this fraction "
                    "of requests share one of --prefix-templates system "
                    "prompts (the rest are unique short prompts), and the "
                    "trace runs cache-off then cache-on at the same page "
                    "budget ('serve_prefix' JSON profile). 0.8 with "
                    "--n-requests 24 is the acceptance workload "
                    "(docs/SERVING.md 'Prefix cache')")
    ap.add_argument("--tp", type=int, default=0,
                    help="> 0 selects the tensor-parallel A/B bench: the "
                    "same trace through a single-chip engine and a mesh-"
                    "sharded engine (params via the megatron tp rules, KV "
                    "pool on the head axis) per cache mode — base dtype, "
                    "int8, self-draft spec — with every match_* required "
                    "exactly 1.0 ('serve_tp' JSON profile). Pair with "
                    "--cpu-devices 8 on this host (docs/SERVING.md "
                    "'Mesh-sharded serving')")
    ap.add_argument("--fleet", type=int, default=0,
                    help=">= 2 selects the fleet availability A/B: the same "
                    "template trace through one prefix-cached engine and "
                    "through N replicas behind the prefix-affinity "
                    "FleetRouter with an engine_crash armed mid-trace — "
                    "zero dropped streams, bit-exact parity (failover "
                    "replays and host-RAM spill re-adoption included), and "
                    "fleet trie hit rate >= the single engine's. Emits the "
                    "'serve_fleet' JSON profile (docs/ROBUSTNESS.md 'Fleet "
                    "serving & failover')")
    ap.add_argument("--fleet-crash-round", type=int, default=6,
                    help="--fleet: router round at which the armed "
                    "engine_crash kills the busiest replica")
    ap.add_argument("--procs", action="store_true",
                    help="--fleet: replicas are separate worker PROCESSES "
                    "(sampling/fleet_proc.py) behind the framed socket "
                    "transport, the single-engine reference runs in its "
                    "own worker, and the mid-trace fault is a real kill "
                    "-9 of the busiest worker. The serve_fleet line adds "
                    "procs/proc_failovers/rpc_p50_ms/rpc_p95_ms/"
                    "wire_bytes (docs/ROBUSTNESS.md 'Cross-process "
                    "fleet')")
    ap.add_argument("--prefix-templates", type=int, default=2,
                    help="distinct shared system prompts in the workload")
    ap.add_argument("--template-tokens", type=int, default=0,
                    help="template length (0 = 5 * page_size)")
    ap.add_argument("--gqa", type=int, default=0,
                    help="> 0 selects the GQA capacity A/B: the same greedy "
                    "trace through an MHA engine and a GQA engine with "
                    "n_kv_heads = n_head / THIS group factor, at the same "
                    "fixed --pool_hbm_bytes (default: ~1/3 of the trace's "
                    "MHA page demand, so the MHA side preempts). Emits the "
                    "'serve_gqa' JSON profile: pages/slots admitted per "
                    "byte, preemptions, and per-variant greedy parity vs "
                    "engine.generate, required exactly 1.0 (docs/SERVING.md "
                    "'Attention variants')")
    ap.add_argument("--sliding-window", type=int, default=0,
                    help="--gqa: the GQA variant also decodes with this "
                    "sliding window (0 = full causal); reclaimed "
                    "behind-window pages ride the line as "
                    "window_reclaimed_pages")
    ap.add_argument("--attn-sinks", type=int, default=0,
                    help="--gqa: always-visible sink prefix tokens for the "
                    "windowed variant (StreamingLLM-style)")
    ap.add_argument("--long-ctx", action="store_true",
                    help="long-context split-K A/B: decode-round latency of "
                    "ONE active slot at --t-long with the engine's auto "
                    "split vs unsplit, the same at --t-short (where auto "
                    "stays unsplit), plus an exact greedy-parity run split "
                    "vs unsplit on a quick-fitted model. Emits the "
                    "'serve_longctx' JSON profile (docs/SERVING.md "
                    "'Split-K decode')")
    ap.add_argument("--t-long", type=int, default=4096,
                    help="--long-ctx: long visible length (>= 1024 so the "
                    "auto bucket rule engages a split)")
    ap.add_argument("--t-short", type=int, default=256,
                    help="--long-ctx: short visible length (expected to "
                    "stay unsplit under the auto rule)")
    ap.add_argument("--rounds", type=int, default=6,
                    help="--long-ctx: timed decode rounds per variant "
                    "(median reported; one extra warm round rides first)")
    ap.add_argument("--hot-swap", action="store_true",
                    help="zero-downtime model-ops bench: a verified-"
                    "checkpoint blue/green weight swap lands mid-trace "
                    "(staged at --swap-round, flipped at the drain "
                    "boundary) followed by a live pool grow, with bit-"
                    "exact parity vs old-/new-weights references, zero "
                    "dropped streams, and a swap-window jit-cache delta "
                    "required to be 0. Emits the 'serve_ops' JSON profile "
                    "(docs/ROBUSTNESS.md 'Zero-downtime model ops')")
    ap.add_argument("--swap-round", type=int, default=5,
                    help="--hot-swap: engine round at which the candidate "
                    "weights are staged")
    ap.add_argument("--trace-out", type=str, default=None,
                    help="plain serve profile: directory to dump the timed "
                    "continuous run's flight recorder as a Chrome-trace "
                    "JSON (+ .prom metrics) — open in Perfetto or roll up "
                    "with tools/trace_view.py (docs/OBSERVABILITY.md)")
    ap.add_argument("--overlap", type=str, default="off",
                    help="round-overlap dispatch A/B for the plain serve "
                    "profile (docs/SERVING.md 'Round-overlap dispatch'): "
                    "'off' (classic rounds), 'double' (dispatch round N+1 "
                    "before round N's host post-processing), or 'group:k' "
                    "(fuse k decode rounds into one on-device scan). The "
                    "line reports overlap_mode/round_group/"
                    "overlap_hidden_ms either way — an honest zero when "
                    "off — so A/B records are self-describing")
    args = ap.parse_args()
    if args.n_layer is None:
        args.n_layer = 6 if args.spec else 4
    if args.n_head is None:
        args.n_head = 6 if args.spec else 4
    if args.n_embd is None:
        args.n_embd = 384 if args.spec else 128

    import jax

    if args.cpu_devices:
        from midgpt_tpu.utils.compat import set_cpu_device_count

        jax.config.update("jax_platforms", "cpu")
        set_cpu_device_count(args.cpu_devices)

    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.models.gpt import GPT, GPTConfig, KVCache
    from midgpt_tpu.sampling.engine import generate
    from midgpt_tpu.sampling.serve import ServeEngine, parse_overlap

    overlap_mode, round_group = parse_overlap(args.overlap)

    on_tpu = jax.default_backend() == "tpu"
    cfg = GPTConfig(
        block_size=args.block_size,
        vocab_size=args.vocab_size,
        n_layer=args.n_layer,
        n_head=args.n_head,
        n_embd=args.n_embd,
    )
    params = GPT.init(cfg, jax.random.PRNGKey(args.seed))
    if on_tpu:
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    baseline_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    quantized = args.kv_dtype == "int8"
    if args.long_ctx:
        return _longctx_bench(args)

    if args.gqa:
        if quantized:
            raise SystemExit(
                "--gqa compares paged streams against dense-cache "
                "engine.generate, which is only bit-exact at the baseline "
                "cache dtype — int8 stacking is the existing quant bench's "
                "claim; run --gqa without --kv_dtype int8"
            )
        return _gqa_bench(args, cfg, baseline_dtype)

    train_loss = None
    if (
        quantized and not args.spec and not args.shared_prefix_frac
        and not args.tp and not args.fleet
        # (fleet parity, like prefix parity, compares same-dtype runs —
        # exact bitwise, nothing for a quick fit to make meaningful)
    ):
        # (the prefix bench skips the fit: its greedy_match_frac compares
        # cache-on vs cache-off at the SAME dtype, which is exact bitwise
        # — no numeric perturbation for training to make meaningful)
        # An untrained model's greedy argmax is fragile under ANY cache
        # perturbation (near-uniform logits), so the int8-vs-bf16 accuracy
        # number is only meaningful on a model that has learned something
        # — same reasoning as the --spec bench's quick fit.
        params, train_loss = _quick_train(cfg, params, args.train_steps, args.seed)
    cache_dtype = "int8" if quantized else baseline_dtype

    if args.shared_prefix_frac:
        return _prefix_bench(args, cfg, params, cache_dtype)

    if args.fleet:
        return _fleet_bench(args, cfg, params, cache_dtype)

    # Mixed-length trace: short chat-y prompts to near-context documents.
    rng = np.random.default_rng(args.seed)
    S = cfg.block_size
    trace = []
    for _ in range(args.n_requests):
        t0 = int(rng.integers(4, max(5, S // 2)))
        m = int(rng.integers(8, max(9, min(64, S - t0))))
        trace.append((rng.integers(0, cfg.vocab_size, t0, dtype=np.int64), m))
    total_new = sum(m for _, m in trace)

    if args.hot_swap:
        return _ops_bench(args, cfg, params, cache_dtype, trace, total_new)

    if args.tp:
        return _tp_bench(args, cfg, params, trace, total_new)

    if args.spec:
        return _spec_bench(args, cfg, params, cache_dtype, trace, total_new)

    pool_kw = (
        {"pool_hbm_bytes": args.pool_hbm_bytes} if args.pool_hbm_bytes else {}
    )

    def run_continuous(dtype, obs=None):
        eng = ServeEngine(
            cfg,
            params,
            max_slots=args.max_slots,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            decode_chunk=args.decode_chunk,
            temperature=0.0,
            cache_dtype=dtype,
            obs=obs,
            overlap=overlap_mode,
            round_group=round_group,
            **pool_kw,
        )
        uids = [(eng.submit(p, m), len(p)) for p, m in trace]
        t0 = time.perf_counter()
        done = eng.run()
        # Force everything to host (np conversion happened per chunk already).
        dt = time.perf_counter() - t0
        return eng, done, dt, t0, uids

    def run_sequential():
        t0 = time.perf_counter()
        outs = [
            generate(cfg, params, jnp.asarray(p, jnp.int32)[None], m, temperature=0.0)
            for p, m in trace
        ]
        outs = [np.asarray(o) for o in outs]  # force
        return time.perf_counter() - t0

    from midgpt_tpu.obs import Observability

    run_continuous(cache_dtype)  # warm every prefill/decode-chunk shape
    # Flight recorder on the TIMED run only: the serve profile reports the
    # decode-round host/device decomposition (docs/OBSERVABILITY.md) next
    # to its throughput, from the same pass.
    obs = Observability()
    eng, done, dt_cont, t_start, uids = run_continuous(cache_dtype, obs=obs)
    run_sequential()  # warm per-prompt-length prefills + decode chunks
    dt_seq = run_sequential()

    # int8 mode: a bf16-cache engine on the SAME trace and pool budget —
    # the capacity/throughput/accuracy comparison the quantized cache
    # exists for (at a fixed byte budget it gets HALF the pages, so on an
    # oversubscribed trace it preempts more and serves slower).
    bf16_fields = {}
    if quantized:
        # genuine bf16 even on the CPU mesh: the capacity claim (2x pages
        # at the same byte budget) and the accuracy claim (greedy match)
        # are both vs the bf16 production baseline, not vs the CPU test
        # mesh's f32 parity dtype
        run_continuous(jnp.bfloat16)  # warm the bf16-cache shapes
        eng_bf, done_bf, dt_bf, _, _ = run_continuous(jnp.bfloat16)
        bf16_fields = {
            "bf16_continuous_tok_s": round(total_new / dt_bf, 2),
            "bf16_num_pages": eng_bf.allocator.num_pages,
            "bf16_preemptions": eng_bf.preemptions,
            "greedy_match_frac": round(_greedy_match_frac(done, done_bf, uids), 4),
            "train_steps": args.train_steps,
            "train_loss": round(train_loss, 3),
        }

    lat, ttft, req_rate = _latency_stats(done, t_start)

    # Round split: host = dispatch (assembly + jit enqueue) + host_post
    # (token commit); device = device_wait (enqueue -> array landed).
    # Percentile sums are a summary convenience, not a joint distribution.
    decomp = obs.round_decomp()
    round_host_ms = {
        "p50": round(
            decomp["dispatch"]["p50_ms"] + decomp["host_post"]["p50_ms"], 3
        ),
        "p95": round(
            decomp["dispatch"]["p95_ms"] + decomp["host_post"]["p95_ms"], 3
        ),
    }
    round_device_ms = {
        "p50": decomp["device_wait"]["p50_ms"],
        "p95": decomp["device_wait"]["p95_ms"],
    }
    if args.trace_out:
        obs.dump(args.trace_out, filename="bench_serve.json")

    # HBM high-water of the caches (analytic; allocator peak if exposed).
    paged_bytes = eng.cache_hbm_bytes()
    itemsize = jnp.dtype(baseline_dtype).itemsize
    contiguous_bytes = (
        2 * cfg.n_layer * cfg.n_head * S * cfg.head_dim * itemsize
    )  # per-request KVCache the sequential engine allocates
    try:
        peak = jax.local_devices()[0].memory_stats().get("peak_bytes_in_use")
    except Exception:
        peak = None

    print(
        json.dumps(
            {
                "bench": "serve",
                "backend": jax.default_backend(),
                "n_requests": args.n_requests,
                "total_new_tokens": total_new,
                "max_slots": args.max_slots,
                "page_size": args.page_size,
                "kv_dtype": args.kv_dtype,
                "num_pages": eng.allocator.num_pages,
                "pool_hbm_bytes": args.pool_hbm_bytes or None,
                "preemptions": eng.preemptions,
                "prefill_chunk": args.prefill_chunk,
                "decode_chunk": args.decode_chunk,
                "model": {
                    "n_layer": cfg.n_layer,
                    "n_head": cfg.n_head,
                    "n_embd": cfg.n_embd,
                    "block_size": S,
                },
                "continuous_tok_s": round(total_new / dt_cont, 2),
                "sequential_tok_s": round(total_new / dt_seq, 2),
                "speedup": round(dt_seq / dt_cont, 3),
                "p50_token_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                "p99_token_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
                "ttft_ms_mean": round(float(np.mean(ttft)) * 1e3, 3),
                "ttft_ms_p50": round(float(np.percentile(ttft, 50)) * 1e3, 3),
                "ttft_ms_p95": round(float(np.percentile(ttft, 95)) * 1e3, 3),
                "req_tok_s_p50": round(float(np.percentile(req_rate, 50)), 2),
                "req_tok_s_p95": round(float(np.percentile(req_rate, 95)), 2),
                "decode_rounds": decomp["rounds"],
                "round_host_ms": round_host_ms,
                "round_device_ms": round_device_ms,
                # round-overlap dispatch A/B identity + the host time the
                # overlap hid (docs/SERVING.md; eng.round_group is the
                # pow2-bucketed value that actually ran, not the CLI ask)
                "overlap_mode": eng.overlap,
                "round_group": eng.round_group,
                "overlap_hidden_ms": {
                    "p50": decomp["overlap_hidden"]["p50_ms"],
                    "p95": decomp["overlap_hidden"]["p95_ms"],
                },
                # pools + (int8) scale side buffers — the true cache spend
                "cache_hbm_bytes": int(paged_bytes),
                "hbm_paged_cache_bytes": int(paged_bytes),
                "hbm_sequential_cache_bytes": int(contiguous_bytes),
                "device_peak_bytes_in_use": peak,
                # Compiled-program census (ServeEngine.compile_stats): the
                # "request churn never recompiles" claim as a number drivers
                # can watch for drift (schema: analysis/bench_contract.py).
                "compile_counts": ServeEngine.compile_stats(),
                **bf16_fields,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
