"""Summarize a JAX profiler trace: per-op exclusive device time, grouped.

The tensorboard profile UI is rarely available on TPU-VM hosts; this reads
the xplane protobuf a `jax.profiler.start_trace` capture writes (e.g.
`python bench.py --profile /tmp/trace` or `launch.py --debug`) and prints
the top ops by exclusive time plus a category rollup — the exact workflow
that drove the round-2 MFU work (RESULTS.md §1).

Usage:
    python tools/profile_summary.py <trace-dir-or-xplane.pb> [--steps N] [--top K]

`--steps` divides totals by the number of profiled steps so numbers read as
per-step costs.
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys


def _find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        sys.exit(f"no .xplane.pb under {path}")
    return hits[-1]


def _categorize(full_name: str) -> str:
    # match on the op name only — the full HLO text embeds OPERAND names
    # (e.g. "%fusion.153 = ... fusion(%copy-done.166 ...)"), which would
    # misbin fusions as copies
    name = full_name.split(" = ", 1)[0]
    if "closed_call" in name or "checkpoint" in name or "rematted" in name:
        return "pallas-kernels"
    if "slice-start" in name or "slice-done" in name:
        return "async-slice"
    if "copy-start" in name or "copy-done" in name or "copy" in name:
        return "copies"
    if "transpose" in name:
        return "transpose"
    if "dynamic-update-slice" in name:
        return "dyn-update-slice"
    if "all-reduce" in name or "all-gather" in name or "reduce-scatter" in name or "collective" in name:
        return "collectives"
    if "while" in name:
        return "while-wrapper"
    if "fusion" in name or "convolution" in name or "dot" in name:
        return "fusions(matmul+elementwise)"
    return "other"


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("trace", help="trace dir or xplane.pb file")
    p.add_argument("--steps", type=int, default=1, help="profiled step count")
    p.add_argument("--top", type=int, default=25)
    args = p.parse_args()

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        sys.exit("needs tensorflow (for the xplane proto); pip install tensorflow-cpu")

    xs = xplane_pb2.XSpace()
    with open(_find_xplane(args.trace), "rb") as f:
        xs.ParseFromString(f.read())

    for plane in xs.planes:
        if "TPU" not in plane.name and "GPU" not in plane.name:
            continue
        ev_names = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            evs = sorted(
                (ev.offset_ps, ev.offset_ps + ev.duration_ps, ev_names.get(ev.metadata_id, "?"))
                for ev in line.events
            )
            # events nest on a line: exclusive time = duration - children
            excl: collections.Counter = collections.Counter()
            cats: collections.Counter = collections.Counter()
            cnt: collections.Counter = collections.Counter()
            stack: list = []
            for start, end, name in evs:
                while stack and stack[-1][1] <= start:
                    stack.pop()
                if stack:
                    excl[stack[-1][2]] -= end - start
                    cats[_categorize(stack[-1][2])] -= end - start
                excl[name] += end - start
                cats[_categorize(name)] += end - start
                cnt[name] += 1
                stack.append((start, end, name))

            total = sum(excl.values())
            print(f"== {plane.name} :: {line.name} — {total/1e9/args.steps:.2f} ms/step ==")
            print("\n-- categories --")
            for cat, t in cats.most_common():
                print(f"{t/1e9/args.steps:9.2f} ms  {cat}")
            print(f"\n-- top {args.top} ops (exclusive) --")
            for name, t in excl.most_common(args.top):
                print(f"{t/1e9/args.steps:9.2f} ms x{cnt[name]//max(args.steps,1):<4} {name[:110]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
