"""Summarize a JAX profiler trace: per-op exclusive device time, grouped.

The tensorboard profile UI is rarely available on TPU-VM hosts; this reads
the xplane protobuf a `jax.profiler.start_trace` capture writes (e.g.
`python bench.py --profile /tmp/trace` or `launch.py --debug`) and prints
the top ops by exclusive time plus a category rollup — the exact workflow
that drove the round-2 MFU work (RESULTS.md §1).

Usage:
    python tools/profile_summary.py <trace-dir-or-xplane.pb> [--steps N] [--top K]
        [--correlate <flight-recorder.json-or-dir>]

`--steps` divides totals by the number of profiled steps so numbers read as
per-step costs. `--correlate` lines the flight recorder's host-side
`train.step` spans (midgpt_tpu/obs/, dumped to the rundir) up against the
xplane's device ms/step: host span minus device time is host overhead
(feed + enqueue) when positive; a host span much SHORTER than device time
means dispatch ran ahead and the wall cost surfaces at the log-interval
sync instead (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys


def _find_xplane(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        sys.exit(f"no .xplane.pb under {path}")
    return hits[-1]


def _categorize(full_name: str) -> str:
    # match on the op name only — the full HLO text embeds OPERAND names
    # (e.g. "%fusion.153 = ... fusion(%copy-done.166 ...)"), which would
    # misbin fusions as copies
    name = full_name.split(" = ", 1)[0]
    if "closed_call" in name or "checkpoint" in name or "rematted" in name:
        return "pallas-kernels"
    if "slice-start" in name or "slice-done" in name:
        return "async-slice"
    if "copy-start" in name or "copy-done" in name or "copy" in name:
        return "copies"
    if "transpose" in name:
        return "transpose"
    if "dynamic-update-slice" in name:
        return "dyn-update-slice"
    if (
        "all-reduce" in name
        or "all-gather" in name
        or "reduce-scatter" in name
        or "all-to-all" in name
        or "collective" in name
    ):
        return "collectives"
    if "while" in name:
        return "while-wrapper"
    if "fusion" in name or "convolution" in name or "dot" in name:
        return "fusions(matmul+elementwise)"
    return "other"


def correlate_flight_recorder(path: str, device_ms_per_step: float) -> None:
    """Print host-side train.step span stats from a flight-recorder dump
    next to the xplane's device ms/step (module docstring on reading the
    difference). JAX-free: reuses tools/trace_view.py's loader."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from trace_view import find_trace, load_trace

    evs = load_trace(find_trace(path))
    spans = [
        e["dur"] / 1e3
        for e in evs
        if e.get("ph") == "X" and e.get("name") == "train.step"
    ]
    print("\n== flight-recorder correlation ==")
    if not spans:
        print("no train.step spans in the dump — was the recorder on "
              "during the profiled steps?")
        return
    spans.sort()
    host_ms = sum(spans) / len(spans)
    print(f"host train.step spans: n={len(spans)}  mean={host_ms:.2f} ms  "
          f"p50={spans[len(spans) // 2]:.2f} ms  max={spans[-1]:.2f} ms")
    if device_ms_per_step > 0:
        print(f"device (xplane):       {device_ms_per_step:.2f} ms/step")
        delta = host_ms - device_ms_per_step
        if delta >= 0:
            print(f"host - device:         {delta:+.2f} ms/step host overhead "
                  "(feed + enqueue)")
        else:
            print(f"host - device:         {delta:+.2f} ms/step — dispatch "
                  "runs ahead; the wall cost lands at the log-interval sync")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("trace", help="trace dir or xplane.pb file")
    p.add_argument("--steps", type=int, default=1, help="profiled step count")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--correlate", default=None, metavar="FLIGHT_RECORDER",
                   help="flight_recorder.json (or a dir holding one): print "
                   "host train.step span stats against the device ms/step")
    args = p.parse_args()

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        sys.exit("needs tensorflow (for the xplane proto); pip install tensorflow-cpu")

    xs = xplane_pb2.XSpace()
    with open(_find_xplane(args.trace), "rb") as f:
        xs.ParseFromString(f.read())

    device_ms_per_step = 0.0
    for plane in xs.planes:
        if "TPU" not in plane.name and "GPU" not in plane.name:
            continue
        ev_names = {k: v.name for k, v in plane.event_metadata.items()}
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            evs = sorted(
                (ev.offset_ps, ev.offset_ps + ev.duration_ps, ev_names.get(ev.metadata_id, "?"))
                for ev in line.events
            )
            # events nest on a line: exclusive time = duration - children
            excl: collections.Counter = collections.Counter()
            cats: collections.Counter = collections.Counter()
            cnt: collections.Counter = collections.Counter()
            stack: list = []
            for start, end, name in evs:
                while stack and stack[-1][1] <= start:
                    stack.pop()
                if stack:
                    excl[stack[-1][2]] -= end - start
                    cats[_categorize(stack[-1][2])] -= end - start
                excl[name] += end - start
                cats[_categorize(name)] += end - start
                cnt[name] += 1
                stack.append((start, end, name))

            total = sum(excl.values())
            device_ms_per_step = max(device_ms_per_step, total / 1e9 / args.steps)
            print(f"== {plane.name} :: {line.name} — {total/1e9/args.steps:.2f} ms/step ==")
            print("\n-- categories --")
            for cat, t in cats.most_common():
                print(f"{t/1e9/args.steps:9.2f} ms  {cat}")
            print(f"\n-- top {args.top} ops (exclusive) --")
            for name, t in excl.most_common(args.top):
                print(f"{t/1e9/args.steps:9.2f} ms x{cnt[name]//max(args.steps,1):<4} {name[:110]}")
    if args.correlate:
        correlate_flight_recorder(args.correlate, device_ms_per_step)
    return 0


if __name__ == "__main__":
    sys.exit(main())
