"""Chaos harness: injected faults against the REAL recovery paths, one
JSON summary line (the driver contract bench.py established).

Training mode (PR 3) — a supervised run through the real rollback/retry/
verification machinery:

    python tools/chaos_run.py --config=shakespeare_char --rundir=/tmp/chaos \
        --fault nan_grad@12 --fault ckpt_io_error*2 \
        [--set max_steps=40 ...] [--max-restarts 3]

Serving mode (`--serve`) — a seeded request trace through the continuous-
batching engine (and, for client faults, the async front door) with one of
the serving fault kinds armed; asserts graceful degradation (engine alive,
pages conserved, unaffected greedy streams bit-identical to a fault-free
run — robustness/chaos_serve.py) and reports shed/timeout counts:

    python tools/chaos_run.py --serve --fault kill_mid_decode@6
    python tools/chaos_run.py --serve --fault poisoned_page@8 --fault slow_client@1

Zero-downtime model-ops gates (docs/ROBUSTNESS.md): a verified-checkpoint
blue/green weight swap mid-trace, and a live grow-then-shrink pool resize
on an int8 cache, both with bit-exact greedy parity and zero drops:

    python tools/chaos_run.py --serve --fault hot_swap_mid_decode@5
    python tools/chaos_run.py --serve --fault pool_resize@4 --fault pool_resize@8

Fleet gates (docs/ROBUSTNESS.md "Fleet serving & failover") — the trace
runs through TWO replicas behind the prefix-affinity FleetRouter with its
shared host-RAM spill tier (sampling/fleet.py): a mid-trace replica kill
drops zero accepted streams (failovers replay bit-identically on the
survivor), and a stalled or corrupted spill page costs a re-prefill, never
a token, with page conservation extended across replicas and tiers:

    python tools/chaos_run.py --serve --fault engine_crash@6
    python tools/chaos_run.py --serve --fault handoff_stall
    python tools/chaos_run.py --serve --fault spill_corrupt

`--list-faults` prints the registered kinds with one-line descriptions;
unknown `--fault` kinds fail up front with that same list.

With `--rundir`, serving mode records the fault pass under a flight
recorder and leaves `flight_recorder.json` (Chrome trace — open in
Perfetto or summarize with tools/trace_view.py) plus `.prom` metrics
there, even when a degradation invariant fails (docs/OBSERVABILITY.md).

Fault spec grammar: `kind[@step][*times]` (robustness/faults.py;
MIDGPT_FAULTS env works too). Serving step keys: engine round for
kill_mid_decode/poisoned_page, victim uid for slow_client, arrival index
for submit_storm.

Platform selection follows launch.py: set MIDGPT_PLATFORM=cpu (and
MIDGPT_CPU_DEVICES=8) to drive recovery scenarios on the virtual CPU mesh.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_launch():
    """launch.py is a top-level script, not a package module."""
    spec = importlib.util.spec_from_file_location(
        "launch_mod",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "launch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _list_faults() -> int:
    """--list-faults: the registered fault kinds with their one-line
    descriptions (robustness/faults.py DESCRIPTIONS) — the discoverable
    index of the registry, so operators don't read the module to learn
    what `--fault` accepts."""
    from midgpt_tpu.robustness import faults

    width = max(len(k) for k in faults.KINDS)
    for kind in faults.KINDS:
        print(f"  {kind:<{width}}  {faults.DESCRIPTIONS[kind]}")
    return 0


def _validate_fault_specs(parser, specs) -> None:
    """Fail unknown --fault kinds up front with the described kind list
    instead of a deep ValueError (or nothing happening at all)."""
    from midgpt_tpu.robustness import faults

    for spec in specs:
        m = faults._PLAN_RE.match(spec.strip())
        kind = m.group("kind") if m else spec
        if m is None or kind not in faults.KINDS:
            lines = "\n".join(
                f"  {k}: {faults.DESCRIPTIONS[k]}" for k in faults.KINDS
            )
            parser.error(
                f"unknown fault spec {spec!r} (want KIND[@STEP][*TIMES]). "
                f"Registered kinds:\n{lines}"
            )


def _serve_main(args) -> int:
    """--serve: one serving chaos scenario, one JSON line. A broken
    degradation invariant (AssertionError) is the chaos verdict — reported
    as data with a nonzero exit, same contract as training mode."""
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    t0 = time.time()
    status = "ok"
    error = None
    result: dict = {}
    try:
        result = run_serving_chaos(
            ",".join(args.fault), seed=args.seed, n_requests=args.n_requests,
            trace_dir=args.rundir,
        )
    except AssertionError as e:
        status = "failed"
        error = str(e)
    summary = {
        "tool": "chaos_run",
        "mode": "serve",
        "status": status,
        "wall_s": round(time.time() - t0, 3),
        "faults_requested": args.fault,
        **result,
    }
    if error is not None:
        summary["error"] = error
    print(json.dumps(summary))
    return 0 if status == "ok" else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=str, default=None)
    parser.add_argument("--rundir", type=str, default=None)
    parser.add_argument(
        "--fault",
        action="append",
        default=[],
        metavar="KIND[@STEP][*TIMES]",
        help="fault to inject (repeatable) — robustness/faults.py",
    )
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="dotted config override (same semantics as launch.py)",
    )
    parser.add_argument(
        "--serve", action="store_true",
        help="serving chaos: drive a seeded trace through the continuous-"
        "batching engine with the armed faults (robustness/chaos_serve.py) "
        "instead of a supervised training run",
    )
    parser.add_argument("--seed", type=int, default=0,
                        help="--serve: trace/model seed")
    parser.add_argument("--n-requests", type=int, default=5,
                        help="--serve: requests in the seeded trace")
    parser.add_argument(
        "--list-faults", action="store_true",
        help="print the registered fault kinds with one-line descriptions "
        "and exit (robustness/faults.py)",
    )
    args = parser.parse_args()

    if args.list_faults:
        return _list_faults()
    _validate_fault_specs(parser, args.fault)

    import jax

    if os.environ.get("MIDGPT_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])
        if os.environ.get("MIDGPT_CPU_DEVICES"):
            from midgpt_tpu.utils.compat import set_cpu_device_count

            set_cpu_device_count(int(os.environ["MIDGPT_CPU_DEVICES"]))

    if args.serve:
        return _serve_main(args)
    if args.config is None or args.rundir is None:
        parser.error("--config and --rundir are required (unless --serve)")

    from midgpt_tpu.config import load_config
    from midgpt_tpu.robustness import faults, preempt
    from midgpt_tpu.robustness.supervisor import supervise

    launch_mod = _load_launch()
    config = load_config(args.config)
    if args.set:
        config = launch_mod.apply_overrides(
            config, [kv.partition("=")[::2] for kv in args.set]
        )
    config = config.replace(rundir=os.path.abspath(args.rundir))
    if args.fault:
        config = config.replace(fault_plan=",".join(args.fault))
    if args.max_restarts is not None:
        config = config.replace(max_restarts=args.max_restarts)

    preempt.install_handlers()
    t0 = time.time()
    status = "ok"
    error = None
    result = None
    try:
        result = supervise(config)
    except (RuntimeError, FloatingPointError) as e:
        # Budget exhaustion / unrecoverable divergence: that outcome IS the
        # chaos result — report it as data, nonzero exit.
        status = "failed"
        error = str(e)
    summary = {
        "tool": "chaos_run",
        "config": args.config,
        "rundir": config.rundir,
        "status": status,
        "wall_s": round(time.time() - t0, 3),
        "faults_requested": args.fault,
        "faults_fired": faults.fired_counts(),
    }
    if result is not None:
        summary["supervisor"] = {
            k: v for k, v in result["supervisor"].items() if k != "faults_fired"
        }
        summary["loss_final"] = result["metrics"].get("loss/final")
        summary["preempted"] = bool(result["metrics"].get("preempted", False))
    if error is not None:
        summary["error"] = error
    print(json.dumps(summary))
    return 0 if status == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
