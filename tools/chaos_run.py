"""Chaos harness: injected faults against the REAL recovery paths, one
JSON summary line (the driver contract bench.py established).

Training mode (PR 3) — a supervised run through the real rollback/retry/
verification machinery:

    python tools/chaos_run.py --config=shakespeare_char --rundir=/tmp/chaos \
        --fault nan_grad@12 --fault ckpt_io_error*2 \
        [--set max_steps=40 ...] [--max-restarts 3]

Serving mode (`--serve`) — a seeded request trace through the continuous-
batching engine (and, for client faults, the async front door) with one of
the serving fault kinds armed; asserts graceful degradation (engine alive,
pages conserved, unaffected greedy streams bit-identical to a fault-free
run — robustness/chaos_serve.py) and reports shed/timeout counts:

    python tools/chaos_run.py --serve --fault kill_mid_decode@6
    python tools/chaos_run.py --serve --fault poisoned_page@8 --fault slow_client@1

Zero-downtime model-ops gates (docs/ROBUSTNESS.md): a verified-checkpoint
blue/green weight swap mid-trace, and a live grow-then-shrink pool resize
on an int8 cache, both with bit-exact greedy parity and zero drops:

    python tools/chaos_run.py --serve --fault hot_swap_mid_decode@5
    python tools/chaos_run.py --serve --fault pool_resize@4 --fault pool_resize@8

Fleet gates (docs/ROBUSTNESS.md "Fleet serving & failover") — the trace
runs through TWO replicas behind the prefix-affinity FleetRouter with its
shared host-RAM spill tier (sampling/fleet.py): a mid-trace replica kill
drops zero accepted streams (failovers replay bit-identically on the
survivor), and a stalled or corrupted spill page costs a re-prefill, never
a token, with page conservation extended across replicas and tiers:

    python tools/chaos_run.py --serve --fault engine_crash@6
    python tools/chaos_run.py --serve --fault handoff_stall
    python tools/chaos_run.py --serve --fault spill_corrupt

Degraded-IO / elastic-topology gates (docs/ROBUSTNESS.md "Elastic resume
& watchdog") — these train-mode kinds emit the `train_chaos` bench-contract
profile (detected_at_ms, restarts, final_mesh, loss_parity vs an unfaulted
reference run) on the summary line:

    python tools/chaos_run.py --config=... --rundir=... \
        --fault hang_step@12 --set watchdog_deadline_s=2
    python tools/chaos_run.py --config=... --rundir=... --fault ckpt_enospc*2
    python tools/chaos_run.py --config=... --rundir=... --fault resume_reshard@6

(`resume_reshard` ends the first attempt like a preemption; the driver then
restarts on HALF the visible devices with on_resume_mesh="any", exercising
the cross-mesh checkpoint resharding resume, and runs to completion.)

`--list-faults` prints the registered kinds — training, serving, and fleet
in one table — with one-line descriptions;
unknown `--fault` kinds fail up front with that same list.

With `--rundir`, serving mode records the fault pass under a flight
recorder and leaves `flight_recorder.json` (Chrome trace — open in
Perfetto or summarize with tools/trace_view.py) plus `.prom` metrics
there, even when a degradation invariant fails (docs/OBSERVABILITY.md).

Fault spec grammar: `kind[@step][*times]` (robustness/faults.py;
MIDGPT_FAULTS env works too). Serving step keys: engine round for
kill_mid_decode/poisoned_page, victim uid for slow_client, arrival index
for submit_storm.

Platform selection follows launch.py: set MIDGPT_PLATFORM=cpu (and
MIDGPT_CPU_DEVICES=8) to drive recovery scenarios on the virtual CPU mesh.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_launch():
    """launch.py is a top-level script, not a package module."""
    spec = importlib.util.spec_from_file_location(
        "launch_mod",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "launch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _list_faults() -> int:
    """--list-faults: the registered fault kinds with their one-line
    descriptions (robustness/faults.py DESCRIPTIONS) — the discoverable
    index of the registry, so operators don't read the module to learn
    what `--fault` accepts."""
    from midgpt_tpu.robustness import faults

    width = max(len(k) for k in faults.KINDS)
    for kind in faults.KINDS:
        print(f"  {kind:<{width}}  {faults.DESCRIPTIONS[kind]}")
    return 0


def _validate_fault_specs(parser, specs) -> None:
    """Fail unknown --fault kinds up front with the described kind list
    instead of a deep ValueError (or nothing happening at all)."""
    from midgpt_tpu.robustness import faults

    for spec in specs:
        m = faults._PLAN_RE.match(spec.strip())
        kind = m.group("kind") if m else spec
        if m is None or kind not in faults.KINDS:
            lines = "\n".join(
                f"  {k}: {faults.DESCRIPTIONS[k]}" for k in faults.KINDS
            )
            parser.error(
                f"unknown fault spec {spec!r} (want KIND[@STEP][*TIMES]). "
                f"Registered kinds:\n{lines}"
            )


def _serve_main(args) -> int:
    """--serve: one serving chaos scenario, one JSON line. A broken
    degradation invariant (AssertionError) is the chaos verdict — reported
    as data with a nonzero exit, same contract as training mode."""
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    t0 = time.time()
    status = "ok"
    error = None
    result: dict = {}
    try:
        result = run_serving_chaos(
            ",".join(args.fault), seed=args.seed, n_requests=args.n_requests,
            trace_dir=args.rundir,
        )
    except AssertionError as e:
        status = "failed"
        error = str(e)
    summary = {
        "tool": "chaos_run",
        "mode": "serve",
        "status": status,
        "wall_s": round(time.time() - t0, 3),
        "faults_requested": args.fault,
        **result,
    }
    if error is not None:
        summary["error"] = error
    print(json.dumps(summary))
    return 0 if status == "ok" else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=str, default=None)
    parser.add_argument("--rundir", type=str, default=None)
    parser.add_argument(
        "--fault",
        action="append",
        default=[],
        metavar="KIND[@STEP][*TIMES]",
        help="fault to inject (repeatable) — robustness/faults.py",
    )
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="dotted config override (same semantics as launch.py)",
    )
    parser.add_argument(
        "--serve", action="store_true",
        help="serving chaos: drive a seeded trace through the continuous-"
        "batching engine with the armed faults (robustness/chaos_serve.py) "
        "instead of a supervised training run",
    )
    parser.add_argument("--seed", type=int, default=0,
                        help="--serve: trace/model seed")
    parser.add_argument("--n-requests", type=int, default=5,
                        help="--serve: requests in the seeded trace")
    parser.add_argument(
        "--list-faults", action="store_true",
        help="print the registered fault kinds with one-line descriptions "
        "and exit (robustness/faults.py)",
    )
    args = parser.parse_args()

    if args.list_faults:
        return _list_faults()
    _validate_fault_specs(parser, args.fault)

    import jax

    if os.environ.get("MIDGPT_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])
        if os.environ.get("MIDGPT_CPU_DEVICES"):
            from midgpt_tpu.utils.compat import set_cpu_device_count

            set_cpu_device_count(int(os.environ["MIDGPT_CPU_DEVICES"]))

    if args.serve:
        return _serve_main(args)
    if args.config is None or args.rundir is None:
        parser.error("--config and --rundir are required (unless --serve)")

    from midgpt_tpu.config import load_config
    from midgpt_tpu.robustness import faults, preempt
    from midgpt_tpu.robustness.supervisor import supervise
    from midgpt_tpu.training.train import make_runtime

    launch_mod = _load_launch()
    config = load_config(args.config)
    if args.set:
        config = launch_mod.apply_overrides(
            config, [kv.partition("=")[::2] for kv in args.set]
        )
    config = config.replace(rundir=os.path.abspath(args.rundir))
    if args.fault:
        config = config.replace(fault_plan=",".join(args.fault))
    if args.max_restarts is not None:
        config = config.replace(max_restarts=args.max_restarts)

    # Degraded-IO / elastic-topology gates (`train_chaos` bench-contract
    # profile): when one of these kinds is requested, the summary grows
    # detection latency, driver-level restart counts, the final mesh, and a
    # loss-parity verdict against an unfaulted reference run.
    TRAIN_CHAOS_KINDS = {"hang_step", "ckpt_enospc", "resume_reshard"}
    requested_kinds = {
        (faults._PLAN_RE.match(s.strip()).group("kind")) for s in args.fault
    }
    train_chaos = bool(requested_kinds & TRAIN_CHAOS_KINDS)

    preempt.install_handlers()
    t0 = time.time()
    # Detection latency: the registry's firing observer timestamps each
    # kind's FIRST firing (the wall clock stays here in tools/, keeping
    # robustness/ clock-free per the GC012 discipline).
    fire_ms: dict = {}
    faults.set_on_fire(
        lambda f: fire_ms.setdefault(f.kind, round((time.time() - t0) * 1000.0, 1))
    )
    status = "ok"
    error = None
    result = None
    # train_chaos drives the runtime explicitly so the driver can (a) report
    # the final mesh and (b) reuse the compiled step for the parity
    # reference run; plain chaos keeps the historical supervise-owned path.
    rt = make_runtime(config) if train_chaos else None
    reshard_restarts = 0
    # The summary line below is the ONLY stdout this tool may produce (the
    # one-JSON-line driver contract); the supervised run's step logs and
    # supervisor prints go to stderr, where operators still see them.
    import contextlib

    _to_stderr = contextlib.redirect_stdout(sys.stderr)
    try:
        with _to_stderr:
            result = supervise(config, runtime=rt)
            # resume_reshard ends the attempt like a preemption; the driver
            # then plays the scheduler: restart on HALF the devices with
            # on_resume_mesh="any" (the cross-mesh resharding resume) and
            # run to completion. Fault re-injection is NOT replayed on
            # restart — the registry keeps the consumed firing, like a real
            # one-shot failure.
            while (
                result is not None
                and result["metrics"].get("preempted")
                and "resume_reshard" in fire_ms
                and reshard_restarts < 4
            ):
                preempt.reset()
                preempt.install_handlers()
                devs = list(jax.devices())
                n_new = len(devs) // 2 if reshard_restarts % 2 == 0 else len(devs)
                n_new = max(1, n_new)
                cfg2 = config.replace(on_resume_mesh="any", fault_plan="")
                rt = rt.rebuild(cfg2, devices=devs[:n_new])
                reshard_restarts += 1
                result = supervise(cfg2, runtime=rt)
    except (RuntimeError, FloatingPointError) as e:
        # Budget exhaustion / unrecoverable divergence: that outcome IS the
        # chaos result — report it as data, nonzero exit.
        status = "failed"
        error = str(e)
    summary = {
        "tool": "chaos_run",
        "config": args.config,
        "rundir": config.rundir,
        "status": status,
        "wall_s": round(time.time() - t0, 3),
        "faults_requested": args.fault,
        "faults_fired": faults.fired_counts(),
    }
    if result is not None:
        summary["supervisor"] = {
            k: v for k, v in result["supervisor"].items() if k != "faults_fired"
        }
        summary["loss_final"] = result["metrics"].get("loss/final")
        summary["preempted"] = bool(result["metrics"].get("preempted", False))
    if train_chaos:
        import numpy as np

        summary["bench"] = "train_chaos"
        fired_ms = [fire_ms[k] for k in TRAIN_CHAOS_KINDS if k in fire_ms]
        summary["detected_at_ms"] = min(fired_ms) if fired_ms else None
        summary["restarts"] = (
            int(result["supervisor"]["restarts"]) if result is not None else 0
        ) + reshard_restarts
        if rt is not None:
            summary["final_mesh"] = {
                "n_devices": int(len(rt.mesh.devices.flatten())),
                "axes": {k: int(v) for k, v in rt.mesh.shape.items()},
            }
            summary["n_devices_final"] = summary["final_mesh"]["n_devices"]
        loss_parity = False
        if status == "ok" and result is not None and summary["loss_final"] is not None:
            # Parity verdict: an UNFAULTED run of the same config (fresh
            # rundir, empty registry) on the final runtime — shares the
            # compiled step, so this costs steps, not compiles. rtol covers
            # the f32 reassociation of a re-derived data-axis all-reduce
            # after a mesh change (~1e-8 measured); the batch order itself
            # is positional and exact.
            faults.clear()
            preempt.reset()
            cfg_ref = config.replace(
                rundir=config.rundir + "_ref", fault_plan="",
                on_resume_mesh="any",
            )
            with contextlib.redirect_stdout(sys.stderr):
                ref = supervise(cfg_ref, runtime=rt)
            ref_loss = ref["metrics"].get("loss/final")
            summary["loss_ref"] = ref_loss
            loss_parity = bool(
                ref_loss is not None
                and np.isfinite(summary["loss_final"])
                and np.allclose(
                    summary["loss_final"], ref_loss, rtol=1e-5, atol=1e-6
                )
            )
        summary["loss_parity"] = loss_parity
    if error is not None:
        summary["error"] = error
    print(json.dumps(summary))
    return 0 if status == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
