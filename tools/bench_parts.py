"""Decompose single-chip step time: fwd / fwd+bwd / optimizer / attention kernel.

Localizes the MFU gap before tuning: prints achieved TFLOP/s per phase so the
slow phase is obvious. Not part of the driver bench contract (bench.py is).

Usage: python tools/bench_parts.py [--batch N] [--attn flash|naive] [--remat ...]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out):
    # Hard host sync: under the axon remote-TPU tunnel block_until_ready
    # returns immediately; fetching a value does not. Fetch ONE element —
    # device_get of a big leaf would drag gigabytes through the tunnel.
    leaf = jax.tree.leaves(out)[0]
    float(jnp.real(leaf.ravel()[0]))


def timeit(fn, *args, n=10, warmup=3):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / n


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--attn", type=str, default="flash")
    p.add_argument("--remat", type=str, default="flash",
                   choices=["off", "none", "dots", "dots_attn", "flash"])
    p.add_argument("--attn-block", type=int, default=1024)
    args = p.parse_args()

    import dataclasses

    from midgpt_tpu.configs.openwebtext import config as base
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.ops.loss import fused_linear_cross_entropy
    from midgpt_tpu.utils.precision import cast_floating

    mc = dataclasses.replace(
        base.model_config,
        attn_impl=args.attn,
        remat=args.remat != "off",
        remat_policy=args.remat if args.remat != "off" else "none",
        attn_block_size=args.attn_block,
    )
    B, T, D = args.batch, mc.block_size, mc.n_embd
    H, C = mc.n_head, mc.head_dim
    L, V = mc.n_layer, mc.vocab_size

    params = jax.jit(lambda k: GPT.init(mc, k))(jax.random.PRNGKey(0))
    params_c = cast_floating(params, jnp.bfloat16)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, V, (B, T), np.int32))
    labels = jnp.roll(tokens, -1, axis=-1)

    n_params = GPT.count_params(params)
    fwd_flops_tok = 2 * n_params + 4 * L * D * T  # fwd matmuls + attention
    print(f"params={n_params/1e6:.1f}M  B={B} T={T}  attn={args.attn} remat={args.remat}")

    # 1. forward only
    fwd = jax.jit(lambda p, t: GPT.apply(mc, p, t, inference=True))
    dt = timeit(fwd, params_c, tokens)
    print(f"fwd:        {dt*1e3:7.1f} ms  {B*T*fwd_flops_tok/dt/1e12:6.1f} TF/s")

    # 2. fwd+bwd of fused loss
    def loss_fn(p, t, y):
        h = GPT.hidden(mc, p, t, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, y, 8192)

    grad = jax.jit(jax.grad(loss_fn))
    dt = timeit(grad, params_c, tokens, labels)
    print(f"fwd+bwd:    {dt*1e3:7.1f} ms  {B*T*3*fwd_flops_tok/dt/1e12:6.1f} TF/s (assumes bwd=2x fwd)")

    # 3. attention kernel alone (all L layers' worth, fwd)
    q = jax.random.normal(jax.random.PRNGKey(1), (B, H, T, C), jnp.bfloat16)
    from midgpt_tpu.ops.attention import multihead_attention

    att = jax.jit(
        lambda q: multihead_attention(
            q, q, q, impl=args.attn, inference=True, block_size=args.attn_block
        )
    )
    dt = timeit(att, q)
    attn_flops = 2 * 2 * B * H * T * T * C / 2  # qk + pv, causal half
    print(f"attn fwd:   {dt*1e3:7.1f} ms  {attn_flops/dt/1e12:6.1f} TF/s (x{L} layers = {L*dt*1e3:.1f} ms)")

    # 4. attention fwd+bwd
    attg = jax.jit(jax.grad(lambda q: multihead_attention(
        q, q, q, impl=args.attn, inference=True, block_size=args.attn_block
    ).sum()))
    dt = timeit(attg, q)
    print(f"attn f+b:   {dt*1e3:7.1f} ms  {3*attn_flops/dt/1e12:6.1f} TF/s (x{L} layers = {L*dt*1e3:.1f} ms)")

    # 5. big matmul reference point (MXU roofline sanity)
    a = jax.random.normal(jax.random.PRNGKey(2), (8192, 8192), jnp.bfloat16)
    mm = jax.jit(lambda a: a @ a)
    dt = timeit(mm, a)
    print(f"8k matmul:  {dt*1e3:7.1f} ms  {2*8192**3/dt/1e12:6.1f} TF/s (achievable peak)")

    # 6. lm_head + loss epilogue alone
    h = jax.random.normal(jax.random.PRNGKey(3), (B, T, D), jnp.bfloat16)
    lm = params_c.lm_head
    lo = jax.jit(lambda h, w, y: fused_linear_cross_entropy(h, w, y, 8192))
    dt = timeit(lo, h, lm, labels)
    print(f"loss fwd:   {dt*1e3:7.1f} ms  {2*B*T*D*V/dt/1e12:6.1f} TF/s")

    log = jax.jit(jax.grad(lambda h, w, y: fused_linear_cross_entropy(h, w, y, 8192), argnums=(0, 1)))
    dt = timeit(log, h, lm, labels)
    print(f"loss f+b:   {dt*1e3:7.1f} ms  {6*B*T*D*V/dt/1e12:6.1f} TF/s")


if __name__ == "__main__":
    main()
