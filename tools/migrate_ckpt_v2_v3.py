"""Migrate a checkpoint from format v2 to v3 (training/checkpoint.py FORMAT).

v2 stored the fused QKV projection as a flat (n_layer, 3D, D) head-major
interleaved matrix (rows: H blocks of (q_h, k_h, v_h)); v3 stores it as
(n_layer, 3, D, D) with an explicit q/k/v axis and head-major features
within each D. The permutation, per layer:

    (3D, D) --reshape--> (H, 3, C, D) --transpose--> (3, H, C, D)
             --reshape--> (3, D, D)

applied to every leaf whose path ends in `wqkv` — which covers the params
AND the optimizer moments (mu/nu mirror the param tree). Everything else is
copied through. The migrated checkpoint is written as a sibling step in a
new directory (source is never modified) with the v3 format marker.

Usage:
    python tools/migrate_ckpt_v2_v3.py SRC_RUNDIR DST_RUNDIR --n-head H
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import orbax.checkpoint as ocp

from midgpt_tpu.parallel.tp import _leaf_name
from midgpt_tpu.training import checkpoint as ckpt_mod


def migrate_tree(tree, n_head: int):
    def rule(path, x):
        if _leaf_name(path) == "wqkv":
            L, threeD, D = x.shape
            assert threeD == 3 * D, f"not a v2 wqkv: {x.shape}"
            C = D // n_head
            x = np.asarray(x).reshape(L, n_head, 3, C, D)
            x = x.transpose(0, 2, 1, 3, 4).reshape(L, 3, D, D)
        return x

    return jax.tree_util.tree_map_with_path(rule, tree)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("src")
    parser.add_argument("dst")
    parser.add_argument("--n-head", type=int, required=True)
    args = parser.parse_args()
    src = os.path.abspath(args.src)
    dst = os.path.abspath(args.dst)

    reader = ocp.CheckpointManager(src)
    step = reader.latest_step()
    if step is None:
        sys.exit(f"no checkpoint under {src}")
    # Raw restore (numpy, no abstract template) + explicit marker check.
    restored = reader.restore(
        step,
        args=ocp.args.Composite(
            format=ocp.args.JsonRestore(),
            params=ocp.args.StandardRestore(),
            opt_state=ocp.args.StandardRestore(),
        ),
    )
    fmt = restored["format"]
    if fmt.get("version") != 2:
        sys.exit(f"source is format {fmt}, not v2 — nothing to migrate")

    out = {
        "params": migrate_tree(restored["params"], args.n_head),
        "opt_state": migrate_tree(restored["opt_state"], args.n_head),
    }
    reader.close()

    writer = ckpt_mod.CheckpointManager(dst, save_interval_steps=1)
    assert writer.save(step, out, force=True)
    # close() barriers the async write AND commits the integrity manifest
    # (training/checkpoint.py), so the migrated checkpoint is born verified
    # and eligible for latest_verified_step resume.
    writer.close()
    print(f"migrated step {step}: {src} (v2) -> {dst} (v{ckpt_mod.FORMAT['version']})")


if __name__ == "__main__":
    main()
