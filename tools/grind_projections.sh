#!/usr/bin/env bash
# Projection-gap grind (single chip, 124M shape): sweep the knobs that plausibly
# move the ~140 TF/s projection rate / step composition (docs/ROADMAP.md §2),
# one bench invocation per line, results appended as JSON lines to $OUT.
# Usage: tools/grind_projections.sh [outfile]
set -u
OUT="${1:-/tmp/grind_results.jsonl}"
: > "$OUT"
run() {
  echo "### $*" >> "$OUT"
  python bench.py --steps 20 --warmup 3 "$@" 2>/dev/null | tail -1 >> "$OUT"
}

run                                  # baseline (B=16, remat off, unroll 1, chunk 8192)
run --batch 24
run --batch 32
run --batch 24 --remat flash
run --unroll 2
run --unroll 4
run --unroll 12                      # fully unrolled layer scan
run --loss-chunk 4096
run --loss-chunk 16384
run --loss-chunk 32768
run --attn-block 256
run --attn-block 1024
echo "grind done -> $OUT"
