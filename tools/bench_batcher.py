"""Host-side batcher benchmark: native C kernel vs numpy double-gather.

Measures the host assembly cost of pod-scale batches (the per-host work of
openwebtext_mh-class configs). Not part of the driver bench contract.

Usage: python tools/bench_batcher.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from midgpt_tpu import native


def main():
    data = np.random.default_rng(0).integers(0, 50304, 200_000_000).astype(np.uint16)
    print(f"stream: {len(data)/1e6:.0f}M tokens; native={native.native_available()}")
    for bs, T in ((256, 1024), (2048, 1024), (512, 4096)):
        starts = np.random.default_rng(1).integers(0, len(data) - T - 1, size=bs)
        offsets = np.arange(T)

        t0 = time.perf_counter()
        for _ in range(5):
            x = data[starts[:, None] + offsets].astype(np.int32)
            y = data[starts[:, None] + offsets + 1].astype(np.int32)
        np_dt = (time.perf_counter() - t0) / 5

        if native.native_available():
            native.sample_windows(data, starts, T)  # warm (build/load)
            t0 = time.perf_counter()
            for _ in range(5):
                xn, yn = native.sample_windows(data, starts, T)
            c_dt = (time.perf_counter() - t0) / 5
            assert (x == xn).all() and (y == yn).all()
            print(
                f"B={bs:5d} T={T}: numpy {np_dt*1e3:7.1f} ms | native "
                f"{c_dt*1e3:6.1f} ms | {np_dt/c_dt:4.1f}x"
            )
        else:
            print(f"B={bs:5d} T={T}: numpy {np_dt*1e3:7.1f} ms | native n/a")


if __name__ == "__main__":
    main()
