"""Load, validate, and summarize a flight-recorder Chrome trace.

The flight recorder (midgpt_tpu/obs/) dumps `{"traceEvents": [...]}` JSON
that Perfetto (https://ui.perfetto.dev) and chrome://tracing open directly.
This tool is the headless companion for hosts without a browser: it
validates the file is a loadable Chrome trace, rolls up span time by name,
and prints the event tail — the postmortem workflow after a chaos run or
a crash dump (docs/OBSERVABILITY.md).

Usage:
    python tools/trace_view.py <flight_recorder.json> [--top K] [--tail N]
    python tools/trace_view.py <dir>        # finds *flight_recorder*.json
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys


def find_trace(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(
        glob.glob(os.path.join(path, "**", "*flight_recorder*.json"),
                  recursive=True)
        + glob.glob(os.path.join(path, "**", "*trace*.json"), recursive=True)
    )
    if not hits:
        sys.exit(f"no flight-recorder json under {path}")
    return hits[-1]


def load_trace(path: str) -> list:
    """Parse and structurally validate; returns the traceEvents list.
    Raises ValueError on anything Perfetto would choke on."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
    evs = doc["traceEvents"]
    if not isinstance(evs, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
            raise ValueError(f"{path}: event {i} missing ph/name")
        if ev["ph"] == "X" and "dur" not in ev:
            raise ValueError(f"{path}: complete event {i} missing dur")
    return evs


def summarize(evs: list) -> dict:
    """Per-name span rollup + per-phase counts (tests use this too)."""
    by_name: collections.Counter = collections.Counter()
    counts: collections.Counter = collections.Counter()
    phases: collections.Counter = collections.Counter()
    threads = {}
    for ev in evs:
        phases[ev["ph"]] += 1
        if ev["ph"] == "M" and ev["name"] == "thread_name":
            threads[ev.get("tid")] = ev.get("args", {}).get("name")
        if ev["ph"] == "X":
            by_name[ev["name"]] += ev["dur"]
            counts[ev["name"]] += 1
    return {
        "n_events": len(evs),
        "phases": dict(phases),
        "threads": threads,
        "span_us_by_name": dict(by_name),
        "span_counts": dict(counts),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help="flight_recorder.json or a dir holding one")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--tail", type=int, default=10,
                    help="print the last N events (the crash-adjacent tail)")
    args = ap.parse_args()

    path = find_trace(args.trace)
    evs = load_trace(path)
    s = summarize(evs)
    print(f"== {path}: {s['n_events']} events ==")
    print("phases:", " ".join(f"{k}={v}" for k, v in sorted(s["phases"].items())))
    if s["threads"]:
        print("threads:", ", ".join(
            f"{lane}:{name}" for lane, name in sorted(s["threads"].items())
        ))
    rollup = sorted(
        s["span_us_by_name"].items(), key=lambda kv: -kv[1]
    )[: args.top]
    if rollup:
        print(f"\n-- top {args.top} spans by total time --")
        for name, us in rollup:
            n = s["span_counts"][name]
            print(f"{us/1e3:10.3f} ms x{n:<6} {name}")
    if args.tail:
        print(f"\n-- last {args.tail} events --")
        timed = [e for e in evs if e["ph"] != "M"]
        for ev in timed[-args.tail:]:
            dur = f" dur={ev['dur']:.1f}us" if "dur" in ev else ""
            print(f"  ts={ev.get('ts', 0):12.1f} [{ev['ph']}]{dur} "
                  f"{ev['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
