"""Sample from a trained checkpoint (reference sample.py's surface, KV-cached).

    python sample.py --ckpt_dir=outputs/<run> [--start="\\n"|FILE:prompt.txt]
        [--num_samples=10] [--max_new_tokens=500] [--temperature=0.8] [--top_k=K] [--top_p=P]

Differences from the reference: decoding uses a static KV cache (one full
forward for the prompt, one single-token step per new token) instead of a
full padded forward per token (reference sample.py:68-95); and only the
model params item is restored from the checkpoint — no optimizer skeleton
reconstruction (reference sample.py:111-137) thanks to the named-item layout.
"""

from __future__ import annotations

import argparse
import os
import pickle


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt_dir", type=str, required=True)
    parser.add_argument("--start", type=str, default="\n")
    parser.add_argument("--num_samples", type=int, default=10)
    parser.add_argument("--max_new_tokens", type=int, default=500)
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--top_k", type=int, default=None)
    parser.add_argument("--top_p", type=float, default=None, help="nucleus sampling mass")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--engine",
        choices=("batch", "continuous"),
        default="batch",
        help="'batch': one fixed (num_samples, T) batch through the KV-cache "
        "loop; 'continuous': the paged continuous-batching server "
        "(sampling/serve.py) — each sample is an independent request, so "
        "mixed --max_new_tokens finish independently instead of padding to "
        "the longest (docs/SERVING.md)",
    )
    parser.add_argument(
        "--max_slots", type=int, default=4,
        help="continuous engine: concurrent decode slots",
    )
    args = parser.parse_args()

    import jax

    if os.environ.get("MIDGPT_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.config import from_json
    from midgpt_tpu.sampling.engine import generate, restore_for_sampling
    from midgpt_tpu.utils.precision import cast_floating

    config_path = os.path.join(args.ckpt_dir, "config.json")
    if args.ckpt_dir.startswith("gs://"):
        import gcsfs

        with gcsfs.GCSFileSystem().open(config_path, "r") as f:
            config = from_json(f.read())
    else:
        with open(config_path, "r") as f:
            config = from_json(f.read())
    model_cfg = config.model_config
    print(config)

    # Restore just the "params" item, sharded over an inference mesh (all
    # local devices on 'fsdp' — the 7B-class checkpoints cannot restore to
    # one device; on a single chip this is the plain restore).
    try:
        params, step = restore_for_sampling(args.ckpt_dir, config)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    print(f"restored checkpoint step {step}")
    params = cast_floating(params, jnp.dtype(config.compute_dtype))

    # Tokenizer: dataset-shipped codec if present (char stoi/itos, or an
    # offline-trained HF BPE from data/local_text/prepare.py), else GPT-2 BPE
    # (reference sample.py:143-159).
    meta_path = os.path.join(config.data_dir, "meta.pkl")
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        if meta.get("kind") == "hf_bpe":
            from tokenizers import Tokenizer

            tok_path = os.path.join(config.data_dir, meta["tokenizer_file"])
            want_sha = meta.get("tokenizer_sha256")
            if want_sha is not None:
                import hashlib

                with open(tok_path, "rb") as tf:
                    got_sha = hashlib.sha256(tf.read()).hexdigest()
                if got_sha != want_sha:
                    raise ValueError(
                        f"{tok_path} does not match the tokenizer this "
                        "dataset (and any checkpoint trained on it) was "
                        "built with — decoding would be silently wrong. "
                        "Re-run the dataset's prepare.py."
                    )
            tok = Tokenizer.from_file(tok_path)
            encode = lambda s: tok.encode(s).ids
            decode = lambda ids: tok.decode(ids, skip_special_tokens=False)
        else:
            stoi, itos = meta["stoi"], meta["itos"]
            encode = lambda s: [stoi[c] for c in s]
            decode = lambda ids: "".join(itos[i] for i in ids)
    else:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        encode = lambda s: enc.encode(s, allowed_special={"<|endoftext|>"})
        decode = enc.decode

    start = args.start
    if start.startswith("FILE:"):
        with open(start[5:], "r", encoding="utf-8") as f:
            start = f.read()
    start_ids = encode(start if start != "" else "\n")
    prompt = np.tile(np.asarray(start_ids, np.int32), (args.num_samples, 1))

    if args.engine == "continuous":
        from midgpt_tpu.sampling.serve import ServeEngine

        eng = ServeEngine(
            model_cfg,
            params,
            max_slots=args.max_slots,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            seed=args.seed,
        )
        uids = [
            eng.submit(prompt[i], args.max_new_tokens)
            for i in range(args.num_samples)
        ]
        finished = eng.run()
        out = [finished[u].tokens for u in uids]
    else:
        out = generate(
            model_cfg,
            params,
            prompt,
            args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            key=jax.random.PRNGKey(args.seed),
        )
    for i in range(args.num_samples):
        print(decode(np.asarray(out[i]).tolist()))
        print("---------------")


if __name__ == "__main__":
    main()
