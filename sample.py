"""Sample from a trained checkpoint (reference sample.py's surface, KV-cached).

    python sample.py --ckpt_dir=outputs/<run> [--start="\\n"|FILE:prompt.txt]
        [--num_samples=10] [--max_new_tokens=500] [--temperature=0.8] [--top_k=K] [--top_p=P]

Differences from the reference: decoding uses a static KV cache (one full
forward for the prompt, one single-token step per new token) instead of a
full padded forward per token (reference sample.py:68-95); and only the
model params item is restored from the checkpoint — no optimizer skeleton
reconstruction (reference sample.py:111-137) thanks to the named-item layout.
"""

from __future__ import annotations

import argparse
import os
import pickle


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt_dir", type=str, required=True)
    parser.add_argument("--start", type=str, default="\n")
    parser.add_argument("--num_samples", type=int, default=10)
    parser.add_argument("--max_new_tokens", type=int, default=500)
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--top_k", type=int, default=None)
    parser.add_argument("--top_p", type=float, default=None, help="nucleus sampling mass")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--engine",
        choices=("batch", "continuous"),
        default="batch",
        help="'batch': one fixed (num_samples, T) batch through the KV-cache "
        "loop; 'continuous': the paged continuous-batching server "
        "(sampling/serve.py) — each sample is an independent request, so "
        "mixed --max_new_tokens finish independently instead of padding to "
        "the longest (docs/SERVING.md)",
    )
    parser.add_argument(
        "--max_slots", type=int, default=4,
        help="continuous engine: concurrent decode slots",
    )
    parser.add_argument(
        "--spec_layers", type=int, default=None,
        help="speculative decoding with a SELF-DRAFT of this many leading "
        "layers (shared embeddings/lm_head, sampling/spec.py). Default: the "
        "checkpoint config's spec_layers (0 = off); 0 forces it off. "
        "Implies --engine=continuous",
    )
    parser.add_argument(
        "--kv_dtype", choices=("bf16", "int8"), default=None,
        help="paged KV cache storage dtype for the continuous engine "
        "(docs/SERVING.md 'Quantized KV cache'): int8 halves cache HBM "
        "and decode-attention traffic. Default: the checkpoint config's "
        "kv_cache_dtype. Implies --engine=continuous (the batch engine's "
        "contiguous cache has no quantized mode)",
    )
    parser.add_argument(
        "--draft_ckpt", type=str, default=None,
        help="speculative decoding with a SEPARATE draft checkpoint dir "
        "(its own config.json; must share vocab and block_size). Implies "
        "--engine=continuous; mutually exclusive with --spec_layers",
    )
    args = parser.parse_args()
    if args.draft_ckpt is not None and args.spec_layers:
        parser.error("--draft_ckpt and --spec_layers are mutually exclusive")
    if args.draft_ckpt is not None or args.spec_layers:
        args.engine = "continuous"  # speculation lives in the serve engine
    if args.kv_dtype == "int8":
        args.engine = "continuous"  # the quantized cache is paged-only

    import jax

    if os.environ.get("MIDGPT_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["MIDGPT_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.config import from_json
    from midgpt_tpu.sampling.engine import generate, restore_for_sampling
    from midgpt_tpu.utils.precision import cast_floating

    config_path = os.path.join(args.ckpt_dir, "config.json")
    if args.ckpt_dir.startswith("gs://"):
        import gcsfs

        with gcsfs.GCSFileSystem().open(config_path, "r") as f:
            config = from_json(f.read())
    else:
        with open(config_path, "r") as f:
            config = from_json(f.read())
    model_cfg = config.model_config
    print(config)

    # Restore just the "params" item, sharded over an inference mesh (all
    # local devices on 'fsdp' — the 7B-class checkpoints cannot restore to
    # one device; on a single chip this is the plain restore).
    try:
        params, step = restore_for_sampling(args.ckpt_dir, config)
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    print(f"restored checkpoint step {step}")
    params = cast_floating(params, jnp.dtype(config.compute_dtype))

    # Tokenizer: dataset-shipped codec if present (char stoi/itos, or an
    # offline-trained HF BPE from data/local_text/prepare.py), else GPT-2 BPE
    # (reference sample.py:143-159).
    meta_path = os.path.join(config.data_dir, "meta.pkl")
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        if meta.get("kind") == "hf_bpe":
            from tokenizers import Tokenizer

            tok_path = os.path.join(config.data_dir, meta["tokenizer_file"])
            want_sha = meta.get("tokenizer_sha256")
            if want_sha is not None:
                import hashlib

                with open(tok_path, "rb") as tf:
                    got_sha = hashlib.sha256(tf.read()).hexdigest()
                if got_sha != want_sha:
                    raise ValueError(
                        f"{tok_path} does not match the tokenizer this "
                        "dataset (and any checkpoint trained on it) was "
                        "built with — decoding would be silently wrong. "
                        "Re-run the dataset's prepare.py."
                    )
            tok = Tokenizer.from_file(tok_path)
            encode = lambda s: tok.encode(s).ids
            decode = lambda ids: tok.decode(ids, skip_special_tokens=False)
        else:
            stoi, itos = meta["stoi"], meta["itos"]
            encode = lambda s: [stoi[c] for c in s]
            decode = lambda ids: "".join(itos[i] for i in ids)
    else:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        encode = lambda s: enc.encode(s, allowed_special={"<|endoftext|>"})
        decode = enc.decode

    start = args.start
    if start.startswith("FILE:"):
        with open(start[5:], "r", encoding="utf-8") as f:
            start = f.read()
    start_ids = encode(start if start != "" else "\n")
    prompt = np.tile(np.asarray(start_ids, np.int32), (args.num_samples, 1))

    if args.engine == "continuous":
        from midgpt_tpu.sampling.serve import ServeEngine

        draft_config = draft_params = None
        draft_shares_cache = False
        spec_layers = (
            config.spec_layers if args.spec_layers is None else args.spec_layers
        )
        if args.draft_ckpt is not None:
            # Separate small draft model: restore its own checkpoint; the
            # rejection sampler only needs matching output spaces.
            with open(os.path.join(args.draft_ckpt, "config.json")) as f:
                draft_exp = from_json(f.read())
            draft_config = draft_exp.model_config
            draft_params, draft_step = restore_for_sampling(
                args.draft_ckpt, draft_exp
            )
            draft_params = cast_floating(
                draft_params, jnp.dtype(config.compute_dtype)
            )
            print(f"draft checkpoint step {draft_step} ({args.draft_ckpt})")
        elif spec_layers:
            from midgpt_tpu.sampling.spec import self_draft

            draft_config, draft_params = self_draft(
                model_cfg, params, spec_layers
            )
            draft_shares_cache = True  # prefix layers ride the target pool
            print(f"self-draft: first {spec_layers}/{model_cfg.n_layer} layers")
        kv_dtype = (
            config.kv_cache_dtype if args.kv_dtype is None else args.kv_dtype
        )
        eng = ServeEngine(
            model_cfg,
            params,
            max_slots=args.max_slots,
            cache_dtype=kv_dtype,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            seed=args.seed,
            draft_params=draft_params,
            draft_config=draft_config,
            draft_shares_cache=draft_shares_cache,
            spec_k_max=config.spec_k_max,
            spec_k_min=config.spec_k_min,
            spec_adapt=config.spec_adapt,
        )
        uids = [
            eng.submit(prompt[i], args.max_new_tokens)
            for i in range(args.num_samples)
        ]
        finished = eng.run()
        out = [finished[u].tokens for u in uids]
        if draft_params is not None:
            s = eng.spec_stats()
            print(
                f"speculative: accept_rate {s['accept_rate']:.2f}, "
                f"tokens/verify {s['tokens_per_verify']:.2f}"
            )
    else:
        out = generate(
            model_cfg,
            params,
            prompt,
            args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            key=jax.random.PRNGKey(args.seed),
        )
    for i in range(args.num_samples):
        print(decode(np.asarray(out[i]).tolist()))
        print("---------------")


if __name__ == "__main__":
    main()
