"""Tensor parallelism (Megatron column/row over the mesh 'tp' axis) on the
8-device virtual CPU mesh: spec placement, numerical parity of the sharded
forward, and train-step trajectory parity vs the FSDP-only schedule.

Beyond the reference's capability set (its only model sharding is FSDP,
reference model.py:167-178) — see parallel/tp.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.data.dataset import TokenDataset
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.parallel.tp import tp_param_specs
from midgpt_tpu.training.train import init_state, make_train_step

import dataclasses

CFG = GPTConfig(block_size=32, vocab_size=256, n_layer=2, n_head=4, n_embd=64)
# What make_train_step selects under tp > 1: the batched per-third QKV
# lowering that keeps each of q/k/v independently column-sharded.
CFG3 = dataclasses.replace(CFG, qkv_proj="split3")


def test_tp_spec_placement():
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, sp=1, tp=4))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = tp_param_specs(params, mesh, shard_model=True, min_size=0)
    # column-parallel: 'tp' on output features, 'fsdp' composed on input
    assert specs.blocks.attn.wqkv == P(None, None, "tp", "fsdp")
    assert specs.blocks.mlp.w_up == P(None, "tp", "fsdp")
    # row-parallel: 'tp' on input features
    assert specs.blocks.attn.wo == P(None, "fsdp", "tp")
    assert specs.blocks.mlp.w_down == P(None, "fsdp", "tp")
    # vocab-parallel (default): wte/lm_head shard the vocab axis over 'tp'
    assert specs.wte == P("tp", "fsdp")
    assert specs.lm_head == P("tp", "fsdp")
    # with vocab_parallel off they fall back to the FSDP rule
    specs_nv = tp_param_specs(params, mesh, True, 0, vocab_parallel=False)
    assert specs_nv.wte == P(None, "fsdp")
    assert specs_nv.lm_head == P(None, "fsdp")
    assert specs_nv.blocks.attn.wqkv == P(None, None, "tp", "fsdp")
    # optimizer-state-shaped trees (params nested deeper) get the same rule
    opt_like = {"mu": params, "nu": params, "count": jnp.zeros(())}
    opt_specs = tp_param_specs(opt_like, mesh, shard_model=True, min_size=0)
    assert opt_specs["mu"].blocks.attn.wqkv == P(None, None, "tp", "fsdp")
    assert opt_specs["count"] == P()


def test_tp_specs_reduce_to_fsdp_at_tp1():
    from midgpt_tpu.parallel.fsdp import fsdp_param_specs

    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1, tp=1))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    assert tp_param_specs(params, mesh, True, 0) == fsdp_param_specs(params, mesh, True, 0)


def test_tp_sharded_forward_matches_single_device():
    """tp x fsdp sharded forward == unsharded forward (GSPMD is semantics-
    preserving; this pins the spec rule to a correct placement)."""
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, sp=1, tp=4))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, CFG.vocab_size)
    base = GPT.apply(CFG, params, tokens, inference=True)

    specs = tp_param_specs(params, mesh, shard_model=True, min_size=0)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    xg = make_global_batch(np.asarray(tokens), mesh, batch_spec(with_accum=False))
    out = jax.jit(lambda p, t: GPT.apply(CFG, p, t, inference=True))(sharded, xg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=2e-5, rtol=2e-5)


def test_tp_forward_is_collective_minimal():
    """The Megatron property, asserted on compiled HLO: with pure tp sharding
    the forward needs ONLY the two all-reduces per block body (after the
    row-parallel wo and w_down) — no all-gather / all-to-all / resharding of
    activations. This is what the (3, D, D) wqkv layout + split3 lowering buy
    (models/gpt.py AttentionParams): sharding a flat stacked [q;k;v] axis
    straddles the q/k/v boundaries and forces GSPMD to reshard every block."""
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sp=1, tp=4))
    params = GPT.init(CFG3, jax.random.PRNGKey(0))
    # vocab_parallel off: full logits out of GPT.apply would legitimately
    # need a vocab gather; the property under test is the BLOCK schedule.
    specs = tp_param_specs(params, mesh, shard_model=True, min_size=0, vocab_parallel=False)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    xg = make_global_batch(np.zeros((8, 32), np.int32), mesh, batch_spec(with_accum=False))
    hlo = (
        jax.jit(lambda p, t: GPT.apply(CFG3, p, t, inference=True))
        .lower(sharded, xg)
        .compile()
        .as_text()
    )
    for banned in ("all-gather", "all-to-all", "collective-permute"):
        assert banned not in hlo, f"unexpected {banned} in tp forward"


def test_tp_vocab_parallel_loss_schedule():
    """Pin the vocab-parallel collective schedule (parallel/tp.py docstring):
    the fused CE over a tp-sharded lm_head must lower to small per-chunk
    psums — never an all-gather (which would rematerialize the V-sized
    buffers the sharding exists to split)."""
    from midgpt_tpu.ops.loss import fused_linear_cross_entropy

    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sp=1, tp=4))
    params = GPT.init(CFG3, jax.random.PRNGKey(0))
    specs = tp_param_specs(params, mesh, shard_model=True, min_size=0)
    assert specs.lm_head == P("tp", None)  # fsdp=1 here: tp on vocab only
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    x = make_global_batch(np.zeros((8, 32), np.int32), mesh, batch_spec(with_accum=False))
    y = make_global_batch(np.ones((8, 32), np.int32), mesh, batch_spec(with_accum=False))

    def loss_fn(p, xx, yy):
        h = GPT.hidden(CFG3, p, xx, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, yy, 8192)

    hlo = (
        jax.jit(jax.value_and_grad(loss_fn)).lower(sharded, x, y).compile().as_text()
    )
    for banned in ("all-gather", "all-to-all", "collective-permute"):
        assert banned not in hlo, f"unexpected {banned} in vocab-parallel loss"


def _run_steps(cfg: ExperimentConfig, data_dir: str, n: int = 5):
    mesh = make_mesh(cfg.mesh)
    ds = TokenDataset(data_dir, seed=cfg.data_seed)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    step, *_ = make_train_step(cfg, optimizer, mesh, specs)
    spec = batch_spec(with_accum=True)
    losses = []
    for itr in range(n):
        x, y = ds.batch("train", itr, cfg.model_config.block_size, cfg.batch_size,
                        cfg.g_accum_iters)
        xg = make_global_batch(x, mesh, spec)
        yg = make_global_batch(y, mesh, spec)
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), itr)
        params, opt_state, loss = step(params, opt_state, xg, yg, key)
        losses.append(float(loss))
    return losses


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tp_data")
    stream = (np.arange(20000) % 23).astype(np.uint16)
    stream.tofile(d / "train.bin")
    stream[:4000].tofile(d / "val.bin")
    return str(d)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_tp_train_step_matches_fsdp_only(data_dir):
    """5-step loss trajectory: (data=2, fsdp=2, tp=2) == (data=2, fsdp=4).

    Same seeds, same data, two different parallelization schedules — the
    tp schedule must compute the same math as the FSDP oracle."""
    base = dict(
        rundir="",
        data_dir=data_dir,
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=60,
        max_steps=60,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=30,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        eval_steps=2,
        fsdp_min_size=0,
        model_config=CFG,
    )
    ref = ExperimentConfig(mesh=MeshConfig(data=2, fsdp=4, sp=1), **base)
    tp = ExperimentConfig(mesh=MeshConfig(data=2, fsdp=2, sp=1, tp=2), **base)
    losses_ref = _run_steps(ref, data_dir)
    losses_tp = _run_steps(tp, data_dir)
    np.testing.assert_allclose(losses_tp, losses_ref, rtol=2e-5, atol=2e-5)
    assert losses_ref[-1] < losses_ref[0]  # and it actually learns


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_tp_ring_sp_composition_matches_fsdp_only(data_dir):
    """All four parallelism kinds at once: a (data=1, fsdp=2, sp=2, tp=2)
    mesh — real FSDP param sharding, ring attention over 'sp', and
    Megatron-sharded (head-sharded, via ring's head_axis) projections over
    'tp' — must reproduce the FSDP-only oracle's loss trajectory."""
    import dataclasses

    base = dict(
        rundir="",
        data_dir=data_dir,
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=60,
        max_steps=60,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=30,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        eval_steps=2,
        fsdp_min_size=0,
    )
    ref = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=4, sp=1), model_config=CFG, **base
    )
    ring_cfg = dataclasses.replace(CFG, attn_impl="ring")
    tpsp = ExperimentConfig(
        mesh=MeshConfig(data=1, fsdp=2, sp=2, tp=2), model_config=ring_cfg, **base
    )
    losses_ref = _run_steps(ref, data_dir, n=4)
    losses_tpsp = _run_steps(tpsp, data_dir, n=4)
    np.testing.assert_allclose(losses_tpsp, losses_ref, rtol=2e-5, atol=2e-5)


def test_tp_config_validation():
    mc = GPTConfig(block_size=32, vocab_size=64, n_layer=1, n_head=3, n_embd=48)
    kw = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8, warmup_steps=1,
        min_lr=1e-4, lr_decay_steps=10, max_steps=10, beta2=0.99, weight_decay=0.0,
        eval_interval=5, param_dtype="float32", compute_dtype="float32",
        g_accum_iters=1, shard_model=True,
    )
    with pytest.raises(ValueError, match="n_head"):
        ExperimentConfig(mesh=MeshConfig(tp=2), model_config=mc, **kw)
    with pytest.raises(ValueError, match="vocab_size"):
        ExperimentConfig(
            mesh=MeshConfig(tp=2),
            model_config=GPTConfig(block_size=32, vocab_size=65, n_layer=1,
                                   n_head=2, n_embd=64),
            **kw,
        )
    # ... but indivisible vocab is fine with tp_vocab off
    ExperimentConfig(
        mesh=MeshConfig(tp=2), tp_vocab=False,
        model_config=GPTConfig(block_size=32, vocab_size=65, n_layer=1,
                               n_head=2, n_embd=64),
        **kw,
    )
    # r5: shard_map composes with tp (auto axis) — but not together with
    # its sequence-parallel schedules yet
    ExperimentConfig(
        mesh=MeshConfig(tp=2), fsdp_mode="shard_map",
        model_config=GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                               n_head=2, n_embd=64),
        **kw,
    )
    with pytest.raises(ValueError, match="sequence parallelism"):
        ExperimentConfig(
            mesh=MeshConfig(tp=2, sp=2), fsdp_mode="shard_map",
            model_config=GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                                   n_head=2, n_embd=64, attn_impl="ring"),
            **kw,
        )
