"""Async streaming front door (sampling/server.py): per-token streaming
with greedy parity, mid-stream client cancellation, bounded backpressure
retry, slow-client shedding, and graceful drain via the PR 3 one-shot
preemption flag. All asyncio tests run through asyncio.run inside plain
pytest functions (no plugin dependency); determinism comes from the
engine's greedy mode and the seeded prompts, not from timing."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.robustness import preempt
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.serve import ServeEngine
from midgpt_tpu.sampling.server import AsyncServeServer, ServerDraining

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _engine(params, **kw):
    base = dict(
        max_slots=2, page_size=8, num_pages=33, prefill_chunk=16,
        decode_chunk=4, temperature=0.0, cache_dtype=jnp.float32,
    )
    base.update(kw)
    return ServeEngine(CFG, params, **base)


def _prompts(n, seed=0, lo=4, hi=24):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG.vocab_size, int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


def test_stream_tokens_match_generate(params):
    """Streamed tokens are exactly the greedy generation, delivered
    incrementally, and the terminal record carries status ok."""
    p1, p2 = _prompts(2, seed=1)
    eng = _engine(params)

    async def main():
        server = AsyncServeServer(eng, idle_poll_s=0.001)
        driver = asyncio.create_task(server.run())

        async def client(p, m):
            uid = await server.submit(p, m)
            toks = []
            async for tok in server.stream(uid):
                toks.append(tok)
            return uid, toks

        (u1, t1), (u2, t2) = await asyncio.gather(client(p1, 10), client(p2, 8))
        await server.drain()
        await driver
        return {u1: (p1, 10, t1), u2: (p2, 8, t2)}

    results = asyncio.run(main())
    for uid, (p, m, toks) in results.items():
        ref = np.asarray(
            generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        )[0]
        np.testing.assert_array_equal(np.asarray(toks), ref[len(p):])
        fr = eng.finished[uid]
        assert fr.status == "ok"
        np.testing.assert_array_equal(fr.tokens, ref)
    assert eng.allocator.free_count == eng.allocator.num_pages - 1


def test_client_disconnect_cancels_and_frees_pages(params):
    """Abandoning a stream mid-decode cancels the request at the next
    round boundary: pages conserved, bystander stream exact, status
    'cancelled' with the delivered prefix intact."""
    p_victim, p_by = _prompts(2, seed=2)
    eng = _engine(params)

    async def main():
        server = AsyncServeServer(eng, idle_poll_s=0.001)
        driver = asyncio.create_task(server.run())

        async def bystander():
            uid = await server.submit(p_by, 12)
            toks = [tok async for tok in server.stream(uid)]
            return uid, toks

        async def victim():
            uid = await server.submit(p_victim, 20)
            got = []
            async for tok in server.stream(uid):
                got.append(tok)
                if len(got) == 3:
                    break  # client walks away mid-stream
            return uid, got

        (u_by, t_by), (u_v, t_v) = await asyncio.gather(bystander(), victim())
        await server.drain()
        await driver
        return u_by, t_by, u_v, t_v

    u_by, t_by, u_v, t_v = asyncio.run(main())
    ref_by = np.asarray(
        generate(CFG, params, jnp.asarray(p_by)[None], 12, temperature=0.0)
    )[0]
    np.testing.assert_array_equal(np.asarray(t_by), ref_by[len(p_by):])
    fr = eng.finished[u_v]
    assert fr.status == "cancelled"
    ref_v = np.asarray(
        generate(CFG, params, jnp.asarray(p_victim)[None], 20, temperature=0.0)
    )[0]
    # the client consumed a prefix of the true greedy stream before leaving
    np.testing.assert_array_equal(
        np.asarray(t_v), ref_v[len(p_victim):len(p_victim) + len(t_v)]
    )
    assert eng.cancelled == 1
    assert eng.allocator.free_count == eng.allocator.num_pages - 1


def test_submit_backpressure_retry_succeeds_when_capacity_frees(params):
    """A retryable BackpressureError is absorbed by the bounded backoff:
    the second submit initially exceeds the backlog budget, then admits on
    a retry once the first request finishes."""
    p = np.arange(10, dtype=np.int32)
    eng = _engine(params, max_slots=1, max_backlog_pages=2)

    async def main():
        server = AsyncServeServer(
            eng, submit_retries=8, retry_backoff_s=0.02, idle_poll_s=0.001
        )
        driver = asyncio.create_task(server.run())
        u1 = await server.submit(p, 6)  # 2 pages: fills the whole budget

        async def consume(uid):
            return [tok async for tok in server.stream(uid)]

        c1 = asyncio.create_task(consume(u1))
        u2 = await server.submit(p, 6)  # sheds, backs off, then admits
        c2 = asyncio.create_task(consume(u2))
        t1, t2 = await asyncio.gather(c1, c2)
        await server.drain()
        await driver
        return u1, t1, u2, t2

    u1, t1, u2, t2 = asyncio.run(main())
    assert eng.shed >= 1, "the second submit must have been shed at least once"
    ref = np.asarray(
        generate(CFG, params, jnp.asarray(p)[None], 6, temperature=0.0)
    )[0]
    for toks in (t1, t2):
        np.testing.assert_array_equal(np.asarray(toks), ref[len(p):])
    assert eng.allocator.free_count == eng.allocator.num_pages - 1


def test_drain_via_preempt_flag_rejects_new_work(params):
    """SIGTERM path: the PR 3 one-shot preemption flag (driven directly,
    robustness/preempt.py test convention) flips the server into draining —
    in-flight requests finish, new submits raise ServerDraining, run()
    returns."""
    p1, p2 = _prompts(2, seed=3)
    eng = _engine(params)
    preempt.reset()

    async def main():
        server = AsyncServeServer(eng, idle_poll_s=0.001)
        driver = asyncio.create_task(server.run())
        u1 = await server.submit(p1, 12)
        stream = server.stream(u1)
        first = await stream.__anext__()
        preempt.request()  # what the SIGTERM handler does
        toks = [first] + [tok async for tok in stream]
        with pytest.raises(ServerDraining):
            await server.submit(p2, 4)
        await asyncio.wait_for(driver, timeout=30)
        assert server.draining
        return u1, toks

    try:
        u1, toks = asyncio.run(main())
    finally:
        preempt.reset()
    ref = np.asarray(
        generate(CFG, params, jnp.asarray(p1)[None], 12, temperature=0.0)
    )[0]
    np.testing.assert_array_equal(np.asarray(toks), ref[len(p1):])
    assert eng.finished[u1].status == "ok"


def test_slow_client_is_shed_not_served_forever(params):
    """The slow_client fault (step key = uid) wedges one stream; the
    bounded per-client buffer sheds exactly that request with status
    'slow_client' while the bystander streams to completion."""
    from midgpt_tpu.robustness import faults

    p_slow, p_by = _prompts(2, seed=4)
    eng = _engine(params)
    faults.clear()

    async def main():
        # bound must exceed a decode-chunk burst (tokens land per ROUND,
        # so a healthy consumer can briefly hold chunk-many undrained)
        server = AsyncServeServer(
            eng, max_buffered_tokens=8, idle_poll_s=0.001
        )
        driver = asyncio.create_task(server.run())
        u_slow = await server.submit(p_slow, 16)
        faults.activate("slow_client", step=u_slow)
        u_by = await server.submit(p_by, 10)

        async def consume(uid):
            return [tok async for tok in server.stream(uid)]

        t_slow, t_by = await asyncio.gather(consume(u_slow), consume(u_by))
        await server.drain()
        await driver
        return u_slow, t_slow, u_by, t_by

    try:
        u_slow, t_slow, u_by, t_by = asyncio.run(main())
    finally:
        faults.clear()
    assert eng.finished[u_slow].status == "slow_client"
    assert t_slow == []  # the wedged stream delivered nothing after stalling
    ref = np.asarray(
        generate(CFG, params, jnp.asarray(p_by)[None], 10, temperature=0.0)
    )[0]
    np.testing.assert_array_equal(np.asarray(t_by), ref[len(p_by):])
    assert eng.allocator.free_count == eng.allocator.num_pages - 1
