"""Cross-process fleet serving tests (sampling/fleet_proc.py).

Three tiers, cheapest first:

  * Transport units — frame codec, corrupt-frame rejection, deadlines,
    backoff-schedule reuse, heartbeat staleness — run against an in-process
    mini peer thread: no worker processes, no engines, milliseconds each.
  * The spill-transfer ledger law across a framed wire round-trip.
  * ONE non-slow end-to-end representative: 2 worker processes behind a
    FleetRouter, kill -9 mid-decode, zero drops + cross-process greedy
    parity (the gate chaos_serve._run_proc_fleet_chaos's docstring promises
    this file runs non-slow). The remaining wire-kind scenarios, SIGTERM
    drain, and live cross-worker spill transfer are @slow.
"""

import os
import signal
import socket
import subprocess
import threading
import zlib

import numpy as np
import pytest

from midgpt_tpu.robustness.backoff import backoff_delays
from midgpt_tpu.sampling import fleet_proc as fp
from midgpt_tpu.sampling.fleet_proc import (
    ReplicaGoneError,
    ReplicaTransport,
    SpillTransferItem,
    TransportError,
    WireFrameError,
    decode_frame,
    encode_frame,
)


# -- frame codec ------------------------------------------------------------


def test_frame_roundtrip_preserves_tree_and_dtypes():
    tree = {
        "op": "submit",
        "none": None,
        "flag": True,
        "n": 7,
        "x": 2.5,
        "s": "tok",
        "nested": {"list": [1, [2, {"deep": "yes"}]]},
        "k_f32": np.linspace(0, 1, 24, dtype=np.float32).reshape(2, 3, 4),
        "v_i8": np.arange(-8, 8, dtype=np.int8).reshape(4, 4),
        "ids": np.array([5, 6, 7], dtype=np.int32),
        "scalar": np.array(3.5, dtype=np.float64),
        "blocks": {"k": np.ones((2, 8), np.uint8)},
    }
    out = decode_frame(encode_frame(tree))
    assert out["op"] == "submit" and out["none"] is None
    assert out["flag"] is True and out["n"] == 7 and out["x"] == 2.5
    assert out["nested"] == {"list": [1, [2, {"deep": "yes"}]]}
    for key in ("k_f32", "v_i8", "ids", "scalar"):
        assert out[key].dtype == tree[key].dtype, key
        assert out[key].shape == tree[key].shape, key
        np.testing.assert_array_equal(out[key], tree[key])
    np.testing.assert_array_equal(out["blocks"]["k"], tree["blocks"]["k"])
    # landed arrays must be mutable (SpillTier.corrupt_one writes in place)
    assert out["k_f32"].flags.writeable
    out["k_f32"][0, 0, 0] = -1.0


def test_frame_rejects_garbage_before_decode():
    data = encode_frame({"op": "step", "payload": list(range(64))})

    with pytest.raises(WireFrameError) as ei:
        decode_frame(data[:3])
    assert ei.value.reason == "truncated" and ei.value.nbytes == 3

    with pytest.raises(WireFrameError) as ei:
        decode_frame(b"XGW1" + data[4:])
    assert ei.value.reason == "bad_magic"

    with pytest.raises(WireFrameError) as ei:
        decode_frame(data[:-2])
    assert ei.value.reason == "truncated"

    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    with pytest.raises(WireFrameError) as ei:
        decode_frame(bytes(flipped))
    assert ei.value.reason == "checksum"

    huge = fp._HEADER.pack(fp._MAGIC, fp.MAX_FRAME_BYTES + 1, 0)
    with pytest.raises(WireFrameError) as ei:
        decode_frame(huge)
    assert ei.value.reason == "length"


def test_error_contract_fields_are_present():
    """The GC016 registry (analysis/error_contracts.py) pins these
    signatures; this is the runtime half — every contract field lands as
    an attribute on a live instance."""
    te = TransportError("x", host="h", port=9, rpc="step", deadline_s=1.5)
    assert (te.host, te.port, te.rpc, te.deadline_s) == ("h", 9, "step", 1.5)
    assert isinstance(te, ConnectionError)

    wf = WireFrameError("x", reason="checksum", nbytes=12)
    assert (wf.reason, wf.nbytes) == ("checksum", 12)
    assert isinstance(wf, ValueError)

    rg = ReplicaGoneError("x", host="h", port=9, rpc="harvest", attempts=3)
    assert (rg.host, rg.port, rg.rpc, rg.attempts) == ("h", 9, "harvest", 3)
    assert isinstance(rg, ConnectionError)

    from midgpt_tpu.analysis.error_contracts import ERROR_CONTRACTS

    for name in ("TransportError", "WireFrameError", "ReplicaGoneError"):
        assert name in ERROR_CONTRACTS


# -- transport vs an in-process mini peer -----------------------------------


class _MiniPeer(threading.Thread):
    """Frame-speaking peer thread: echoes each request as
    {"ok": True, "seq": ...}; mode "mute" swallows requests so the
    caller's per-RPC deadline is the only way out."""

    def __init__(self, mode: str = "echo"):
        super().__init__(daemon=True)
        self.mode = mode
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self._halt = threading.Event()
        self.start()

    def run(self):
        self.srv.settimeout(0.05)
        while not self._halt.is_set():
            try:
                conn, _ = self.srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # stop() closed the listener under us
            with conn:
                conn.settimeout(0.05)
                while not self._halt.is_set():
                    try:
                        req = fp.read_frame(conn)
                    except socket.timeout:
                        continue
                    except (ConnectionError, OSError, WireFrameError):
                        break
                    if self.mode == "mute":
                        continue
                    try:
                        fp.write_frame(
                            conn, {"ok": True, "seq": req.get("seq")}
                        )
                    except (ConnectionError, OSError):
                        break

    def stop(self):
        self._halt.set()
        self.srv.close()
        self.join(timeout=2)


@pytest.fixture
def echo_peer():
    peer = _MiniPeer("echo")
    yield peer
    peer.stop()


@pytest.fixture
def mute_peer():
    peer = _MiniPeer("mute")
    yield peer
    peer.stop()


def test_deadline_expiry_escalates_to_replica_gone(mute_peer):
    slept = []
    t = ReplicaTransport(
        "127.0.0.1",
        mute_peer.port,
        rpc_deadline_s=0.15,
        call_retries=2,
        retry_base_s=0.01,
        sleep=slept.append,
    )
    with pytest.raises(ReplicaGoneError) as ei:
        t.call("ping")
    e = ei.value
    assert e.attempts == 2 and e.rpc == "ping"
    assert (e.host, e.port) == ("127.0.0.1", mute_peer.port)
    # both attempts timed out at the socket, each dropping the connection
    assert t.deadline_expiries == 2
    assert t.connects == 2 and t.reconnects == 1
    assert isinstance(e.__cause__, TransportError)
    assert e.__cause__.deadline_s == 0.15
    t.close()


def test_retry_sleeps_follow_the_shared_backoff_schedule(mute_peer):
    """The transport must reuse robustness/backoff.py verbatim: the sleeps
    between attempts ARE backoff_delays(retries, base_s), not a private
    schedule (pinned so the two can't drift apart)."""
    slept = []
    t = ReplicaTransport(
        "127.0.0.1",
        mute_peer.port,
        rpc_deadline_s=0.1,
        call_retries=3,
        retry_base_s=0.07,
        sleep=slept.append,
    )
    with pytest.raises(ReplicaGoneError):
        t.call("ping")
    assert slept == list(backoff_delays(3, 0.07))
    assert t.retries == len(slept) == 2
    t.close()


def test_connect_refused_is_replica_gone():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    t = ReplicaTransport(
        "127.0.0.1", dead_port, call_retries=2, retry_base_s=0.0,
        sleep=lambda _d: None,
    )
    with pytest.raises(ReplicaGoneError) as ei:
        t.call("hello")
    assert ei.value.attempts == 2
    assert t.connects == 0  # never got a connection at all


def test_heartbeat_tracks_injected_clock(echo_peer):
    ticks = iter([10.0, 10.5, 20.0, 20.25])
    t = ReplicaTransport(
        "127.0.0.1", echo_peer.port, clock=lambda: next(ticks)
    )
    assert t.heartbeat_age(99.0) is None  # no RPC yet: no heartbeat
    t.call("ping")
    assert t.last_ok == 10.5
    assert t.heartbeat_age(12.5) == pytest.approx(2.0)
    t.call("ping")  # a fresh RPC resets staleness
    assert t.last_ok == 20.25
    assert t.heartbeat_age(20.25) == pytest.approx(0.0)
    assert t.stats()["rpc_p95_ms"] >= t.stats()["rpc_p50_ms"] > 0
    t.close()


def test_wire_corrupt_rejected_pre_decode_then_recovers(echo_peer):
    t = ReplicaTransport(
        "127.0.0.1", echo_peer.port, call_retries=3, retry_base_s=0.0,
        sleep=lambda _d: None,
    )
    t.arm_wire_corrupt()
    reply = t.call("ping")
    assert reply["ok"] is True
    assert t.corrupt_frames == 1  # checksum rejected the flipped frame
    assert t.retries == 1 and t.reconnects == 1  # fresh conn recovered it
    t.close()


def test_wire_stall_counts_deadline_then_recovers(echo_peer):
    t = ReplicaTransport(
        "127.0.0.1", echo_peer.port, call_retries=3, retry_base_s=0.0,
        sleep=lambda _d: None,
    )
    t.arm_wire_stall()
    reply = t.call("ping")
    assert reply["ok"] is True
    assert t.deadline_expiries == 1 and t.retries == 1
    t.close()


def test_conn_drop_reconnects_transparently(echo_peer):
    t = ReplicaTransport("127.0.0.1", echo_peer.port)
    assert t.call("ping")["ok"] is True
    t.drop_conn()
    assert t.call("ping")["ok"] is True  # no retry needed, just reconnect
    assert t.forced_drops == 1 and t.reconnects == 1 and t.retries == 0
    assert t.stats()["rpc_count"] == 2
    t.close()


# -- spill transfer ledger across the wire ----------------------------------


def _transfer_items(n, wv="inline"):
    rng = np.random.default_rng(42)
    return [
        SpillTransferItem(
            key=(7, i),
            blocks={
                "k": rng.standard_normal((2, 8, 4)).astype(np.float32),
                "v": rng.standard_normal((2, 8, 4)).astype(np.float32),
            },
            checksum=zlib.crc32(b"page-%d" % i),
            weights_version=wv,
        )
        for i in range(n)
    ]


def test_spill_transfer_ledger_closes_across_wire_roundtrip():
    """Conservation across the boundary: pages leaving one tier through
    `transferred` re-enter another through `received` — after a real frame
    encode/decode — and BOTH ledgers keep closing (SpillTier.assert_ledger).
    Checksums must arrive unchanged: take-side verification covers transit
    and residence with the one spill-time number."""
    from midgpt_tpu.sampling.fleet import SpillTier

    items = _transfer_items(3)
    a, b = SpillTier(), SpillTier()
    a.import_entries(items)
    assert a.ledger()["received"] == 3 and a.resident_count() == 3
    a.assert_ledger("after landing")

    exported = a.export_entries()
    assert a.resident_count() == 0 and a.ledger()["transferred"] == 3
    a.assert_ledger("after export")  # moved out, still conserved

    # the actual wire: frame the export exactly like the spill RPCs do
    wired = decode_frame(
        encode_frame(
            [
                {
                    "key": list(it.key),
                    "blocks": it.blocks,
                    "checksum": it.checksum,
                    "weights_version": it.weights_version,
                }
                for it in exported
            ]
        )
    )
    landed = [
        SpillTransferItem(
            key=tuple(int(t) for t in d["key"]),
            blocks=d["blocks"],
            checksum=int(d["checksum"]),
            weights_version=str(d["weights_version"]),
        )
        for d in wired
    ]
    assert b.import_entries(landed) == 3
    b.assert_ledger("after import")
    out = {it.key: it for it in b.export_entries()}
    for it in items:
        got = out[it.key]
        assert got.checksum == it.checksum  # original spill-time crc32
        np.testing.assert_array_equal(got.blocks["k"], it.blocks["k"])

    # a duplicate delivery (retried RPC) discards, never double-counts
    b.import_entries(landed)
    c = SpillTier()
    c.import_entries(landed)
    c.import_entries(landed)
    led = c.ledger()
    assert led["received"] == 6 and led["stale_discarded"] == 3
    assert c.resident_count() == 3
    c.assert_ledger("after duplicate delivery")


# -- end-to-end worker processes --------------------------------------------


def test_proc_kill9_failover_representative():
    """THE cheap cross-process gate (kept non-slow deliberately — the
    chaos_serve proc docstrings cite this file for it): two worker
    processes behind a FleetRouter, SIGKILL the busiest mid-decode, and
    the fleet must finish every accepted stream token-for-token equal to
    a fault-free single-worker reference, with the router process
    compiling nothing."""
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    s = run_serving_chaos("proc_kill9@6", seed=0, n_requests=4)
    assert s["procs"] is True
    assert s["faults_fired"].get("proc_kill9", 0) >= 1
    assert s["dropped_streams"] == 0
    assert s["parity_checked"] == 4 and s["parity_ok"] == 4
    assert s["proc_failovers"] >= 1 and s["failovers"] >= 1
    assert s["failed_over_streams"] >= 1
    assert s["fleet_size"] == 2 and s["alive"] == 1
    assert s["pages_conserved"] is True
    assert s["router_compiles_delta"] == 0
    assert s["transport"]["rpc_count"] > 0


@pytest.mark.slow
def test_proc_conn_drop_absorbed():
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    s = run_serving_chaos("conn_drop@5", seed=0, n_requests=4)
    assert s["dropped_streams"] == 0
    assert s["parity_ok"] == s["parity_checked"] == 4
    assert s["transport"]["reconnects"] >= 1
    assert s["alive"] == 2  # absorbed by the transport: nobody failed over
    assert s["proc_failovers"] == 0


@pytest.mark.slow
def test_proc_wire_corrupt_absorbed():
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    s = run_serving_chaos("wire_corrupt@5", seed=0, n_requests=4)
    assert s["dropped_streams"] == 0
    assert s["parity_ok"] == s["parity_checked"] == 4
    assert s["transport"]["corrupt_frames"] >= 1
    assert s["transport"]["retries"] >= 1
    assert s["alive"] == 2


@pytest.mark.slow
def test_proc_wire_stall_absorbed():
    from midgpt_tpu.robustness.chaos_serve import run_serving_chaos

    s = run_serving_chaos("wire_stall@5", seed=0, n_requests=4)
    assert s["dropped_streams"] == 0
    assert s["parity_ok"] == s["parity_checked"] == 4
    assert s["transport"]["deadline_expiries"] >= 1
    assert s["alive"] == 2


@pytest.mark.slow
def test_sigterm_drains_worker_to_clean_exit():
    """SIGTERM routes through the preempt flag: the worker refuses new
    admissions with NON-retryable backpressure, finishes its in-flight
    streams, and exits 0 once idle and disconnected."""
    from midgpt_tpu.robustness.chaos_serve import _tiny_cfg, _trace, proc_worker_spec
    from midgpt_tpu.sampling.serve import BackpressureError

    proc, port = fp.spawn_worker(proc_worker_spec(0))
    try:
        rep = fp.connect_replica(port)
        trace = _trace(_tiny_cfg(), 1, 3, shared=True)
        uids = [rep.submit(p, m) for p, m in trace[:2]]
        os.kill(rep.pid, signal.SIGTERM)
        rep.step()  # worker notices the flag between RPCs
        with pytest.raises(BackpressureError) as ei:
            rep.submit(*trace[2])
        assert ei.value.retryable is False
        rep.run()  # in-flight streams still finish
        for uid in uids:
            assert rep.finished[uid].status == "ok"
        rep.assert_conserved("after drain")
        rep.close()
        assert proc.wait(timeout=60) == 0
    finally:
        try:
            proc.kill()
        except OSError:
            pass


@pytest.mark.slow
def test_spill_transfer_between_live_workers():
    """A trie flush spills KV into worker A's host tier; export_spill /
    import_spill move it to worker B over the wire and BOTH workers'
    conservation laws (pool + spill ledger, checked in-process via the
    conserve RPC) keep closing."""
    from midgpt_tpu.robustness.chaos_serve import _tiny_cfg, _trace, proc_worker_spec

    workers = fp.spawn_workers(proc_worker_spec(0), 2)
    try:
        a, b = (fp.connect_replica(port) for _, port in workers)
        trace = _trace(_tiny_cfg(), 1, 4, shared=True)
        for prompt, m in trace:
            a.submit(prompt, m)
        a.run()
        a._evict_shared_prefix_fault()  # flush the trie -> spill to tier

        items = a.export_spill()
        assert items, "trie flush spilled nothing — the test lost its prey"
        assert b.import_spill(items) == len(items)

        a.assert_conserved("after export")
        b.assert_conserved("after import")
        assert a.spill_ledger()["transferred"] == len(items)
        assert b.spill_ledger()["received"] == len(items)
        assert b.spill_ledger()["resident"] == len(items)
        a.close()
        b.close()
    finally:
        for proc, _port in workers:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
