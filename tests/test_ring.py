"""Ring attention (sequence-parallel) parity vs the single-device oracle.

The `sp` mesh axis stops being plumbing here: these tests shard the sequence
over 2 and 4 virtual CPU devices and assert the ring produces the same
outputs AND the same gradients as unsharded causal attention, including the
long-context shape (T=4096) the reference cannot represent at all (its
materialized T x T scores, reference model.py:71-73).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from midgpt_tpu.ops.attention import naive_causal_attention
from midgpt_tpu.parallel.ring_attention import ring_attention_sharded


def _mesh(sp: int) -> Mesh:
    devs = np.array(jax.devices()[: 2 * sp]).reshape(2, 1, sp)
    return Mesh(devs, ("data", "fsdp", "sp"))


def _qkv(B=4, H=2, T=128, C=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (B, H, T, C), dtype) for k in ks)


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_naive_forward(sp):
    q, k, v = _qkv()
    mesh = _mesh(sp)
    out = ring_attention_sharded(q, k, v, mesh)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gradients_match(sp=2):
    """AD through the ring (scan + ppermute) equals AD through the oracle."""
    q, k, v = _qkv(B=2, H=2, T=64, C=8)
    mesh = _mesh(sp)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring_attention_sharded(q, k, v, mesh)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=3e-5, rtol=3e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_ring_long_context_t4096():
    """T=4096 across sp=4: per-device score blocks are (1024, 1024) — the
    full T x T matrix is never materialized on any device."""
    q, k, v = _qkv(B=2, H=1, T=4096, C=8, dtype=jnp.bfloat16)
    mesh = _mesh(4)
    out = ring_attention_sharded(q, k, v, mesh)
    assert out.shape == (2, 1, 4096, 8)
    # oracle on a slice: the final 16 positions attend across every shard
    ref = naive_causal_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out[..., -16:, :], dtype=np.float32),
        np.asarray(ref[..., -16:, :]),
        atol=3e-2,
        rtol=3e-2,
    )


def test_ring_respects_sharding_layout():
    """Inputs placed with the T axis actually sharded over sp stay sharded:
    the ring only ever moves K/V shards (neighbor ppermute), never gathers."""
    q, k, v = _qkv(T=128)
    mesh = _mesh(2)
    sh = NamedSharding(mesh, P(("data", "fsdp"), None, "sp", None))
    q, k, v = (jax.device_put(a, sh) for a in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh))(q, k, v)
    assert out.sharding.spec == P(("data", "fsdp"), None, "sp", None)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_train_step_matches_naive_sp1():
    """One full training step (FSDP x SP mesh, ring attention, T sharded over
    'sp') produces the same loss as the naive-attention sp=1 step on the same
    batch and seed — sequence parallelism changes the schedule, not the math."""
    import dataclasses

    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPTConfig
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.train import init_state, make_train_step

    base = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=10,
        min_lr=1e-4,
        lr_decay_steps=100,
        max_steps=100,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=50,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        mesh=MeshConfig(data=2, fsdp=2, sp=2),
        model_config=GPTConfig(
            block_size=64, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            attn_impl="ring",
        ),
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, (1, 8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)

    losses = {}
    for name, cfg in {
        "ring_sp2": base,
        "naive_sp1": base.replace(
            mesh=MeshConfig(data=2, fsdp=4, sp=1),
            model_config=dataclasses.replace(base.model_config, attn_impl="naive"),
        ),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, _, _ = make_train_step(cfg, optimizer, mesh, specs)
        sp = batch_spec(shard_seq=cfg.mesh.sp > 1)
        xg = make_global_batch(x, mesh, sp)
        yg = make_global_batch(y, mesh, sp)
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)

    assert np.isfinite(losses["ring_sp2"])
    np.testing.assert_allclose(losses["ring_sp2"], losses["naive_sp1"], rtol=1e-5)


@pytest.mark.parametrize("block_size", [16, 32, 48, 64])
def test_ring_kv_subblocking_parity(block_size):
    """Sub-blocking the visiting K/V shard (bounded scores memory) is exact:
    same outputs for any block size, including non-dividing relationships."""
    q, k, v = _qkv(B=2, H=2, T=128, C=16)
    mesh = _mesh(2)
    out = ring_attention_sharded(q, k, v, mesh, block_size=block_size)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_kernel_path_forward_parity(sp):
    """The Pallas-kernel per-pair path (the one a real TPU slice runs):
    interpret mode on CPU, forced with use_kernel=True. The diagonal pair
    uses the causal kernel, off-diagonal pairs the non-causal kernel."""
    q, k, v = _qkv(T=128, C=32)
    mesh = _mesh(sp)
    out = ring_attention_sharded(q, k, v, mesh, use_kernel=True)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_kernel_path_gradients(sp):
    """Backward through the authored ring backward pass (custom VJP, flash
    backward kernels per pair, dK/dV riding the ring) equals oracle AD."""
    q, k, v = _qkv(B=2, H=2, T=128, C=32)
    mesh = _mesh(sp)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring_attention_sharded(q, k, v, mesh, use_kernel=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), atol=3e-5, rtol=3e-5, err_msg=f"d{name}"
        )


def test_ring_kernel_jnp_paths_agree():
    """Both per-pair implementations of the same ring schedule produce the
    same result (kernel path in interpret mode vs blockwise jnp)."""
    q, k, v = _qkv(B=2, H=2, T=256, C=16)
    mesh = _mesh(4)
    out_k = ring_attention_sharded(q, k, v, mesh, use_kernel=True)
    out_j = ring_attention_sharded(q, k, v, mesh, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_j), atol=2e-5, rtol=2e-5)


def test_ring_kernel_auto_falls_back_on_unservable_shard():
    """Shard lengths with no kernel-servable block (Tl=160 at block 64: no
    8-aligned divisor of 160 in [128, 64] exists) fall back to the jnp pair
    path rather than erroring — parity holds, and the perf cliff announces
    itself with a one-time RuntimeWarning naming the shapes."""
    from midgpt_tpu.parallel import ring_attention as ring_mod

    q, k, v = _qkv(B=2, H=1, T=320, C=16)  # Tl=160 over sp=2; 160 % 64 != 0
    mesh = _mesh(2)
    ring_mod._WARNED.clear()
    with pytest.warns(RuntimeWarning, match="shard length 160"):
        out = ring_attention_sharded(q, k, v, mesh, block_size=64, use_kernel=True)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_ring_kernel_block_auto_adjusts_to_divisor():
    """Tl=1280 at the default block 1024 does NOT fall back: the plan
    auto-adjusts to the largest 8-aligned divisor in [128, 1024] (640) and
    stays on the kernel path — no warning, kernel parity."""
    import warnings

    from midgpt_tpu.parallel import ring_attention as ring_mod

    assert ring_mod._resolve_pair_plan(1280, 1024, True) == (True, 640)
    # already servable (the dispatcher clamps the block to Tl): unchanged
    assert ring_mod._resolve_pair_plan(160, 1024, True) == (True, 1024)
    # fallback cases return use_kernel=False unchanged
    ring_mod._WARNED.clear()
    with pytest.warns(RuntimeWarning):
        assert ring_mod._resolve_pair_plan(120, 64, True) == (False, 64)

    q, k, v = _qkv(B=2, H=2, T=2560, C=32, dtype=jnp.float32)  # Tl=1280 over sp=2
    mesh = _mesh(2)
    ring_mod._WARNED.clear()
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no fallback warning
        out = ring_attention_sharded(q, k, v, mesh, block_size=1024, use_kernel=True)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5)
