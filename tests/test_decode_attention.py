"""Paged decode attention (kernels/decode_attention.py): the Pallas kernel
(interpret mode — no TPU in CI), the XLA gather fallback, and a dense
masked reference must agree on arbitrary page tables and ragged lengths."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.kernels.decode_attention import (
    paged_attention,
    paged_attention_gather,
    paged_attention_kernel,
)

B, H, C = 3, 2, 128  # C spans the full Mosaic lane dim
PS, NP, MP = 8, 7, 4  # page_size, pool pages, max logical pages/slot


def _problem(seed=0, dtype=jnp.float32):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (B, H, C), dtype)
    k_pages = jax.random.normal(keys[1], (H, NP, PS, C), dtype)
    v_pages = jax.random.normal(keys[2], (H, NP, PS, C), dtype)
    # Non-trivial allocation: slots own disjoint, non-contiguous pages;
    # unallocated logical pages point at the sink (0).
    page_table = jnp.asarray(
        [[3, 1, 0, 0], [5, 2, 6, 0], [4, 0, 0, 0]], jnp.int32
    )
    lengths = jnp.asarray([11, 24, 1], jnp.int32)  # ragged, page-unaligned
    return q, k_pages, v_pages, page_table, lengths


def _dense_reference(q, k_pages, v_pages, page_table, lengths):
    """Materialize each slot's logical K/V and run plain masked attention."""
    out = []
    for b in range(B):
        kb = np.concatenate(
            [np.asarray(k_pages)[:, p] for p in np.asarray(page_table)[b]], axis=1
        )  # (H, MP*PS, C)
        vb = np.concatenate(
            [np.asarray(v_pages)[:, p] for p in np.asarray(page_table)[b]], axis=1
        )
        n = int(lengths[b])
        s = np.einsum("hc,hkc->hk", np.asarray(q)[b], kb) / math.sqrt(C)
        s[:, n:] = -np.inf
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out.append(np.einsum("hk,hkc->hc", p, vb))
    return np.stack(out)


def test_gather_fallback_matches_dense_reference():
    q, kp, vp, pt, ln = _problem()
    got = paged_attention_gather(q, kp, vp, pt, ln)
    np.testing.assert_allclose(
        np.asarray(got), _dense_reference(q, kp, vp, pt, ln), atol=2e-5, rtol=2e-5
    )


def test_kernel_interpret_matches_gather():
    """The Mosaic kernel (interpret mode off-TPU) must reproduce the gather
    fallback — including mid-page masking and the length-0/sink-read path —
    so the serving engine can switch impl by backend without parity drift."""
    q, kp, vp, pt, ln = _problem(seed=1)
    want = np.asarray(paged_attention_gather(q, kp, vp, pt, ln))
    got = np.asarray(paged_attention_kernel(q, kp, vp, pt, ln))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_kernel_zero_length_slot_is_finite_zero():
    """A just-admitted (length 0) slot must emit zeros, not NaN (the
    l == 0 safe-divide in the kernel epilogue)."""
    q, kp, vp, pt, _ = _problem(seed=2)
    ln = jnp.asarray([0, 5, 0], jnp.int32)
    got = np.asarray(paged_attention_kernel(q, kp, vp, pt, ln))
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[0], 0.0)
    np.testing.assert_array_equal(got[2], 0.0)


def test_dispatcher_selects_gather_off_tpu():
    q, kp, vp, pt, ln = _problem(seed=3)
    auto = paged_attention(q, kp, vp, pt, ln, impl="auto")
    gather = paged_attention_gather(q, kp, vp, pt, ln)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(gather))
    with pytest.raises(ValueError, match="unknown paged attention impl"):
        paged_attention(q, kp, vp, pt, ln, impl="nope")
