"""End-to-end training tests on the virtual 8-device mesh: loss goes down,
grad accumulation is consistent, the compiled step donates its buffers."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.data.dataset import TokenDataset, sample_batch
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.training.train import init_state, make_train_step, train


def tiny_config(tmpdir, **overrides) -> ExperimentConfig:
    base = dict(
        rundir="",
        data_dir=str(tmpdir),
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=60,
        max_steps=60,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=30,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        mesh=MeshConfig(data=2, fsdp=4, sp=1),
        eval_steps=4,
        fsdp_min_size=0,
        model_config=GPTConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64
        ),
    )
    base.update(overrides)
    return ExperimentConfig(**base)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    """Synthetic learnable token stream: token[i+1] = (token[i] + 1) % 17."""
    d = tmp_path_factory.mktemp("data")
    stream = (np.arange(20000) % 17).astype(np.uint16)
    stream.tofile(d / "train.bin")
    stream[:4000].tofile(d / "val.bin")
    return d


def test_sample_batch_shapes_and_shift(data_dir):
    ds = TokenDataset(str(data_dir), seed=7)
    x, y = ds.batch("train", 0, 16, 4, 2)
    assert x.shape == (2, 4, 16) and y.shape == (2, 4, 16)
    np.testing.assert_array_equal(y[..., :-1], x[..., 1:])
    # determinism / resumability: same (split, step) -> same batch
    x2, y2 = ds.batch("train", 0, 16, 4, 2)
    np.testing.assert_array_equal(x, x2)
    x3, _ = ds.batch("train", 1, 16, 4, 2)
    assert not np.array_equal(x, x3)


def test_loss_decreases(data_dir):
    cfg = tiny_config(data_dir)
    result = train(cfg)
    m = result["metrics"]
    assert m["loss/final"] < 1.0, f"final loss too high: {m}"
    assert m["loss/final"] < m["loss/val"], "loss did not improve"


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_grad_accum_equivalence(data_dir):
    """G=2 with batch B must match G=1 with batch 2B (same data, same key)."""
    cfg1 = tiny_config(data_dir, g_accum_iters=1, batch_size=16, compute_dtype="float32")
    cfg2 = tiny_config(data_dir, g_accum_iters=2, batch_size=8, compute_dtype="float32")
    mesh = make_mesh(cfg1.mesh)

    params, opt_state, specs, optimizer = init_state(cfg1, mesh)
    step1, *_ = make_train_step(cfg1, optimizer, mesh, specs)
    step2, *_ = make_train_step(cfg2, optimizer, mesh, specs)

    ds = TokenDataset(str(data_dir), seed=3)
    x, y = ds.batch("train", 0, cfg1.model_config.block_size, 16, 1)  # (1, 16, T)
    key = jax.random.PRNGKey(42)

    p1, o1, loss1 = step1(
        jax.tree.map(jnp.copy, params),
        jax.tree.map(jnp.copy, opt_state),
        make_global_batch(x, mesh, batch_spec()),
        make_global_batch(y, mesh, batch_spec()),
        key,
    )
    x2 = x.reshape(2, 8, -1)
    y2 = y.reshape(2, 8, -1)
    p2, o2, loss2 = step2(
        jax.tree.map(jnp.copy, params),
        jax.tree.map(jnp.copy, opt_state),
        make_global_batch(x2, mesh, batch_spec()),
        make_global_batch(y2, mesh, batch_spec()),
        key,
    )
    # Same total data: mean loss equal, updated params equal (both fp32).
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_mixed_precision_step_runs(data_dir):
    cfg = tiny_config(data_dir, compute_dtype="bfloat16", max_steps=3, eval_interval=100)
    mesh = make_mesh(cfg.mesh)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    step, *_ = make_train_step(cfg, optimizer, mesh, specs)
    ds = TokenDataset(str(data_dir), seed=3)
    x, y = ds.batch("train", 0, cfg.model_config.block_size, cfg.batch_size, 1)
    loss = None
    for i in range(3):
        params, opt_state, loss = step(
            params,
            opt_state,
            make_global_batch(x, mesh, batch_spec()),
            make_global_batch(y, mesh, batch_spec()),
            jax.random.PRNGKey(i),
        )
        # master params stay fp32
        assert params.wte.dtype == jnp.float32
    assert bool(jnp.isfinite(loss))


def test_evaluate_chunked_matches_monolithic(data_dir):
    """Bounded-host-memory eval (eval_host_chunk) sums the same windows as a
    single-program eval: same result up to f32 chunk-subtotal association."""
    from midgpt_tpu.training.train import evaluate

    cfg = tiny_config(data_dir, eval_steps=8, eval_host_chunk=3)
    mesh = make_mesh(cfg.mesh)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    _, _, eval_loss_many = make_train_step(cfg, optimizer, mesh, specs)

    ds = TokenDataset(str(data_dir), seed=cfg.data_seed)
    chunked = evaluate(cfg, eval_loss_many, params, ds, "val", mesh, 0)
    mono = evaluate(
        cfg.replace(eval_host_chunk=1000), eval_loss_many, params, ds, "val", mesh, 0
    )
    np.testing.assert_allclose(chunked, mono, rtol=1e-6)

    # accum_slice windows == the corresponding slice of the monolithic draw
    xa, _ = ds.batch("val", 5, 16, 4, g_accum_iters=8)
    xs, _ = ds.batch("val", 5, 16, 4, g_accum_iters=8, accum_slice=(2, 3))
    np.testing.assert_array_equal(xa[2:5], xs)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_divergence_guard_stops_loudly(data_dir, tmp_path):
    """A diverging run (absurd lr) must raise FloatingPointError instead of
    training on — or CHECKPOINTING — NaNs (auxiliary failure-detection the
    reference lacks; SURVEY §5.3). The step folds a post-update finiteness
    flag into the reported loss, so the pre-save gate sees poisoned params
    the same iteration they appear: any checkpoint left behind must restore
    to fully finite state."""
    cfg = tiny_config(
        data_dir,
        rundir=str(tmp_path),
        learning_rate=1e25,
        min_lr=1e24,
        warmup_steps=1,
        log_interval=1,
        max_steps=30,
        eval_interval=2,  # saves would happen every 2 steps — none may be NaN
    )
    with pytest.raises(FloatingPointError, match="non-finite"):
        train(cfg)

    from midgpt_tpu.training.checkpoint import CheckpointManager
    from midgpt_tpu.training.train import init_state

    mngr = CheckpointManager(str(tmp_path))
    step = mngr.latest_step()
    if step is not None:  # whatever was saved must be clean
        mesh = make_mesh(cfg.mesh)
        params, opt_state, *_ = init_state(cfg, mesh)
        state = mngr.restore(step, {"params": params, "opt_state": opt_state})
        for leaf in jax.tree.leaves(state["params"]):
            assert bool(jnp.isfinite(leaf).all()), "poisoned checkpoint saved"


def test_health_flag_semantics():
    """The step's health check (train.health_flag) must (a) NOT flag
    large-but-finite grads whose squared global norm overflows fp32 — clip(1.0)
    recovers from those, a hard stop would be spurious (ADVICE r4); (b) flag
    any NaN/Inf grad leaf or loss; (c) be sticky through the reported-loss
    carrier: a finite step after a poisoned one must still report NaN."""
    from midgpt_tpu.training.train import health_flag

    ok = jnp.float32(2.5)
    prev = jnp.float32(0.1)
    huge = {"a": jnp.full((64,), 1e20, jnp.float32), "b": jnp.ones((3,))}
    # (a) squared norm overflows to inf, but every leaf is finite -> healthy
    import optax

    assert not bool(jnp.isfinite(optax.global_norm(huge))), "premise: overflow"
    assert float(health_flag(huge, ok, prev)) == 2.5
    # (b) one NaN leaf / one inf leaf / NaN loss -> poisoned
    bad_nan = {"a": jnp.ones((4,)).at[2].set(jnp.nan)}
    bad_inf = {"a": jnp.ones((4,)).at[0].set(jnp.inf)}
    assert not np.isfinite(float(health_flag(bad_nan, ok, prev)))
    assert not np.isfinite(float(health_flag(bad_inf, ok, prev)))
    assert not np.isfinite(float(health_flag(huge, jnp.float32(jnp.nan), prev)))
    # (c) sticky: clean step, poisoned history -> still NaN
    assert not np.isfinite(float(health_flag(huge, ok, jnp.float32(jnp.nan))))


def test_step_sticky_health(data_dir):
    """End-to-end stickiness through the compiled step: passing a NaN
    prev_loss into an otherwise healthy step must return NaN loss, so a
    poisoning at a never-inspected step reaches the next log/save gate."""
    cfg = tiny_config(data_dir, max_steps=2, eval_interval=100)
    mesh = make_mesh(cfg.mesh)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    step, *_ = make_train_step(cfg, optimizer, mesh, specs)
    ds = TokenDataset(str(data_dir), seed=3)
    x, y = ds.batch("train", 0, cfg.model_config.block_size, cfg.batch_size, 1)
    xg = make_global_batch(x, mesh, batch_spec())
    yg = make_global_batch(y, mesh, batch_spec())
    _, _, loss = step(
        jax.tree.map(jnp.copy, params), jax.tree.map(jnp.copy, opt_state),
        xg, yg, jax.random.PRNGKey(0), jnp.float32(jnp.nan),
    )
    assert not np.isfinite(float(loss)), "health flag not sticky"
    # and a clean history reports the true (finite) loss
    _, _, loss2 = step(params, opt_state, xg, yg, jax.random.PRNGKey(0),
                       jnp.float32(0.0))
    assert np.isfinite(float(loss2))


def test_beta2_validated_at_construction(data_dir):
    """beta2 >= 1 would NaN adam's bias correction with finite grads —
    invisible to the step's grad-norm health check — so it must be rejected
    at config construction."""
    for bad in (1.0, 1.5, 0.0):
        with pytest.raises(ValueError, match="beta2"):
            tiny_config(data_dir, beta2=bad)


def test_qkv_proj_validated_at_construction(data_dir):
    """A qkv_proj typo must fail at construction — it would otherwise
    silently select the fused lowering AND bypass the tp auto-switch."""
    import dataclasses

    with pytest.raises(ValueError, match="qkv_proj"):
        tiny_config(
            data_dir,
            model_config=dataclasses.replace(
                tiny_config(data_dir).model_config, qkv_proj="fuesd"
            ),
        )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_resume_rejects_corrupt_checkpoint(data_dir, tmp_path):
    """The health induction's base case: a restored checkpoint containing
    NaN (corruption, bad migration) must abort the resume, not train on."""
    cfg = tiny_config(
        data_dir, rundir=str(tmp_path), max_steps=4, eval_interval=2,
    )
    train(cfg)  # writes a good checkpoint

    from midgpt_tpu.training.checkpoint import CheckpointManager

    mngr = CheckpointManager(str(tmp_path))
    step = mngr.latest_step()
    mesh = make_mesh(cfg.mesh)
    params, opt_state, *_ = init_state(cfg, mesh)
    state = mngr.restore(step, {"params": params, "opt_state": opt_state})
    # poison one master-param leaf and save it back as a NEWER step
    poisoned = state["params"]
    poisoned = jax.tree.map(lambda x: x, poisoned)
    leaves, treedef = jax.tree.flatten(poisoned)
    leaves[0] = leaves[0].at[0].set(jnp.nan)
    poisoned = jax.tree.unflatten(treedef, leaves)
    mngr.save(step + 1, {"params": poisoned, "opt_state": state["opt_state"]}, force=True)
    mngr.wait()
    mngr.close()

    with pytest.raises(FloatingPointError, match="corrupt"):
        train(cfg.replace(max_steps=10))
