import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.ops import (
    cross_entropy_loss,
    dropout,
    head_layer_norm,
    multihead_attention,
    rms_norm,
)
from midgpt_tpu.ops.attention import blockwise_causal_attention, naive_causal_attention


def test_rms_norm_unit_scale():
    x = jnp.full((4, 8), 3.0)
    out = rms_norm(x)
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 8)), rtol=1e-5)


def test_rms_norm_matches_formula():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (3, 16))
    expected = x * (1.0 / np.sqrt(np.mean(np.square(np.asarray(x)), axis=-1, keepdims=True) + 1e-6))
    np.testing.assert_allclose(np.asarray(rms_norm(x)), expected, rtol=1e-5)


def test_rms_norm_weighted():
    x = jnp.ones((2, 4))
    w = jnp.arange(4.0)
    out = rms_norm(x, weight=w)
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(4.0) * np.asarray(rms_norm(x))[0, 0], rtol=1e-5)


def test_head_layer_norm_zero_mean_unit_var():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (5, 32)) * 4 + 7
    out = head_layer_norm(x, jnp.ones((32,)))
    np.testing.assert_allclose(np.asarray(jnp.mean(out, -1)), np.zeros(5), atol=1e-5)
    np.testing.assert_allclose(np.asarray(jnp.std(out, -1)), np.ones(5), atol=1e-2)


def test_dropout_inference_identity():
    x = jnp.ones((8, 8))
    assert (dropout(x, 0.5, None, inference=True) == x).all()
    assert (dropout(x, 0.0, None, inference=False) == x).all()


def test_dropout_scales_kept_values():
    key = jax.random.PRNGKey(2)
    x = jnp.ones((1000,))
    out = np.asarray(dropout(x, 0.25, key, inference=False))
    kept = out[out != 0]
    np.testing.assert_allclose(kept, np.full_like(kept, 1 / 0.75), rtol=1e-5)
    assert 0.6 < (out != 0).mean() < 0.9


def test_cross_entropy_uniform_logits():
    logits = jnp.zeros((4, 7, 13))
    labels = jnp.zeros((4, 7), dtype=jnp.int32)
    np.testing.assert_allclose(float(cross_entropy_loss(logits, labels)), np.log(13), rtol=1e-5)


def test_cross_entropy_peaked_logits():
    labels = jnp.array([[2, 5]])
    logits = jnp.full((1, 2, 8), -30.0)
    logits = logits.at[0, 0, 2].set(30.0).at[0, 1, 5].set(30.0)
    assert float(cross_entropy_loss(logits, labels)) < 1e-5


@pytest.mark.parametrize("T,block", [(64, 16), (128, 128), (96, 32), (100, 32), (7, 16)])
def test_blockwise_attention_matches_naive(T, block):
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    B, H, C = 2, 3, 16
    q = jax.random.normal(kq, (B, H, T, C))
    k = jax.random.normal(kk, (B, H, T, C))
    v = jax.random.normal(kv, (B, H, T, C))
    ref = naive_causal_attention(q, k, v)
    out = blockwise_causal_attention(q, k, v, block_size=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_attention_causality():
    """Changing a future token must not change earlier outputs."""
    key = jax.random.PRNGKey(4)
    B, H, T, C = 1, 2, 32, 8
    q, k, v = jax.random.split(key, 3)
    q = jax.random.normal(q, (B, H, T, C))
    k = jax.random.normal(k, (B, H, T, C))
    v = jax.random.normal(v, (B, H, T, C))
    out1 = multihead_attention(q, k, v, impl="naive", inference=True)
    k2 = k.at[:, :, -1].add(100.0)
    v2 = v.at[:, :, -1].add(100.0)
    out2 = multihead_attention(q, k2, v2, impl="naive", inference=True)
    np.testing.assert_allclose(np.asarray(out1[:, :, :-1]), np.asarray(out2[:, :, :-1]), atol=1e-5)
