import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.ops import (
    cross_entropy_loss,
    dropout,
    head_layer_norm,
    multihead_attention,
    rms_norm,
)
from midgpt_tpu.ops.attention import blockwise_causal_attention, naive_causal_attention


def test_rms_norm_unit_scale():
    x = jnp.full((4, 8), 3.0)
    out = rms_norm(x)
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 8)), rtol=1e-5)


def test_rms_norm_matches_formula():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (3, 16))
    expected = x * (1.0 / np.sqrt(np.mean(np.square(np.asarray(x)), axis=-1, keepdims=True) + 1e-6))
    np.testing.assert_allclose(np.asarray(rms_norm(x)), expected, rtol=1e-5)


def test_rms_norm_weighted():
    x = jnp.ones((2, 4))
    w = jnp.arange(4.0)
    out = rms_norm(x, weight=w)
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(4.0) * np.asarray(rms_norm(x))[0, 0], rtol=1e-5)


def test_head_layer_norm_zero_mean_unit_var():
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (5, 32)) * 4 + 7
    out = head_layer_norm(x, jnp.ones((32,)))
    np.testing.assert_allclose(np.asarray(jnp.mean(out, -1)), np.zeros(5), atol=1e-5)
    np.testing.assert_allclose(np.asarray(jnp.std(out, -1)), np.ones(5), atol=1e-2)


def test_dropout_inference_identity():
    x = jnp.ones((8, 8))
    assert (dropout(x, 0.5, None, inference=True) == x).all()
    assert (dropout(x, 0.0, None, inference=False) == x).all()


def test_dropout_scales_kept_values():
    key = jax.random.PRNGKey(2)
    x = jnp.ones((1000,))
    out = np.asarray(dropout(x, 0.25, key, inference=False))
    kept = out[out != 0]
    np.testing.assert_allclose(kept, np.full_like(kept, 1 / 0.75), rtol=1e-5)
    assert 0.6 < (out != 0).mean() < 0.9


def test_cross_entropy_uniform_logits():
    logits = jnp.zeros((4, 7, 13))
    labels = jnp.zeros((4, 7), dtype=jnp.int32)
    np.testing.assert_allclose(float(cross_entropy_loss(logits, labels)), np.log(13), rtol=1e-5)


def test_cross_entropy_peaked_logits():
    labels = jnp.array([[2, 5]])
    logits = jnp.full((1, 2, 8), -30.0)
    logits = logits.at[0, 0, 2].set(30.0).at[0, 1, 5].set(30.0)
    assert float(cross_entropy_loss(logits, labels)) < 1e-5


@pytest.mark.parametrize(
    # (256, 16) = 16 Q blocks: exercises the rolled lax.map path (> 8 blocks)
    "T,block", [(64, 16), (128, 128), (96, 32), (100, 32), (7, 16), (256, 16)]
)
def test_blockwise_attention_matches_naive(T, block):
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    B, H, C = 2, 3, 16
    q = jax.random.normal(kq, (B, H, T, C))
    k = jax.random.normal(kk, (B, H, T, C))
    v = jax.random.normal(kv, (B, H, T, C))
    ref = naive_causal_attention(q, k, v)
    out = blockwise_causal_attention(q, k, v, block_size=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_attention_causality():
    """Changing a future token must not change earlier outputs."""
    key = jax.random.PRNGKey(4)
    B, H, T, C = 1, 2, 32, 8
    q, k, v = jax.random.split(key, 3)
    q = jax.random.normal(q, (B, H, T, C))
    k = jax.random.normal(k, (B, H, T, C))
    v = jax.random.normal(v, (B, H, T, C))
    out1 = multihead_attention(q, k, v, impl="naive", inference=True)
    k2 = k.at[:, :, -1].add(100.0)
    v2 = v.at[:, :, -1].add(100.0)
    out2 = multihead_attention(q, k2, v2, impl="naive", inference=True)
    np.testing.assert_allclose(np.asarray(out1[:, :, :-1]), np.asarray(out2[:, :, :-1]), atol=1e-5)


@pytest.mark.parametrize("chunk", [64, 256, 1000])  # 1000: bulk chunks + remainder tail
def test_fused_linear_cross_entropy_matches_unfused(chunk):
    from midgpt_tpu.ops.loss import fused_linear_cross_entropy

    key = jax.random.PRNGKey(7)
    kh, kw, kl = jax.random.split(key, 3)
    B, T, D, V = 2, 128, 16, 97
    hidden = jax.random.normal(kh, (B, T, D))
    lm_head = jax.random.normal(kw, (V, D)) * 0.1
    labels = jax.random.randint(kl, (B, T), 0, V)

    ref = cross_entropy_loss(jnp.einsum("btd,vd->btv", hidden, lm_head), labels)
    out = fused_linear_cross_entropy(hidden, lm_head, labels, chunk)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-6)


def test_fused_linear_cross_entropy_grads_match():
    from midgpt_tpu.ops.loss import fused_linear_cross_entropy

    key = jax.random.PRNGKey(8)
    kh, kw, kl = jax.random.split(key, 3)
    B, T, D, V = 2, 64, 8, 33
    hidden = jax.random.normal(kh, (B, T, D))
    lm_head = jax.random.normal(kw, (V, D)) * 0.1
    labels = jax.random.randint(kl, (B, T), 0, V)

    def ref_loss(h, w):
        return cross_entropy_loss(jnp.einsum("btd,vd->btv", h, w), labels)

    def fused_loss(h, w):
        return fused_linear_cross_entropy(h, w, labels, 32)

    gh_ref, gw_ref = jax.grad(ref_loss, argnums=(0, 1))(hidden, lm_head)
    gh, gw = jax.grad(fused_loss, argnums=(0, 1))(hidden, lm_head)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref), atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref), atol=1e-6, rtol=1e-5)
