"""Mixture-of-experts MLP (models/gpt.py MoEParams) + expert parallelism.

The only §2.3 parallelism strategy absent from BOTH trees until r5
(VERDICT r4 #9): dense -> top-k routed MLP over a mesh 'ep' axis."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig, MLPParams, MoEParams
from midgpt_tpu.ops.loss import cross_entropy_loss
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh

CFG = GPTConfig(block_size=32, vocab_size=96, n_layer=2, n_head=2, n_embd=32)
MOE1 = dataclasses.replace(CFG, n_experts=1, moe_top_k=1)
MOE4 = dataclasses.replace(CFG, n_experts=4, moe_top_k=2)


def _dense_to_moe1(params):
    """Map dense params onto the E=1 routed tree (same weights)."""

    def convert(mlp: MLPParams) -> MoEParams:
        L = mlp.w_up.shape[0]
        return MoEParams(
            router=jnp.zeros((L, 1, CFG.n_embd), mlp.w_up.dtype),
            experts_up=mlp.w_up[:, None],
            experts_down=mlp.w_down[:, None],
        )

    return dataclasses.replace(
        params, blocks=dataclasses.replace(params.blocks, mlp=convert(params.blocks.mlp))
    )


def test_moe_e1_matches_dense_forward_and_grads():
    """At E=1/top_k=1 the routed MLP is EXACTLY the dense MLP (gate softmax
    over one expert is 1.0): logits and the shared leaves' grads match; the
    router grad is exactly zero (constant gate)."""
    dense = GPT.init(CFG, jax.random.PRNGKey(0))
    moe = _dense_to_moe1(dense)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size

    l_dense = GPT.apply(CFG, dense, tokens, inference=True)
    l_moe = GPT.apply(MOE1, moe, tokens, inference=True)
    np.testing.assert_allclose(np.asarray(l_moe), np.asarray(l_dense), atol=1e-6)

    def loss(cfg, p):
        return cross_entropy_loss(GPT.apply(cfg, p, tokens, inference=True), labels)

    g_dense = jax.grad(lambda p: loss(CFG, p))(dense)
    g_moe = jax.grad(lambda p: loss(MOE1, p))(moe)
    np.testing.assert_allclose(
        np.asarray(g_moe.blocks.mlp.experts_up[:, 0]),
        np.asarray(g_dense.blocks.mlp.w_up),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(g_moe.blocks.mlp.experts_down[:, 0]),
        np.asarray(g_dense.blocks.mlp.w_down),
        atol=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(g_moe.blocks.mlp.router), 0.0)
    np.testing.assert_allclose(
        np.asarray(g_moe.wte), np.asarray(g_dense.wte), atol=1e-6
    )


def test_moe_top_k_routing_properties():
    """E=4/top_k=2: gates are a distribution with at most k nonzeros per
    token, the forward is finite, and gradients flow to every expert (the
    batch is big enough that each expert wins somewhere)."""
    params = GPT.init(MOE4, jax.random.PRNGKey(2))
    mlp = jax.tree.map(lambda x: x[0], params.blocks.mlp)  # layer 0 slice
    h = jax.random.normal(jax.random.PRNGKey(3), (4, 32, CFG.n_embd)) * 0.5
    logits = jnp.einsum("btd,ed->bte", h, mlp.router)
    kth = jax.lax.top_k(logits, 2)[0][..., -1:]
    gates = jax.nn.softmax(
        jnp.where(logits >= kth, logits, -jnp.inf), axis=-1
    )
    nnz = jnp.sum(gates > 0, axis=-1)
    assert int(nnz.max()) <= 2
    np.testing.assert_allclose(np.asarray(gates.sum(-1)), 1.0, rtol=1e-6)

    out = GPT._moe_mlp(MOE4, mlp, h)
    assert out.shape == h.shape and bool(jnp.isfinite(out).all())

    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size
    g = jax.grad(
        lambda p: cross_entropy_loss(
            GPT.apply(MOE4, p, tokens, inference=True), labels
        )
    )(params)
    for leaf in (g.blocks.mlp.router, g.blocks.mlp.experts_up, g.blocks.mlp.experts_down):
        assert float(jnp.abs(leaf).max()) > 0


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_moe_train_step_ep2_matches_ep1():
    """Expert parallelism: one full train step with the experts sharded over
    a real 'ep' axis reproduces the unsharded (ep=1) loss — same math,
    different placement (the combine einsum's E contraction becomes the EP
    all-reduce)."""
    from midgpt_tpu.training.train import init_state, make_train_step

    base = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        eval_interval=5,
        beta2=0.95,
        weight_decay=1e-4,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        mesh=MeshConfig(data=2, fsdp=2, sp=1, ep=2),
        model_config=MOE4,
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, CFG.vocab_size, (1, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    for name, cfg in {
        "ep2": base,
        "ep1": base.replace(mesh=MeshConfig(data=2, fsdp=4, sp=1)),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        if name == "ep2":  # the experts really shard over 'ep'
            assert "ep" in str(specs.blocks.mlp.experts_up)
        step, *_ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["ep2"], losses["ep1"], rtol=1e-5)


def test_moe_top_k_exact_on_ties():
    """Regression (ADVICE r5): `logits >= kth` threshold masking admits
    MORE than k experts on exact ties — a zero/collapsed router (all-equal
    logits) silently turned routing dense. Selection now goes through
    jax.lax.top_k INDICES: exactly k experts per token, ties broken by
    lowest expert index, even in the fully degenerate state."""
    params = GPT.init(MOE4, jax.random.PRNGKey(5))
    mlp = jax.tree.map(lambda x: x[0], params.blocks.mlp)
    mlp = dataclasses.replace(mlp, router=jnp.zeros_like(mlp.router))
    h = jax.random.normal(jax.random.PRNGKey(6), (2, 8, CFG.n_embd))
    gates, aux = GPT._moe_gates(MOE4, mlp, h)
    nnz = jnp.sum(gates > 0, axis=-1)
    np.testing.assert_array_equal(np.asarray(nnz), 2)  # exactly k, not E
    # the k survivors split the mass evenly (equal logits)
    np.testing.assert_allclose(np.asarray(gates.max(-1)), 0.5, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gates.sum(-1)), 1.0, rtol=1e-6)
    # tie-break is deterministic: lowest expert indices win
    np.testing.assert_array_equal(np.asarray(gates[..., :2] > 0), True)


def test_moe_aux_loss_value_and_balance():
    """The Switch-style load-balance term: exactly 1.0 under perfectly
    balanced routing (uniform router), > 1 when the router collapses onto
    one expert, and differentiable through the router."""
    params = GPT.init(MOE4, jax.random.PRNGKey(7))
    mlp = jax.tree.map(lambda x: x[0], params.blocks.mlp)
    h = jax.random.normal(jax.random.PRNGKey(8), (2, 16, CFG.n_embd))

    uniform = dataclasses.replace(mlp, router=jnp.zeros_like(mlp.router))
    _, aux_uniform = GPT._moe_gates(MOE4, uniform, h)
    np.testing.assert_allclose(float(aux_uniform), 1.0, rtol=1e-6)

    # Collapse deterministically: h = all-ones and router row 0 = ones
    # makes expert 0's logit D and the rest 0 for EVERY token, so P ~ e_0
    # and assignment is always {0, 1} (tie-break): aux = E * (1 * 1/2) = 2.
    collapsed = dataclasses.replace(
        mlp, router=jnp.zeros_like(mlp.router).at[0].set(1.0)
    )
    ones = jnp.ones_like(h)
    _, aux_collapsed = GPT._moe_gates(MOE4, collapsed, ones)
    np.testing.assert_allclose(float(aux_collapsed), 2.0, rtol=1e-5)

    g = jax.grad(
        lambda r: GPT._moe_gates(MOE4, dataclasses.replace(mlp, router=r), h)[1]
    )(mlp.router)
    assert float(jnp.abs(g).max()) > 0  # pressure flows through P_e


@pytest.mark.slow
def test_moe_aux_coef_zero_impact_when_disabled():
    """ISSUE satellite pin: with moe_aux_coef=0.0 (default) the train-step
    loss is EXACTLY the CE loss (the aux term is never requested, so it
    cannot perturb the graph); with a nonzero coef the reported loss shifts
    by coef * aux."""
    from midgpt_tpu.training.train import init_state, make_train_step

    base = ExperimentConfig(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8,
        warmup_steps=2, min_lr=1e-4, lr_decay_steps=10, max_steps=10,
        eval_interval=5, beta2=0.95, weight_decay=0.0,
        param_dtype="float32", compute_dtype="float32", g_accum_iters=1,
        shard_model=True, fsdp_min_size=0,
        mesh=MeshConfig(data=2, fsdp=4, sp=1), model_config=MOE4,
    )
    rng = np.random.default_rng(3)
    x = rng.integers(0, CFG.vocab_size, (1, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)

    losses = {}
    for name, cfg in {
        "off": base,
        "on": base.replace(moe_aux_coef=0.01),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, eval_loss, _ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
        if name == "off":
            # dropout=0, so the dropout-free eval CE on the same batch IS
            # the pre-knob training loss — byte-for-byte zero impact.
            params2, *_ = init_state(cfg, make_mesh(cfg.mesh))
            ce = float(eval_loss(params2, xg[0], yg[0]))
            np.testing.assert_allclose(losses["off"], ce, rtol=1e-6)
    # aux >= 1 always (Cauchy-Schwarz equality at perfect balance), so a
    # nonzero coef must move the loss by at least coef * 1.
    assert losses["on"] > losses["off"] + 0.009


def test_moe_aux_coef_config_validation():
    kw = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8, warmup_steps=1,
        min_lr=1e-4, lr_decay_steps=10, max_steps=10, beta2=0.99, weight_decay=0.0,
        eval_interval=5, param_dtype="float32", compute_dtype="float32",
        g_accum_iters=1, shard_model=True,
    )
    with pytest.raises(ValueError, match="routed MLP"):
        ExperimentConfig(moe_aux_coef=0.01, model_config=CFG, **kw)
    with pytest.raises(ValueError, match="gspmd"):
        ExperimentConfig(
            moe_aux_coef=0.01, fsdp_mode="shard_map", model_config=MOE4, **kw
        )


def test_moe_config_validation():
    kw = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8, warmup_steps=1,
        min_lr=1e-4, lr_decay_steps=10, max_steps=10, beta2=0.99, weight_decay=0.0,
        eval_interval=5, param_dtype="float32", compute_dtype="float32",
        g_accum_iters=1, shard_model=True,
    )
    with pytest.raises(ValueError, match="n_experts"):
        ExperimentConfig(mesh=MeshConfig(ep=2), model_config=CFG, **kw)
    with pytest.raises(ValueError, match="divisible"):
        ExperimentConfig(
            mesh=MeshConfig(ep=2),
            model_config=dataclasses.replace(CFG, n_experts=3),
            **kw,
        )
    with pytest.raises(ValueError, match="pp"):
        ExperimentConfig(mesh=MeshConfig(fsdp=1, pp=2), model_config=MOE4, **kw)
