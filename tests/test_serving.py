"""Continuous-batching serving engine (sampling/serve.py): scheduler
behavior (admission, lazy page growth, eviction/preemption, EOS), and
greedy token parity with the fixed-batch engine on mixed-length traces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.serve import PageAllocator, ServeEngine

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _trace(seed=0, lengths=(5, 23, 11, 37, 3), max_new=(10, 12, 20, 8, 15)):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in zip(lengths, max_new)
    ]


def test_page_allocator():
    a = PageAllocator(8)  # pages 1..7 allocatable, 0 is the sink
    assert a.free_count == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.alloc(5) is None and a.free_count == 4  # failed alloc is a no-op
    a.free(got)
    assert a.free_count == 7
    with pytest.raises(AssertionError):
        a.free([0])  # the sink must never enter the free list


def test_submit_rejects_oversized_requests(params):
    eng = ServeEngine(CFG, params, max_slots=2, cache_dtype=jnp.float32)
    with pytest.raises(ValueError, match="block_size"):
        eng.submit(np.zeros(60, np.int32), 10)
    small = ServeEngine(
        CFG, params, max_slots=1, num_pages=3, cache_dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="pages"):
        small.submit(np.zeros(30, np.int32), 30)


@pytest.mark.slow
def test_serve_greedy_parity_with_generate(params):
    """The acceptance pin: a continuous-batched greedy run reproduces
    engine.generate token-for-token for every request in a mixed-length
    trace — admissions, chunked prefill, and slot churn included
    (more slots than requests is deliberate: requests overlap/rotate)."""
    trace = _trace()
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, prefill_chunk=16,
        decode_chunk=8, temperature=0.0, cache_dtype=jnp.float32,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    assert set(done) == set(uids)
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(
            done[u].tokens, np.asarray(ref[0]), err_msg=f"request {u}"
        )


def test_serve_parity_under_eviction(params):
    """A pool too small for the working set forces recompute-style
    preemption (evict youngest, re-queue with generated tokens folded into
    the prompt); outputs must STILL match the un-preempted reference."""
    trace = _trace()[:3]
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=10,
        temperature=0.0, cache_dtype=jnp.float32,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(
            done[u].tokens, np.asarray(ref[0]), err_msg=f"request {u}"
        )


def test_serve_decode_time_eviction_of_active_slot(params):
    """Regression: mid-decode page growth for an OLDER slot evicts the
    youngest slot, which can sit at a LATER index of the same decode
    round's loop. The round must skip the freed slot (it re-queues and
    re-prefills) rather than dereference None — and parity must survive
    the preemption. Short prompts make eviction fire during decode, not
    prefill (test_serve_parity_under_eviction covers the prefill case)."""
    rng = np.random.default_rng(3)
    trace = [
        (rng.integers(0, CFG.vocab_size, 8).astype(np.int32), 40)
        for _ in range(3)
    ]
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=10,
        temperature=0.0, cache_dtype=jnp.float32,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    assert set(done) == set(uids)
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(
            done[u].tokens, np.asarray(ref[0]), err_msg=f"request {u}"
        )


def test_serve_eos_frees_slot_early(params):
    """EOS finishes a request mid-chunk; its pages return to the pool and
    its tokens stop at the EOS."""
    p = _trace()[0][0]
    probe = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    u = probe.submit(p, 10)
    full = probe.run()[u].tokens
    eos = int(full[len(p) + 2])  # a token we know greedy decoding emits

    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    u2 = eng.submit(p, 10, eos_id=eos)
    out = eng.run()[u2].tokens
    assert out[-1] == eos and len(out) == len(p) + 3
    assert eng.allocator.free_count == eng.allocator.num_pages - 1
    assert eng.idle


def test_serve_pages_grow_lazily(params):
    """Admission must NOT reserve worst-case pages: right after the first
    prefill chunk, a long-prompt request holds only the pages that chunk
    touched."""
    rng = np.random.default_rng(1)
    p = rng.integers(0, CFG.vocab_size, 40).astype(np.int32)
    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, page_size=8,
        prefill_chunk=16, temperature=0.0, cache_dtype=jnp.float32,
    )
    eng.submit(p, 8)
    eng._admit()
    eng._prefill_round()  # 16 of 40 prompt tokens -> 2 pages
    slot = eng.slots[0]
    assert slot.prompt_pos == 16 and len(slot.pages) == 2


def test_serve_interleaves_prefill_with_decode(params):
    """A long prompt admitted while another request decodes must not stall
    it: each round advances the prompt by at most one chunk AND decodes the
    running slot."""
    rng = np.random.default_rng(2)
    short = rng.integers(0, CFG.vocab_size, 4).astype(np.int32)
    long_p = rng.integers(0, CFG.vocab_size, 48).astype(np.int32)
    eng = ServeEngine(
        CFG, params, max_slots=2, num_pages=33, page_size=8,
        prefill_chunk=16, decode_chunk=4, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    u_short = eng.submit(short, 12)
    eng.step()  # short prefills (one chunk) + first decode chunk
    produced_before = len(eng.slots[0].generated)
    assert produced_before > 0
    u_long = eng.submit(long_p, 4)
    eng.step()  # long's chunk 1 of 3 interleaves with short's decode
    long_slot = next(
        s for s in eng.slots if s is not None and s.request.uid == u_long
    )
    assert long_slot.prompt_pos == 16  # exactly one chunk of prefill
    assert len(eng.slots[0].generated) > produced_before  # short kept going
    done = eng.run()
    for u, (p, m) in ((u_short, (short, 12)), (u_long, (long_p, 4))):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(done[u].tokens, np.asarray(ref[0]))


def test_serve_stochastic_sampling_runs(params):
    """temperature > 0 exercises the keyed sampling path (no parity claim —
    different key stream than generate); output must be in-vocab and the
    right length."""
    trace = _trace()[:2]
    eng = ServeEngine(
        CFG, params, max_slots=2, num_pages=17, temperature=0.8, top_k=20,
        seed=7, cache_dtype=jnp.float32,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        out = done[u].tokens
        assert len(out) == len(p) + m
        assert (out >= 0).all() and (out < CFG.vocab_size).all()


class FakeClock:
    """Injectable engine clock (satellite): TTL tests advance time
    explicitly instead of racing wall-clock sleeps on the 1-core host."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_serve_request_ttl_timeout(params):
    """Satellite (robustness PR): a deadline-expired request is finished
    with status='timeout' (partial tokens returned, pages freed) instead of
    occupying the pool forever — queued and running requests alike. Driven
    entirely by the injectable clock: zero sleeps, zero flakiness."""
    clock = FakeClock()
    eng = ServeEngine(
        CFG, params, max_slots=1, page_size=8, num_pages=17,
        prefill_chunk=16, cache_dtype=jnp.float32, clock=clock,
    )
    p = np.arange(5, dtype=np.int32)
    u_dead = eng.submit(p, 8, ttl_s=5.0)
    u_live = eng.submit(p, 8)  # no TTL: immune to the clock jump
    clock.advance(10.0)  # u_dead expires while still queued
    done = eng.run()
    assert done[u_dead].status == "timeout"
    assert len(done[u_dead].tokens) == len(p)  # nothing generated
    assert done[u_live].status == "ok"
    assert len(done[u_live].tokens) == len(p) + 8
    assert eng.timeouts == 1
    assert eng.allocator.free_count == eng.allocator.num_pages - 1  # all freed

    # running slot: expire mid-generation -> partial tokens, pages freed
    clock2 = FakeClock()
    eng2 = ServeEngine(
        CFG, params, max_slots=1, page_size=8, num_pages=17,
        prefill_chunk=16, decode_chunk=1, cache_dtype=jnp.float32,
        clock=clock2,
    )
    u = eng2.submit(p, 12, ttl_s=60.0)
    for _ in range(3):
        eng2.step()  # prefill + a couple of decode rounds, well inside TTL
    slot = next(s for s in eng2.slots if s is not None)
    n_before = len(slot.generated)
    assert 0 < n_before < 12
    clock2.advance(61.0)  # sail past the deadline, deterministically
    eng2.step()
    assert eng2.slots[0] is None and u in eng2.finished
    assert eng2.finished[u].status == "timeout"
    assert len(eng2.finished[u].tokens) == len(p) + n_before
    assert eng2.timeouts == 1
    assert eng2.allocator.free_count == eng2.allocator.num_pages - 1


def test_serve_backpressure_admission(params):
    """Satellite (robustness PR): beyond max_backlog_pages, submit raises
    BackpressureError instead of growing the queue without bound; capacity
    frees as requests finish."""
    from midgpt_tpu.sampling.serve import BackpressureError

    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, num_pages=17,
        prefill_chunk=16, cache_dtype=jnp.float32, max_backlog_pages=4,
    )
    p = np.arange(10, dtype=np.int32)  # 10 + 6 tokens -> 2 pages worst case
    u1 = eng.submit(p, 6)
    u2 = eng.submit(p, 6)
    with pytest.raises(BackpressureError, match="backlog"):
        eng.submit(p, 6)
    done = eng.run()
    assert done[u1].status == "ok" and done[u2].status == "ok"
    u3 = eng.submit(p, 6)  # backlog drained: admission works again
    assert eng.run()[u3].status == "ok"


def test_backpressure_error_structured_fields(params):
    """Satellite: BackpressureError carries the retry ergonomics as fields
    (needed/backlog/budget pages, retry_after_pages, retryable) so the
    async server backs off on data instead of string-parsing messages."""
    from midgpt_tpu.sampling.serve import BackpressureError

    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, num_pages=17,
        prefill_chunk=16, cache_dtype=jnp.float32, max_backlog_pages=4,
    )
    p = np.arange(10, dtype=np.int32)  # 10 + 6 tokens -> 2 pages worst case
    eng.submit(p, 6)
    eng.submit(p, 6)
    with pytest.raises(BackpressureError) as ei:
        eng.submit(p, 6)
    e = ei.value
    assert e.needed_pages == 2
    assert e.backlog_pages == 4
    assert e.budget_pages == 4
    assert e.retry_after_pages == 2  # pages that must free before retry
    assert e.retryable  # capacity sheds are retryable (deadline sheds not)
    assert eng.shed == 1


def _co_resident_pair(params, **kw):
    """Two-slot engine plus a long victim prompt and a short bystander
    prompt; returns (eng, p_victim, p_bystander)."""
    rng = np.random.default_rng(11)
    p_victim = rng.integers(0, CFG.vocab_size, 48).astype(np.int32)
    p_by = rng.integers(0, CFG.vocab_size, 7).astype(np.int32)
    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, num_pages=33,
        prefill_chunk=16, decode_chunk=4, temperature=0.0,
        cache_dtype=jnp.float32, **kw,
    )
    return eng, p_victim, p_by


def _assert_bystander_exact(eng, u_by, p_by, m_by, params):
    ref = generate(CFG, params, jnp.asarray(p_by)[None], m_by, temperature=0.0)
    np.testing.assert_array_equal(
        eng.finished[u_by].tokens, np.asarray(ref[0]),
        err_msg="cancellation perturbed a co-resident slot",
    )
    assert eng.allocator.free_count == eng.allocator.num_pages - 1


def test_cancel_during_prefill_conserves_pages(params):
    """Satellite: client disconnect while the victim is STILL MID-PROMPT —
    its chunk-held pages return to the pool, nothing was generated, and the
    co-resident decode stream is untouched."""
    eng, p_victim, p_by = _co_resident_pair(params)
    u_by = eng.submit(p_by, 10)
    u_victim = eng.submit(p_victim, 8)
    eng.step()  # victim prefilled one chunk of three; bystander decodes
    slot = next(
        s for s in eng.slots if s is not None and s.request.uid == u_victim
    )
    assert slot.prefilling and slot.pages, "victim must be mid-prefill"
    assert eng.cancel(u_victim)
    assert eng.finished[u_victim].status == "cancelled"
    assert len(eng.finished[u_victim].tokens) == len(p_victim)  # prompt only
    eng.run()
    _assert_bystander_exact(eng, u_by, p_by, 10, params)
    assert not eng.cancel(u_victim)  # already finished: no-op


def test_cancel_during_decode_conserves_pages(params):
    """Satellite: disconnect mid-DECODE — partial tokens recorded, pages
    freed, bystander exact."""
    eng, p_victim, p_by = _co_resident_pair(params)
    u_by = eng.submit(p_by, 12)
    u_victim = eng.submit(p_victim[:9], 20)
    for _ in range(4):
        eng.step()
    slot = next(
        s for s in eng.slots if s is not None and s.request.uid == u_victim
    )
    n_gen = len(slot.generated)
    assert 0 < n_gen < 20, "victim must be mid-decode"
    assert eng.cancel(u_victim)
    fr = eng.finished[u_victim]
    assert fr.status == "cancelled" and len(fr.tokens) == 9 + n_gen
    # the delivered prefix is exactly the greedy stream (no corruption)
    ref = generate(CFG, params, jnp.asarray(p_victim[:9])[None], 20,
                   temperature=0.0)
    np.testing.assert_array_equal(fr.tokens, np.asarray(ref[0])[: 9 + n_gen])
    eng.run()
    _assert_bystander_exact(eng, u_by, p_by, 12, params)


def test_cancel_during_spec_rounds_conserves_pages(params):
    """Satellite: disconnect between SPECULATIVE verify rounds of a
    self-draft engine — rollback bookkeeping must not leak the victim's
    pages nor perturb the co-resident stream (greedy spec serving is
    token-identical to generate, tests/test_spec.py)."""
    from midgpt_tpu.sampling.spec import self_draft

    dcfg, dparams = self_draft(CFG, params, 1)
    eng, p_victim, p_by = _co_resident_pair(
        params,
        draft_params=dparams, draft_config=dcfg, draft_shares_cache=True,
        spec_k_max=4, spec_k_min=4, spec_adapt=False,
    )
    u_by = eng.submit(p_by, 12)
    u_victim = eng.submit(p_victim[:9], 20)
    for _ in range(4):
        eng.step()
    slot = next(
        s for s in eng.slots if s is not None and s.request.uid == u_victim
    )
    assert len(slot.generated) > 0, "victim must be mid-speculation"
    assert eng._spec_rounds > 0, "engine must actually be speculating"
    assert eng.cancel(u_victim)
    eng.run()
    assert eng.finished[u_victim].status == "cancelled"
    _assert_bystander_exact(eng, u_by, p_by, 12, params)


def test_cancel_queued_request(params):
    """Cancelling a request that never reached a slot frees nothing but
    still records the terminal status (and FCFS admission skips it)."""
    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, cache_dtype=jnp.float32,
    )
    p = np.arange(5, dtype=np.int32)
    u1 = eng.submit(p, 6)
    u2 = eng.submit(p, 6)  # queued behind u1 (one slot)
    assert eng.cancel(u2)
    assert eng.finished[u2].status == "cancelled"
    done = eng.run()
    assert done[u1].status == "ok"
    assert eng.cancelled == 1
    assert eng.allocator.free_count == eng.allocator.num_pages - 1
