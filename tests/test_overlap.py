"""Round-overlap dispatch pins (docs/SERVING.md "Round-overlap dispatch"):
every overlap mode is bit-exact vs the classic engine, the double-buffered
scheduler boundary is one round late BY CONSTRUCTION (policy decisions made
during round N's host phase first reach round N+2's dispatch — pinned via
`engine.dispatch_log` for FCFS and SLO), fused groups handle EOS and budget
edges inside the group, an in-flight victim's un-settled tokens are
discarded without perturbing anyone, and the hung-step watchdog stays armed
across the overlapped settle. Compile-count pins live in
tests/test_recompile_pins.py; the chaos gate (kill_overlapped_round) in
tests/test_chaos_serve.py.

Geometry discipline: 39 pages — a fresh program-key pool (not 25/31/51/57/
61/71, the recompile-pin baselines, nor 27/29/33/41, the tool/chaos/serving
geometries), so nothing here pre-warms a pinned program set.
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.robustness.errors import StepHangError
from midgpt_tpu.robustness.watchdog import StepWatchdog
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.scheduler import FCFSScheduler, SLOScheduler
from midgpt_tpu.sampling.serve import ServeEngine, parse_overlap

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _eng(params, overlap="off", round_group=1, cache_dtype=jnp.float32, **kw):
    return ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=39,
        prefill_chunk=16, decode_chunk=8, temperature=0.0,
        cache_dtype=cache_dtype, overlap=overlap, round_group=round_group,
        **kw,
    )


def _trace(seed=0, lengths=(25, 34, 47), max_new=(9, 17, 17)):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in zip(lengths, max_new)
    ]


def _ref(params, prompt, max_new):
    return np.asarray(
        generate(CFG, params, jnp.asarray(prompt)[None], max_new,
                 temperature=0.0)[0]
    )


def _assert_conserved(eng):
    trie = 0 if eng.prefix_cache is None else eng.prefix_cache.page_count()
    assert eng.allocator.free_count + trie == eng.allocator.num_pages - 1, (
        f"page leak: {eng.allocator.free_count} free + {trie} trie of "
        f"{eng.allocator.num_pages - 1} allocatable"
    )


# ----------------------------------------------------------------------
# parse_overlap: the one CLI form both tools share
# ----------------------------------------------------------------------


def test_parse_overlap():
    assert parse_overlap("off") == ("off", 1)
    assert parse_overlap("double") == ("double", 1)
    assert parse_overlap("group:4") == ("group", 4)
    for bad in ("", "group", "group:", "group:0", "group:x", "triple"):
        with pytest.raises(ValueError, match="bad overlap spec"):
            parse_overlap(bad)


# ----------------------------------------------------------------------
# the tentpole parity pin: every mode is bit-exact
# ----------------------------------------------------------------------


def test_overlap_modes_bit_exact_vs_generate(params):
    """off / double / group:2 on the same mixed trace all reproduce
    `generate` token-for-token — overlap restructures WHEN host work runs
    and how many rounds one dispatch carries, never what is computed. The
    group budget edge rides along: max_new=9 leaves 8 decode-side tokens,
    under one fused group's span, so emission must stop at the budget
    inside the group."""
    trace = _trace()
    for overlap, rg in (("off", 1), ("double", 1), ("group", 2)):
        eng = _eng(params, overlap, rg)
        uids = [eng.submit(p, m) for p, m in trace]
        done = eng.run()
        for (p, m), u in zip(trace, uids):
            np.testing.assert_array_equal(
                done[u].tokens, _ref(params, p, m),
                err_msg=f"mode {overlap}:{rg}, request {u}",
            )
            assert len(done[u].tokens) == len(p) + m
        assert eng.stats()["overlap_mode"] == overlap
        assert eng.stats()["round_group"] == rg
        _assert_conserved(eng)


@pytest.mark.slow
def test_overlap_wide_matrix_bit_exact(params):
    """The wide acceptance matrix: group:4 and double x {int8 cache,
    speculative draft, prefix cache, tp=2 sharded decode} all stay
    bit-exact vs the classic engine on the same trace."""
    from midgpt_tpu.parallel.serve_tp import make_serve_mesh
    from midgpt_tpu.sampling.spec import self_draft

    trace = _trace(seed=3)
    dcfg, dparams = self_draft(CFG, params, 1)
    variants = [
        dict(),  # f32 group:4
        dict(cache_dtype="int8"),
        dict(prefix_cache=True),
        dict(draft_params=dparams, draft_config=dcfg,
             draft_shares_cache=True, spec_k_max=4, spec_k_min=4,
             spec_adapt=False),
        dict(mesh=make_serve_mesh(tp_size=2)),
    ]
    for i, kw in enumerate(variants):
        spec = "draft_params" in kw
        # spec mode keeps its own draft/verify rounds: "double" falls back
        # to the classic order (serve.py step()) and "group" fuses nothing
        # through the verify path — the mode must still be SAFE to set.
        modes = (("double", 1),) if spec else (("group", 4), ("double", 1))
        base = _eng(params, "off", 1, **kw)
        uids = [base.submit(p, m) for p, m in trace]
        want = {u: np.asarray(base.run()[u].tokens) for u in uids}
        for overlap, rg in modes:
            eng = _eng(params, overlap, rg, **kw)
            uids2 = [eng.submit(p, m) for p, m in trace]
            done = eng.run()
            for u0, u1 in zip(uids, uids2):
                np.testing.assert_array_equal(
                    done[u1].tokens, want[u0],
                    err_msg=f"variant {i}, mode {overlap}:{rg}",
                )
            _assert_conserved(eng)


def test_eos_at_group_interior_stops_exactly(params):
    """EOS fired INSIDE a fused group (not at its edge) must stop the
    stream at exactly the same token as the classic engine: the in-program
    deactivation masks the remaining scan steps and the host discards
    nothing it should keep. The eos token is picked from the reference
    stream so greedy decoding deterministically hits it mid-group."""
    p, m = _trace(seed=7, lengths=(25,), max_new=(17,))[0]
    ref = _ref(params, p, m)
    eos_tok = int(ref[len(p) + 5])  # greedy emits this 6 tokens in
    outs = {}
    for overlap, rg in (("off", 1), ("group", 2), ("double", 1)):
        eng = _eng(params, overlap, rg)
        u = eng.submit(p, m, eos_id=eos_tok)
        outs[(overlap, rg)] = np.asarray(eng.run()[u].tokens)
        _assert_conserved(eng)
    want = outs[("off", 1)]
    assert len(want) < len(p) + m, "eos never fired — test staged wrong"
    for k, got in outs.items():
        np.testing.assert_array_equal(got, want, err_msg=f"mode {k}")


# ----------------------------------------------------------------------
# the one-round-late scheduler boundary (dispatch_log pins)
# ----------------------------------------------------------------------


@pytest.mark.parametrize("make_sched", [FCFSScheduler, SLOScheduler],
                         ids=["fcfs", "slo"])
def test_double_admission_lands_two_dispatches_late(params, make_sched):
    """The deferred-effect pin (sampling/scheduler.py docstring): under
    overlap="double", round N+1's dispatch is enqueued BEFORE round N's
    host phase runs, so a request admitted during that host phase first
    appears in round N+2's dispatch — for any policy, because the
    boundary is the engine's, not the scheduler's. The classic engine
    admits before it dispatches, so there the same arrival lands one
    round later, not two. B's prompt fits ONE prefill chunk, so it is
    decode-ready in the same host phase that admits it — the dispatch
    distance measured is purely the policy boundary, not prefill time."""
    trace = _trace(seed=11, lengths=(25, 12), max_new=(33, 9))
    (pa, ma), (pb, mb) = trace
    first_round = {}
    for overlap in ("double", "off"):
        eng = _eng(params, overlap, 1, scheduler=make_sched())
        ua = eng.submit(pa, ma)
        for _ in range(3):
            eng.step()
        r0 = eng.rounds
        assert any(ua in uids for _, uids in eng.dispatch_log)
        ub = eng.submit(pb, mb)
        eng.step()
        eng.step()
        log = list(eng.dispatch_log)
        first_round[overlap] = min(
            r for r, uids in log if ub in uids
        ) - r0
        done = eng.run()
        for (p, m), u in zip(trace, (ua, ub)):
            np.testing.assert_array_equal(done[u].tokens, _ref(params, p, m))
        _assert_conserved(eng)
    assert first_round["double"] == 2, (
        f"double-buffered admission landed {first_round['double']} rounds "
        "late, want exactly 2 (the one-round-late policy boundary)"
    )
    assert first_round["off"] == 1, (
        "classic admission must stay same-round-visible (admit precedes "
        f"dispatch), got {first_round['off']}"
    )


def test_inflight_victim_tokens_discarded_without_collateral(params):
    """Cancelling a slot whose round is still IN FLIGHT discards its
    un-settled tokens (identity mismatch at settle) and touches nobody
    else: the survivor stays bit-exact and every page comes home."""
    (pa, ma), (pc, mc) = _trace(seed=13, lengths=(25, 12), max_new=(17, 33))
    eng = _eng(params, "double", 1)
    ua = eng.submit(pa, ma)
    uc = eng.submit(pc, mc)
    for _ in range(8):
        eng.step()
        if eng._inflight is not None and uc in dict(eng.dispatch_log).get(
            eng.rounds, ()
        ):
            break
    else:
        pytest.fail("victim never entered an in-flight dispatch")
    assert eng.cancel(uc)
    done = eng.run()
    assert done[uc].status == "cancelled"
    assert len(done[uc].tokens) < len(pc) + mc  # partial by design
    np.testing.assert_array_equal(done[ua].tokens, _ref(params, pa, ma))
    _assert_conserved(eng)


# ----------------------------------------------------------------------
# watchdog stays armed across the overlapped settle
# ----------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_watchdog_armed_through_overlap_is_invisible(params):
    """An armed-but-never-expiring watchdog changes nothing under
    overlap="double" — bit-exact streams — and every overlapped settle
    goes through its sync funnel (syncs counted, zero expiries)."""
    wd = StepWatchdog(60.0, poll_s=0.001)
    eng = _eng(params, "double", 1, watchdog=wd)
    trace = _trace(seed=17, lengths=(25, 34), max_new=(17, 9))
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        np.testing.assert_array_equal(done[u].tokens, _ref(params, p, m))
    assert wd.syncs >= 2, "overlapped settles bypassed the watchdog funnel"
    assert wd.expiries == 0
    _assert_conserved(eng)


def test_watchdog_bounds_a_hung_overlapped_settle(params):
    """A settle that never lands (dead-tunnel model: the in-flight
    handle's device arrays hang on materialization) must end in
    StepHangError via the armed watchdog — labeled as the overlap sync —
    not in a wedged server. The hang is injected by swapping the handle's
    unforced outputs for objects whose __array__ parks forever."""
    clock = _FakeClock()
    wd = StepWatchdog(5.0, clock=clock, poll_s=0.001)
    eng = _eng(params, "double", 1, watchdog=wd)
    p, m = _trace(seed=19, lengths=(25,), max_new=(33,))[0]
    eng.submit(p, m)
    for _ in range(3):
        eng.step()
    assert eng._inflight is not None

    class _Hang:
        def __array__(self, dtype=None, copy=None):
            clock.t = 100.0
            threading.Event().wait()

    eng._inflight = dataclasses.replace(
        eng._inflight, toks=_Hang(), emitted=_Hang()
    )
    with pytest.raises(StepHangError) as ei:
        eng.step()  # next step settles the (hung) in-flight round
    assert "serve.overlap_sync" in str(ei.value)
    assert wd.expiries == 1
