"""Int8 quantized paged KV cache (ops/quant.py + PagedKVCache int8 mode +
kernels/decode_attention.py dual-mode kernels + ServeEngine byte budget):

* quantizer invariants and the einsum-dequant oracle parity of BOTH Pallas
  kernels (decode and the multi-row verify kernel) in interpret mode,
  bf16/f32 and int8;
* end-to-end int8 serving: deterministic under recompute-style preemption
  (the PR-1 decode-time-eviction regression scenario, quantized), greedy
  speculative == greedy plain on the SAME int8 cache, stochastic runs;
* byte-budgeted paging: at a fixed pool_hbm_bytes an int8 pool admits
  exactly 2x the pages of bf16 and suffers strictly fewer preemptions on
  the same oversubscribed trace;
* the compiled-artifact pin: zero pool-sized AND zero scale-buffer-sized
  copies inside the int8 decode/verify loops (the aliasing-scatter
  property extended to the side buffers).
"""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.kernels.decode_attention import (
    paged_attention_gather,
    paged_attention_kernel,
    paged_verify_attention,
    paged_verify_attention_gather,
    paged_verify_attention_kernel,
)
from midgpt_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
from midgpt_tpu.ops.quant import Q8_MAX, dequantize_q8, quantize_q8
from midgpt_tpu.sampling.serve import ServeEngine, normalize_cache_dtype

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


# ----------------------------------------------------------------------
# quantizer
# ----------------------------------------------------------------------


def test_quantize_roundtrip_invariants():
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 3, 17)) * 3.0
    q, s = quantize_q8(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert s.shape == x.shape[:-1]
    qn = np.asarray(q, np.int32)
    assert np.abs(qn).max() <= Q8_MAX  # -128 never produced
    np.testing.assert_allclose(
        np.asarray(s),
        np.abs(np.asarray(x)).max(-1) / Q8_MAX,
        rtol=1e-6,
    )
    err = np.abs(np.asarray(dequantize_q8(q, s)) - np.asarray(x))
    # round-to-nearest: at most half a quantization step, elementwise
    assert (err <= np.asarray(s)[..., None] * 0.5 + 1e-7).all()
    # an all-zero vector stores scale 0 and dequantizes to exact zeros
    q0, s0 = quantize_q8(jnp.zeros((2, 4)))
    assert float(jnp.abs(dequantize_q8(q0, s0)).max()) == 0.0


# ----------------------------------------------------------------------
# kernels vs the einsum dequant oracle (interpret mode off-TPU)
# ----------------------------------------------------------------------

B, H, C = 3, 2, 128  # C spans the full Mosaic lane dim
PS, NP, MP = 8, 7, 4


def _quantized_problem(seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (B, H, C), jnp.float32)
    kf = jax.random.normal(keys[1], (H, NP, PS, C), jnp.float32)
    vf = jax.random.normal(keys[2], (H, NP, PS, C), jnp.float32)
    # quantize per (page, head, position) over C -> scale layout (P, H, ps)
    kq, ks = quantize_q8(kf.transpose(1, 0, 2, 3))
    vq, vs = quantize_q8(vf.transpose(1, 0, 2, 3))
    kq, vq = kq.transpose(1, 0, 2, 3), vq.transpose(1, 0, 2, 3)
    pt = jnp.asarray([[3, 1, 0, 0], [5, 2, 6, 0], [4, 0, 0, 0]], jnp.int32)
    ln = jnp.asarray([11, 24, 1], jnp.int32)
    return q, kq, vq, ks, vs, pt, ln


def _dense_dequant_oracle(q, kq, vq, ks, vs, pt, ln, counts=None):
    """Materialize each slot's logical K/V by EXACT dequantization
    (int8 * f32, ops/quant.py) and run plain masked attention — the
    oracle both lowerings must reproduce."""
    import math

    kd = np.asarray(dequantize_q8(kq.transpose(1, 0, 2, 3), ks))  # (P,H,ps,C)
    vd = np.asarray(dequantize_q8(vq.transpose(1, 0, 2, 3), vs))
    out = []
    qn = np.asarray(q)
    multi = qn.ndim == 4  # (B, T, H, C) verify problem
    for b in range(qn.shape[0]):
        kb = np.concatenate([kd[p] for p in np.asarray(pt)[b]], axis=1)  # (H,S,C)
        vb = np.concatenate([vd[p] for p in np.asarray(pt)[b]], axis=1)
        kb = kb.transpose(0, 1, 2) if kb.ndim == 3 else kb
        rows = qn[b] if multi else qn[b][None]  # (T, H, C)
        row_counts = (
            np.asarray(counts)[b] if counts is not None
            else np.asarray([int(ln[b])])
        )
        os = []
        for t, row in enumerate(rows):
            s = np.einsum("hc,hkc->hk", row, kb) / math.sqrt(C)
            s[:, row_counts[t]:] = -np.inf
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            os.append(np.einsum("hk,hkc->hc", p, vb))
        out.append(np.stack(os))
    out = np.stack(out)  # (B, T, H, C)
    return out if multi else out[:, 0]


def test_gather_int8_matches_dense_dequant_oracle():
    q, kq, vq, ks, vs, pt, ln = _quantized_problem()
    got = paged_attention_gather(q, kq, vq, pt, ln, k_scale=ks, v_scale=vs)
    want = _dense_dequant_oracle(q, kq, vq, ks, vs, pt, ln)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_decode_kernel_int8_matches_oracle():
    """The Mosaic decode kernel's in-VMEM dequant must reproduce the
    einsum dequant oracle (both dequantize the same int8+scale pairs
    exactly, so only softmax-order float noise separates them)."""
    q, kq, vq, ks, vs, pt, ln = _quantized_problem(seed=1)
    got = np.asarray(
        paged_attention_kernel(q, kq, vq, pt, ln, k_scale=ks, v_scale=vs)
    )
    want = _dense_dequant_oracle(q, kq, vq, ks, vs, pt, ln)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("quantized", (False, True), ids=("f32", "int8"))
def test_verify_kernel_matches_gather(quantized):
    """The multi-row Pallas verify kernel (the compiled verify path on
    TPU) against the gather lowering, ragged per-row counts included —
    bf16/f32 and int8 modes."""
    T = 3
    q, kq, vq, ks, vs, pt, ln = _quantized_problem(seed=2)
    qv = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, C), jnp.float32)
    counts = jnp.asarray([[9, 10, 11], [22, 23, 24], [1, 1, 1]], jnp.int32)
    if quantized:
        kp, vp, scales = kq, vq, dict(k_scale=ks, v_scale=vs)
    else:
        keys = jax.random.split(jax.random.PRNGKey(3), 2)
        kp = jax.random.normal(keys[0], (H, NP, PS, C), jnp.float32)
        vp = jax.random.normal(keys[1], (H, NP, PS, C), jnp.float32)
        scales = {}
    want = np.asarray(
        paged_verify_attention_gather(qv, kp, vp, pt, counts, **scales)
    )
    got = np.asarray(
        paged_verify_attention_kernel(qv, kp, vp, pt, counts, **scales)
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)
    if quantized:
        oracle = _dense_dequant_oracle(qv, kq, vq, ks, vs, pt, ln, counts)
        np.testing.assert_allclose(got, oracle, atol=2e-5, rtol=2e-5)


def test_verify_dispatcher_validates_impl():
    q, kq, vq, ks, vs, pt, ln = _quantized_problem(seed=3)
    qv = jnp.zeros((B, 2, H, C))
    counts = jnp.ones((B, 2), jnp.int32)
    with pytest.raises(ValueError, match="unknown paged verify"):
        paged_verify_attention(qv, kq, vq, pt, counts, impl="nope")


# ----------------------------------------------------------------------
# end-to-end int8 serving
# ----------------------------------------------------------------------


def _run_engine(params, trace, **kw):
    eng = ServeEngine(
        CFG, params, page_size=8, prefill_chunk=16, decode_chunk=8,
        temperature=0.0, **kw,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    assert set(done) == set(uids)
    return eng, [done[u].tokens for u in uids]


@pytest.mark.slow
def test_int8_serving_deterministic_under_eviction(params):
    """The PR-1 decode-time-eviction regression scenario, quantized: an
    oversubscribed int8 pool forces recompute-style preemption mid-decode
    (older slot growth evicts the youngest ACTIVE slot), and the outputs
    must equal an un-preempted int8 run token for token — preemption
    re-prefills the same tokens, which re-quantize to the same int8
    values, so the quantized engine is exactly as deterministic as the
    bf16 one (pinned here; the bf16 pin is
    tests/test_serving.py::test_serve_decode_time_eviction_of_active_slot)."""
    rng = np.random.default_rng(3)
    # same shape as the PR-1 scenario (3 short prompts, decode-dominated),
    # 24 new tokens instead of 40: 3 x 4 pages of demand against a 9-page
    # pool still forces decode-time eviction every run, at ~60% of the cost
    trace = [
        (rng.integers(0, CFG.vocab_size, 8).astype(np.int32), 24)
        for _ in range(3)
    ]
    big, ref = _run_engine(
        params, trace, max_slots=3, num_pages=33, cache_dtype="int8"
    )
    assert big.preemptions == 0, "reference run must not preempt"
    small, out = _run_engine(
        params, trace, max_slots=3, num_pages=10, cache_dtype="int8"
    )
    assert small.preemptions > 0, "10-page pool must force eviction"
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_int8_spec_greedy_matches_plain_int8(params):
    """Greedy speculative serving on the int8 cache == greedy plain int8
    serving, token for token: the draft's speculative writes and the
    verify rewrite quantize identical values (same inputs through the same
    quantized prefix cache), so acceptance decisions replay plain decode
    exactly — the quantized analogue of tests/test_spec.py's parity pin."""
    from midgpt_tpu.sampling.spec import self_draft

    dcfg, dparams = self_draft(CFG, params, 1)
    rng = np.random.default_rng(5)
    trace = [
        (rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in ((5, 12), (19, 10))
    ]
    _, ref = _run_engine(
        params, trace, max_slots=2, num_pages=25, cache_dtype="int8"
    )
    _, out = _run_engine(
        params, trace, max_slots=2, num_pages=25, cache_dtype="int8",
        draft_params=dparams, draft_config=dcfg, draft_shares_cache=True,
        # pin k at 4: parity holds for any k, and one k-bucket means one
        # draft+verify compile instead of one per adaptive halving
        spec_k_max=4, spec_k_min=4, spec_adapt=False,
    )
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)


def test_int8_stochastic_serving_runs(params):
    """temperature > 0 through the quantized cache: in-vocab tokens of the
    right length (the existing statistical pins cover the sampler itself —
    it consumes logits, not cache bytes)."""
    rng = np.random.default_rng(11)
    trace = [(rng.integers(0, CFG.vocab_size, 7).astype(np.int32), 9)]
    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, temperature=0.8, top_k=20,
        seed=7, cache_dtype="int8",
    )
    u = eng.submit(*trace[0])
    out = eng.run()[u].tokens
    assert len(out) == 7 + 9
    assert (out >= 0).all() and (out < CFG.vocab_size).all()


# ----------------------------------------------------------------------
# byte-budgeted paging
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_byte_budget_doubles_pages_and_reduces_preemptions(params):
    """THE capacity claim: at a fixed pool_hbm_bytes, the int8 pool admits
    exactly 2x the pages of bf16 (the budget covers the K/V pools;
    PagedKVCache.page_bytes documents that the f32 scale side buffer rides
    on top and cache_hbm_bytes reports it), and on the same oversubscribed
    trace the int8 engine preempts strictly less while every request still
    completes."""
    budget = PagedKVCache.page_bytes(CFG, 8, jnp.bfloat16) * 10  # bf16: 10pg
    e_bf = ServeEngine(
        CFG, params, page_size=8, pool_hbm_bytes=budget, cache_dtype="bf16"
    )
    e_i8 = ServeEngine(
        CFG, params, page_size=8, pool_hbm_bytes=budget, cache_dtype="int8"
    )
    assert e_bf.allocator.num_pages == 10
    assert e_i8.allocator.num_pages == 20
    # the side buffer is the documented +4/head_dim on top, not hidden
    kv_bytes = sum(
        a.nbytes for a in (e_i8.cache.k, e_i8.cache.v)
    )
    assert e_i8.cache_hbm_bytes() > kv_bytes

    rng = np.random.default_rng(3)
    # 3 x 4 pages of demand: oversubscribes bf16's 9 allocatable pages
    # (evicts), fits int8's 19 (doesn't)
    trace = [
        (rng.integers(0, CFG.vocab_size, 8).astype(np.int32), 24)
        for _ in range(3)
    ]
    eng_bf, out_bf = _run_engine(
        params, trace, max_slots=3, pool_hbm_bytes=budget, cache_dtype="bf16"
    )
    eng_i8, out_i8 = _run_engine(
        params, trace, max_slots=3, pool_hbm_bytes=budget, cache_dtype="int8"
    )
    assert eng_bf.preemptions > eng_i8.preemptions, (
        eng_bf.preemptions, eng_i8.preemptions,
    )
    for (p, m), toks in zip(trace, out_i8):
        assert len(toks) == len(p) + m


def test_pool_sizing_validation(params):
    with pytest.raises(ValueError, match="not both"):
        ServeEngine(CFG, params, num_pages=10, pool_hbm_bytes=1 << 20)
    with pytest.raises(ValueError, match="unknown cache dtype"):
        ServeEngine(CFG, params, cache_dtype="fp4")
    assert normalize_cache_dtype("bf16") == jnp.bfloat16
    assert normalize_cache_dtype(jnp.float32) == jnp.float32


def test_kv_cache_dtype_config_validation():
    from midgpt_tpu.config import ExperimentConfig, MeshConfig

    base = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8,
        warmup_steps=1, min_lr=1e-4, lr_decay_steps=10, max_steps=10,
        beta2=0.99, weight_decay=0.0, eval_interval=5,
        param_dtype="float32", compute_dtype="float32", g_accum_iters=1,
        shard_model=False, mesh=MeshConfig(data=-1, fsdp=1), model_config=CFG,
    )
    ExperimentConfig(**base, kv_cache_dtype="int8")  # valid
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ExperimentConfig(**base, kv_cache_dtype="fp8")


# ----------------------------------------------------------------------
# compiled-artifact pin
# ----------------------------------------------------------------------


def test_int8_programs_have_no_in_loop_pool_or_scale_copies():
    """ISSUE acceptance HLO pin: with the int8 cache, the decode chunk's
    while body and the verify program's layer loop contain zero POOL-sized
    copies and zero SCALE-buffer-sized copies — the quantizing scatters
    alias through the donated carry exactly like the bf16 writes (the
    while_body_pool_copies census covers the side buffers too;
    `python -m midgpt_tpu.analysis --audit` runs the same checks)."""
    from midgpt_tpu.analysis.hlo_audit import while_body_pool_copies
    from midgpt_tpu.sampling import serve

    B_, ps, n_pages, K = 2, 8, 12, 2
    cfg = dataclasses.replace(CFG, decode_layer_scan=True)
    L, H_, C_ = cfg.n_layer, cfg.n_head, cfg.head_dim
    mp = cfg.block_size // ps
    abstract = jax.eval_shape(lambda k: GPT.init(cfg, k), jax.random.PRNGKey(0))
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, jnp.bfloat16), abstract
    )
    cache = jax.eval_shape(
        lambda: PagedKVCache.init(cfg, num_pages=n_pages, page_size=ps,
                                  dtype=jnp.int8)
    )
    pool = f"s8[{L},{H_},{n_pages},{ps},{C_}]"
    scale = f"f32[{L},{n_pages},{H_},{ps}]"

    decode_txt = (
        serve._serve_decode_chunk.lower(
            cfg,
            abstract,
            jax.ShapeDtypeStruct((B_,), jnp.int32),
            cache,
            jax.ShapeDtypeStruct((B_, mp), jnp.int32),
            jax.ShapeDtypeStruct((B_,), jnp.int32),
            jax.ShapeDtypeStruct((B_,), jnp.bool_),
            4,
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    verify_txt = (
        serve._spec_verify_chunk.lower(
            cfg,
            abstract,
            jax.ShapeDtypeStruct((B_,), jnp.int32),
            jax.ShapeDtypeStruct((K, B_), jnp.int32),
            jax.ShapeDtypeStruct((K, B_, cfg.vocab_size), jnp.float32),
            cache,
            jax.ShapeDtypeStruct((B_, mp), jnp.int32),
            jax.ShapeDtypeStruct((B_,), jnp.int32),
            jax.ShapeDtypeStruct((B_,), jnp.bool_),
            0.0,
            None,
            None,
            "gather",
            None,
        )
        .compile()
        .as_text()
    )
    for name, txt in (("decode", decode_txt), ("verify", verify_txt)):
        for label, shape in (("pool", pool), ("scale", scale)):
            census = while_body_pool_copies(txt, shape)
            assert census, f"{name}: no while body found"
            offenders = {b: ls for b, ls in census.items() if ls}
            assert not offenders, f"{name} {label} in-loop copies: {offenders}"
            # and nowhere else either: entry copies of the pool are allowed
            # in general but the quantized pools should alias end to end
            n_total = len(re.findall(rf"= {re.escape(shape)}[^=]*copy\(", txt))
            assert n_total <= 2, f"{name}: {n_total} {label}-sized copies"
