"""Cross-request prefix cache (sampling/prefix_cache.py): trie unit
behavior, and the serving-level acceptance pins — greedy token parity with
the cache ON in every cache mode (f32, int8, speculative), copy-on-write
isolation for duplicate prompts, page + refcount conservation across the
full slot lifecycle (finish/cancel/TTL/preemption), and the r10
self-re-prefill regression: a preempted request resumes by re-matching its
own donated pages instead of re-prefilling its whole folded prompt."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.prefix_cache import PrefixCache
from midgpt_tpu.sampling.serve import ServeEngine

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)
PS = 8


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


# ----------------------------------------------------------------------
# trie unit behavior (pure host code, no model)
# ----------------------------------------------------------------------


def test_trie_insert_match_release_roundtrip():
    pc = PrefixCache(4)
    a = list(range(9))  # 2 full pages + 1-token tail
    assert pc.insert_live(a, [5, 6, 7], 0) == 2
    assert pc.page_count() == 2 and pc.referenced_page_count() == 2

    mr = pc.match(a, max_tokens=len(a) - 1)
    assert mr.pages == [5, 6] and mr.tokens == 8
    # two readers now: the inserter and the matcher
    assert pc.stats()["refs"] == 4

    # matcher departs: sheds its refs, its private tail page is freed
    assert pc.release(a, [5, 6, 9], 2) == [9]
    # inserter departs: trie keeps the content at refcount 0
    assert pc.release(a, [5, 6, 7], 2) == [7]
    assert pc.referenced_page_count() == 0 and pc.page_count() == 2
    # and an identical future request still matches it
    assert pc.peek(a) == 2


def test_trie_split_on_divergence():
    pc = PrefixCache(4)
    a = [1] * 4 + [2] * 4
    b = [1] * 4 + [3] * 4
    assert pc.insert_live(a, [1, 2], 0) == 2
    mr = pc.match(b)  # shares the first page only
    assert mr.pages == [1] and mr.tokens == 4
    # b's second page diverges inside the compressed chain -> split
    assert pc.insert_live(b, [1, 4], 1) == 2
    assert pc.page_count() == 3
    assert pc.match(a).pages == [1, 2]
    assert pc.match(b).pages == [1, 4]


def test_trie_match_cap_reserves_last_token_and_flags_cow():
    pc = PrefixCache(4)
    a = list(range(8))
    pc.insert_live(a, [1, 2], 0)
    # the engine's cap: a prompt of exactly 8 tokens may match only 1 page
    # (the final token must re-prefill), and because the trie's second page
    # carries the rest of the prompt, the truncation is a COW event
    mr = pc.match(a, max_tokens=len(a) - 1)
    assert mr.pages == [1] and mr.tokens == 4 and mr.cow_truncated
    # a prompt diverging right after the match is a plain miss, not COW
    mr2 = pc.match([0, 1, 2, 3, 90, 91], max_tokens=5)
    assert mr2.pages == [1] and not mr2.cow_truncated


def test_trie_peek_is_side_effect_free():
    pc = PrefixCache(4)
    a = list(range(8))
    pc.insert_live(a, [1, 2], 0)
    before = pc.stats()
    assert pc.peek(a, max_tokens=len(a) - 1) == 1
    assert pc.peek(a) == 2
    assert pc.stats() == before


def test_trie_release_frees_content_duplicates():
    """Two slots prefilled the same content concurrently (neither could
    match the other mid-flight): the second insert stops sharing at the
    duplicate, and its release frees the private copies instead of
    double-registering the content."""
    pc = PrefixCache(4)
    a = list(range(8))
    assert pc.insert_live(a, [1, 2], 0) == 2
    assert pc.insert_live(a, [3, 4], 0) == 0  # duplicate raced in
    assert sorted(pc.release(a, [3, 4], 0)) == [3, 4]
    assert pc.page_count() == 2 and pc.pages_held() == {1, 2}


def test_trie_evict_lru_deepest_first_and_never_referenced():
    pc = PrefixCache(4)
    a = list(range(12))  # one chain of 3 entries
    pc.insert_live(a, [1, 2, 3], 0)
    assert pc.evict(3) == []  # all referenced: nothing reclaimable
    pc.release(a, [1, 2, 3], 3)
    # deepest entry first: a page never leaves while pages extending it stay
    assert pc.evict(1) == [3]
    b = [7] * 8
    pc.insert_live(b, [4, 5], 0)
    pc.release(b, [4, 5], 2)
    pc.match(a[:8])  # touch the a-branch: b's branch is now LRU-oldest
    pc.release(a[:8], [1, 2], 2)
    assert pc.evict(2) == [5, 4]
    assert pc.evict(0, force_all=True) == [2, 1]
    assert pc.page_count() == 0


# ----------------------------------------------------------------------
# serving-level pins
# ----------------------------------------------------------------------


def _engine(params, prefix, num_pages=29, cache_dtype=jnp.float32, **kw):
    # NOT num_pages=25: the pool size is a program-key dim and the recompile
    # pins (tests/test_recompile_pins.py) count the 25-page f32 program set
    # from a pristine baseline (same rule as chaos_serve.py).
    return ServeEngine(
        CFG, params, max_slots=3, page_size=PS, num_pages=num_pages,
        prefill_chunk=16, decode_chunk=4, temperature=0.0,
        cache_dtype=cache_dtype, prefix_cache=prefix, **kw,
    )


def _template_trace(seed=0, n_templated=6, n_unique=3, t_len=24):
    """Template-heavy traffic: two shared t_len-token heads with short
    unique tails, plus a few fully unique prompts."""
    rng = np.random.default_rng(seed)
    templates = [
        rng.integers(0, CFG.vocab_size, t_len).astype(np.int32)
        for _ in range(2)
    ]
    trace = []
    for i in range(n_templated):
        tail = rng.integers(
            0, CFG.vocab_size, int(rng.integers(2, 7))
        ).astype(np.int32)
        trace.append(
            (np.concatenate([templates[i % 2], tail]), int(rng.integers(6, 12)))
        )
    for _ in range(n_unique):
        trace.append((
            rng.integers(
                0, CFG.vocab_size, int(rng.integers(4, 12))
            ).astype(np.int32),
            int(rng.integers(6, 12)),
        ))
    return trace


def _assert_conserved(eng):
    """Drained-engine conservation with the cache on: every page is either
    free or a trie entry, and no trie refcount outlived its reader."""
    assert eng.idle
    assert (
        eng.allocator.free_count + eng.prefix_cache.page_count()
        == eng.allocator.num_pages - 1
    )
    assert eng.prefix_cache.referenced_page_count() == 0


def _run_pair(params, trace, **kw):
    """The same trace through a cache-off and a cache-on engine."""
    outs = []
    for prefix in (False, True):
        eng = _engine(params, prefix, **kw)
        uids = [eng.submit(p, m) for p, m in trace]
        done = eng.run()
        assert set(done) == set(uids)
        outs.append((eng, [np.asarray(done[u].tokens) for u in uids]))
    return outs


def test_prefix_greedy_parity_f32(params):
    """The acceptance pin: enabling the cache on a template-heavy trace is
    token-invisible — every stream is bit-identical to the cache-off run
    AND to the fixed-batch reference — while the trie demonstrably absorbs
    prefill work."""
    trace = _template_trace()
    (eng_off, toks_off), (eng_on, toks_on) = _run_pair(params, trace)
    for i, ((p, m), a, b) in enumerate(zip(trace, toks_off, toks_on)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(b, np.asarray(ref[0]), err_msg=f"request {i}")
    st = eng_on.prefix_stats()
    assert st["hit_rate"] > 0.0, "template traffic must hit the trie"
    assert eng_on.prefilled_tokens < eng_off.prefilled_tokens
    assert eng_on.prefilled_tokens + st["matched_tokens"] >= eng_off.prefilled_tokens
    _assert_conserved(eng_on)


def test_prefix_cow_duplicate_prompt_isolated(params):
    """An exact-duplicate prompt (a retried query) matches up to the
    reserve-the-last-token cap and re-prefills the remainder into a PRIVATE
    page even though a trie page carries the same leading tokens — the
    copy-on-write truncation. Both runs must produce identical tokens, and
    the second must be flagged as a COW admission."""
    rng = np.random.default_rng(11)
    p = rng.integers(0, CFG.vocab_size, 21).astype(np.int32)  # mid-page end
    eng = _engine(params, True)
    u1 = eng.submit(p, 10)
    eng.run()
    assert eng.cow_pages == 0
    u2 = eng.submit(p, 10)
    eng.run()
    assert eng.cow_pages == 1, "duplicate admission must be a COW truncation"
    assert eng.prefix_cache.match(p, max_tokens=len(p) - 1).tokens == 16
    np.testing.assert_array_equal(
        eng.finished[u1].tokens, eng.finished[u2].tokens
    )
    ref = generate(CFG, params, jnp.asarray(p)[None], 10, temperature=0.0)
    np.testing.assert_array_equal(eng.finished[u2].tokens, np.asarray(ref[0]))


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_prefix_parity_int8_shares_scales(params):
    """int8 pool mode: the per-page absmax scales are indexed by physical
    page alongside the int8 columns, so a shared page shares its scales by
    construction — cache-on must stay bit-identical to cache-off at the
    SAME dtype (quantization is deterministic, so this is exact equality,
    not a tolerance)."""
    trace = _template_trace(seed=2)
    (eng_off, toks_off), (eng_on, toks_on) = _run_pair(
        params, trace, cache_dtype="int8"
    )
    for i, (a, b) in enumerate(zip(toks_off, toks_on)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
    assert eng_on.prefix_stats()["hit_rate"] > 0.0
    _assert_conserved(eng_on)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_prefix_parity_spec_self_draft(params):
    """Speculative self-draft mode: the draft IS the target's first layers
    on the target's pool, so trie-shared pages serve draft and verify alike
    and spec rollback never strips a shared page (keep >= n_shared). Greedy
    spec+cache must equal greedy cache-off spec AND the plain reference."""
    from midgpt_tpu.sampling.spec import self_draft

    dcfg, dparams = self_draft(CFG, params, 1)
    trace = _template_trace(seed=3, n_templated=4, n_unique=2)
    spec_kw = dict(
        draft_params=dparams, draft_config=dcfg, draft_shares_cache=True,
        spec_k_max=4, spec_k_min=4, spec_adapt=False,
    )
    (eng_off, toks_off), (eng_on, toks_on) = _run_pair(
        params, trace, **spec_kw
    )
    for i, ((p, m), a, b) in enumerate(zip(trace, toks_off, toks_on)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(b, np.asarray(ref[0]), err_msg=f"request {i}")
    assert eng_on.prefix_stats()["hit_rate"] > 0.0
    _assert_conserved(eng_on)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_r10_preemption_resume_skips_self_reprefill(params):
    """The r10 regression pin. UNIQUE prompts in a pool too small for the
    working set: sharing between requests is impossible, so every trie hit
    is a preempted request re-matching its OWN donated pages. Cache off,
    each preemption re-prefills the whole folded prompt; cache on, resume
    costs at most the sub-page tail (< page_size tokens) per preemption —
    prefilled_tokens collapses to ~first-admission cost."""
    rng = np.random.default_rng(5)
    trace = []
    for i in range(3):
        p = rng.integers(0, CFG.vocab_size, 20).astype(np.int32)
        p[0] = i  # force distinct first pages: no cross-request sharing
        trace.append((p, 20))
    sum_len = sum(len(p) for p, _ in trace)
    (eng_off, toks_off), (eng_on, toks_on) = _run_pair(
        params, trace, num_pages=14
    )
    assert eng_off.preemptions >= 1, "the pool must actually force preemption"
    for i, ((p, m), a, b) in enumerate(zip(trace, toks_off, toks_on)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
    # cache off: every preemption re-prefilled a whole folded prompt
    assert eng_off.prefilled_tokens >= sum_len + eng_off.preemptions * min(
        len(p) for p, _ in trace
    )
    # cache on: resume re-matches the donated pages, so each preemption
    # costs at most the sub-page tail (plus the pending token a fold
    # appends) — UNLESS pool pressure trie-reclaimed a donated page first,
    # which costs at most page_size more per reclaimed page (the
    # prefix_evictions term; at this pool size it stays small)
    assert eng_on.prefilled_tokens <= (
        sum_len
        + eng_on.preemptions * (PS + 1)
        + eng_on.prefix_evictions * PS
    )
    assert eng_on.prefilled_tokens < eng_off.prefilled_tokens
    _assert_conserved(eng_on)


def test_prefix_conservation_across_cancel_and_ttl(params):
    """Every departure path goes through the trie release funnel: finish,
    client cancel, and TTL expiry must all conserve pages and drop every
    refcount — with the trie still holding re-matchable content after."""
    t = [0.0]
    eng = _engine(params, True, clock=lambda: t[0])
    trace = _template_trace(seed=7, n_templated=4, n_unique=1)
    uids = [
        eng.submit(p, m, ttl_s=(0.5 if i == 2 else None))
        for i, (p, m) in enumerate(trace)
    ]
    for _ in range(2):
        eng.step()
    assert eng.cancel(uids[1])
    t[0] = 1.0  # the TTL'd request expires on the next round
    eng.run()
    statuses = {u: eng.finished[u].status for u in uids}
    assert statuses[uids[1]] == "cancelled"
    assert statuses[uids[2]] == "timeout"
    assert sum(1 for s in statuses.values() if s == "ok") == len(uids) - 2
    _assert_conserved(eng)
    assert eng.prefix_cache.page_count() > 0, (
        "departing slots must donate their committed pages"
    )
