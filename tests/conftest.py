"""Test harness: 8 virtual CPU devices so mesh/FSDP/collective code paths run
without TPUs (the test infra the reference lacks — SURVEY.md §4).

Note: under the axon TPU plugin the JAX_PLATFORMS env var is overridden, so
platform selection must go through the config API before first backend use.
"""

import jax

from midgpt_tpu.utils.compat import set_cpu_device_count

jax.config.update("jax_platforms", "cpu")
set_cpu_device_count(8)
jax.config.update("jax_threefry_partitionable", True)
# This JAX build defaults matmuls to reduced (bf16-style) precision even on
# CPU; force full f32 so numerical parity tests are meaningful.
jax.config.update("jax_default_matmul_precision", "highest")
