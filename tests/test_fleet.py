"""Fleet serving primitives (sampling/fleet.py): the host-RAM spill
tier's checksum/version/ledger discipline, the PageHandoffQueue
bounded-retry transport it shares with disagg, and the FleetRouter's
affinity / health-check / failover policies. Router policy runs against
duck-typed fake replicas — the policies are pure host-side scheduling, a
model would only slow the assertions down. The end-to-end gates (crash
parity, corrupt-spill discard, cross-tier conservation) live in
test_chaos_serve.py and the serve_fleet bench contract."""

import types

import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.robustness import faults
from midgpt_tpu.sampling.disagg import (
    HandoffRetryExhausted,
    PageHandoffQueue,
)
from midgpt_tpu.sampling.fleet import (
    FleetRouter,
    SpillTier,
    _blocks_crc,
    assert_fleet_conserved,
)
from midgpt_tpu.sampling.serve import BackpressureError, FinishedRequest


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- spill tier -----------------------------------------------------------

PS = 8


def _fake_cache(n_pages, *, quantized=False, seed=0):
    """The slice of the KV pool SpillTier.spill reads: k/v with the page
    axis at 2, optional per-page scales with the page axis at 1."""
    rng = np.random.default_rng(seed)
    ns = types.SimpleNamespace(
        k=jnp.asarray(rng.standard_normal((2, 2, n_pages, PS, 4)),
                      jnp.float32),
        v=jnp.asarray(rng.standard_normal((2, 2, n_pages, PS, 4)),
                      jnp.float32),
        k_scale=None,
        v_scale=None,
    )
    if quantized:
        ns.k_scale = jnp.asarray(
            rng.standard_normal((2, n_pages, PS)), jnp.float32
        )
        ns.v_scale = jnp.asarray(
            rng.standard_normal((2, n_pages, PS)), jnp.float32
        )
    return ns


def _spill_prompt(cache, tier, prompt, pages, version="v0"):
    """Spill `pages` pool pages as the consecutive page-prefixes of
    `prompt` (what PrefixCache.on_evict hands the tier)."""
    for depth, page in enumerate(pages):
        tier.spill(cache, tuple(prompt[: (depth + 1) * PS]), page, version)


def test_spill_roundtrip_closes_ledger():
    """Pages spilled under a prompt's page-prefixes come back bit-exact
    via peek_run/take_run, and every counter lands in exactly one ledger
    bucket (the cross-tier half of assert_fleet_conserved)."""
    cache = _fake_cache(4)
    tier = SpillTier()
    tier.set_page_size(PS)
    prompt = list(range(100, 100 + 3 * PS))
    _spill_prompt(cache, tier, prompt, [1, 2])
    assert tier.resident_count() == 2
    assert tier.peek_run(prompt, 0, 3, "v0") == 2  # run stops at depth 2
    got = tier.take_run(prompt, 0, 2, "v0")
    assert len(got) == 2
    np.testing.assert_array_equal(
        got[0]["k"], np.asarray(cache.k[:, :, 1])
    )
    np.testing.assert_array_equal(
        got[1]["v"], np.asarray(cache.v[:, :, 2])
    )
    # move-on-take: the tier no longer holds them
    assert tier.resident_count() == 0
    assert tier.readopted == 2
    tier.assert_ledger("roundtrip")


def test_spill_quantized_blocks_carry_scales():
    """int8 pools spill quantized — the per-page scales must travel with
    the columns or re-adoption would decode garbage."""
    cache = _fake_cache(4, quantized=True)
    tier = SpillTier()
    tier.set_page_size(PS)
    prompt = list(range(2 * PS))
    _spill_prompt(cache, tier, prompt, [3])
    (blocks,) = tier.take_run(prompt, 0, 1, "v0")
    assert set(blocks) == {"k", "v", "k_scale", "v_scale"}
    np.testing.assert_array_equal(
        blocks["k_scale"], np.asarray(cache.k_scale[:, 3])
    )
    tier.assert_ledger("quantized")


def test_spill_checksum_catches_corruption():
    """A flipped byte between spill and take is caught by the crc32
    verify: the entry is discarded (never handed to a decode), the run
    truncates, and the discard is ledgered."""
    cache = _fake_cache(4)
    tier = SpillTier()
    tier.set_page_size(PS)
    prompt = list(range(3 * PS))
    _spill_prompt(cache, tier, prompt, [1, 2])
    assert tier.corrupt_one()  # targets the most recent spill (depth 1)
    got = tier.take_run(prompt, 0, 2, "v0")
    assert len(got) == 1  # depth 0 fine, depth 1 discarded -> truncated
    assert tier.corrupt_discarded == 1
    assert tier.resident_count() == 0  # the corrupt entry is GONE
    tier.assert_ledger("corrupt")


def test_spill_stall_refuses_once_then_recovers():
    """An armed stall refuses the first consult that would return pages
    (the caller re-prefills — slower, never wrong), then clears."""
    cache = _fake_cache(4)
    tier = SpillTier()
    tier.set_page_size(PS)
    prompt = list(range(2 * PS))
    _spill_prompt(cache, tier, prompt, [1])
    tier.arm_stall()
    assert tier.peek_run(prompt, 0, 1, "v0") == 0
    assert tier.stall_fallbacks == 1
    assert tier.peek_run(prompt, 0, 1, "v0") == 1  # cleared
    tier.assert_ledger("stall")


def test_spill_capacity_drops_oldest():
    cache = _fake_cache(6)
    entry_bytes = 2 * np.asarray(cache.k[:, :, 0]).nbytes
    tier = SpillTier(capacity_bytes=2 * entry_bytes)
    tier.set_page_size(PS)
    prompt = list(range(4 * PS))
    _spill_prompt(cache, tier, prompt, [1, 2, 3])
    assert tier.resident_count() == 2
    assert tier.capacity_dropped == 1
    # the OLDEST (depth 0) was dropped: the run now starts broken
    assert tier.peek_run(prompt, 0, 3, "v0") == 0
    assert tier.peek_run(prompt, 1, 2, "v0") == 2
    tier.assert_ledger("capacity")


def test_spill_version_discipline():
    """Weights-version rules: a duplicate under the same version is
    skipped (same tokens + same weights => same KV), a duplicate across a
    hot swap replaces the stale entry, and a take under the wrong version
    discards instead of re-adopting another model's KV."""
    cache = _fake_cache(4)
    tier = SpillTier()
    tier.set_page_size(PS)
    prompt = list(range(2 * PS))
    _spill_prompt(cache, tier, prompt, [1], version="v0")
    _spill_prompt(cache, tier, prompt, [1], version="v0")
    assert tier.duplicate_skips == 1 and tier.total_spilled == 1
    _spill_prompt(cache, tier, prompt, [2], version="v1")  # post-swap
    assert tier.stale_discarded == 1
    assert tier.peek_run(prompt, 0, 1, "v0") == 0
    assert tier.take_run(prompt, 0, 1, "v0") == []
    assert tier.stale_discarded == 2
    assert tier.resident_count() == 0
    tier.assert_ledger("version")


def test_spill_page_size_binds_once():
    tier = SpillTier()
    with pytest.raises(RuntimeError, match="before any engine"):
        tier.peek_run([0] * 16, 0, 1, "v0")
    tier.set_page_size(8)
    tier.set_page_size(8)  # idempotent
    with pytest.raises(ValueError, match="already bound"):
        tier.set_page_size(16)


# -- the shared page-transport queue --------------------------------------


def _item(uid=7, n_pages=2):
    return types.SimpleNamespace(
        uid=uid, n_pages=n_pages, blocks={"k": np.zeros(4, np.float32)}
    )


def test_handoff_queue_backoff_schedule_and_exhaustion():
    """The failover/disagg transport: a refused item returns to the FRONT
    under the shared exponential backoff (robustness/backoff.py), shields
    the items behind it, and raises the structured HandoffRetryExhausted
    past the bounded budget instead of spinning."""
    clock = _FakeClock()
    q = PageHandoffQueue(retries=3, base_s=1.0, clock=clock)
    q.push(_item(uid=7))
    q.push(_item(uid=8))
    it = q.pop()
    assert it.uid == 7
    q.requeue(it)  # attempt 1: delay base_s * 2**0
    assert q.pop() is None  # backed off, and uid=8 is shielded behind it
    clock.t += 1.0
    it = q.pop()
    assert it.uid == 7  # kept its place
    q.requeue(it)  # attempt 2: delay 2.0
    clock.t += 2.0
    it = q.pop()
    with pytest.raises(HandoffRetryExhausted) as ei:
        q.requeue(it)  # attempt 3 == budget
    assert ei.value.uid == 7 and ei.value.attempts == 3
    assert q.retry_exhausted == 1
    assert q.pop().uid == 8  # the queue keeps serving
    assert q.stats()["enqueued"] == 2


def test_handoff_queue_rejects_zero_retries():
    with pytest.raises(ValueError, match="retries"):
        PageHandoffQueue(retries=0)


# -- router policy, against fake replicas ---------------------------------


class _FakeEngine:
    """Duck-typed stand-in for ServeEngine: just enough surface for the
    router's admission/health/failover policy (capacity-bounded submit,
    deterministic finish after `steps_to_finish` rounds, injectable step
    failures and clock stalls)."""

    def __init__(self, *, capacity=4, steps_to_finish=2, page_size=8,
                 clock=None, retryable_shed=True):
        self.prefix_cache = object()  # router requires a trie
        self.temperature = 0.0
        self.page_size = page_size
        self.capacity = capacity
        self.steps_to_finish = steps_to_finish
        self.retryable_shed = retryable_shed
        self.on_token = None
        self.finished = {}
        self.active = {}
        self.spill = None
        self._uid = 0
        self.fail_steps = 0  # raise in step() this many times
        self.stall_s = 0.0  # advance `clock` by this much per step
        self._clock = clock
        # stats() surface
        self.rounds = 0
        self.preemptions = 0
        self.shed = 0
        self.spill_readopted_pages = 0
        self._prefix_matched_tokens = 0
        self._prefix_matchable_tokens = 0

    def attach_spill(self, spill):
        self.spill = spill
        spill.set_page_size(self.page_size)

    def prefix_stats(self):
        return {"hit_rate": 0.0}

    def submit(self, prompt, max_new_tokens, eos_id=None, ttl_s=None):
        if len(self.active) >= self.capacity:
            self.shed += 1
            raise BackpressureError(
                "fake full", needed_pages=1, backlog_pages=self.capacity,
                budget_pages=self.capacity, retryable=self.retryable_shed,
            )
        uid = self._uid
        self._uid += 1
        self.active[uid] = [
            np.asarray(prompt, np.int32), int(max_new_tokens),
            self.steps_to_finish,
        ]
        return uid

    @property
    def idle(self):
        return not self.active

    def step(self):
        self.rounds += 1
        if self.fail_steps > 0:
            self.fail_steps -= 1
            raise RuntimeError("injected replica failure")
        if self.stall_s and self._clock is not None:
            self._clock.t += self.stall_s
        for uid in [u for u, rec in self.active.items()
                    if rec[2] <= 1]:
            prompt, m, _ = self.active.pop(uid)
            # deterministic "generation": prompt echoed + counted tokens
            toks = np.concatenate(
                [prompt, np.arange(m, dtype=np.int32)]
            )
            self.finished[uid] = FinishedRequest(uid, toks, [0.0] * m, "ok")
        for rec in self.active.values():
            rec[2] -= 1


def _prompt(template, tail):
    return np.asarray(list(template) + list(tail), np.int32)


def test_router_affinity_is_deterministic_and_rendezvous_stable():
    """The rendezvous property failover depends on: a prompt's affinity
    replica is a pure function of its first page, and when a replica dies
    only ITS prompts remap — every other prompt keeps its replica, so the
    surviving tries stay hot."""
    clock = _FakeClock()
    router = FleetRouter(
        [_FakeEngine() for _ in range(3)], clock=clock
    )
    prompts = [
        _prompt(range(t * 50, t * 50 + 8), [1, 2, 3]) for t in range(6)
    ]
    full = [router._affinity(p, [0, 1, 2]) for p in prompts]
    assert full == [router._affinity(p, [0, 1, 2]) for p in prompts]
    dead = full[0]
    survivors = [i for i in range(3) if i != dead]
    for p, aff in zip(prompts, full):
        remapped = router._affinity(p, survivors)
        if aff != dead:
            assert remapped == aff  # rendezvous: unaffected keys stay put
        else:
            assert remapped in survivors
    # prompts shorter than a full shareable page have no affinity
    assert router._affinity(np.arange(8, dtype=np.int32), [0, 1, 2]) is None


def test_router_places_by_affinity_then_least_loaded():
    clock = _FakeClock()
    router = FleetRouter([_FakeEngine(capacity=8) for _ in range(2)],
                         clock=clock)
    p = _prompt(range(8), [9, 9])
    aff = router._affinity(p, [0, 1])
    for _ in range(3):  # same template -> same replica, every time
        uid = router.submit(p, 4)
        assert router._pending[uid].replica == aff
    # affinity replica full: spillover to the other survivor, not a shed
    router.engines[aff].capacity = 3
    uid = router.submit(p, 4)
    assert router._pending[uid].replica == 1 - aff


def test_router_failover_zero_drops_on_consecutive_failures():
    """The health-check path: a replica that keeps throwing is declared
    dead at max_consecutive_failures; its accepted streams replay on the
    survivor with the ORIGINAL prompt and full budget, and finish with
    the same deterministic output — zero drops, no duplicates."""
    clock = _FakeClock()
    router = FleetRouter(
        [_FakeEngine(capacity=8, clock=clock) for _ in range(2)],
        clock=clock, max_consecutive_failures=2,
    )
    uids = [router.submit(_prompt(range(t, t + 8), [1]), 3)
            for t in (0, 100, 200)]
    victim = router._pending[uids[0]].replica
    expected = {
        u: np.concatenate([router._pending[u].prompt,
                           np.arange(3, dtype=np.int32)])
        for u in uids
    }
    router.engines[victim].fail_steps = 2
    done = router.run()
    assert set(done) == set(uids)
    assert router.failovers == 1
    assert router.alive[victim] is False
    assert router.crash_log[0]["reason"] == "consecutive_failures"
    moved = sum(1 for u in uids
                if router._pending.get(u) is None)  # all drained
    assert moved == 3 and router.failed_over_streams >= 1
    for u in uids:
        assert done[u].status == "ok"
        np.testing.assert_array_equal(done[u].tokens, expected[u])


def test_router_heartbeat_staleness_crashes_the_wedged_replica():
    """A replica whose rounds stop returning within heartbeat_timeout_s
    is declared dead even though step() never raised — the wedged-host
    failure mode consecutive-failure counting cannot see."""
    clock = _FakeClock()
    router = FleetRouter(
        [_FakeEngine(capacity=8, clock=clock) for _ in range(2)],
        clock=clock, heartbeat_timeout_s=5.0,
    )
    uid = router.submit(_prompt(range(8), [1]), 3)
    victim = router._pending[uid].replica
    router.engines[victim].stall_s = 50.0  # each round eats 50 "seconds"
    done = router.run()
    assert router.alive[victim] is False
    assert router.crash_log[0]["reason"] == "heartbeat_stale"
    assert done[uid].status == "ok"  # failed over, not dropped


def test_router_aggregated_shed_is_structured_and_retryable():
    clock = _FakeClock()
    router = FleetRouter(
        [_FakeEngine(capacity=0, clock=clock) for _ in range(2)],
        clock=clock,
    )
    with pytest.raises(BackpressureError) as ei:
        router.submit(_prompt(range(8), [1]), 4)
    assert ei.value.retryable is True  # any retryable replica => retryable
    assert router.router_shed == 1
    router2 = FleetRouter(
        [_FakeEngine(capacity=0, retryable_shed=False)], clock=clock,
    )
    with pytest.raises(BackpressureError) as ei:
        router2.submit(_prompt(range(8), [1]), 4)
    assert ei.value.retryable is False


def test_router_failover_past_budget_is_terminal_shed():
    """When every survivor refuses a failed-over stream past the bounded
    retry budget, the stream terminates with a structured "shed" status —
    a graceful-degradation verdict the client can see, never a silent
    drop or an infinite requeue spin."""
    clock = _FakeClock()
    eng0 = _FakeEngine(capacity=1, clock=clock)
    eng1 = _FakeEngine(capacity=0, clock=clock)  # survivor always refuses
    router = FleetRouter(
        [eng0, eng1], clock=clock, max_consecutive_failures=1,
        failover_retries=3,
    )
    # place on eng0 regardless of affinity (eng1 has no room)
    uid = router.submit(_prompt(range(8), [1]), 3)
    assert router._pending[uid].replica == 0
    eng0.fail_steps = 1  # first step kills it
    done = router.run()
    assert done[uid].status == "shed"
    assert router.shed_streams == 1
    assert router.failover_queue.retry_exhausted == 1


def test_router_requires_greedy_and_prefix_cache():
    eng = _FakeEngine()
    eng.temperature = 0.7
    with pytest.raises(ValueError, match="greedy"):
        FleetRouter([eng])
    eng2 = _FakeEngine()
    eng2.prefix_cache = None
    with pytest.raises(ValueError, match="prefix cache"):
        FleetRouter([eng2])
    with pytest.raises(ValueError, match="page_size"):
        FleetRouter([_FakeEngine(page_size=8), _FakeEngine(page_size=16)])


# -- real engines: the availability story ---------------------------------


def test_fleet_absorbs_burst_a_single_engine_sheds():
    """The acceptance story behind `loadgen --fleet`: under a bounded
    admission budget (max_backlog_pages), a burst that a single engine
    must shed fits the fleet's aggregate budget — the affinity replica
    refuses and the request spills over to the other survivor instead of
    bouncing to the client. The fleet then drains every admitted stream
    with pages conserved on every replica and the spill ledger closed."""
    import jax

    from midgpt_tpu.models.gpt import GPT, GPTConfig
    from midgpt_tpu.sampling.serve import ServeEngine

    cfg = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2,
                    n_embd=32)
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    template = rng.integers(0, cfg.vocab_size, 8).astype(np.int32)
    burst = [
        (np.concatenate([template,
                         rng.integers(0, cfg.vocab_size, 4).astype(np.int32)]),
         8)
        for _ in range(4)
    ]  # worst case ceil((12+8)/8) = 3 pages each

    def mk():
        return ServeEngine(
            cfg, params, max_slots=3, page_size=8, num_pages=31,
            prefill_chunk=16, decode_chunk=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=True,
            max_backlog_pages=7,  # fits 2 bursts of 3 pages, not 3
        )

    single = mk()
    admitted, shed = 0, 0
    for p, m in burst:
        try:
            single.submit(p, m)
            admitted += 1
        except BackpressureError as e:
            assert e.retryable
            shed += 1
    assert shed >= 1, "the burst must overrun one engine's budget"

    router = FleetRouter([mk(), mk()])
    uids = [router.submit(p, m) for p, m in burst]  # all admitted
    done = router.run()
    assert all(done[u].status == "ok" for u in uids)
    assert router.router_shed == 0
    assert len({router.finished[u].tokens.tobytes() for u in uids}) >= 1
    assert_fleet_conserved(router, "burst")


def test_blocks_crc_is_order_and_content_sensitive():
    a = {"k": np.arange(8, dtype=np.float32),
         "v": np.arange(8, 16).astype(np.float32)}
    b = {k: v.copy() for k, v in a.items()}
    assert _blocks_crc(a) == _blocks_crc(b)
    b["k"][0] += 1
    assert _blocks_crc(a) != _blocks_crc(b)
