"""Worker for the 2-process distributed CPU test (run via subprocess).

Each process: jax.distributed.initialize on localhost, 2 local CPU devices
(4 global), per-process data shard via TokenDataset(shard_by_process=True),
global batch assembly via make_global_batch, ONE compiled train step over a
(data=2, fsdp=2) mesh. Prints `LOSS <value>` — the parent test asserts both
processes print the same finite number (proving global-array assembly, not
just single-process SPMD).

Usage: python multiproc_worker.py <coordinator> <n_proc> <proc_id> <data_dir>
"""

import sys

import jax

coordinator, n_proc, proc_id, data_dir = (
    sys.argv[1],
    int(sys.argv[2]),
    int(sys.argv[3]),
    sys.argv[4],
)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=n_proc, process_id=proc_id
)


from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.data.dataset import TokenDataset
from midgpt_tpu.models.gpt import GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.training.train import init_state, make_train_step

assert jax.process_count() == n_proc, jax.process_count()
assert jax.device_count() == 2 * n_proc, jax.device_count()

config = ExperimentConfig(
    rundir="",
    data_dir=data_dir,
    learning_rate=1e-3,
    batch_size=8,  # global
    warmup_steps=2,
    min_lr=1e-4,
    lr_decay_steps=10,
    max_steps=10,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=5,
    param_dtype="float32",
    compute_dtype="float32",
    g_accum_iters=2,
    shard_model=True,
    fsdp_min_size=0,
    mesh=MeshConfig(data=2, fsdp=2, sp=1),
    model_config=GPTConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=32),
)

mesh = make_mesh(config.mesh)
dataset = TokenDataset(data_dir, seed=7, shard_by_process=True)
# each process must hold a distinct, equal-length contiguous slice
n_total = 4096
assert len(dataset["train"]) == n_total // n_proc, len(dataset["train"])

params, opt_state, specs, optimizer = init_state(config, mesh)
step, *_ = make_train_step(config, optimizer, mesh, specs)

local_bs = config.batch_size // n_proc
x, y = dataset.batch("train", 0, config.model_config.block_size, local_bs, config.g_accum_iters)
xg = make_global_batch(x, mesh, batch_spec())
yg = make_global_batch(y, mesh, batch_spec())
assert xg.shape == (config.g_accum_iters, config.batch_size, config.model_config.block_size)

params, opt_state, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
print(f"LOSS {float(loss):.6f}", flush=True)
