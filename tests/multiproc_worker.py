"""Worker for the 2-process distributed CPU tests (run via subprocess).

Each process: jax.distributed.initialize on localhost, 2 local CPU devices
(4 global), per-process data shard via TokenDataset(shard_by_process=True),
global batch assembly via make_global_batch, compiled train steps over a
(data=2, fsdp=2) mesh.

Modes (argv[5], default "train"):
  * train        — one step, print `LOSS <value>`: the parent asserts both
                   processes print the same finite number (proving global
                   array assembly, not just single-process SPMD).
  * ckpt_save    — two steps, save a SHARDED checkpoint (each process writes
                   its shards) to argv[6], then run step 2 and print
                   `CONT <loss>` — the continued-training oracle.
  * ckpt_restore — fresh processes RESTORE the sharded checkpoint from
                   argv[6] (never recomputing steps 0-1), run step 2, print
                   `CONT <loss>`. The parent asserts it matches the oracle:
                   a failed or no-op restore would diverge, because restored
                   params+opt state after 2 steps differ from a fresh init.
    This beats the reference's pod-only checkpoint smoke (reference
    scripts/test_ckpt.py:8-24, print-only) — it runs anywhere and asserts.

Usage: python multiproc_worker.py <coordinator> <n_proc> <proc_id> <data_dir>
           [mode] [rundir]
"""

import sys

import jax

coordinator, n_proc, proc_id, data_dir = (
    sys.argv[1],
    int(sys.argv[2]),
    int(sys.argv[3]),
    sys.argv[4],
)
mode = sys.argv[5] if len(sys.argv) > 5 else "train"
rundir = sys.argv[6] if len(sys.argv) > 6 else ""

jax.config.update("jax_platforms", "cpu")
from midgpt_tpu.utils.compat import set_cpu_device_count

set_cpu_device_count(2)
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=n_proc, process_id=proc_id
)


from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.data.dataset import TokenDataset
from midgpt_tpu.models.gpt import GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.training.train import init_state, make_train_step

assert jax.process_count() == n_proc, jax.process_count()
assert jax.device_count() == 2 * n_proc, jax.device_count()

config = ExperimentConfig(
    rundir="",
    data_dir=data_dir,
    learning_rate=1e-3,
    batch_size=8,  # global
    warmup_steps=2,
    min_lr=1e-4,
    lr_decay_steps=10,
    max_steps=10,
    beta2=0.95,
    weight_decay=1e-4,
    eval_interval=5,
    param_dtype="float32",
    compute_dtype="float32",
    g_accum_iters=2,
    shard_model=True,
    fsdp_min_size=0,
    mesh=MeshConfig(data=2, fsdp=2, sp=1),
    model_config=GPTConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=32),
)

mesh = make_mesh(config.mesh)
dataset = TokenDataset(data_dir, seed=7, shard_by_process=True)
# each process must hold a distinct, equal-length contiguous slice
n_total = 4096
assert len(dataset["train"]) == n_total // n_proc, len(dataset["train"])

params, opt_state, specs, optimizer = init_state(config, mesh)
step, *_ = make_train_step(config, optimizer, mesh, specs)

local_bs = config.batch_size // n_proc
base_key = jax.random.PRNGKey(0)


def run_step(itr, params, opt_state):
    x, y = dataset.batch(
        "train", itr, config.model_config.block_size, local_bs, config.g_accum_iters
    )
    xg = make_global_batch(x, mesh, batch_spec())
    yg = make_global_batch(y, mesh, batch_spec())
    assert xg.shape == (
        config.g_accum_iters, config.batch_size, config.model_config.block_size,
    )
    return step(params, opt_state, xg, yg, jax.random.fold_in(base_key, itr))


if mode == "train":
    params, opt_state, loss = run_step(0, params, opt_state)
    print(f"LOSS {float(loss):.6f}", flush=True)
elif mode == "ckpt_save":
    from midgpt_tpu.training.checkpoint import CheckpointManager

    for itr in (0, 1):
        params, opt_state, loss = run_step(itr, params, opt_state)
    mngr = CheckpointManager(rundir, max_to_keep=1, save_interval_steps=1)
    mngr.save(1, {"params": params, "opt_state": opt_state}, force=True)
    mngr.close()
    params, opt_state, loss = run_step(2, params, opt_state)  # oracle
    print(f"CONT {float(loss):.6f}", flush=True)
elif mode == "ckpt_restore":
    from midgpt_tpu.training.checkpoint import CheckpointManager

    mngr = CheckpointManager(rundir, max_to_keep=1, save_interval_steps=1)
    assert mngr.latest_step() == 1, mngr.latest_step()
    state = mngr.restore(1, {"params": params, "opt_state": opt_state})
    params, opt_state = state["params"], state["opt_state"]
    mngr.close()
    params, opt_state, loss = run_step(2, params, opt_state)
    print(f"CONT {float(loss):.6f}", flush=True)
else:
    raise SystemExit(f"unknown mode {mode!r}")
