"""launch.py --set override semantics: typed, nested, order-independent."""

import importlib.util
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "launch_mod", os.path.join(os.path.dirname(__file__), "..", "launch.py")
)
launch_mod = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(launch_mod)

from midgpt_tpu.configs.shakespeare_char import config as base


def test_typed_nested_overrides():
    cfg = launch_mod.apply_overrides(
        base,
        [("max_steps", "123"), ("model_config.n_layer", "3"), ("mesh.sp", "2"),
         ("shard_model", "true")],
    )
    assert cfg.max_steps == 123 and isinstance(cfg.max_steps, int)
    assert cfg.model_config.n_layer == 3
    assert cfg.mesh.sp == 2
    assert cfg.shard_model is True
    assert base.max_steps != 123  # original untouched


def test_cross_field_validation_sees_final_state():
    """attn_impl=ring + dropout=0.0 must work in EITHER order (the combined
    state is valid even though ring + the preset's dropout 0.2 is not)."""
    for pairs in (
        [("model_config.attn_impl", "ring"), ("model_config.dropout", "0.0")],
        [("model_config.dropout", "0.0"), ("model_config.attn_impl", "ring")],
    ):
        cfg = launch_mod.apply_overrides(base, pairs)
        assert cfg.model_config.attn_impl == "ring"
        assert cfg.model_config.dropout == 0.0


def test_invalid_final_state_still_rejected():
    with pytest.raises(ValueError):
        launch_mod.apply_overrides(base, [("model_config.attn_impl", "flash")])


def test_set_optional_int_parses_as_int():
    """n_kv_heads is Optional[int] (None default = MHA): '--set
    model_config.n_kv_heads=2' must become int 2 — the None current value
    can't drive parsing, so the annotation must — and 'none' restores MHA."""
    ov = launch_mod.apply_overrides
    cfg = ov(base, [("model_config.n_kv_heads", "2"),
                    ("model_config.n_head", "4")])
    assert cfg.model_config.n_kv_heads == 2
    assert isinstance(cfg.model_config.n_kv_heads, int)
    assert ov(base, [("model_config.n_kv_heads", "none")]) \
        .model_config.n_kv_heads is None
    with pytest.raises(ValueError, match="n_kv_heads"):
        ov(base, [("model_config.n_kv_heads", "5")])  # not a divisor


def test_set_optional_bool_parses_numeric_and_none():
    """loss_remat_chunks is Optional[bool] (None default): '--set
    loss_remat_chunks=0' must become bool False (not the truthy string '0'),
    and 'none' restores auto."""
    ov = launch_mod.apply_overrides
    assert ov(base, [("loss_remat_chunks", "0")]).loss_remat_chunks is False
    assert ov(base, [("loss_remat_chunks", "1")]).loss_remat_chunks is True
    assert ov(base, [("loss_remat_chunks", "false")]).loss_remat_chunks is False
    assert ov(base, [("loss_remat_chunks", "none")]).loss_remat_chunks is None
