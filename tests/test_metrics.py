"""MetricLogger: jsonl sink + wandb run-id persistence for resume.

wandb is not installed on test hosts; these tests stub the module to verify
the resume contract (reference launch.py:59-68: a relaunched run must reuse
the id persisted in rundir/wandb_id.txt) without the dependency.
"""

import json
import os
import types

import midgpt_tpu.training.metrics as metrics
from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig


def _config(rundir):
    return ExperimentConfig(
        rundir=str(rundir),
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=1,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=False,
        mesh=MeshConfig(),
        model_config=GPTConfig(
            block_size=8, vocab_size=16, n_layer=1, n_head=1, n_embd=8
        ),
    )


class _FakeRun:
    def __init__(self, id):
        self.id = id
        self.logged = []

    def log(self, m, step=None):
        self.logged.append((step, m))

    def finish(self):
        pass


def _fake_wandb(created):
    fake = types.SimpleNamespace()
    fake.util = types.SimpleNamespace(generate_id=lambda: "generated123")

    def init(project=None, id=None, resume=None, config=None):
        run = _FakeRun(id)
        created.append(run)
        return run

    fake.init = init
    return fake


def test_jsonl_always_written(tmp_path):
    logger = metrics.MetricLogger(_config(tmp_path), use_wandb=False)
    logger.log(3, {"loss": 1.5})
    logger.close()
    rec = json.loads(open(tmp_path / "metrics.jsonl").read().splitlines()[0])
    assert rec["step"] == 3 and rec["loss"] == 1.5


def test_wandb_id_persisted_and_reused(tmp_path, monkeypatch):
    created = []
    monkeypatch.setattr(metrics, "_wandb", _fake_wandb(created))

    # first launch: generates an id and persists it
    logger = metrics.MetricLogger(_config(tmp_path))
    logger.close()
    id_file = tmp_path / "wandb_id.txt"
    assert id_file.read_text().strip() == "generated123"
    assert created[0].id == "generated123"

    # relaunch (resume): must reuse the persisted id, not generate a new one
    monkeypatch.setattr(
        metrics.MetricLogger, "_persistent_run_id",
        metrics.MetricLogger._persistent_run_id,
    )
    id_file.write_text("previous-run-id")
    logger2 = metrics.MetricLogger(_config(tmp_path))
    logger2.close()
    assert created[1].id == "previous-run-id"


def test_explicit_resume_id_wins(tmp_path, monkeypatch):
    created = []
    monkeypatch.setattr(metrics, "_wandb", _fake_wandb(created))
    logger = metrics.MetricLogger(_config(tmp_path), resume_id="explicit-id")
    logger.close()
    assert created[0].id == "explicit-id"
