"""Trace-level compile checks for the at-scale configs.

The 7B-class configs can't be materialized on a CPU test host, but the whole
training step — FSDP sharding specs, ring/flash attention dispatch, grad
accumulation, optimizer — can be traced and lowered against abstract inputs.
This catches shape/sharding/spec bugs in exactly the configurations that
only ever run on pods (`jit.lower` runs full tracing + SPMD spec checks; it
stops short of backend codegen).
"""

import dataclasses

import pytest

from midgpt_tpu.utils.hlo import lower_abstract_train_step as _lower_train_step


@pytest.mark.parametrize(
    "name", ["llama7b_long", "llama7b_32k", "openwebtext_xl", "wide610m"]
)
def test_at_scale_config_train_step_lowers(name):
    import importlib

    config = importlib.import_module(f"midgpt_tpu.configs.{name}").config
    # Shrink only what tracing doesn't need big: steps/batch stay as-is,
    # layer count drops (the scan makes depth O(1) for tracing anyway, but
    # 32 unrolled grad-accum microsteps x 32 layers is slow to trace).
    config = config.replace(
        g_accum_iters=min(config.g_accum_iters, 2),
        # Single-chip configs (wide610m: batch 12) must still shard over the
        # 8-device test mesh — round the batch up, shapes are abstract anyway.
        batch_size=-(-config.batch_size // 8) * 8,
        model_config=dataclasses.replace(config.model_config, n_layer=2),
        # serving-only knob: must shrink with n_layer (validated against
        # it) and is irrelevant to the train step being lowered here
        spec_layers=min(config.spec_layers, 1),
    )
    lowered = _lower_train_step(config)
    assert "main" in lowered.as_text()[:2000]
