"""Trace-level compile checks for the at-scale configs.

The 7B-class configs can't be materialized on a CPU test host, but the whole
training step — FSDP sharding specs, ring/flash attention dispatch, grad
accumulation, optimizer — can be traced and lowered against abstract inputs.
This catches shape/sharding/spec bugs in exactly the configurations that
only ever run on pods (`jit.lower` runs full tracing + SPMD spec checks; it
stops short of backend codegen).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.parallel.fsdp import fsdp_param_specs, named_shardings
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.training.optim import make_optimizer
from midgpt_tpu.training.train import make_train_step


def _lower_train_step(config):
    mesh = make_mesh(config.mesh)
    mc = config.model_config
    optimizer, _ = make_optimizer(config)

    abstract_params = jax.eval_shape(
        lambda k: GPT.init(mc, k), jax.random.PRNGKey(0)
    )
    param_specs = fsdp_param_specs(
        abstract_params, mesh, config.shard_model, config.fsdp_min_size
    )
    p_sh = named_shardings(param_specs, mesh)
    params_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, jnp.float32, sharding=s),
        abstract_params,
        p_sh,
    )
    opt_abs = jax.eval_shape(optimizer.init, params_abs)
    opt_specs = fsdp_param_specs(opt_abs, mesh, config.shard_model, config.fsdp_min_size)
    o_sh = named_shardings(opt_specs, mesh)
    opt_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s), opt_abs, o_sh
    )

    step, _, _ = make_train_step(config, optimizer, mesh, param_specs)
    G, B, T = config.g_accum_iters, config.batch_size, mc.block_size
    data_sh = NamedSharding(mesh, batch_spec(shard_seq=mesh.shape["sp"] > 1))
    x_abs = jax.ShapeDtypeStruct((G, B, T), jnp.int32, sharding=data_sh)
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return step.lower(params_abs, opt_abs, x_abs, x_abs, key_abs)


@pytest.mark.parametrize(
    "name", ["llama7b_long", "llama7b_32k", "openwebtext_xl", "wide610m"]
)
def test_at_scale_config_train_step_lowers(name):
    import importlib

    config = importlib.import_module(f"midgpt_tpu.configs.{name}").config
    # Shrink only what tracing doesn't need big: steps/batch stay as-is,
    # layer count drops (the scan makes depth O(1) for tracing anyway, but
    # 32 unrolled grad-accum microsteps x 32 layers is slow to trace).
    config = config.replace(
        g_accum_iters=min(config.g_accum_iters, 2),
        # Single-chip configs (wide610m: batch 12) must still shard over the
        # 8-device test mesh — round the batch up, shapes are abstract anyway.
        batch_size=-(-config.batch_size // 8) * 8,
        model_config=dataclasses.replace(config.model_config, n_layer=2),
    )
    lowered = _lower_train_step(config)
    assert "main" in lowered.as_text()[:2000]
