"""GPipe pipeline parallelism (parallel/pipeline.py) on the virtual mesh:
spec placement, loss/grad parity vs the dense model, and full-train-step
trajectory parity vs the FSDP oracle. Beyond the reference's capability set
(its only model sharding is FSDP, reference model.py:167-178)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh

# The tp>1 composition runs the shard_map body partial-manual (GSPMD 'auto'
# axes); on this container's old jax the XLA CPU backend aborts in a CHECK
# on that combination, so utils/compat.py refuses it up front — skip
# cleanly here (runs on TPU backends / newer jax).
_JAX = tuple(int(x) for x in jax.__version__.split(".")[:2])
requires_partial_manual_cpu = pytest.mark.skipif(
    _JAX < (0, 5) and jax.default_backend() == "cpu",
    reason=f"partial-manual shard_map aborts XLA CPU on jax {jax.__version__}",
)

from midgpt_tpu.parallel.pipeline import make_pipeline_loss, pipeline_param_specs
from midgpt_tpu.training.train import init_state, make_train_step

CFG = GPTConfig(block_size=32, vocab_size=128, n_layer=4, n_head=2, n_embd=64)


def _dense_loss(params, x, y):
    h = GPT.hidden(CFG, params, x, inference=True)
    return fused_linear_cross_entropy(h, params.lm_head, y, 8192)


def test_pipeline_param_specs():
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params)
    assert specs.blocks.attn.wqkv == P("pp", None, None, None)
    assert specs.blocks.mlp.w_up == P("pp", None, None)
    assert specs.blocks.attn.q_scale == P("pp", None)
    assert specs.wte == P()
    assert specs.lm_head == P()
    opt_like = {"mu": params, "count": jnp.zeros(())}
    opt_specs = pipeline_param_specs(opt_like)
    assert opt_specs["mu"].blocks.attn.wqkv == P("pp", None, None, None)
    assert opt_specs["count"] == P()


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (4, 4), (4, 8)])
def test_pipeline_loss_matches_dense(pp, microbatches):
    mesh = make_mesh(MeshConfig(data=8 // pp, fsdp=1, sp=1, tp=1, pp=pp))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    rng = np.random.default_rng(0)
    # per-data-shard batch must divide into M microbatches
    B = (8 // pp) * microbatches
    x = rng.integers(0, CFG.vocab_size, (B, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))

    pipe_loss = make_pipeline_loss(CFG, mesh, specs, 8192, microbatches=microbatches)
    got = jax.jit(lambda p, a, b: pipe_loss(p, a, b, None))(sharded, xg, yg)
    want = _dense_loss(params, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_pipeline_gradients_match_dense():
    """Reverse AD through the tick scan + ppermute (the GPipe backward
    schedule) must equal dense-model gradients — including the replicated
    wte/lm_head grads that shard_map's transpose psums across stages."""
    pp = 4
    mesh = make_mesh(MeshConfig(data=8 // pp, fsdp=1, sp=1, tp=1, pp=pp))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    rng = np.random.default_rng(1)
    x = rng.integers(0, CFG.vocab_size, (8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))

    pipe_loss = make_pipeline_loss(CFG, mesh, specs, 8192)
    g_pipe = jax.jit(jax.grad(lambda p, a, b: pipe_loss(p, a, b, None)))(sharded, xg, yg)
    g_dense = jax.grad(_dense_loss)(params, jnp.asarray(x), jnp.asarray(y))
    for gp, gd in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_dense)):
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gd), atol=3e-5, rtol=3e-5
        )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_pipeline_train_step_matches_fsdp_only():
    """One full training step on a (data=2, pp=4) mesh reproduces the
    FSDP-only oracle's loss on the same batch and seed."""
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
        model_config=CFG,
    )
    oracle_cfg = ExperimentConfig(mesh=MeshConfig(data=2, fsdp=4, sp=1), **base)
    pp_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=1, sp=1, tp=1, pp=4), **base
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, CFG.vocab_size, (2, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    evals = {}
    for name, cfg in (("oracle", oracle_cfg), ("pp", pp_cfg)):
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, eval_loss, _ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        params, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
        evals[name] = float(eval_loss(params, xg[0], yg[0]))
    np.testing.assert_allclose(losses["pp"], losses["oracle"], rtol=1e-5)
    np.testing.assert_allclose(evals["pp"], evals["oracle"], rtol=1e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_pipeline_fsdp_composition_train_step_matches_oracle():
    """v2: stage weights shard over 'fsdp' (per-layer gathers inside the
    stage scan, ZeRO-3 style) — one full train step + eval on a
    (fsdp=2, pp=4) mesh reproduces the FSDP-only oracle."""
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
        model_config=CFG,
    )
    oracle_cfg = ExperimentConfig(mesh=MeshConfig(data=2, fsdp=4, sp=1), **base)
    pp_cfg = ExperimentConfig(
        mesh=MeshConfig(data=1, fsdp=2, sp=1, tp=1, pp=4), **base
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, CFG.vocab_size, (2, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses, evals = {}, {}
    for name, cfg in (("oracle", oracle_cfg), ("pp_fsdp", pp_cfg)):
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, eval_loss, _ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        params, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
        evals[name] = float(eval_loss(params, xg[0], yg[0]))
    np.testing.assert_allclose(losses["pp_fsdp"], losses["oracle"], rtol=1e-5)
    np.testing.assert_allclose(evals["pp_fsdp"], evals["oracle"], rtol=1e-5)


@requires_partial_manual_cpu
def test_pipeline_tp_composition_train_step_matches_oracle():
    """r5 composition: Megatron 'tp' rides a GSPMD auto axis INSIDE the
    pipeline shard_map (manual axes: data/fsdp/sp/pp only) — the stage
    weights shard their Megatron axes over 'tp' (pipeline_param_specs), the
    tick body stays written in pp/fsdp collectives, and GSPMD inserts the
    tp psums at the block joins. One full train step + eval on a
    (fsdp=2, tp=2, pp=2) mesh reproduces the FSDP-only oracle."""
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
        model_config=CFG,
    )
    oracle_cfg = ExperimentConfig(mesh=MeshConfig(data=2, fsdp=4, sp=1), **base)
    pp_tp_cfg = ExperimentConfig(
        mesh=MeshConfig(data=1, fsdp=2, sp=1, tp=2, pp=2), **base
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, CFG.vocab_size, (2, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses, evals = {}, {}
    for name, cfg in (("oracle", oracle_cfg), ("pp_tp", pp_tp_cfg)):
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, eval_loss, _ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        params, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
        evals[name] = float(eval_loss(params, xg[0], yg[0]))
    np.testing.assert_allclose(losses["pp_tp"], losses["oracle"], rtol=1e-5)
    np.testing.assert_allclose(evals["pp_tp"], evals["oracle"], rtol=1e-5)
    # and the stage weights really are tp-sharded (not silently replicated)
    mesh = make_mesh(pp_tp_cfg.mesh)
    params, _, specs, _ = init_state(pp_tp_cfg, mesh)
    assert specs.blocks.attn.wqkv == P("pp", None, "tp", "fsdp")
    assert specs.blocks.mlp.w_up == P("pp", "tp", "fsdp")
    assert specs.blocks.mlp.w_down == P("pp", "fsdp", "tp")


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_1f1b_loss_and_grads_match_gpipe():
    """The hand-written 1F1B backward (make_pipeline_loss_and_grad) computes
    the SAME loss and gradients as reverse AD of the GPipe schedule — and
    both match the dense oracle."""
    from midgpt_tpu.parallel.pipeline import make_pipeline_loss_and_grad

    pp, M = 4, 8
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sp=1, tp=1, pp=pp))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params, mesh)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    rng = np.random.default_rng(2)
    B = 2 * M * pp  # per-data-shard batch M*pp: microbatches divide by pp
    x = rng.integers(0, CFG.vocab_size, (B, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))

    pipe_loss = make_pipeline_loss(CFG, mesh, specs, 8192, microbatches=M)
    l_g, g_g = jax.jit(
        jax.value_and_grad(lambda p, a, b: pipe_loss(p, a, b, None))
    )(sharded, xg, yg)

    lag = make_pipeline_loss_and_grad(CFG, mesh, specs, 8192, microbatches=M)
    l_f, g_f = jax.jit(lambda p, a, b: lag(p, a, b, None))(sharded, xg, yg)

    np.testing.assert_allclose(float(l_f), float(l_g), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_g), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=3e-5, rtol=3e-5
        )
    # and against the dense oracle
    want = _dense_loss(params, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(float(l_f), float(want), rtol=1e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_1f1b_grads_match_gpipe_with_fsdp_replicated_leaves():
    """Regression (r5 review): with mesh.fsdp>1 and block leaves that are
    fsdp-REPLICATED (here: default fsdp_min_size leaves q/k scales and, with
    shard_model=False, everything replicated), each fsdp rank's grads must
    still be summed over 'fsdp' — GPipe's shard_map AD inserts that psum;
    the hand-written 1F1B backward must too. Loss alone cannot catch this
    (it matched while grads were ~31% off)."""
    from midgpt_tpu.parallel.pipeline import make_pipeline_loss_and_grad

    pp, M = 2, 2
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sp=1, tp=1, pp=pp))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params, mesh, shard_model=False)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    rng = np.random.default_rng(4)
    B = 2 * 2 * M * pp
    x = rng.integers(0, CFG.vocab_size, (B, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))

    pipe_loss = make_pipeline_loss(CFG, mesh, specs, 8192, microbatches=M)
    l_g, g_g = jax.jit(
        jax.value_and_grad(lambda p, a, b: pipe_loss(p, a, b, None))
    )(sharded, xg, yg)
    lag = make_pipeline_loss_and_grad(CFG, mesh, specs, 8192, microbatches=M)
    l_f, g_f = jax.jit(lambda p, a, b: lag(p, a, b, None))(sharded, xg, yg)
    np.testing.assert_allclose(float(l_f), float(l_g), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_g), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=3e-5, rtol=3e-5
        )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_1f1b_activation_stash_is_m_independent():
    """THE point of 1F1B (VERDICT r4 #5): growing the microbatch count must
    not grow the backward's activation memory. Compare compiled temp memory
    at M=4 vs M=16 for both schedules: GPipe's stash grows ~4x (reverse AD
    saves every tick's stage input), 1F1B's 2*pp-slot ring buffer does not.
    Asserted as a ratio so absolute allocator noise can't flake it."""
    from midgpt_tpu.parallel.pipeline import make_pipeline_loss_and_grad

    pp = 4
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, sp=1, tp=1, pp=pp))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params, mesh)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)

    def temp_bytes(schedule, M):
        B = 2 * M * pp
        xg = jax.device_put(
            jnp.zeros((B, 32), jnp.int32),
            jax.sharding.NamedSharding(mesh, batch_spec(with_accum=False)),
        )
        if schedule == "gpipe":
            pipe = make_pipeline_loss(CFG, mesh, specs, 8192, microbatches=M)
            fn = jax.jit(jax.value_and_grad(lambda p, a, b: pipe(p, a, b, None)))
        else:
            lag = make_pipeline_loss_and_grad(CFG, mesh, specs, 8192, microbatches=M)
            fn = jax.jit(lambda p, a, b: lag(p, a, b, None))
        mem = fn.lower(sharded, xg, xg).compile().memory_analysis()
        assert mem is not None, "backend reports no memory analysis"
        return mem.temp_size_in_bytes

    gpipe_growth = temp_bytes("gpipe", 16) / max(temp_bytes("gpipe", 4), 1)
    f1b_growth = temp_bytes("1f1b", 16) / max(temp_bytes("1f1b", 4), 1)
    # GPipe stash scales with M (16/4 -> ~4x); 1F1B must stay ~flat.
    assert gpipe_growth > 2.0, f"premise broken: gpipe growth {gpipe_growth}"
    assert f1b_growth < 1.5, (
        f"1F1B temp memory grew {f1b_growth:.2f}x with 4x microbatches — "
        "the activation stash is no longer M-independent"
    )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_1f1b_train_step_matches_gpipe_step():
    """One full training step with pipeline_schedule='1f1b' reproduces the
    GPipe step's loss (same params/batch/seed) through make_train_step."""
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=32,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
        model_config=CFG,
        # per-data-shard batch 16, M=4 -> microbatch 4, divisible by pp=4
        # (the 1F1B scattered CE's extra constraint)
        pipeline_microbatches=4,
    )
    rng = np.random.default_rng(3)
    x = rng.integers(0, CFG.vocab_size, (1, 32, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    for name, sched in (("gpipe", "gpipe"), ("1f1b", "1f1b")):
        cfg = ExperimentConfig(
            mesh=MeshConfig(data=2, fsdp=1, sp=1, tp=1, pp=4),
            pipeline_schedule=sched,
            **base,
        )
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, *_ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["1f1b"], losses["gpipe"], rtol=1e-5)


def test_pipeline_ce_volume_sharded_over_pp():
    """FLOP-level proof the lm_head/CE volume is 1x, not pp x: with a
    CE-dominated shape (V >> L·D), the compiled per-device program must cost
    ~F_dense/(data·pp) flops. The v1 schedule (every stage computing the
    full-batch CE on its collected outputs) costs ~F_dense/data per device —
    4x the asserted bound on this mesh."""
    cfg = dataclasses.replace(CFG, vocab_size=4096)
    data, pp = 2, 4
    mesh = make_mesh(MeshConfig(data=data, fsdp=1, sp=1, tp=1, pp=pp))
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    specs = pipeline_param_specs(params, mesh)
    sharded = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    rng = np.random.default_rng(0)
    B = 16
    x = rng.integers(0, cfg.vocab_size, (B, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))

    pipe_loss = make_pipeline_loss(cfg, mesh, specs, 8192)
    comp_pp = (
        jax.jit(lambda p, a, b: pipe_loss(p, a, b, None))
        .lower(sharded, xg, yg)
        .compile()
    )

    def dense_loss(p, a, b):
        h = GPT.hidden(cfg, p, a, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, b, 8192)

    comp_dense = (
        jax.jit(dense_loss).lower(params, jnp.asarray(x), jnp.asarray(y)).compile()
    )

    def flops(comp):
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"])

    # margin covers the bubble-inflated backbone + replicated embedding;
    # a pp x CE (v1) would exceed this bound ~4x.
    assert flops(comp_pp) < flops(comp_dense) / (data * pp) * 1.6, (
        flops(comp_pp), flops(comp_dense)
    )


def test_pipeline_config_validation():
    kw = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8, warmup_steps=1,
        min_lr=1e-4, lr_decay_steps=10, max_steps=10, beta2=0.99, weight_decay=0.0,
        eval_interval=5, param_dtype="float32", compute_dtype="float32",
        g_accum_iters=1, shard_model=True,
    )
    with pytest.raises(ValueError, match="n_layer"):
        ExperimentConfig(
            mesh=MeshConfig(fsdp=1, pp=3),
            model_config=CFG,  # n_layer=4 % 3 != 0
            **kw,
        )
    with pytest.raises(ValueError, match="dropout"):
        ExperimentConfig(
            mesh=MeshConfig(fsdp=1, pp=2),
            model_config=dataclasses.replace(CFG, dropout=0.1),
            **kw,
        )
    # v2: fsdp composes with pp; r5: tp does too; sp still does not
    ExperimentConfig(mesh=MeshConfig(fsdp=2, pp=2), model_config=CFG, **kw)
    ExperimentConfig(mesh=MeshConfig(fsdp=1, tp=2, pp=2), model_config=CFG, **kw)
    with pytest.raises(ValueError, match="sp"):
        ExperimentConfig(mesh=MeshConfig(fsdp=1, sp=2, pp=2), model_config=CFG, **kw)
