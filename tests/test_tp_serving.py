"""Mesh-sharded serving: tp greedy parity, recompile pins, and
disaggregated prefill/decode parity (docs/SERVING.md "Mesh-sharded
serving").

The load-bearing claim is BIT-parity: a tp-sharded engine partitions
head-aligned einsums whose megatron all-reduce restores the same f32
activations a single chip computes, and the paged KV pool shards on the
head axis without crossing shards — so the token streams must be
IDENTICAL to the single-chip engine's, across cache dtype, prefix cache,
and self-draft speculation. Any divergence means a wrong PartitionSpec or
a torn collective, not numerical noise (the same invariant the serve_tp
bench profile schema-enforces, analysis/bench_contract.py).

Pool geometry: num_pages=29/31 here, NOT 25 — pool size is a jit
program-key dim and tests/test_recompile_pins.py counts compiles of the
25-page geometry from a pristine baseline (alphabetical ordering runs it
first, but keeping the geometries disjoint makes the pins order-proof).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import CompileCounter, jit_cache_size
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.serve_tp import make_serve_mesh
from midgpt_tpu.sampling.disagg import DisaggServe
from midgpt_tpu.sampling.serve import (
    ServeEngine,
    _serve_decode_chunk,
    _spec_draft_chunk,
    _spec_verify_chunk,
)
from midgpt_tpu.sampling.spec import self_draft

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh():
    return make_serve_mesh(tp_size=2)


def _trace(seed, n=4):
    rng = np.random.default_rng(seed)
    lens = rng.integers(5, 30, size=n)
    return (
        [rng.integers(1, CFG.vocab_size, size=int(l)).tolist() for l in lens],
        [int(b) for b in rng.integers(5, 18, size=n)],
    )


def _run(params, *, mesh=None, dtype=jnp.float32, prefix=False, spec=False,
         seed=0, num_pages=29, **kw):
    skw = {}
    if spec:
        dcfg, dparams = self_draft(CFG, params, 1)
        skw = dict(draft_params=dparams, draft_config=dcfg,
                   draft_shares_cache=True, spec_k_max=4)
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=num_pages,
        prefill_chunk=8, decode_chunk=8, temperature=0.0, cache_dtype=dtype,
        prefix_cache=prefix, mesh=mesh, **skw, **kw,
    )
    prompts, budgets = _trace(seed)
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    done = eng.run()
    return eng, [done[u].tokens.tolist() for u in uids]


@pytest.mark.parametrize(
    # int8 rows carry the heaviest tp compiles (33 s measured r22); the
    # prefix-f32 row keeps tp-parity coverage inside the tier-1 870 s
    # gate and test_disagg_parity[int8] keeps a cheap int8 tp pin non-slow.
    "dtype",
    [jnp.float32, pytest.param("int8", marks=pytest.mark.slow)],
    ids=["f32", "int8"],
)
@pytest.mark.parametrize(
    # noprefix rows pay the full-prefill compiles
    "prefix",
    [pytest.param(False, marks=pytest.mark.slow), True],
    ids=["noprefix", "prefix"],
)
def test_tp_greedy_parity(params, mesh, dtype, prefix):
    """tp=2 token streams bit-identical to single-chip, per cache dtype and
    prefix-cache mode (prefix sharing is host-side page-table indirection —
    orthogonal to sharding, and the composition must stay exact)."""
    _, ref = _run(params, dtype=dtype, prefix=prefix)
    _, out = _run(params, mesh=mesh, dtype=dtype, prefix=prefix)
    assert out == ref


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [jnp.float32, "int8"], ids=["f32", "int8"])
def test_tp_spec_parity(params, mesh, dtype):
    """Self-draft speculation under tp: draft, verify, and rollback all run
    on sharded pools, and greedy spec output is defined to equal plain
    greedy decoding — so the tp spec stream must match the single-chip
    PLAIN stream too, not just the single-chip spec stream."""
    _, plain = _run(params, dtype=dtype)
    _, ref = _run(params, dtype=dtype, spec=True)
    eng, out = _run(params, mesh=mesh, dtype=dtype, spec=True)
    assert out == ref
    assert out == plain
    assert eng.spec_stats()["accept_rate"] >= 0.0  # counters alive under tp


def _pin_mix(params, mesh, lengths, max_new, seed, *, dtype=jnp.float32,
             spec=False, **kw):
    """Bucket-pinned mix (design from tests/test_recompile_pins.py): budgets
    ≡ 1 (mod decode_chunk=8) so every decode round runs a full chunk — one
    decode program per (dtype, mesh); prompts 25..47 pin the pow2 page
    bucket; prompt + max_new <= block_size=64; 31-page pool never evicts."""
    skw = {}
    if spec:
        dcfg, dparams = self_draft(CFG, params, 1)
        skw = dict(draft_params=dparams, draft_config=dcfg,
                   draft_shares_cache=True, spec_k_max=4, spec_k_min=4,
                   spec_adapt=False)
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=31,
        prefill_chunk=16, decode_chunk=8, temperature=0.0, cache_dtype=dtype,
        mesh=mesh, **skw, **kw,
    )
    rng = np.random.default_rng(seed)
    uids = {
        eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in zip(lengths, max_new)
    }
    assert set(eng.run()) == uids


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_tp_mix_change_compiles_nothing(params, mesh):
    """Recompile pin (mirrors tests/test_recompile_pins.py): the tp engine
    compiles one decode program per cache dtype and one draft+verify
    program per k-bucket, then serves further distinct mixes — and a
    scheduler swap — with ZERO compiles. The mesh is a static jit arg, so
    tp programs are new cache entries; request mix, page tables, and the
    host-side scheduler must not be. Geometry: num_pages=31 (the tp
    31-page programs are cold here even after the parity tests above)."""
    from midgpt_tpu.sampling.scheduler import SLOScheduler

    d0 = jit_cache_size(_serve_decode_chunk)
    sd0 = jit_cache_size(_spec_draft_chunk)
    sv0 = jit_cache_size(_spec_verify_chunk)
    _pin_mix(params, mesh, (25, 34, 47), (9, 17, 17), seed=1)
    assert jit_cache_size(_serve_decode_chunk) - d0 == 1
    _pin_mix(params, mesh, (25, 34, 47), (9, 17, 17), seed=2, dtype="int8")
    assert jit_cache_size(_serve_decode_chunk) - d0 == 2  # dtype IS a key
    _pin_mix(params, mesh, (31, 38, 45), (13, 9, 15), seed=3, spec=True)
    assert jit_cache_size(_spec_draft_chunk) - sd0 == 1
    assert jit_cache_size(_spec_verify_chunk) - sv0 == 1
    with CompileCounter() as cc:
        _pin_mix(params, mesh, (26, 33, 40), (9, 17, 9), seed=4)
        _pin_mix(params, mesh, (29, 41, 45), (17, 9, 17), seed=5,
                 dtype="int8")
        _pin_mix(params, mesh, (33, 40, 47), (9, 11, 13), seed=6, spec=True)
        _pin_mix(params, mesh, (31, 38, 47), (17, 17, 9), seed=7,
                 scheduler=SLOScheduler(min_headroom_s=0.0))
    assert cc.count == 0, f"tp mix/scheduler change recompiled {cc.count}"


def test_tp_stats_and_per_shard_bytes(params, mesh):
    """Observability: stats() carries the mesh shape (how serve_slo lines
    distinguish sharded runs) and the head-axis pool split is exact —
    per-shard bytes * tp == pool bytes."""
    eng, _ = _run(params, mesh=mesh)
    st = eng.stats()
    assert st["mesh"] == {"data": 1, "tp": 2}
    assert st["cache_hbm_bytes_per_shard"] * 2 == st["cache_hbm_bytes"]
    eng1, _ = _run(params)
    assert eng1.mesh_shape() is None


def test_tp_rejects_indivisible_heads(params, mesh):
    with pytest.raises(ValueError, match="n_head"):
        ServeEngine(
            dataclasses.replace(CFG, n_head=3, n_embd=48),
            GPT.init(dataclasses.replace(CFG, n_head=3, n_embd=48),
                     jax.random.PRNGKey(0)),
            max_slots=2, page_size=8, num_pages=29, temperature=0.0,
            cache_dtype=jnp.float32, mesh=make_serve_mesh(tp_size=2),
        )


def test_tp_kernel_shard_map_parity(mesh):
    """The Pallas paged decode / multi-row verify kernels invoked per-shard
    through shard_map (interpret mode on CPU) match the gather reference —
    the lowering path the TPU tp engine takes (kernels/decode_attention.py)."""
    from midgpt_tpu.kernels.decode_attention import (
        paged_attention,
        paged_verify_attention,
    )

    rng = np.random.default_rng(0)
    B, H, C, ps, NP, MP = 2, 4, 128, 8, 9, 4
    q = jnp.asarray(rng.normal(size=(B, H, C)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(H, NP, ps, C)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(H, NP, ps, C)), jnp.float32)
    pt = jnp.asarray(rng.integers(1, NP, size=(B, MP)), jnp.int32)
    lengths = jnp.asarray([11, 25], jnp.int32)
    ref = paged_attention(q, kp, vp, pt, lengths, impl="gather")
    out = paged_attention(q, kp, vp, pt, lengths, impl="kernel", mesh=mesh)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    T = 3
    qv = jnp.asarray(rng.normal(size=(B, T, H, C)), jnp.float32)
    counts = lengths[:, None] + jnp.arange(1, T + 1)[None, :]
    refv = paged_verify_attention(qv, kp, vp, pt, counts, impl="gather")
    outv = paged_verify_attention(qv, kp, vp, pt, counts, impl="kernel",
                                  mesh=mesh)
    np.testing.assert_allclose(outv, refv, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, "int8"], ids=["f32", "int8"])
def test_disagg_parity(params, dtype):
    """Disaggregated prefill/decode: token streams bit-identical to a
    monolithic prefix-cache engine — the handoff moves finished page
    prefixes between pools byte-for-byte, and the decode engine re-admits
    through the ordinary trie-match path. Real handoffs must happen (the
    queue's page counter moves) and nothing may fall back to re-prefill."""
    kw = dict(max_slots=3, num_pages=29, page_size=8, prefill_chunk=8,
              decode_chunk=8, temperature=0.0, cache_dtype=dtype)
    prompts, budgets = _trace(seed=0)
    mono = ServeEngine(CFG, params, prefix_cache=True, **kw)
    mu = [mono.submit(p, b) for p, b in zip(prompts, budgets)]
    mdone = mono.run()

    dis = DisaggServe(CFG, params, **kw)
    du = [dis.submit(p, b) for p, b in zip(prompts, budgets)]
    ddone = dis.run()

    for a, b in zip(mu, du):
        assert mdone[a].tokens.tolist() == ddone[b].tokens.tolist()
    st = dis.stats()
    assert st["queue"]["pages_copied"] > 0
    assert st["fallback_reprefills"] == 0


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_disagg_on_role_mesh(params):
    """Roles on the data axis of a (data=2, tp=2) mesh over 4 devices:
    prefill row 0, decode row 1, both tp-sharded — still bit-identical to
    an unsharded monolithic engine."""
    kw = dict(max_slots=3, num_pages=29, page_size=8, prefill_chunk=8,
              decode_chunk=8, temperature=0.0, cache_dtype=jnp.float32)
    prompts, budgets = _trace(seed=0)
    mono = ServeEngine(CFG, params, prefix_cache=True, **kw)
    mu = [mono.submit(p, b) for p, b in zip(prompts, budgets)]
    mdone = mono.run()

    dis = DisaggServe(
        CFG, params, mesh=make_serve_mesh(tp_size=2, data=2), **kw
    )
    du = [dis.submit(p, b) for p, b in zip(prompts, budgets)]
    ddone = dis.run()
    for a, b in zip(mu, du):
        assert mdone[a].tokens.tolist() == ddone[b].tokens.tolist()
    assert dis.prefill.mesh_shape() == {"data": 1, "tp": 2}
    assert dis.decode.mesh_shape() == {"data": 1, "tp": 2}
