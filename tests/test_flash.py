"""Pallas flash-attention parity vs the naive fp32-softmax oracle — forward
and backward — in interpret mode on CPU (compiled on real TPU)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.kernels.flash_attention import flash_attention
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.attention import naive_causal_attention
from midgpt_tpu.ops.loss import cross_entropy_loss


def make_qkv(key, B, H, T, C, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, T, C), dtype)
    k = jax.random.normal(kk, (B, H, T, C), dtype)
    v = jax.random.normal(kv, (B, H, T, C), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "T,blk_q,blk_k",
    [(128, 128, 128), (128, 64, 64), (256, 64, 128), (128, 32, 64)],
)
def test_forward_parity_f32(T, blk_q, blk_k):
    q, k, v = make_qkv(jax.random.PRNGKey(0), 2, 2, T, 64)
    ref = naive_causal_attention(q, k, v)
    out = flash_attention(q, k, v, blk_q, blk_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_parity_bf16():
    q, k, v = make_qkv(jax.random.PRNGKey(1), 1, 2, 128, 64, jnp.bfloat16)
    ref = naive_causal_attention(q, k, v)
    out = flash_attention(q, k, v, 64, 64)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2, rtol=2e-2
    )


def test_backward_parity_f32():
    q, k, v = make_qkv(jax.random.PRNGKey(2), 1, 2, 128, 32)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, 64, 64)))

    def loss_naive(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-5, err_msg=f"d{name}"
        )


@pytest.fixture
def force_flash_interpret(monkeypatch):
    """Route the model's 'flash' dispatch to the real kernel (interpret mode)
    instead of the off-TPU blockwise fallback."""
    import importlib

    fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")
    monkeypatch.setattr(fa, "RUN_INTERPRET_OFF_TPU", True)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_model_end_to_end_flash_matches_naive(force_flash_interpret):
    """Full GPT fwd+bwd with attn_impl='flash' vs 'naive'."""
    cfg = GPTConfig(
        block_size=64, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
        attn_impl="naive",
    )
    cfg_flash = dataclasses.replace(cfg, attn_impl="flash", attn_block_size=32)
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, 64)

    def loss(p, c):
        return cross_entropy_loss(GPT.apply(c, p, tokens, inference=True), labels)

    l1, g1 = jax.value_and_grad(loss)(params, cfg)
    l2, g2 = jax.value_and_grad(loss)(params, cfg_flash)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_indivisible_blocks_adjust_not_raise():
    """Explicit block sizes that don't tile T adjust to ones that do (the
    KV block widens to T, the Q block follows) instead of raising."""
    q, k, v = make_qkv(jax.random.PRNGKey(3), 1, 1, 96, 32)
    ref = naive_causal_attention(q, k, v)
    out = flash_attention(q, k, v, 64, 64)  # 96 % 64 != 0 -> blocks become (96, 96)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_dispatch_falls_back_on_indivisible_len():
    """multihead_attention(impl='flash') must handle arbitrary T (KV-cache
    prefill) by taking the blockwise path instead of crashing."""
    from midgpt_tpu.ops.attention import multihead_attention

    q, k, v = make_qkv(jax.random.PRNGKey(4), 1, 2, 90, 32)
    ref = naive_causal_attention(q, k, v)
    out = multihead_attention(q, k, v, impl="flash", inference=True, block_size=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_backward_parity_fused_single_step():
    """blk_k == T <= 1024 routes backward through the fully-fused dQ/dK/dV
    kernel (one probability reconstruction) — the hot path at T=1024."""
    q, k, v = make_qkv(jax.random.PRNGKey(5), 1, 2, 128, 32)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, 64, 128)))

    def loss_naive(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-5, err_msg=f"d{name}"
        )


def test_backward_parity_single_kv_long_seq():
    """blk_k == T > 1024 skips the fused kernel: stateless dq-single +
    tiled dk/dv kernels (the long-context backward split)."""
    q, k, v = make_qkv(jax.random.PRNGKey(6), 1, 1, 2048, 8)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, 512, 2048)))

    def loss_naive(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
        )


def test_default_blocks_fallback_non_divisible_T():
    """Direct flash_attention(q, k, v) calls with the default block sizes
    must serve sequence lengths the defaults don't divide (e.g. T=96): the
    KV block widens to T and the Q block follows, instead of raising."""
    q, k, v = make_qkv(jax.random.PRNGKey(9), 1, 2, 96, 32)
    ref = naive_causal_attention(q, k, v)
    out = flash_attention(q, k, v)  # defaults (512, 1024) do not divide 96
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    gf = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(flash_attention(q, k, v))), argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(naive_causal_attention(q, k, v))), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-5, err_msg=f"d{name}"
        )
