"""KV-cache decode parity: incremental decoding must reproduce the full
forward pass, and generation must match a no-cache reference loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig, KVCache
from midgpt_tpu.sampling.engine import generate, sample_logits

CFG = GPTConfig(block_size=32, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def test_prefill_matches_forward(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    full = GPT.apply(CFG, params, tokens, inference=True)
    cache = KVCache.init(CFG, 2, dtype=jnp.float32)
    logits, cache = GPT.prefill(CFG, params, tokens, cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full), atol=2e-5, rtol=2e-5)
    assert int(cache.length) == 16


def test_decode_step_matches_forward(params):
    """Prefill T tokens then decode 5 more one-by-one; logits at each new
    position must match a fresh full forward over the growing sequence."""
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (2, 10), 0, CFG.vocab_size)
    extra = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, CFG.vocab_size)

    cache = KVCache.init(CFG, 2, dtype=jnp.float32)
    _, cache = GPT.prefill(CFG, params, tokens, cache)

    seq = tokens
    for i in range(5):
        tok = extra[:, i]
        logits, cache = GPT.decode_step(CFG, params, tok, cache)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
        full = GPT.apply(CFG, params, seq, inference=True)[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), atol=3e-5, rtol=3e-5,
            err_msg=f"decode step {i}",
        )


def test_generate_greedy_matches_no_cache_loop(params):
    """Greedy generation with the cache == greedy windowed full-forward loop
    (the reference's scheme, reference sample.py:68-95)."""
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0, CFG.vocab_size)
    n_new = 12
    out = generate(CFG, params, prompt, n_new, temperature=0.0)

    seq = prompt
    for _ in range(n_new):
        logits = GPT.apply(CFG, params, seq[:, -CFG.block_size :], inference=True)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generate_past_block_size(params):
    """Generation must keep going past the cache/window capacity."""
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 30), 0, CFG.vocab_size)
    n_new = 10  # 30 + 10 > block_size=32 -> exercises the overflow path
    out = generate(CFG, params, prompt, n_new, temperature=0.0)
    assert out.shape == (1, 40)
    assert bool((out[:, :30] == prompt).all())


@pytest.mark.slow
def test_generate_overflow_compiles_once(params, monkeypatch):
    """Generation past the cache must not retrace per token OR per call:
    the overflow window is a static (B, S) slice served by the module-level
    `_window_forward` jit, so GPT.apply traces exactly ONCE across many
    overflow tokens and repeated generate() calls (the fast path's
    prefill/decode jits don't go through GPT.apply at all)."""
    from midgpt_tpu.sampling import engine

    jax.clear_caches()  # drop any _window_forward entry from earlier tests
    calls = {"n": 0}
    orig_apply = GPT.apply

    def counting_apply(*a, **k):
        calls["n"] += 1
        return orig_apply(*a, **k)

    monkeypatch.setattr(GPT, "apply", staticmethod(counting_apply))
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, CFG.vocab_size)
    out = engine.generate(CFG, params, prompt, 40, temperature=0.0)
    assert out.shape == (2, 48)  # 8 + 40 > S=32: 15+ overflow tokens
    out2 = engine.generate(CFG, params, prompt, 44, temperature=0.0)
    assert out2.shape == (2, 52)
    assert calls["n"] == 1, f"overflow forward traced {calls['n']} times"


def test_prefill_blockwise_arbitrary_length(params):
    """Prefill must handle prompt lengths that are not block multiples
    (regression: blockwise path used to require divisibility)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, attn_impl="blockwise", attn_block_size=16)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (1, 13), 0, CFG.vocab_size)
    full = GPT.apply(CFG, params, tokens, inference=True)
    logits, cache = GPT.prefill(cfg, params, tokens, KVCache.init(cfg, 1, jnp.float32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_generate_exact_fill_uses_cache(params):
    """Generation that exactly fills the context must stay on the cache path
    (regression: off-by-one guard dropped the last cache slot)."""
    prompt = jax.random.randint(jax.random.PRNGKey(6), (1, 8), 0, CFG.vocab_size)
    n_new = CFG.block_size - 8  # lands exactly on S
    out = generate(CFG, params, prompt, n_new, temperature=0.0)
    seq = prompt
    for _ in range(n_new):
        logits = GPT.apply(CFG, params, seq[:, -CFG.block_size :], inference=True)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_restore_for_sampling_sharded_over_virtual_mesh(params, tmp_path):
    """Mesh-aware sampling restore: the checkpoint loads straight into
    fsdp-sharded arrays on the 8-device virtual mesh (no single-device
    staging — how the 7B-class checkpoints must load), values match the
    saved params exactly, and greedy generation from the sharded restore
    reproduces the unsharded model's output."""
    import numpy as np

    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.sampling.engine import generate, restore_for_sampling
    from midgpt_tpu.training.checkpoint import CheckpointManager

    mngr = CheckpointManager(str(tmp_path), max_to_keep=1, save_interval_steps=1)
    mngr.save(0, {"params": params}, force=True)
    mngr.wait()
    mngr.close()

    cfg = ExperimentConfig(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8,
        warmup_steps=1, min_lr=1e-4, lr_decay_steps=10, max_steps=10,
        beta2=0.99, weight_decay=0.0, eval_interval=5, param_dtype="float32",
        compute_dtype="float32", g_accum_iters=1, shard_model=True,
        fsdp_min_size=0, model_config=CFG,
    )
    restored, step = restore_for_sampling(str(tmp_path), cfg)
    assert step == 0
    shard_specs = [str(l.sharding.spec) for l in jax.tree.leaves(restored)]
    assert any("fsdp" in s for s in shard_specs), shard_specs
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, CFG.vocab_size)
    out_sharded = generate(CFG, restored, prompt, 6, temperature=0.0)
    out_ref = generate(CFG, params, prompt, 6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out_sharded), np.asarray(out_ref))


def test_sample_logits_modes():
    logits = jnp.asarray([[0.0, 5.0, 1.0, -2.0]])
    key = jax.random.PRNGKey(0)
    assert int(sample_logits(logits, key, temperature=0.0)[0]) == 1
    # top_k=1 forces the argmax regardless of temperature
    assert int(sample_logits(logits, key, temperature=2.0, top_k=1)[0]) == 1
    # high temperature with full vocab still returns a valid index
    idx = int(sample_logits(logits, key, temperature=5.0)[0])
    assert 0 <= idx < 4


def test_top_p_nucleus():
    """top-p keeps the smallest prefix of descending-prob tokens reaching p:
    a tiny p degenerates to the argmax token; p=1.0 is a no-op filter."""
    from midgpt_tpu.sampling.engine import sample_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    # p below the top token's mass -> only token 0 survives, any key
    for seed in range(5):
        tok = sample_logits(logits, jax.random.PRNGKey(seed), 1.0, top_p=0.3)
        assert int(tok[0]) == 0
    # p covering the top two -> samples only from {0, 1}
    seen = set()
    for seed in range(20):
        tok = sample_logits(logits, jax.random.PRNGKey(seed), 1.0, top_p=0.75)
        seen.add(int(tok[0]))
    assert seen <= {0, 1} and 0 in seen
    # p=1.0 leaves the distribution untouched (same draws as unfiltered)
    for seed in range(5):
        a = sample_logits(logits, jax.random.PRNGKey(seed), 1.0, top_p=1.0)
        b = sample_logits(logits, jax.random.PRNGKey(seed), 1.0)
        assert int(a[0]) == int(b[0])


def test_decode_layer_scan_matches_unrolled(params):
    """GPTConfig.decode_layer_scan swaps the decode layer loop's lowering
    (Python-unrolled DUS chain vs rolled lax.scan — compile-time/copy
    trade-off documented on the config field); both must produce the same
    logits and cache."""
    import dataclasses

    cfg_scan = dataclasses.replace(CFG, decode_layer_scan=True)
    tokens = jax.random.randint(jax.random.PRNGKey(11), (2, 9), 0, CFG.vocab_size)
    extra = jax.random.randint(jax.random.PRNGKey(12), (2, 3), 0, CFG.vocab_size)

    caches = {}
    for name, cfg in (("unroll", CFG), ("scan", cfg_scan)):
        cache = KVCache.init(cfg, 2, dtype=jnp.float32)
        _, cache = GPT.prefill(cfg, params, tokens, cache)
        logits = []
        for i in range(3):
            l, cache = GPT.decode_step(cfg, params, extra[:, i], cache)
            logits.append(l)
        caches[name] = (jnp.stack(logits), cache)
    np.testing.assert_allclose(
        np.asarray(caches["scan"][0]), np.asarray(caches["unroll"][0]),
        atol=1e-6, rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(caches["scan"][1].k), np.asarray(caches["unroll"][1].k),
        atol=1e-6,
    )


def test_paged_decode_matches_contiguous_token_for_token(params):
    """ISSUE acceptance pin: greedy decode through the paged cache + page
    table samples the SAME tokens as the contiguous-cache engine, for a
    fixed seed, across chunked prefill and per-slot positions."""
    from midgpt_tpu.sampling.serve import ServeEngine

    prompt = jax.random.randint(jax.random.PRNGKey(13), (1, 19), 0, CFG.vocab_size)
    ref = generate(CFG, params, prompt, 10, temperature=0.0)

    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, prefill_chunk=8,
        decode_chunk=4, temperature=0.0, cache_dtype=jnp.float32,
    )
    uid = eng.submit(np.asarray(prompt[0]), 10)
    out = eng.run()[uid].tokens
    np.testing.assert_array_equal(out, np.asarray(ref[0]))


def test_serve_decode_chunk_has_no_in_loop_cache_copies():
    """The r5 structural pin, extended to the PAGED serve step (ISSUE
    acceptance): inside the compiled serve chunk's decode loop, no
    pool-sized copy may appear — the per-slot column writes must lower to
    in-place scatters aliasing through the loop carry. One-time entry
    copies outside the loop are allowed (same allowance as the contiguous
    pin below)."""
    import re

    from midgpt_tpu.models.gpt import PagedKVCache
    from midgpt_tpu.sampling import serve
    from midgpt_tpu.utils.hlo import hlo_computations, while_body_names

    cfg = GPTConfig(
        block_size=256, vocab_size=96, n_layer=4, n_head=2, n_embd=64
    )
    B, ps, n_pages = 4, 8, 40
    L, H, C = cfg.n_layer, cfg.n_head, cfg.head_dim
    max_pages = cfg.block_size // ps
    abstract = jax.eval_shape(lambda k: GPT.init(cfg, k), jax.random.PRNGKey(0))
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, jnp.bfloat16), abstract
    )
    cache = jax.eval_shape(
        lambda: PagedKVCache.init(cfg, num_pages=n_pages, page_size=ps)
    )
    tok = jax.ShapeDtypeStruct((B,), jnp.int32)
    pt = jax.ShapeDtypeStruct((B, max_pages), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    act = jax.ShapeDtypeStruct((B,), jnp.bool_)
    fn = jax.jit(
        lambda p, t, c, table, lens, a: serve._serve_decode_chunk(
            cfg, p, t, c, table, lens, a, 8, 0.0, None, None, "gather", None
        )
    )
    txt = fn.lower(abstract, tok, cache, pt, ln, act).compile().as_text()
    bodies = while_body_names(txt)
    shape = re.escape(f"bf16[{L},{H},{n_pages},{ps},{C}]")
    offenders = [
        (name, l)
        for name, lines in hlo_computations(txt).items()
        if name in bodies
        for l in lines
        if re.search(rf"= {shape}[^=]*copy\(", l)
    ]
    assert not offenders, (
        "pool-sized copies inside the serve decode loop body — the paged KV "
        f"cache no longer aliases through the carry: {offenders[:2]}"
    )


def test_decode_chunk_has_no_in_loop_cache_copies():
    """Structural pin of the r5 decode restructure: inside the chunked
    decode loop, NO full-cache-sized copy may appear — the per-token column
    writes must alias through the loop carry. The r1-r4 structure (cache as
    inner-scan xs + stacked ys) copied both (L, B, H, S, C) buffers every
    token (2.5 ms/token measured on v5e at 124M/B=8); a rolled inner layer
    scan still paid 2 copies/step at the carry boundary. One-time entry
    copies outside the loop are allowed."""
    import re

    from midgpt_tpu.sampling import engine
    from midgpt_tpu.utils.hlo import hlo_computations, while_body_names

    cfg = GPTConfig(
        block_size=256, vocab_size=96, n_layer=4, n_head=2, n_embd=64
    )
    B, L, H, S, C = 4, cfg.n_layer, cfg.n_head, cfg.block_size, cfg.head_dim
    abstract = jax.eval_shape(lambda k: GPT.init(cfg, k), jax.random.PRNGKey(0))
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, jnp.bfloat16), abstract
    )
    cache = jax.eval_shape(lambda: KVCache.init(cfg, B))
    tok = jax.ShapeDtypeStruct((B,), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    fn = jax.jit(
        lambda p, t, c, k: engine._decode_chunk(cfg, p, t, c, 1.0, 50, None, 8, k)
    )
    txt = fn.lower(abstract, tok, cache, key).compile().as_text()
    bodies = while_body_names(txt)
    shape = re.escape(f"bf16[{L},{B},{H},{S},{C}]")
    offenders = [
        (name, l)
        for name, lines in hlo_computations(txt).items()
        if name in bodies
        for l in lines
        if re.search(rf"= {shape}[^=]*copy\(", l)
    ]
    assert not offenders, (
        "full-cache copies inside the decode loop body — the KV cache no "
        f"longer aliases through the carry: {offenders[:2]}"
    )
