"""Numerical parity: explicit shard_map FSDP vs the implicit GSPMD path.

Same params, same batch → same loss and same gradients (fp32 tolerance) on
the 8-device CPU mesh. This is the acceptance test for the authored
per-layer all-gather / reduce-scatter schedule (parallel/shard_map_fsdp.py).
"""

import jax
import numpy as np

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain, fsdp_param_specs
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.parallel.shard_map_fsdp import make_shard_map_loss

CHUNK = 1 << 30  # no loss chunking: keeps the comparison single-variable


def _setup(dropout=0.0):
    cfg = GPTConfig(
        block_size=64,
        vocab_size=128,
        n_layer=2,
        n_head=2,
        n_embd=32,
        dropout=dropout,
        remat=True,
    )
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1))
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    params = jax.jit(lambda p: constrain(p, specs, mesh))(params)

    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))
    return cfg, mesh, params, specs, xg, yg


def test_loss_and_grads_match_gspmd():
    cfg, mesh, params, specs, xg, yg = _setup()

    def gspmd_loss(p, x, y):
        h = GPT.hidden(cfg, p, x, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, y, CHUNK)

    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK)

    ref_l, ref_g = jax.jit(jax.value_and_grad(gspmd_loss))(params, xg, yg)
    sm_l, sm_g = jax.jit(
        jax.value_and_grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)

    np.testing.assert_allclose(float(sm_l), float(ref_l), rtol=1e-6)
    for ref, got, path in zip(
        jax.tree.leaves(ref_g), jax.tree.leaves(sm_g), jax.tree.leaves(specs)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-4
        )


def test_grads_sharded_like_params():
    """Grads must come back in the FSDP layout (reduce-scattered, not dense)."""
    cfg, mesh, params, specs, xg, yg = _setup()
    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK)
    grads = jax.jit(
        jax.grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)
    flat_g, _ = jax.tree.flatten_with_path(grads)
    flat_p, _ = jax.tree.flatten_with_path(params)
    for (path, g), (_, p) in zip(flat_g, flat_p):
        assert g.sharding == p.sharding, f"{path}: {g.sharding} != {p.sharding}"


def test_train_step_e2e_shard_map():
    """One full training step with fsdp_mode='shard_map' runs and is finite."""
    from midgpt_tpu.training.train import init_state, make_train_step

    config = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=2, fsdp=4, sp=1),
        model_config=GPTConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=32
        ),
    )
    mesh = make_mesh(config.mesh)
    params, opt_state, specs, optimizer = init_state(config, mesh)
    step, *_ = make_train_step(config, optimizer, mesh, specs)

    rng = np.random.default_rng(1)
    G, B, T = config.g_accum_iters, config.batch_size, config.model_config.block_size
    x = rng.integers(0, config.model_config.vocab_size, (G, B, T), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec())
    yg = make_global_batch(y, mesh, batch_spec())
    params, opt_state, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_loss_and_grads_match_gspmd_with_ring():
    """The composition: explicit shard_map FSDP x ring sequence parallelism
    in ONE shard_map body (per-layer weight gathers on 'fsdp', K/V rotation
    on 'sp') against the dense unsharded oracle — loss AND grads."""
    import dataclasses

    cfg = GPTConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
        attn_impl="ring", remat=True,
    )
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sp=2))
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    params = jax.jit(lambda p: constrain(p, specs, mesh))(params)

    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False, shard_seq=True))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False, shard_seq=True))

    oracle_cfg = dataclasses.replace(cfg, attn_impl="naive")

    def gspmd_loss(p, x, y):
        h = GPT.hidden(oracle_cfg, p, x, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, y, CHUNK)

    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK, sequence_parallel="ring")

    ref_l, ref_g = jax.jit(jax.value_and_grad(gspmd_loss))(params, xg, yg)
    sm_l, sm_g = jax.jit(
        jax.value_and_grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)

    np.testing.assert_allclose(float(sm_l), float(ref_l), rtol=1e-6)
    for ref, got in zip(jax.tree.leaves(ref_g), jax.tree.leaves(sm_g)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-4
        )


def test_train_step_shard_map_ring_matches_gspmd_sp1():
    """One full training step: fsdp_mode='shard_map' + ring/sp=2 produces
    the same loss as the implicit-GSPMD naive sp=1 step on the same batch
    and seed — a third independently-authored parallelization schedule
    computing the same math."""
    import dataclasses

    base = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=2, fsdp=2, sp=2),
        model_config=GPTConfig(
            block_size=64, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            attn_impl="ring",
        ),
    )
    from midgpt_tpu.training.train import init_state, make_train_step

    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, (1, 8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)

    losses = {}
    for name, cfg in {
        "shard_map_ring": base,
        "gspmd_naive_sp1": base.replace(
            fsdp_mode="gspmd",
            mesh=MeshConfig(data=2, fsdp=4, sp=1),
            model_config=dataclasses.replace(base.model_config, attn_impl="naive"),
        ),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, *_ = make_train_step(cfg, optimizer, mesh, specs)
        sp = batch_spec(shard_seq=cfg.mesh.sp > 1)
        xg = make_global_batch(x, mesh, sp)
        yg = make_global_batch(y, mesh, sp)
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)

    assert np.isfinite(losses["shard_map_ring"])
    np.testing.assert_allclose(
        losses["shard_map_ring"], losses["gspmd_naive_sp1"], rtol=1e-5
    )
