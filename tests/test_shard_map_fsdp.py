"""Numerical parity: explicit shard_map FSDP vs the implicit GSPMD path.

Same params, same batch → same loss and same gradients (fp32 tolerance) on
the 8-device CPU mesh. This is the acceptance test for the authored
per-layer all-gather / reduce-scatter schedule (parallel/shard_map_fsdp.py).
"""

import jax
import numpy as np

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.loss import fused_linear_cross_entropy
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain, fsdp_param_specs
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.parallel.shard_map_fsdp import make_shard_map_loss

import pytest
# The tp>1 composition runs the shard_map body partial-manual (GSPMD 'auto'
# axes); on this container's old jax the XLA CPU backend aborts in a CHECK
# on that combination, so utils/compat.py refuses it up front — skip
# cleanly here (runs on TPU backends / newer jax).
_JAX = tuple(int(x) for x in jax.__version__.split(".")[:2])
requires_partial_manual_cpu = pytest.mark.skipif(
    _JAX < (0, 5) and jax.default_backend() == "cpu",
    reason=f"partial-manual shard_map aborts XLA CPU on jax {jax.__version__}",
)


CHUNK = 1 << 30  # no loss chunking: keeps the comparison single-variable


def _setup(dropout=0.0):
    cfg = GPTConfig(
        block_size=64,
        vocab_size=128,
        n_layer=2,
        n_head=2,
        n_embd=32,
        dropout=dropout,
        remat=True,
    )
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1))
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    params = jax.jit(lambda p: constrain(p, specs, mesh))(params)

    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False))
    return cfg, mesh, params, specs, xg, yg


def test_loss_and_grads_match_gspmd():
    cfg, mesh, params, specs, xg, yg = _setup()

    def gspmd_loss(p, x, y):
        h = GPT.hidden(cfg, p, x, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, y, CHUNK)

    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK)

    ref_l, ref_g = jax.jit(jax.value_and_grad(gspmd_loss))(params, xg, yg)
    sm_l, sm_g = jax.jit(
        jax.value_and_grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)

    np.testing.assert_allclose(float(sm_l), float(ref_l), rtol=1e-6)
    for ref, got, path in zip(
        jax.tree.leaves(ref_g), jax.tree.leaves(sm_g), jax.tree.leaves(specs)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-4
        )


def test_grads_sharded_like_params():
    """Grads must come back in the FSDP layout (reduce-scattered, not dense)."""
    cfg, mesh, params, specs, xg, yg = _setup()
    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK)
    grads = jax.jit(
        jax.grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)
    # tree_util spelling: jax.tree.flatten_with_path arrived later than
    # this container's jax; the tree_util alias exists in both.
    flat_g, _ = jax.tree_util.tree_flatten_with_path(grads)
    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    for (path, g), (_, p) in zip(flat_g, flat_p):
        assert g.sharding == p.sharding, f"{path}: {g.sharding} != {p.sharding}"


def test_train_step_e2e_shard_map():
    """One full training step with fsdp_mode='shard_map' runs and is finite."""
    from midgpt_tpu.training.train import init_state, make_train_step

    config = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=2, fsdp=4, sp=1),
        model_config=GPTConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=32
        ),
    )
    mesh = make_mesh(config.mesh)
    params, opt_state, specs, optimizer = init_state(config, mesh)
    step, *_ = make_train_step(config, optimizer, mesh, specs)

    rng = np.random.default_rng(1)
    G, B, T = config.g_accum_iters, config.batch_size, config.model_config.block_size
    x = rng.integers(0, config.model_config.vocab_size, (G, B, T), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec())
    yg = make_global_batch(y, mesh, batch_spec())
    params, opt_state, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


from midgpt_tpu.utils.hlo import (  # noqa: E402
    hlo_computations as _hlo_computations,
    in_shard_map_scope,
    is_forward_shmap_line,
)


def _fusion_calls_dot(line, comps, _seen=None):
    """Does this fusion instruction's called computation (transitively,
    through nested fusions/calls) contain a dot?"""
    import re

    _seen = _seen if _seen is not None else set()
    for callee in re.findall(r"calls=%([\w.\-]+)", line):
        if callee in _seen or callee not in comps:
            continue
        _seen.add(callee)
        for inner in comps[callee]:
            if " dot(" in inner:
                return True
            if "calls=%" in inner and _fusion_calls_dot(inner, comps, _seen):
                return True
    return False


def test_zero3_gathers_schedulable_ahead_of_compute():
    """Structural pin of the ZeRO-3 overlap claim (shard_map_fsdp.py header;
    VERDICT r4 weak #2): in the compiled layer-scan body at scan_unroll=2,
    EVERY weight all-gather's transitive operand chain is free of compute
    (dot, or fusion-calling-dot) from the same body. That is the dataflow
    property that lets XLA's latency-hiding scheduler issue the gather of
    layer l+1 during layer l's compute; if a refactor ever made the gathers
    depend on activations (serializing the stream), this fails. The actual
    async overlap (all-gather-start/-done split around compute) is a TPU
    scheduler behavior — asserted against the real backend by
    tools/check_overlap_tpu.py, whose measured result is recorded in
    RESULTS.md; the CPU backend emits synchronous all-gathers.

    Also pins that unroll=2 exposes BOTH layers' gathers in one body (the
    precondition for cross-layer overlap): 2 layers x 6 block leaves = 12."""
    import re

    from midgpt_tpu.utils.hlo import lower_abstract_train_step

    config = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        eval_interval=5,
        beta2=0.95,
        weight_decay=1e-4,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=1, fsdp=8, sp=1),
        model_config=GPTConfig(
            block_size=64, vocab_size=64, n_layer=4, n_head=2, n_embd=64,
            scan_unroll=2,
        ),
    )
    txt = lower_abstract_train_step(config).compile().as_text()

    comps = _hlo_computations(txt)
    # Computations containing shard_map weight gathers next to compute: the
    # forward layer-scan body (jvp) and the backward one (transpose(jvp),
    # ZeRO-3 re-gather under remat). XLA may fully unroll the short forward
    # scan into its caller on some backends/versions — the gathers keep
    # their shard_map provenance metadata either way, so match on that
    # rather than on living inside a while body.
    bodies = {
        name: lines
        for name, lines in comps.items()
        if any(" all-gather(" in l and in_shard_map_scope(l) for l in lines)
        and any(" dot(" in l for l in lines)
    }
    assert bodies, "no computation with shard_map all-gathers found — did lowering change?"

    fwd_counts = []
    for name, lines in bodies.items():
        defs = {}
        for line in lines:
            m = re.match(r"^(?:ROOT\s+)?%([\w.\-]+)\s*=", line)
            if not m:
                continue
            iname = m.group(1)
            deps = [r for r in re.findall(r"%([\w.\-]+)", line) if r != iname]
            defs[iname] = (line, deps)
        gathers = [n for n, (l, _) in defs.items() if " all-gather(" in l]
        n_fwd = sum(
            1
            for n, (l, _) in defs.items()
            if " all-gather(" in l and is_forward_shmap_line(l)
        )
        if n_fwd:
            fwd_counts.append(n_fwd)
        for g in gathers:
            seen, stack = set(), list(defs[g][1])
            while stack:
                d = stack.pop()
                if d in seen or d not in defs:
                    continue
                seen.add(d)
                line, deps = defs[d]
                assert " dot(" not in line, (
                    f"{name}: gather %{g} depends on compute %{d} — the "
                    "ZeRO-3 weight stream is serialized behind layer compute"
                )
                assert not (" fusion(" in line and _fusion_calls_dot(line, comps)), (
                    f"{name}: gather %{g} depends on dot-fusion %{d}"
                )
                stack.extend(deps)
    # Both unrolled layers' gathers live in one forward body: 2 x 6 leaves.
    assert any(c >= 12 for c in fwd_counts), (
        f"forward body gather counts {fwd_counts} — expected >= 12 "
        "(scan_unroll=2 no longer exposes both layers' gathers in one body)"
    )


@requires_partial_manual_cpu
def test_train_step_shard_map_tp_matches_gspmd():
    """r5: the explicit ZeRO-3 body composes with Megatron tp — 'tp' rides
    a GSPMD auto axis inside the shard_map (parallel/shard_map_fsdp.py)
    while the authored per-layer gathers stay on 'fsdp'. One full train
    step on a (data=2, fsdp=2, tp=2) mesh matches BOTH the GSPMD tp step
    and the fsdp-only oracle on the same batch/seed."""
    from midgpt_tpu.training.train import init_state, make_train_step

    base = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        mesh=MeshConfig(data=2, fsdp=2, sp=1, tp=2),
        model_config=GPTConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=32
        ),
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (1, 8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    for name, cfg in {
        "shard_map_tp": base.replace(fsdp_mode="shard_map"),
        "gspmd_tp": base,
        "fsdp_only": base.replace(mesh=MeshConfig(data=2, fsdp=4, sp=1)),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, *_ = make_train_step(cfg, optimizer, mesh, specs)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["shard_map_tp"], losses["gspmd_tp"], rtol=1e-5)
    np.testing.assert_allclose(losses["shard_map_tp"], losses["fsdp_only"], rtol=1e-5)


def test_loss_and_grads_match_gspmd_with_ring():
    """The composition: explicit shard_map FSDP x ring sequence parallelism
    in ONE shard_map body (per-layer weight gathers on 'fsdp', K/V rotation
    on 'sp') against the dense unsharded oracle — loss AND grads."""
    import dataclasses

    cfg = GPTConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
        attn_impl="ring", remat=True,
    )
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sp=2))
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    params = jax.jit(lambda p: constrain(p, specs, mesh))(params)

    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    xg = make_global_batch(x, mesh, batch_spec(with_accum=False, shard_seq=True))
    yg = make_global_batch(y, mesh, batch_spec(with_accum=False, shard_seq=True))

    oracle_cfg = dataclasses.replace(cfg, attn_impl="naive")

    def gspmd_loss(p, x, y):
        h = GPT.hidden(oracle_cfg, p, x, inference=True)
        return fused_linear_cross_entropy(h, p.lm_head, y, CHUNK)

    sm_loss = make_shard_map_loss(cfg, mesh, specs, CHUNK, sequence_parallel="ring")

    ref_l, ref_g = jax.jit(jax.value_and_grad(gspmd_loss))(params, xg, yg)
    sm_l, sm_g = jax.jit(
        jax.value_and_grad(lambda p, x, y: sm_loss(p, x, y, None))
    )(params, xg, yg)

    np.testing.assert_allclose(float(sm_l), float(ref_l), rtol=1e-6)
    for ref, got in zip(jax.tree.leaves(ref_g), jax.tree.leaves(sm_g)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-4
        )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_train_step_shard_map_ring_matches_gspmd_sp1():
    """One full training step: fsdp_mode='shard_map' + ring/sp=2 produces
    the same loss as the implicit-GSPMD naive sp=1 step on the same batch
    and seed — a third independently-authored parallelization schedule
    computing the same math."""
    import dataclasses

    base = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=2,
        min_lr=1e-4,
        lr_decay_steps=10,
        max_steps=10,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=5,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        fsdp_mode="shard_map",
        mesh=MeshConfig(data=2, fsdp=2, sp=2),
        model_config=GPTConfig(
            block_size=64, vocab_size=128, n_layer=2, n_head=2, n_embd=32,
            attn_impl="ring",
        ),
    )
    from midgpt_tpu.training.train import init_state, make_train_step

    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, (1, 8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)

    losses = {}
    for name, cfg in {
        "shard_map_ring": base,
        "gspmd_naive_sp1": base.replace(
            fsdp_mode="gspmd",
            mesh=MeshConfig(data=2, fsdp=4, sp=1),
            model_config=dataclasses.replace(base.model_config, attn_impl="naive"),
        ),
    }.items():
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, *_ = make_train_step(cfg, optimizer, mesh, specs)
        sp = batch_spec(shard_seq=cfg.mesh.sp > 1)
        xg = make_global_batch(x, mesh, sp)
        yg = make_global_batch(y, mesh, sp)
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)

    assert np.isfinite(losses["shard_map_ring"])
    np.testing.assert_allclose(
        losses["shard_map_ring"], losses["gspmd_naive_sp1"], rtol=1e-5
    )
