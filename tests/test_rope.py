"""RoPE properties. The shift-equivariance test promotes the reference's
manual eyeball script (reference scripts/test_rotary.py:11-32) into a real
assertion: rolling Q and K by s positions must roll the attention scores."""

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.ops.rope import apply_rope, rope_table, rotate_interleaved


def test_rotate_interleaved_pattern():
    x = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    np.testing.assert_allclose(np.asarray(rotate_interleaved(x)), [[-2.0, 1.0, -4.0, 3.0]])


def test_rope_preserves_norm():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 4, 32, 16))
    sin, cos = rope_table(16, 32)
    out = apply_rope(x, sin, cos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )


def test_rope_shift_equivariance():
    """scores(rope(q), rope(k)) shifted == scores(rope(roll(q)), rope(roll(k)))."""
    key = jax.random.PRNGKey(1)
    kq, kk = jax.random.split(key)
    H, T, C, s = 2, 64, 16, 5
    q = jax.random.normal(kq, (H, T, C))
    k = jax.random.normal(kk, (H, T, C))
    sin, cos = rope_table(C, T)

    def scores(q, k):
        qr = apply_rope(q, sin, cos)
        kr = apply_rope(k, sin, cos)
        return jnp.einsum("hqc,hkc->hqk", qr, kr)

    base = scores(q, k)
    rolled = scores(jnp.roll(q, s, axis=1), jnp.roll(k, s, axis=1))
    # Valid region: both query and key indices >= s after the roll.
    np.testing.assert_allclose(
        np.asarray(rolled[:, s:, s:]), np.asarray(base[:, :-s, :-s]), atol=1e-4
    )


def test_rope_positions_gather():
    """Explicit positions must equal the contiguous-prefix default."""
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (1, 8, 16))
    sin, cos = rope_table(16, 32)
    out_default = apply_rope(x, sin, cos)
    out_positions = apply_rope(x, sin, cos, positions=jnp.arange(8))
    np.testing.assert_allclose(np.asarray(out_default), np.asarray(out_positions), atol=1e-6)
    # A single token at absolute position p == slicing it out of a longer pass.
    p = 5
    single = apply_rope(x[:, p : p + 1], sin, cos, positions=jnp.array([p]))
    np.testing.assert_allclose(np.asarray(single), np.asarray(out_default[:, p : p + 1]), atol=1e-6)
