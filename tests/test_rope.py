"""RoPE properties. The shift-equivariance test promotes the reference's
manual eyeball script (reference scripts/test_rotary.py:11-32) into a real
assertion: rolling Q and K by s positions must roll the attention scores."""

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.ops.rope import apply_rope, rope_table, rotate_interleaved


def test_rotate_interleaved_pattern():
    x = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    np.testing.assert_allclose(np.asarray(rotate_interleaved(x)), [[-2.0, 1.0, -4.0, 3.0]])


def test_rope_preserves_norm():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 4, 32, 16))
    sin, cos = rope_table(16, 32)
    out = apply_rope(x, sin, cos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )


def test_rope_shift_equivariance():
    """scores(rope(q), rope(k)) shifted == scores(rope(roll(q)), rope(roll(k)))."""
    key = jax.random.PRNGKey(1)
    kq, kk = jax.random.split(key)
    H, T, C, s = 2, 64, 16, 5
    q = jax.random.normal(kq, (H, T, C))
    k = jax.random.normal(kk, (H, T, C))
    sin, cos = rope_table(C, T)

    def scores(q, k):
        qr = apply_rope(q, sin, cos)
        kr = apply_rope(k, sin, cos)
        return jnp.einsum("hqc,hkc->hqk", qr, kr)

    base = scores(q, k)
    rolled = scores(jnp.roll(q, s, axis=1), jnp.roll(k, s, axis=1))
    # Valid region: both query and key indices >= s after the roll.
    np.testing.assert_allclose(
        np.asarray(rolled[:, s:, s:]), np.asarray(base[:, :-s, :-s]), atol=1e-4
    )


def test_split_style_is_permutation_conjugate():
    """The split lowering computes the SAME rotation as the reference's
    interleaved form after the C axis is permuted by `split_permutation` —
    the op-level exactness behind rope_style='split' (models/gpt.py applies
    the permutation to the q/k projection rows, so QK^T is unchanged)."""
    from midgpt_tpu.ops.rope import apply_rope_bthc, split_permutation

    key = jax.random.PRNGKey(3)
    B, T, H, C = 2, 16, 3, 32
    x = jax.random.normal(key, (B, T, H, C))
    sin, cos = rope_table(C, T)
    perm = split_permutation(C)
    ref = apply_rope_bthc(x, sin, cos, style="interleaved")
    got = apply_rope_bthc(x[..., perm], sin, cos, style="split")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref[..., perm]), atol=1e-6
    )
    # and scores are invariant: q.k == q[perm].k[perm]
    q, k = x, jnp.roll(x, 1, axis=0)
    s_ref = jnp.einsum(
        "bthc,bshc->bhts",
        apply_rope_bthc(q, sin, cos),
        apply_rope_bthc(k, sin, cos),
    )
    s_split = jnp.einsum(
        "bthc,bshc->bhts",
        apply_rope_bthc(q[..., perm], sin, cos, style="split"),
        apply_rope_bthc(k[..., perm], sin, cos, style="split"),
    )
    np.testing.assert_allclose(np.asarray(s_split), np.asarray(s_ref), atol=1e-5)


def test_rope_per_slot_positions():
    """apply_rope_positions ((B, T) per-token absolute positions — the
    continuous-batching decode path, where B slots sit at B different
    write positions) must be bit-identical to apply_rope_bthc run per-row
    at that row's position, in both rotation styles."""
    from midgpt_tpu.ops.rope import apply_rope_bthc, apply_rope_positions

    key = jax.random.PRNGKey(4)
    B, T, H, C = 3, 2, 2, 16
    x = jax.random.normal(key, (B, T, H, C))
    sin, cos = rope_table(C, 64)
    positions = jnp.asarray([[0, 1], [17, 18], [40, 41]])
    for style in ("interleaved", "split"):
        got = apply_rope_positions(x, sin, cos, positions, style=style)
        for b in range(B):
            want = apply_rope_bthc(
                x[b : b + 1], sin, cos, positions=positions[b], style=style
            )
            np.testing.assert_array_equal(np.asarray(got[b]), np.asarray(want[0]))


def test_rope_positions_gather():
    """Explicit positions must equal the contiguous-prefix default."""
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (1, 8, 16))
    sin, cos = rope_table(16, 32)
    out_default = apply_rope(x, sin, cos)
    out_positions = apply_rope(x, sin, cos, positions=jnp.arange(8))
    np.testing.assert_allclose(np.asarray(out_default), np.asarray(out_positions), atol=1e-6)
    # A single token at absolute position p == slicing it out of a longer pass.
    p = 5
    single = apply_rope(x[:, p : p + 1], sin, cos, positions=jnp.array([p]))
    np.testing.assert_allclose(np.asarray(single), np.asarray(out_default[:, p : p + 1]), atol=1e-6)
