"""The driver contract, executed: bench.py and tools/bench_serve.py must
emit exactly ONE schema-conformant JSON line on stdout. Runs the real
entry-point main()s in-process (tiny shapes, CPU mesh) and validates their
stdout through the shared checker in analysis/bench_contract.py — the one
place the contract is written down, so a silently renamed field or a stray
print fails here instead of in the driver."""

import json
import os
import runpy
import sys

import pytest

from midgpt_tpu.analysis.bench_contract import (
    check_bench_stdout,
    check_graftcheck,
    check_serve_bench,
    check_serve_fleet_bench,
    check_serve_gqa_bench,
    check_serve_longctx_bench,
    check_serve_ops_bench,
    check_serve_prefix_bench,
    check_serve_slo_bench,
    check_serve_tp_bench,
    check_train_bench,
    check_train_chaos,
    parse_single_json_line,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_entry_point(path, argv, capsys):
    """Load a script module and run its main() with patched argv, returning
    captured stdout (run_name != '__main__' so nothing auto-executes)."""
    mod = runpy.run_path(path, run_name="bench_under_test")
    old_argv = sys.argv
    sys.argv = argv
    try:
        rc = mod["main"]()
    finally:
        sys.argv = old_argv
    assert rc == 0
    return capsys.readouterr().out


@pytest.mark.slow
def test_bench_serve_emits_conformant_json_line(capsys):
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--n-requests", "3",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve")
    assert not problems, problems
    assert rec["n_requests"] == 3
    assert rec["continuous_tok_s"] > 0 and rec["sequential_tok_s"] > 0
    # the counter hooks ride along: serving compiled a bounded program set
    assert rec["compile_counts"]["decode"] >= 1
    assert rec["compile_counts"]["prefill"] >= 1


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_bench_serve_spec_emits_conformant_json_line(capsys):
    """--spec mode: the serve_spec profile (speculative vs plain continuous
    engine) must hold the one-JSON-line contract too. Tiny shapes, 2 quick
    train steps — structure check, not a perf claim."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--spec",
            "--n-requests", "2",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
            "--spec-draft-layers", "1",
            "--spec-k", "4",
            "--train-steps", "2",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_spec")
    assert not problems, problems
    assert rec["draft_layers"] == 1 and rec["spec_k_max"] == 4
    assert rec["baseline_tok_s"] > 0 and rec["spec_tok_s"] > 0
    assert 0.0 <= rec["accept_rate"] <= 1.0
    assert rec["tokens_per_verify"] >= 1.0
    assert rec["compile_counts"]["spec_draft"] >= 1
    assert rec["compile_counts"]["spec_verify"] >= 1
    # prefix self-draft: speculation must not cost extra cache HBM
    assert rec["hbm_draft_cache_bytes"] == 0


def test_bench_serve_prefix_emits_conformant_json_line(capsys):
    """--shared-prefix-frac mode: the serve_prefix profile (prefix cache
    on vs off over a template-heavy workload) must hold the one-JSON-line
    contract, report exact greedy parity, and never prefill MORE with the
    cache on. Tiny shapes — structure check, not a perf claim."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--shared-prefix-frac", "0.8",
            "--n-requests", "6",
            "--template-tokens", "24",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_prefix")
    assert not problems, problems
    assert rec["greedy_match_frac"] == 1.0
    assert 0.0 < rec["prefix_hit_rate"] <= 1.0
    assert rec["prefix_prefill_tokens"] <= rec["baseline_prefill_tokens"]
    # checker drift behavior on the real record: inexact parity and a
    # prefill regression are contract violations, not numbers
    assert any(
        "greedy_match_frac" in p
        for p in check_serve_prefix_bench(dict(rec, greedy_match_frac=0.99))
    )
    assert any(
        "prefill" in p
        for p in check_serve_prefix_bench(
            dict(rec, prefix_prefill_tokens=rec["baseline_prefill_tokens"] + 1)
        )
    )


@pytest.mark.slow
def test_bench_serve_tp_emits_conformant_json_line(capsys):
    """--tp mode: the serve_tp profile (single-chip vs tensor-parallel
    engine per cache mode) must hold the one-JSON-line contract with every
    match_* EXACTLY 1.0 and the per-shard HBM arithmetic exact. Tiny
    shapes + few quick-train steps — structure check, not a perf claim.
    Default (17-page) pool geometry: disjoint from the 25/27/31-page
    geometries the recompile pins count from a pristine baseline."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--tp", "2",
            "--n-requests", "4",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
            "--train-steps", "8",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_tp")
    assert not problems, problems
    assert rec["match_f32"] == rec["match_int8"] == rec["match_spec"] == 1.0
    assert rec["mesh"] == {"data": 1, "tp": 2}
    assert rec["cache_hbm_bytes_per_shard"] * 2 == rec["cache_hbm_bytes"]
    # checker drift behavior on the real record: inexact parity and broken
    # shard arithmetic are contract violations, not numbers
    assert any(
        "match_int8" in p
        for p in check_serve_tp_bench(dict(rec, match_int8=0.998))
    )
    assert any(
        "per-shard" in p
        for p in check_serve_tp_bench(
            dict(rec, cache_hbm_bytes_per_shard=rec["cache_hbm_bytes"])
        )
    )


@pytest.mark.slow
def test_bench_serve_longctx_emits_conformant_json_line(capsys):
    """--long-ctx mode: the serve_longctx profile (split-K decode A/B at a
    long and a short context) must hold the one-JSON-line contract with
    EXACT greedy parity, the auto bucket rule engaged at t_long and
    resolving to the unsplit program at t_short. Small t_long=1024 point
    (the smallest the profile admits), tiny model, 2 quick-train steps —
    structure check, not a latency claim."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--long-ctx",
            "--t-long", "1024",
            "--t-short", "64",
            "--rounds", "2",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "32",
            "--decode-chunk", "4",
            "--train-steps", "2",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_longctx")
    assert not problems, problems
    assert rec["greedy_match_frac"] == 1.0
    assert rec["split_k_long"] == 2  # the 1024-token bucket
    assert rec["split_k_short"] == 1  # auto: short traffic stays unsplit
    assert rec["ms_round_long_split"] > 0 and rec["ms_round_long_unsplit"] > 0
    # checker drift behavior on the real record: inexact parity, a split
    # bucket leaking into short traffic, a vacuous (unsplit or short-T)
    # long arm, and a dead timing are contract violations, not numbers
    assert any(
        "greedy_match_frac" in p
        for p in check_serve_longctx_bench(dict(rec, greedy_match_frac=0.99))
    )
    assert any(
        "split_k_short" in p
        for p in check_serve_longctx_bench(dict(rec, split_k_short=2))
    )
    assert any(
        "split_k_long" in p
        for p in check_serve_longctx_bench(dict(rec, split_k_long=1))
    )
    assert any(
        "t_long" in p for p in check_serve_longctx_bench(dict(rec, t_long=512))
    )
    assert any(
        "ms_round_long_split" in p
        for p in check_serve_longctx_bench(dict(rec, ms_round_long_split=0.0))
    )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_bench_serve_gqa_emits_conformant_json_line(capsys):
    """--gqa mode: the serve_gqa profile (GQA vs MHA KV-capacity A/B at a
    fixed pool byte budget, docs/SERVING.md 'Attention variants') must hold
    the one-JSON-line contract: G-fold page capacity from the same bytes,
    strictly fewer preemptions on an oversubscribed trace, and EXACT greedy
    parity on both arms. Tiny model — structure check, not a perf claim."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--gqa", "4",
            "--n-requests", "8",
            "--block-size", "128",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "4",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_gqa")
    assert not problems, problems
    assert rec["kv_groups"] == 4 and rec["n_kv_heads"] == 1
    # same bytes, 4x smaller pages -> ~4x pages (max(2,...) rounding aside)
    assert rec["gqa_page_bytes"] * 4 == rec["mha_page_bytes"]
    assert rec["pages_ratio"] >= 3.0
    assert rec["mha_preemptions"] > rec["gqa_preemptions"]
    assert rec["greedy_match_frac_mha"] == 1.0
    assert rec["greedy_match_frac_gqa"] == 1.0


def test_serve_gqa_checker_catches_drift():
    """The serve_gqa gates hold on a synthetic record without running the
    bench: the capacity conversion, the oversubscription requirement, and
    exact two-sided parity are contract, not numbers."""
    good = {
        "bench": "serve_gqa", "backend": "cpu", "n_requests": 8,
        "total_new_tokens": 96, "max_slots": 4, "page_size": 8,
        "kv_dtype": "bf16", "pool_hbm_bytes": 100000, "model": {},
        "kv_groups": 4, "n_kv_heads": 1, "sliding_window": 0,
        "attn_sinks": 0, "mha_page_bytes": 4096, "gqa_page_bytes": 1024,
        "mha_num_pages": 24, "gqa_num_pages": 96, "pages_ratio": 4.0,
        "mha_slots_capacity": 3, "gqa_slots_capacity": 12,
        "mha_preemptions": 16, "gqa_preemptions": 0,
        "mha_tok_s": 100.0, "gqa_tok_s": 220.0,
        "window_reclaimed_pages": 0,
        "greedy_match_frac_mha": 1.0, "greedy_match_frac_gqa": 1.0,
        "mha_cache_hbm_bytes": 98304, "gqa_cache_hbm_bytes": 98304,
        "compile_counts": {},
    }
    assert check_serve_gqa_bench(good) == []
    # an MHA-vs-MHA "A/B" is vacuous
    assert any("kv_groups" in p
               for p in check_serve_gqa_bench(dict(good, kv_groups=1)))
    # the byte budget must convert into KV-head-scaled page capacity
    assert any("pages_ratio" in p
               for p in check_serve_gqa_bench(dict(good, pages_ratio=2.0)))
    # a trace the MHA pool absorbs proves nothing about capacity
    assert any(
        "mha_preemptions" in p
        for p in check_serve_gqa_bench(
            dict(good, mha_preemptions=0, gqa_preemptions=0)
        )
    )
    # the extra pages must buy strictly fewer preemptions
    assert any("gqa_preemptions" in p
               for p in check_serve_gqa_bench(dict(good, gqa_preemptions=16)))
    # parity is exact on BOTH arms — 0.9999 is a kernel bug, not noise
    assert any(
        "greedy_match_frac_mha" in p
        for p in check_serve_gqa_bench(dict(good, greedy_match_frac_mha=0.9999))
    )
    assert any(
        "greedy_match_frac_gqa" in p
        for p in check_serve_gqa_bench(dict(good, greedy_match_frac_gqa=0.9999))
    )
    missing = dict(good)
    missing.pop("window_reclaimed_pages")
    assert any("window_reclaimed_pages" in p
               for p in check_serve_gqa_bench(missing))


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_bench_serve_ops_emits_conformant_json_line(capsys):
    """--hot-swap mode: the serve_ops profile (verified-checkpoint
    blue/green swap mid-trace + live pool grow) must hold the one-JSON-
    line contract with zero dropped streams, a zero swap-window jit-cache
    delta, both parity sides non-empty and summing to n_requests, and a
    non-vacuous migration. Tiny shapes — structure check; the full-size
    run is the driver's serve_ops gate (docs/ROBUSTNESS.md)."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--hot-swap",
            "--n-requests", "8",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_ops")
    assert not problems, problems
    assert rec["dropped"] == 0 and rec["swap_recompiles"] == 0
    assert rec["parity_old_side"] >= 1 and rec["parity_new_side"] >= 1
    assert rec["parity_old_side"] + rec["parity_new_side"] == 8
    assert rec["weights_version_after"].startswith(
        f"{rec['checkpoint_step']}:"
    )
    assert rec["pages_migrated"] >= 1 and rec["pages_conserved"] is True
    # checker drift behavior on the real record: a dropped stream, a swap
    # recompile, a vacuous parity side, and an unchanged version are each
    # contract violations, not numbers
    assert any("dropped" in p for p in check_serve_ops_bench(dict(rec, dropped=1)))
    assert any(
        "swap_recompiles" in p
        for p in check_serve_ops_bench(dict(rec, swap_recompiles=2))
    )
    assert any(
        "parity" in p
        for p in check_serve_ops_bench(
            dict(rec, parity_old_side=0,
                 parity_new_side=rec["n_requests"])
        )
    )
    assert any(
        "weights_version" in p
        for p in check_serve_ops_bench(
            dict(rec, weights_version_after=rec["weights_version_before"])
        )
    )
    assert any(
        "pages_migrated" in p
        for p in check_serve_ops_bench(dict(rec, pages_migrated=0))
    )


@pytest.mark.slow
def test_bench_serve_fleet_emits_conformant_json_line(capsys):
    """--fleet mode: the serve_fleet profile (single engine vs a crashed-
    replica fleet over the same template trace, with the shared mid-trace
    trie flush exercising the spill tier) must hold the one-JSON-line
    contract: a replica actually died, zero streams dropped, every stream
    bit-matched the single-engine pass, and affinity + spill re-adoption
    kept the fleet trie hit rate >= the single engine's. Tiny shapes —
    structure check; docs/ROBUSTNESS.md 'Fleet serving & failover'."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--fleet", "2",
            "--n-requests", "10",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_fleet")
    assert not problems, problems
    assert rec["fleet_size"] == 2 and rec["alive"] == 1
    assert rec["failovers"] >= 1 and rec["dropped"] == 0
    assert rec["greedy_match_frac"] == 1.0
    assert rec["parity_checked"] == 10
    assert rec["fleet_hit_rate"] >= rec["single_hit_rate"]
    assert rec["spill_readopted_pages"] >= 1  # the flush spilled, half 2 re-adopted
    assert rec["spill"]["total_spilled"] >= 1
    # checker drift behavior on the real record: an unfaulted fleet, a
    # dropped stream, inexact parity, and a diluted trie are each
    # contract violations, not numbers
    assert any("failovers" in p
               for p in check_serve_fleet_bench(dict(rec, failovers=0)))
    assert any("dropped" in p
               for p in check_serve_fleet_bench(dict(rec, dropped=1)))
    assert any(
        "greedy_match_frac" in p
        for p in check_serve_fleet_bench(dict(rec, greedy_match_frac=0.99))
    )
    assert any(
        "hit_rate" in p
        for p in check_serve_fleet_bench(
            dict(rec, fleet_hit_rate=rec["single_hit_rate"] / 2 - 0.01)
        )
    )


@pytest.mark.slow
def test_bench_serve_proc_fleet_emits_conformant_json_line(capsys):
    """--fleet --procs: the serve_fleet line from a cross-process fleet
    (worker processes behind the socket transport, a real kill -9
    mid-trace — docs/ROBUSTNESS.md 'Cross-process fleet') must conform,
    carry the transport claim, and hold zero-drop + exact parity across
    the process boundary. Tiny shapes — structure check."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "bench_serve.py"),
        [
            "bench_serve.py",
            "--fleet", "2",
            "--procs",
            "--n-requests", "10",
            "--block-size", "64",
            "--vocab-size", "96",
            "--n-layer", "2",
            "--n-head", "2",
            "--n-embd", "32",
            "--prefill-chunk", "16",
            "--decode-chunk", "4",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_fleet")
    assert not problems, problems
    assert rec["procs"] is True
    assert rec["fleet_size"] == 2 and rec["alive"] == 1
    assert rec["proc_failovers"] >= 1 and rec["failovers"] >= 1
    assert rec["dropped"] == 0
    assert rec["greedy_match_frac"] == 1.0
    assert rec["parity_checked"] == 10
    assert rec["wire_bytes"] >= 1
    assert rec["transport"]["rpc_count"] >= 1
    assert rec["router_compiles_delta"] == 0


@pytest.mark.slow
def test_loadgen_hot_swap_surfaces_version_transition(capsys):
    """tools/loadgen.py --hot-swap: the serve_slo line still conforms, a
    swap lands at every point, the headline carries the version
    transition, and the SLO acceptance (zero shed through the swap on an
    unbounded backlog) holds with no special-casing."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "loadgen.py"),
        [
            "loadgen.py",
            "--rates", "30,90",
            "--n-requests", "4",
            "--hot-swap",
            "--seed", "0",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_slo")
    assert not problems, problems
    assert rec["hot_swaps"] == 2  # one flip per point
    assert rec["weights_versions"][0] == "inline"
    assert rec["weights_versions"][1].startswith("3:")
    for p in rec["points"]:
        assert p["hot_swaps"] == 1
        assert p["weights_version"] == rec["weights_versions"][1]
        assert p["shed"] == 0 and p["completed"] == p["n_offered"]
    assert rec["slo_ok"] is True


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_loadgen_prefix_cache_emits_hit_rate(capsys):
    """tools/loadgen.py --prefix-cache: the serve_slo line still conforms
    and carries per-point + headline prefix_hit_rate fields."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "loadgen.py"),
        [
            "loadgen.py",
            "--rates", "30,90",
            "--n-requests", "4",
            "--template-frac", "0.75",
            "--prefix-cache",
            "--seed", "0",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_slo")
    assert not problems, problems
    assert rec["prefix_cache"] is True
    for p in rec["points"]:
        assert 0.0 <= p["prefix_hit_rate"] <= 1.0
    assert 0.0 <= rec["prefix_hit_rate"] <= 1.0


def test_loadgen_fleet_emits_fleet_headline(capsys):
    """tools/loadgen.py --fleet: the serve_slo line still conforms and
    every point plus the headline carries the fleet availability fields
    (fleet_size / failovers / spill_hits / fleet-wide prefix_hit_rate) —
    the serve_slo checker validates their types and ranges whenever
    fleet_size is present."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "loadgen.py"),
        [
            "loadgen.py",
            "--rates", "30,90",
            "--n-requests", "4",
            "--fleet", "2",
            "--template-frac", "0.75",
            "--seed", "0",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_slo")
    assert not problems, problems
    assert rec["prefix_cache"] is True  # --fleet implies the trie
    assert rec["fleet_size"] == 2
    assert rec["failovers"] >= 0 and rec["spill_hits"] >= 0
    for p in rec["points"]:
        assert p["fleet_size"] == 2
        assert p["failovers"] >= 0 and p["spill_hits"] >= 0
        assert 0.0 <= p["prefix_hit_rate"] <= 1.0
        assert p["shed"] == 0 and p["completed"] == p["n_offered"]
    # fleet-field drift is a contract violation once fleet_size appears
    bad = dict(rec, failovers="1")
    assert any("failovers" in p for p in check_serve_slo_bench(bad))


def test_loadgen_long_mixture_emits_conformant_serve_slo_line(capsys):
    """tools/loadgen.py --long-frac: the long-prompt/long-output mixture
    keeps the serve_slo line conformant and records the mixture knob. The
    pool default stays the auto rule's 27-page geometry below the
    long-context regime, so this composes with every other loadgen pin."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "loadgen.py"),
        [
            "loadgen.py",
            "--rates", "30,90",
            "--n-requests", "4",
            "--long-frac", "0.5",
            "--seed", "0",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_slo")
    assert not problems, problems
    assert rec["long_frac"] == 0.5
    assert rec["points"][0]["completed"] >= 1


def test_loadgen_emits_conformant_serve_slo_line(capsys):
    """tools/loadgen.py (SLO load harness) holds the one-JSON-line
    contract: a short seeded-arrival Poisson run against the CPU-mesh
    engine at TWO offered-load points, validated by the serve_slo profile.
    Structure check, not a latency claim — arrivals are deterministic
    (seeded), wall-clock percentiles are whatever the host gives."""
    out = _run_entry_point(
        os.path.join(REPO, "tools", "loadgen.py"),
        [
            "loadgen.py",
            "--rates", "30,90",
            "--n-requests", "4",
            "--seed", "0",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "serve_slo")
    assert not problems, problems
    assert rec["process"] == "poisson" and rec["scheduler"] == "fcfs"
    assert len(rec["points"]) == 2
    assert [p["offered_rps"] for p in rec["points"]] == [30.0, 90.0]
    for p in rec["points"]:
        assert p["n_offered"] == 4
        assert p["completed"] + p["shed"] + p["timeouts"] <= p["n_offered"]
        assert 0.0 <= p["shed_frac"] <= 1.0
    assert isinstance(rec["slo_ok"], bool)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_bench_train_emits_conformant_json_line(capsys):
    out = _run_entry_point(
        os.path.join(REPO, "bench.py"),
        [
            "bench.py",
            "--steps", "1",
            "--warmup", "1",
            "--batch", "1",
            "--layers", "1",
            "--seq", "64",
            "--vocab", "256",
            "--attn", "naive",
        ],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "train")
    assert not problems, problems
    assert rec["metric"].startswith("train_mfu_124m_naive")
    assert rec["detail"]["seq_len"] == 64 and rec["detail"]["n_devices"] == 8


def test_graftcheck_cli_emits_conformant_json_line(capsys, tmp_path):
    """tools/graftcheck.py --json through the SAME in-process harness as
    the benches: its line must satisfy the graftcheck profile, including
    the pass-3/pass-4 stats fields and the jit-surface census count."""
    p = tmp_path / "clean.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x + 1\n")
    out = _run_entry_point(
        os.path.join(REPO, "tools", "graftcheck.py"),
        ["graftcheck.py", "--json", str(p)],
        capsys,
    )
    rec, problems = check_bench_stdout(out, "graftcheck")
    assert not problems, problems
    assert rec["tool"] == "graftcheck"
    assert rec["count"] == 0 and rec["files_scanned"] == 1
    assert rec["pass3_count"] == 0 and rec["pass3_wall_ms"] >= 0
    assert rec["pass4_count"] == 0 and rec["pass4_wall_ms"] >= 0
    assert rec["jit_surface_count"] == 1  # the @jax.jit wrapper above


# ----------------------------------------------------------------------
# checker unit behavior (no bench run needed)
# ----------------------------------------------------------------------


def test_checker_rejects_multiline_and_nonjson():
    rec, problems = parse_single_json_line('{"a": 1}\nextra line\n')
    assert any("exactly 1" in p for p in problems)
    rec, problems = parse_single_json_line("not json at all\n")
    assert rec is None and any("not valid JSON" in p for p in problems)


def test_checker_rejects_nan():
    """json.dumps happily emits bare NaN — which no strict consumer parses.
    The checker must treat it as a contract violation, not a number."""
    line = json.dumps({"metric": "m", "value": float("nan")}) + "\n"
    rec, problems = parse_single_json_line(line)
    assert rec is None and any("NaN" in p or "non-finite" in p for p in problems)


def test_graftcheck_checker_catches_pass4_field_drift():
    """The graftcheck profile holds on a synthetic record without running
    the CLI: dropping or mistyping any pass-4 / jit-surface stat field is
    a contract violation, not a number."""
    good = {
        "tool": "graftcheck", "count": 0, "suppressed": 0,
        "files_scanned": 1, "findings": [],
        "pass3_count": 0, "pass3_suppressed": 0, "pass3_wall_ms": 1.0,
        "pass4_count": 0, "pass4_suppressed": 0, "pass4_wall_ms": 1.0,
        "jit_surface_count": 3,
    }
    assert check_graftcheck(good) == []
    for field in (
        "pass4_count",
        "pass4_suppressed",
        "pass4_wall_ms",
        "jit_surface_count",
    ):
        missing = dict(good)
        missing.pop(field)
        assert any(field in p for p in check_graftcheck(missing)), field
    wrong_type = dict(good, pass4_count="0")
    assert any("pass4_count" in p for p in check_graftcheck(wrong_type))
    assert any(
        "jit_surface_count" in p
        for p in check_graftcheck(dict(good, jit_surface_count=2.5))
    )


def test_checker_catches_field_drift():
    good = {
        "metric": "train_mfu",
        "value": 48.5,
        "unit": "% MFU",
        "vs_baseline": 1.01,
        "detail": {"tokens_per_sec": 1.0, "step_ms": 2.0, "n_devices": 1},
    }
    assert check_train_bench(good) == []
    renamed = dict(good)
    renamed["vs_base"] = renamed.pop("vs_baseline")
    assert any("vs_baseline" in p for p in check_train_bench(renamed))
    wrong_type = dict(good, value="48.5")
    assert any("value" in p for p in check_train_bench(wrong_type))
    assert any(
        "bench" in p for p in check_serve_bench({"bench": "other"})
    )


def test_serve_fleet_checker_catches_drift():
    """The serve_fleet gates hold on a synthetic record without running
    the bench: structural availability claims (a replica died, zero
    drops, exact parity, undiluted trie) are contract, not numbers."""
    good = {
        "bench": "serve_fleet", "backend": "cpu", "n_requests": 12,
        "total_new_tokens": 120, "fleet_size": 2, "model": {},
        "kv_dtype": "bf16", "num_pages": 41, "n_templates": 2,
        "single_tok_s": 100.0, "fleet_tok_s": 90.0,
        "single_hit_rate": 0.2, "fleet_hit_rate": 0.6,
        "failovers": 1, "failed_over_streams": 2, "dropped": 0,
        "parity_checked": 12, "greedy_match_frac": 1.0,
        "spill_readopted_pages": 10, "spill": {}, "compile_counts": {},
        "pages_conserved": True,
    }
    assert check_serve_fleet_bench(good) == []
    assert any("fleet_size" in p
               for p in check_serve_fleet_bench(dict(good, fleet_size=1)))
    assert any("failovers" in p
               for p in check_serve_fleet_bench(dict(good, failovers=0)))
    assert any("dropped" in p
               for p in check_serve_fleet_bench(dict(good, dropped=1)))
    assert any(
        "greedy_match_frac" in p
        for p in check_serve_fleet_bench(dict(good, greedy_match_frac=0.9999))
    )
    assert any(
        "parity_checked" in p
        for p in check_serve_fleet_bench(dict(good, parity_checked=11))
    )
    assert any(
        "hit_rate" in p
        for p in check_serve_fleet_bench(dict(good, fleet_hit_rate=0.1))
    )
    assert any(
        "pages_conserved" in p
        for p in check_serve_fleet_bench(dict(good, pages_conserved="yes"))
    )
    # cross-process variant (bench_serve --fleet --procs): the hit-rate
    # ordering is waived — a SIGKILLed worker takes its host-RAM tier
    # with it, so the survivor honestly re-prefills — but the transport
    # claim becomes required (docs/ROBUSTNESS.md "Cross-process fleet")
    procs = dict(
        good, procs=True, fleet_hit_rate=0.1,
        proc_failovers=1, worker_pids=[11, 12], transport={},
        rpc_p50_ms=0.5, rpc_p95_ms=20.0, wire_bytes=4096,
    )
    assert check_serve_fleet_bench(procs) == []
    assert any(
        "proc_failovers" in p
        for p in check_serve_fleet_bench(dict(procs, proc_failovers=0))
    )
    assert any(
        "wire_bytes" in p
        for p in check_serve_fleet_bench(dict(procs, wire_bytes=0))
    )
    no_rpc = dict(procs)
    no_rpc.pop("rpc_p50_ms")
    assert any("rpc_p50_ms" in p for p in check_serve_fleet_bench(no_rpc))
    # the waiver is procs-only: the same diluted trie still fails in-proc
    assert any(
        "hit_rate" in p
        for p in check_serve_fleet_bench(dict(procs, procs=False))
    )


def test_serve_slo_checker_catches_drift():
    decomp = {"p50": 1.0, "p95": 2.0}
    point = {
        "offered_rps": 30.0, "n_offered": 4, "completed": 4, "shed": 0,
        "timeouts": 0, "shed_frac": 0.0, "timeout_frac": 0.0,
        "ttft_p50_ms": 5.0, "ttft_p95_ms": 9.0, "tpot_p50_ms": 1.0,
        "tpot_p95_ms": 2.0, "rounds": 8,
        "round_host_ms": dict(decomp), "round_device_ms": dict(decomp),
        "overlap_hidden_ms": dict(decomp), "overlap_mode": "off",
        "round_group": 1,
    }
    good = {
        "bench": "serve_slo", "backend": "cpu", "process": "poisson",
        "scheduler": "fcfs", "seed": 0, "n_requests": 4,
        "error_budget": 0.2, "model": {}, "slo_ok": True,
        "points": [point, dict(point, offered_rps=90.0)],
        "ttft_p50_ms": 5.0, "ttft_p95_ms": 9.0, "tpot_p50_ms": 1.0,
        "tpot_p95_ms": 2.0, "shed_frac": 0.0, "timeout_frac": 0.0,
        "round_host_ms": dict(decomp), "round_device_ms": dict(decomp),
        "overlap_hidden_ms": dict(decomp), "overlap_mode": "off",
        "round_group": 1,
    }
    assert check_serve_slo_bench(good) == []
    # round-overlap drift (docs/SERVING.md "Round-overlap dispatch"): a
    # bad mode name fails, and round_group != 1 demands mode == "group"
    assert any("overlap_mode" in p
               for p in check_serve_slo_bench(dict(good, overlap_mode="on")))
    assert any("round_group" in p
               for p in check_serve_slo_bench(dict(good, round_group=2)))
    # round-decomposition drift (docs/OBSERVABILITY.md): a missing or
    # malformed host/device object fails, as does a negative quantile
    no_decomp = dict(good, round_host_ms=None)
    assert any("round_host_ms" in p for p in check_serve_slo_bench(no_decomp))
    neg = dict(good, round_device_ms={"p50": -1.0, "p95": 2.0})
    assert any("round_device_ms.p50" in p for p in check_serve_slo_bench(neg))
    # one load point is a measurement, not the SLO curve the profile wants
    one_point = dict(good, points=[point])
    assert any(">= 2" in p for p in check_serve_slo_bench(one_point))
    # a renamed per-point percentile field fails with the point index
    bad_point = dict(point)
    bad_point["ttft95_ms"] = bad_point.pop("ttft_p95_ms")
    drifted = dict(good, points=[point, bad_point])
    assert any("points[1]" in p and "ttft_p95_ms" in p
               for p in check_serve_slo_bench(drifted))
    # shed_frac outside [0, 1] is a contract violation, not a number
    assert any("outside" in p
               for p in check_serve_slo_bench(dict(good, shed_frac=1.5)))
    # cross-process fleet (loadgen --fleet --procs): the transport
    # headline must be present and sane when procs is true
    procs = dict(
        good, procs=True, fleet_size=2, failovers=0, spill_hits=0,
        prefix_hit_rate=0.0, rpc_p50_ms=0.5, rpc_p95_ms=9.0,
        wire_bytes=1024,
    )
    assert check_serve_slo_bench(procs) == []
    assert any("wire_bytes" in p
               for p in check_serve_slo_bench(dict(procs, wire_bytes=0)))
    assert any("rpc_p95_ms" in p
               for p in check_serve_slo_bench(dict(procs, rpc_p95_ms=-1.0)))
    assert any("fleet_size" in p
               for p in check_serve_slo_bench(dict(procs, fleet_size=None)))


def test_train_chaos_checker_catches_drift():
    """The train_chaos gates hold on a synthetic record without running
    the chaos bench: the recovery claims (a fault FIRED, detection was
    timestamped, the recovered trajectory matches the unfaulted reference,
    the finishing mesh is named) are contract, not numbers."""
    good = {
        "tool": "chaos_run", "config": "shakespeare_char", "rundir": "/r",
        "status": "ok", "wall_s": 10.5,
        "faults_requested": ["resume_reshard@6"],
        "faults_fired": {"resume_reshard": 1},
        "supervisor": {"restarts": 0, "hung_steps": []},
        "loss_final": 4.5, "preempted": False, "bench": "train_chaos",
        "detected_at_ms": 5001.7, "restarts": 1,
        "final_mesh": {"n_devices": 4, "axes": {"data": 1, "fsdp": 4}},
        "n_devices_final": 4, "loss_ref": 4.5, "loss_parity": True,
    }
    assert check_train_chaos(good) == []
    assert any("loss_parity" in p
               for p in check_train_chaos(dict(good, loss_parity=False)))
    missing = dict(good)
    missing.pop("detected_at_ms")
    assert any("detected_at_ms" in p for p in check_train_chaos(missing))
    assert any("faults_fired" in p
               for p in check_train_chaos(dict(good, faults_fired={})))
    assert any("status" in p
               for p in check_train_chaos(dict(good, status="failed")))
    assert any("bench" in p
               for p in check_train_chaos(dict(good, bench="train")))
    assert any(
        "n_devices" in p
        for p in check_train_chaos(
            dict(good, final_mesh={"n_devices": 0, "axes": {"data": 1}})
        )
    )
    assert any(
        "axes" in p
        for p in check_train_chaos(
            dict(good, final_mesh={"n_devices": 4, "axes": {}})
        )
    )
    assert any("restarts" in p
               for p in check_train_chaos(dict(good, restarts=-1)))


@pytest.mark.slow
def test_chaos_run_train_cli_emits_conformant_train_chaos_line(
    capsys, tmp_path
):
    """`chaos_run.py --fault resume_reshard@6` (train mode) holds the
    one-JSON-line driver contract end to end: the fault ends attempt one
    like a preemption, the driver restarts on HALF the devices with
    on_resume_mesh='any', the run completes on the 4-device mesh, and the
    summary passes the train_chaos profile. Step logs and supervisor
    prints go to stderr — stdout is the summary line, full stop."""
    import numpy as np

    from midgpt_tpu.robustness import faults, preempt

    data = tmp_path / "data"
    data.mkdir()
    stream = (np.arange(20000) % 17).astype(np.uint16)
    stream.tofile(data / "train.bin")
    stream[:4000].tofile(data / "val.bin")

    mod = runpy.run_path(
        os.path.join(REPO, "tools", "chaos_run.py"), run_name="chaos_under_test"
    )
    argv, sys.argv = sys.argv, [
        "chaos_run.py", "--config=shakespeare_char",
        f"--rundir={tmp_path / 'run'}",
        "--fault", "resume_reshard@6",
        "--set", "max_steps=16", "--set", "eval_interval=8",
        "--set", "eval_steps=2", "--set", "batch_size=8",
        "--set", "log_interval=4",
        "--set", "model_config.n_layer=1", "--set", "model_config.n_head=2",
        "--set", "model_config.n_embd=32",
        "--set", "model_config.block_size=32",
        "--set", "model_config.vocab_size=96",
        f"--set", f"data_dir={data}",
        "--set", "mesh.data=2", "--set", "mesh.fsdp=4",
        "--set", "param_dtype=float32", "--set", "compute_dtype=float32",
        "--set", "restart_backoff_sec=0.0",
    ]
    try:
        rc = mod["main"]()
    finally:
        sys.argv = argv
        faults.clear()
        preempt.reset()
    assert rc == 0
    out = capsys.readouterr().out
    rec, problems = check_bench_stdout(out, "train_chaos")
    assert not problems, problems
    assert rec["faults_fired"] == {"resume_reshard": 1}
    # the topology actually changed hands: started on 8, finished on 4
    assert rec["final_mesh"]["n_devices"] == 4
    assert rec["restarts"] >= 1
    assert rec["loss_parity"] is True
    history = rec["supervisor"]["mesh_history"]
    assert [m["n_devices"] for m in history] == [8, 4]
    json.loads(out)  # strict JSON round-trip (no NaN etc.)


def test_bench_probe_unreachable_backend_emits_error_json(
    capsys, monkeypatch
):
    """bench.py with a wedged backend emits ONE machine-readable
    {'error': 'backend_unreachable'} line within the probe budget and
    exits nonzero — instead of hanging until the driver's timeout with
    an empty stdout. The dead tunnel is modeled in-process via the
    hang_step fault hook the probe honors."""
    from midgpt_tpu.robustness import faults

    monkeypatch.setenv("MIDGPT_FAULTS", "hang_step")
    mod = runpy.run_path(
        os.path.join(REPO, "bench.py"), run_name="bench_under_test"
    )
    argv, sys.argv = sys.argv, ["bench.py", "--probe-deadline", "0.3"]
    try:
        rc = mod["main"]()
    finally:
        sys.argv = argv
        faults.clear()
    assert rc == 1  # NOT the _run_entry_point helper: failure IS the pin
    out = capsys.readouterr().out
    rec, problems = parse_single_json_line(out)
    assert not problems, problems
    assert rec["error"] == "backend_unreachable"
    assert rec["metric"] == "train_mfu" and rec["value"] is None
    assert rec["detail"]["probe_deadline_s"] == 0.3
    json.loads(out)
