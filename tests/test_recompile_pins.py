"""graftcheck pass-2 recompile pins: the compile-behavior claims of PR 1's
serving engine and the training step, held by counter instead of comment.

* ServeEngine (SERVING.md): page tables / lengths / active masks are plain
  jit inputs and chunk shapes are padded/pow2-bucketed, so a CHANGING
  REQUEST MIX never recompiles — the decode program compiles exactly once,
  prefill once per pow2 page bucket, and replaying three further distinct
  mixes compiles nothing at all.
* Train step (training/train.py): the whole step is ONE XLA program; three
  steps, one compile.
* The compiled artifacts themselves: no all-gathers in the decode while
  body, fp32 master params + bf16 compute in the lowered train step
  (SURVEY.md §7.4) — via analysis.hlo_audit.run_audit, the same suite
  `python -m midgpt_tpu.analysis --audit` runs.

Mix design (why these exact numbers pin "exactly one decode program"):
decode_chunk=8 and every request's max_new_tokens ≡ 1 (mod 8) — the first
generated token is sampled host-side at end of prefill, so the decode-side
remainder is a multiple of 8 and every decode round runs a full chunk
(n_steps=8); prompts are 25..47 tokens with prompt+max_new <= block_size=64,
so the pow2 page bucket is pinned at the 8-page cap from the first decode
round and the pool (24 allocatable pages) never forces an eviction. Any
scheduler change that starts re-bucketing or splitting chunks shows up here
as a compile-count bump.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import CompileCounter, jit_cache_size, run_audit
from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
from midgpt_tpu.sampling.serve import (
    ServeEngine,
    _serve_decode_chunk,
    _serve_prefill_chunk,
)
from midgpt_tpu.training.train import init_state, make_train_step

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _serve_mix(params, lengths, max_new, seed):
    eng = ServeEngine(
        CFG,
        params,
        max_slots=3,
        page_size=8,
        num_pages=25,  # full working set fits: no eviction churn in the pin
        prefill_chunk=16,
        decode_chunk=8,
        temperature=0.0,
        cache_dtype=jnp.float32,
    )
    rng = np.random.default_rng(seed)
    uids = {
        eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m): (n, m)
        for n, m in zip(lengths, max_new)
    }
    done = eng.run()
    assert set(done) == set(uids)
    for uid, (n, m) in uids.items():
        assert len(done[uid].tokens) == n + m
    return eng


def test_serve_mixes_exactly_one_decode_compile(params):
    """The acceptance pin: >= 3 distinct request mixes, 1 decode-program
    compile total — and zero compiles of any kind after the first mix."""
    d0 = jit_cache_size(_serve_decode_chunk)
    p0 = jit_cache_size(_serve_prefill_chunk)
    eng = _serve_mix(params, (25, 34, 47), (9, 17, 17), seed=0)
    d1 = jit_cache_size(_serve_decode_chunk)
    assert d1 - d0 == 1, "decode must be ONE program (fixed n_steps x bucket)"
    # prefill compiles once per pow2 page bucket the mix touches: {2, 4, 8}
    assert jit_cache_size(_serve_prefill_chunk) - p0 == 3
    stats = eng.compile_stats()
    assert stats["decode"] == d1 and stats["prefill"] == p0 + 3

    with CompileCounter() as cc:
        _serve_mix(params, (26, 33, 40), (9, 17, 9), seed=1)
        _serve_mix(params, (29, 41, 45), (17, 9, 17), seed=2)
        _serve_mix(params, (31, 38, 47), (17, 17, 9), seed=3)
    assert cc.count == 0, f"request-mix change recompiled {cc.count} program(s)"
    assert jit_cache_size(_serve_decode_chunk) == d1


def test_spec_mixes_one_draft_and_verify_program_per_k_bucket(params):
    """Satellite pin: across 4 request mixes with varying acceptance
    patterns (different seeds — acceptance is DATA, so it must never be a
    compile key), the engine compiles exactly one draft program and one
    verify program per k-bucket. Mix design mirrors the decode pin above:
    prompts 31..47 pin the page bucket at the 8-page cap from the first
    speculative round even at k=1 (length + k + 1 >= 33), prompt + max_new
    <= 60 keeps capacity from ever clamping k, and the 25-page pool never
    evicts. k is pinned per engine (spec_adapt=False, k_min=k_max) the way
    decode lengths are pow2-bucketed."""
    from midgpt_tpu.sampling.serve import _spec_draft_chunk, _spec_verify_chunk
    from midgpt_tpu.sampling.spec import self_draft

    dcfg, dparams = self_draft(CFG, params, 1)

    def spec_mix(k, seed, lengths=(31, 38, 45), max_new=(13, 9, 15)):
        eng = ServeEngine(
            CFG,
            params,
            max_slots=3,
            page_size=8,
            num_pages=25,
            prefill_chunk=16,
            temperature=0.0,
            cache_dtype=jnp.float32,
            draft_params=dparams,
            draft_config=dcfg,
            draft_shares_cache=True,
            spec_k_max=k,
            spec_k_min=k,
            spec_adapt=False,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip(lengths, max_new)
        }
        done = eng.run()
        assert set(done) == uids
        return eng

    d0 = jit_cache_size(_spec_draft_chunk)
    v0 = jit_cache_size(_spec_verify_chunk)
    spec_mix(4, seed=0)  # k-bucket 4, acceptance pattern A
    spec_mix(4, seed=1, lengths=(33, 40, 47), max_new=(9, 11, 13))  # pattern B
    assert jit_cache_size(_spec_draft_chunk) - d0 == 1, "draft: one program per k"
    assert jit_cache_size(_spec_verify_chunk) - v0 == 1, "verify: one program per k"
    spec_mix(1, seed=2)  # second k-bucket
    assert jit_cache_size(_spec_draft_chunk) - d0 == 2
    assert jit_cache_size(_spec_verify_chunk) - v0 == 2
    with CompileCounter() as cc:
        spec_mix(4, seed=3, lengths=(32, 39, 46), max_new=(11, 13, 9))
    assert cc.count == 0, f"4th mix recompiled {cc.count} program(s)"
    stats = ServeEngine.compile_stats()
    assert stats["spec_draft"] == jit_cache_size(_spec_draft_chunk)
    assert stats["spec_verify"] == jit_cache_size(_spec_verify_chunk)


@pytest.mark.slow  # heavy long-tail (~10 s of int8 compiles): full suite
# only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_int8_cache_is_a_program_key_but_compiles_once_per_bucket(params):
    """Satellite pin (int8 KV-cache PR): the cache dtype IS part of the
    program key — the int8 pool's avals (s8 pages + f32 scale leaves)
    lower distinct decode/draft/verify programs from bf16's — but each
    dtype still compiles exactly one decode program and one draft+verify
    program per k-bucket, and a second int8 mix with a different request
    pattern compiles NOTHING. Mix design mirrors the plain pins above
    (non-evicting 25-page pool, pow2-pinned buckets)."""
    from midgpt_tpu.sampling.serve import _spec_draft_chunk, _spec_verify_chunk
    from midgpt_tpu.sampling.spec import self_draft

    def int8_mix(lengths, max_new, seed, spec=False):
        kw = {}
        if spec:
            dcfg, dparams = self_draft(CFG, params, 1)
            kw = dict(
                draft_params=dparams, draft_config=dcfg,
                draft_shares_cache=True, spec_k_max=4, spec_k_min=4,
                spec_adapt=False,
            )
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=25,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype="int8", **kw,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip(lengths, max_new)
        }
        assert set(eng.run()) == uids

    d0 = jit_cache_size(_serve_decode_chunk)
    sd0 = jit_cache_size(_spec_draft_chunk)
    sv0 = jit_cache_size(_spec_verify_chunk)
    int8_mix((25, 34, 47), (9, 17, 17), seed=0)
    assert jit_cache_size(_serve_decode_chunk) - d0 == 1, (
        "int8 decode must be ONE new program"
    )
    int8_mix((31, 38, 45), (13, 9, 15), seed=1, spec=True)
    assert jit_cache_size(_spec_draft_chunk) - sd0 == 1
    assert jit_cache_size(_spec_verify_chunk) - sv0 == 1
    with CompileCounter() as cc:
        int8_mix((26, 33, 40), (9, 17, 9), seed=2)
        int8_mix((33, 40, 47), (9, 11, 13), seed=3, spec=True)
    assert cc.count == 0, f"int8 request-mix change recompiled {cc.count}"


def test_prefix_cache_compiles_zero_new_programs(params):
    """Tentpole pin (prefix-cache PR): cross-request sharing is page-table
    indirection over existing jit inputs, so a cache-ON engine serving
    hit, miss, and COW-duplicate admissions compiles NOTHING a cache-off
    engine at the same geometry didn't already compile. Warm-then-count on
    a non-25-page pool so this pin composes with the pristine-baseline
    pins above. Mix design: all prompts 28 tokens / budget 9 so both modes
    touch the same pow2 page buckets (a trie-matched admission can only
    SKIP early prefill buckets, never reach a new one)."""

    def mix(prefix, seed):
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=31,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=prefix,
        )
        rng = np.random.default_rng(seed)
        head = rng.integers(0, CFG.vocab_size, 24).astype(np.int32)
        tails = [rng.integers(0, CFG.vocab_size, 4).astype(np.int32)
                 for _ in range(2)]
        prompts = [np.concatenate([head, t]) for t in tails]
        prompts.append(rng.integers(0, CFG.vocab_size, 28).astype(np.int32))
        uids = [eng.submit(p, 9) for p in prompts]
        assert set(eng.run()) == set(uids)
        # second wave against a warm trie: template hit, exact-duplicate
        # COW truncation, and a plain unique miss
        uids += [eng.submit(p, 9) for p in (prompts[0], prompts[2])]
        assert set(eng.run()) == set(uids)
        if prefix:
            assert eng.prefix_stats()["hit_rate"] > 0.0
            assert eng.cow_pages >= 1, "the duplicate must take the COW path"
        return eng

    mix(False, seed=0)  # warm every program this geometry/mix reaches
    with CompileCounter() as cc:
        mix(True, seed=0)  # same trace, cache on: hits + COW + misses
        mix(True, seed=1)  # fresh content, cold trie again
    assert cc.count == 0, f"prefix cache compiled {cc.count} new program(s)"


def test_obs_toggle_compiles_zero_new_programs(params):
    """Tentpole pin (observability PR): the flight recorder is host-side
    only — clock reads and ring appends around the jit calls, never
    through them — so an obs-ON engine compiles NOTHING an obs-off engine
    at the same geometry didn't already compile, and no span/metric state
    ever becomes a jit static. Warm-then-count on the 31-page pool so this
    pin composes with the pristine-baseline pins above."""
    from midgpt_tpu.obs import Observability

    def mix(obs, seed):
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=31,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, obs=obs,
        )
        rng = np.random.default_rng(seed)
        uids = [
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip((25, 34, 47), (9, 17, 17))
        ]
        assert set(eng.run()) == set(uids)
        return eng

    mix(None, seed=0)  # warm every program this geometry/mix reaches
    with CompileCounter() as cc:
        eng = mix(Observability(), seed=0)  # same mix, recorder on
        mix(Observability(), seed=1)  # fresh content, same buckets
    assert cc.count == 0, f"obs toggle compiled {cc.count} new program(s)"
    assert eng.stats()["obs"]["round_decomp"]["rounds"] > 0


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_hot_swap_and_ops_ticks_compile_zero_new_programs(params):
    """Tentpole pin (model-ops PR): a same-shape blue/green hot-swap is a
    pointer flip — the candidate params are device_put onto the LIVE
    params' shardings and params are traced args of every serving jit, so
    the swap compiles NOTHING; obs-on ModelOps controller ticks are pure
    host reads (allocator counters, backlog arithmetic) and also compile
    nothing. Warm-then-count on a fresh 51-page pool: the identical
    two-wave schedule runs once swap-free to warm every program this
    geometry reaches, then twice with the swap and the controller live
    under a CompileCounter. All three submissions land in slots before
    the swap stages, so the admission pause cannot alter the schedule."""
    from midgpt_tpu.obs import Observability
    from midgpt_tpu.sampling.ops import ModelOps

    # COMMITTED initial params (like a restored engine's): the staged
    # candidate is device_put onto the live shardings, and a committed
    # vs uncommitted input is a distinct executable key — an engine
    # born from uncommitted arrays would recompile once on the first
    # swap for that reason alone, not because of the swap protocol.
    params_a = jax.device_put(params, jax.devices()[0])
    params_b = GPT.init(CFG, jax.random.PRNGKey(7))

    def mix(swap, seed):
        eng = ServeEngine(
            CFG, params_a, max_slots=3, page_size=8, num_pages=51,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, obs=Observability(),
        )
        mops = ModelOps(eng, clock=lambda: 0.0, apply=False)
        rng = np.random.default_rng(seed)
        for wave in range(2):
            uids = {
                eng.submit(
                    rng.integers(0, CFG.vocab_size, n).astype(np.int32), m
                )
                for n, m in zip((25, 34, 47), (9, 17, 17))
            }
            for _ in range(3):
                eng.step()
            mops.tick()  # advisory mid-wave tick: host-only
            if swap:
                eng.hot_swap(params_b, version=f"v{wave}")
            done = eng.run()  # drains the wave; a staged swap flips here
            assert uids <= set(done)
        mops.tick()
        return eng

    mix(False, seed=0)  # warm every program this geometry/schedule reaches
    d0 = jit_cache_size(_serve_decode_chunk)
    p0 = jit_cache_size(_serve_prefill_chunk)
    eng = mix(True, seed=0)  # same trace, swap + controller live: the
    # SERVING programs must not grow (params are traced args; the swap's
    # per-leaf-shape transfer helpers warm here like any host glue)
    assert eng.hot_swaps == 2, "both staged swaps must have flipped"
    assert jit_cache_size(_serve_decode_chunk) == d0, (
        "a same-shape hot-swap recompiled the decode program"
    )
    assert jit_cache_size(_serve_prefill_chunk) == p0, (
        "a same-shape hot-swap recompiled a prefill bucket"
    )
    with CompileCounter() as cc:
        mix(True, seed=1)  # full replay, swap + ticks included
    assert cc.count == 0, f"hot-swap/ops ticks compiled {cc.count} program(s)"


@pytest.mark.slow  # heavy long-tail (~9 s, two fresh pool geometries):
# full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_resize_compiles_bounded_then_zero_on_replay(params):
    """Satellite pin (model-ops PR): a live pool resize may compile only
    the migration's pow2-bucketed gather/scatter programs and the
    destination geometry's fresh-pool fills — a constant, not a function
    of the resident count — and an identical resize schedule replayed on
    a fresh engine compiles NOTHING at all (both geometries, the
    migration, and the post-resize serving all replay from cache).
    Geometries 57 -> 71 are this pin's own (program-shape keys)."""

    def mix(seed, counter=None):
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=57,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip((25, 34, 47), (9, 17, 17))
        }
        for _ in range(3):
            eng.step()
        if counter is not None:
            with counter:
                rec = eng.resize(71)
        else:
            rec = eng.resize(71)
        assert rec["pages_migrated"] >= 1
        assert set(eng.run()) == uids
        return eng

    resize_cc = CompileCounter()
    mix(seed=0, counter=resize_cc)  # warm pass; count the resize alone
    # one gather + one adoption scatter + the new pool's zero-fills per
    # pool (f32: no scale leaves) — the sink-padded pow2 bucket keeps the
    # gather/scatter shapes off the resident count, so this is a small
    # constant, not O(pages)
    assert 0 < resize_cc.count <= 10, (
        f"resize compiled {resize_cc.count} programs — the migration must "
        "stay a bounded set of bucket-shaped gathers/scatters"
    )
    with CompileCounter() as cc:
        mix(seed=1)
    assert cc.count == 0, f"resize replay compiled {cc.count} program(s)"


@pytest.mark.slow  # heavy long-tail (~10 s, cold geometry-61 compiles):
# full suite only; the audit-suite group census stays tier-1
def test_overlap_modes_compile_one_group_program_per_bucket(params):
    """Tentpole pin (round-overlap PR): the fused group program compiles
    exactly once per (geometry, round_group bucket) — round_group is a
    pow2-bucketed static (`_round_group_bucket`), so group:3 reuses
    group:2's program — and flipping the overlap mode off<->double<->group
    on warm programs compiles NOTHING: overlap is host-side dispatch
    restructuring over the same jit inputs. Geometry 61 is this pin's own
    fresh pool (tests/test_overlap.py warms 39; the baselines above own
    25/31/51/57/71)."""
    from midgpt_tpu.sampling.serve import _serve_decode_group

    def mix(overlap, round_group, seed):
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=61,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, overlap=overlap,
            round_group=round_group,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip((25, 34, 47), (9, 17, 17))
        }
        assert set(eng.run()) == uids
        return eng

    mix("off", 1, seed=0)  # warm prefill buckets + the classic decode
    g0 = jit_cache_size(_serve_decode_group)
    mix("double", 1, seed=1)
    g1 = jit_cache_size(_serve_decode_group)
    assert g1 - g0 == 1, "double-buffering must be ONE group program (k=1)"
    mix("group", 2, seed=2)
    g2 = jit_cache_size(_serve_decode_group)
    assert g2 - g1 == 1, "group:2 must be ONE more program (k-bucket 2)"
    eng = mix("group", 3, seed=3)  # 3 buckets down to 2: same program
    assert eng.round_group == 2
    assert jit_cache_size(_serve_decode_group) == g2, (
        "round_group=3 must bucket to the k=2 program, not compile a third"
    )
    with CompileCounter() as cc:
        mix("off", 1, seed=4)
        mix("double", 1, seed=5)
        mix("group", 2, seed=6)
    assert cc.count == 0, f"overlap mode flip compiled {cc.count} program(s)"
    assert ServeEngine.compile_stats()["decode_group"] == g2


def test_train_step_compiles_exactly_once():
    cfg = ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=60,
        max_steps=60,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=30,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        mesh=MeshConfig(data=2, fsdp=4, sp=1),
        fsdp_min_size=0,
        model_config=CFG,
    )
    mesh = make_mesh(cfg.mesh)
    p, opt, specs, optimizer = init_state(cfg, mesh)
    step, _, _ = make_train_step(cfg, optimizer, mesh, specs)
    rng = np.random.default_rng(0)
    T = CFG.block_size

    def batch(i):
        x = rng.integers(0, CFG.vocab_size, (1, 8, T), dtype=np.int32)
        return make_global_batch(x, mesh, batch_spec()), make_global_batch(
            np.roll(x, -1, -1), mesh, batch_spec()
        )

    key = jax.random.PRNGKey(0)
    # Warm step 0 exactly as the train loop calls it: the sticky-loss
    # carrier is a COMMITTED mesh-replicated f32 scalar from the start
    # (training/train.py). Both an uncommitted zeros() and the bare-float
    # default would give step 0 a different input aval than step 1+ and
    # compile the whole step twice — the original shipped loop did exactly
    # that, and this pin is what caught it.
    loss = jax.device_put(
        jnp.zeros((), jnp.float32),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    x, y = batch(0)
    p, opt, loss = step(p, opt, x, y, jax.random.fold_in(key, 0), loss)
    assert jit_cache_size(step) == 1
    with CompileCounter() as cc:
        for i in (1, 2):
            x, y = batch(i)
            p, opt, loss = step(p, opt, x, y, jax.random.fold_in(key, i), loss)
    assert cc.count == 0, "train step recompiled on a later step"
    assert jit_cache_size(step) == 1
    assert np.isfinite(float(loss))


def test_audit_suite_passes_on_cpu_mesh():
    """run_audit = what `python -m midgpt_tpu.analysis --audit` executes:
    fp32 master params + bf16 compute on the lowered train step, and a
    collective-free decode while body. Raises on violation.

    Every numeric budget asserted here is read from the declarative
    manifest (analysis/budgets.py) — the same module run_audit lowers
    against — so this pin and the audit cannot drift apart; the manifest
    is the single place a serving mode's budget is declared."""
    from midgpt_tpu.analysis import budgets

    report = run_audit()
    fp = report["train_step_fp32_master"]
    assert fp["n_reduced"] == 0 and fp["n_f32"] > 0 and fp["has_bf16_compute"]
    assert report["decode_while_bodies"], "decode program lost its scan?"
    assert all(n == 0 for n in report["decode_while_bodies"].values())
    # speculative-verify extensions: collective-free layer loop and the
    # zero-in-loop-cache-copy census on BOTH serving programs
    assert report["verify_while_bodies"], "verify program lost its layer scan?"
    assert all(n == 0 for n in report["verify_while_bodies"].values())
    zero = budgets.LOOP_POOL_COPY_BUDGET
    assert all(n == zero for n in report["decode_loop_pool_copies"].values())
    assert all(n == zero for n in report["verify_loop_pool_copies"].values())
    # mesh-sharded serving extensions: per-program in-loop collective
    # census on the tp lowerings — exactly the megatron activation
    # all-reduce budget the manifest declares per program, no other
    # collective op anywhere in a loop, and zero per-shard pool/scale
    # copies
    assert report["tp_mesh"] == budgets.tp_mesh_shape()
    for name in budgets.TP_PROGRAMS:
        assert (
            report[f"{name}_loop_all_reduces"]
            == budgets.tp_loop_all_reduce_budget(name)
        ), name
        assert report[f"{name}_loop_pool_copies"] == zero, name
    # split-K extensions: sequence partitioning is a softmax-statistics
    # restructure, so the split lowerings must add ZERO pool traffic (no
    # pool- or scale-sized copy in any decode/verify loop) and zero
    # collectives beyond the megatron all-reduces the unsplit tp program
    # carries (tp_decode_split is asserted with the rest of TP_PROGRAMS)
    assert report["split_decode_while_bodies"], "split decode lost its scan?"
    for key in budgets.SPLIT_ZERO_COLLECTIVE_KEYS + budgets.SPLIT_ZERO_COPY_KEYS:
        assert all(n == zero for n in report[key].values()), key
    # round-overlap extensions: the fused multi-round group program must
    # add ZERO in-loop pool/scale traffic and zero collectives at every
    # audited k — a group multiplies any in-loop copy cost by k, so the
    # census is the load-bearing claim of the fusion (budgets.py)
    for key in budgets.GROUP_ZERO_COLLECTIVE_KEYS + budgets.GROUP_ZERO_COPY_KEYS:
        assert report[key], f"{key}: group program lost its scan?"
        assert all(n == zero for n in report[key].values()), key
    # attention-variant extensions (docs/SERVING.md "Attention variants"):
    # the KV-head-shrunk GQA/MQA pools still alias through every decode
    # loop carry (f32 AND int8+scales), window masking adds zero pool
    # traffic, and GQA under tp pays exactly the same megatron all-reduce
    # budget as MHA — grouping moves pool bytes, never collectives
    for key in budgets.VARIANT_ZERO_COLLECTIVE_KEYS + budgets.VARIANT_ZERO_COPY_KEYS:
        assert all(n == zero for n in report[key].values()), key
    assert report["tp_decode_gqa_loop_all_reduces"] == (
        budgets.tp_loop_all_reduce_budget("tp_decode_gqa", budgets.AUDIT_GQA_TP)
    )
    assert report["tp_decode_gqa_loop_pool_copies"] == zero
