"""The serving chaos gate: every serving fault kind, end to end through
robustness/chaos_serve.py (the exact scenario `chaos_run.py --serve`
drives). Each scenario runs a fault-free reference and a faulted pass of
the same seeded trace and asserts the degradation invariants internally —
engine alive, every page conserved, unaffected greedy streams bit-identical
— so these tests mostly assert on the returned summary. The CLI JSON line
is validated through the shared single-line parser at the end."""

import json

import pytest

from midgpt_tpu.analysis.bench_contract import parse_single_json_line
from midgpt_tpu.robustness import faults
from midgpt_tpu.robustness.chaos_serve import run_serving_chaos


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def test_chaos_kill_mid_decode_full_parity():
    """A killed decode round recompute-preempts every decode-ready slot;
    recovery is parity-preserving, so NO request may diverge."""
    s = run_serving_chaos("kill_mid_decode@6", seed=0)
    assert s["faults_fired"] == {"kill_mid_decode": 1}
    assert s["decode_kills"] == 1
    assert s["preemptions"] >= 1, "the kill must actually preempt someone"
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_checked"] == s["n_requests"]
    assert s["parity_ok"] == s["parity_checked"]
    assert s["pages_conserved"]


def test_chaos_kill_overlapped_round_recompute_parity():
    """Round-overlap twin of the kill_mid_decode gate (docs/SERVING.md
    "Round-overlap dispatch"): the engine runs double-buffered and the
    fault drops the IN-FLIGHT dispatched group un-settled mid host phase.
    Recovery is the same recompute preemption, so NO request may diverge —
    and because the reference pass runs un-overlapped, full parity here
    also re-proves overlap-on vs overlap-off bit-exactness under fault
    pressure. Pages conserved; zero silent drops."""
    s = run_serving_chaos("kill_overlapped_round@6", seed=0)
    assert s["faults_fired"] == {"kill_overlapped_round": 1}
    assert s["overlap_mode"] == "double"
    assert s["overlap_kills"] == 1
    assert s["preemptions"] >= 1, "the kill must actually preempt someone"
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_checked"] == s["n_requests"]
    assert s["parity_ok"] == s["parity_checked"]
    assert s["pages_conserved"]


def test_chaos_poisoned_page_isolates_the_victim():
    """HBM damage to one slot's page corrupts at most that slot: every
    other stream is bit-identical and the pool stays conserved."""
    s = run_serving_chaos("poisoned_page@3", seed=0)
    assert s["faults_fired"] == {"poisoned_page": 1}
    assert s["poisoned"] == 1
    assert s["parity_checked"] == s["n_requests"] - 1  # victim excluded
    assert s["parity_ok"] == s["parity_checked"]
    assert s["pages_conserved"]


def test_chaos_slow_client_shed_without_collateral():
    """A wedged streaming client is shed with status slow_client; the
    engine keeps serving and the other clients' DELIVERED streams match
    the reference."""
    s = run_serving_chaos("slow_client@1", seed=0)
    assert s["faults_fired"] == {"slow_client": 1}
    assert s["statuses"].get("slow_client") == 1
    assert s["cancelled"] == 1
    assert s["statuses"].get("ok") == s["n_requests"] - 1
    assert s["parity_ok"] == s["parity_checked"] == s["n_requests"] - 1
    assert s["pages_conserved"]


def test_chaos_submit_storm_sheds_and_survivors_finish():
    """A burst of duplicate submissions beyond the backpressure budget
    sheds (BackpressureError) instead of wedging the pool; whatever was
    admitted serves to completion with exact streams."""
    s = run_serving_chaos("submit_storm@2", seed=0)
    assert s["faults_fired"] == {"submit_storm": 1}
    assert s["shed"] >= 1, "the storm must overrun the backlog budget"
    assert s["parity_ok"] == s["parity_checked"] >= 1
    assert s["pages_conserved"]


def test_chaos_evict_shared_prefix_flush_never_corrupts_readers():
    """A forced flush of the prefix trie (pressure spike, LRU ignored)
    reclaims every unreferenced shared page mid-trace; referenced entries
    survive by construction, so every live stream stays bit-identical,
    later requests just re-prefill, and pages + refcounts are conserved
    through the flush. Both passes run cache-ON over template-shared
    traffic, so the reference pass doubles as a cache parity check."""
    s = run_serving_chaos("evict_shared_prefix@7", seed=0, n_requests=6)
    assert s["faults_fired"] == {"evict_shared_prefix": 1}
    assert s["prefix_cache"] is True
    assert s["prefix_reclaimed"] > 0, "the flush must reclaim trie pages"
    assert s["statuses"] == {"ok": 6}
    assert s["parity_ok"] == s["parity_checked"] == 6
    assert 0.0 < s["prefix_hit_rate"] < 1.0  # the flush cost later matches
    assert s["pages_conserved"]


@pytest.mark.slow  # heavy long-tail (~16 s): full suite only, per the
# tier-1 870 s gate budget (CLAUDE.md); the cheaper swap pins stay tier-1
def test_chaos_hot_swap_mid_decode_blue_green_parity():
    """The zero-downtime swap gate (docs/ROBUSTNESS.md 'Zero-downtime
    model ops'): a verified-checkpoint blue/green weight swap lands mid-
    trace with trickle arrivals. Zero streams drop; streams served before
    the flip are bit-identical to the fault-free OLD-weights pass, post-
    flip admissions to the NEW-weights pass; both sides non-empty; pages
    conserved through the flip."""
    s = run_serving_chaos("hot_swap_mid_decode@5", seed=0)
    assert s["faults_fired"] == {"hot_swap_mid_decode": 1}
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["dropped"] == 0
    # a REAL verified version: "<step>:<sha12>" from the manifest hash
    step = s["checkpoint_step"]
    assert s["weights_version"].startswith(f"{step}:")
    assert len(s["weights_version"].split(":")[1]) == 12
    assert s["swap"]["flip_round"] >= s["swap"]["staged_round"]
    assert s["parity_old_side"] >= 1 and s["parity_new_side"] >= 1
    assert s["parity_old_side"] + s["parity_new_side"] == s["n_requests"]
    assert s["pages_conserved"]


@pytest.mark.slow  # heavy long-tail (~25 s, the suite's priciest chaos
# gate): full suite only; the resize recompile pin stays tier-1
def test_chaos_pool_resize_grow_shrink_int8_parity():
    """The elastic-resize gate: grow then shrink mid-trace on an int8
    cache (scales must migrate with their pages or parity breaks). Every
    stream stays greedy-bit-exact vs the no-resize reference; page
    conservation holds at every boundary (asserted inside resize_pool on
    both sides of each migration)."""
    s = run_serving_chaos("pool_resize@4,pool_resize@8", seed=0)
    assert s["faults_fired"] == {"pool_resize": 2}
    assert s["cache_dtype"] == "int8"
    assert len(s["resizes"]) == 2
    grow, shrink = s["resizes"]
    assert grow["to_pages"] > grow["from_pages"]
    assert shrink["to_pages"] < shrink["from_pages"]
    assert s["final_num_pages"] == shrink["to_pages"]
    assert s["pages_migrated"] >= 1
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_ok"] == s["parity_checked"] == s["n_requests"]
    assert s["pages_conserved"]


def test_chaos_engine_crash_failover_zero_drops():
    """The fleet gate (docs/ROBUSTNESS.md 'Fleet serving & failover'): a
    replica killed mid-trace drops ZERO accepted streams — its in-flight
    work is resubmitted through the retryable path with the original
    prompt and full budget, and greedy batch-composition-independence
    makes the failover replays bit-identical to the fault-free single-
    engine reference. Page conservation holds on every survivor."""
    s = run_serving_chaos("engine_crash@6", seed=0)
    assert s["faults_fired"] == {"engine_crash": 1}
    assert s["fleet_size"] == 2 and s["alive"] == 1
    assert s["failovers"] == 1
    assert s["failed_over_streams"] >= 1, "the crash must orphan someone"
    assert s["dropped_streams"] == 0
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_ok"] == s["parity_checked"] == s["n_requests"]
    assert s["pages_conserved"]


def test_chaos_handoff_stall_falls_back_to_prefill():
    """A stalled spill-tier consult costs a re-prefill, never a wrong
    token: the router refuses the spilled run once (stall_fallbacks), the
    request recomputes its prefix, and every stream stays bit-identical
    with the cross-tier ledger closed."""
    s = run_serving_chaos("handoff_stall", seed=0)
    assert s["faults_fired"] == {"handoff_stall": 1}
    assert s["spill"]["stall_fallbacks"] >= 1
    assert s["dropped_streams"] == 0
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_ok"] == s["parity_checked"] == s["n_requests"]
    assert s["pages_conserved"]


def test_chaos_spill_corrupt_discards_never_poisons():
    """Host-RAM corruption of a spilled KV page is caught by the crc32
    verify at re-adoption and discarded — the page NEVER re-enters the
    device pool, so no stream can decode from damaged KV. The victim
    re-prefills; parity stays exact; the spill ledger accounts for the
    discard (total_spilled = resident + readopted + corrupt_discarded +
    capacity_dropped + stale_discarded)."""
    s = run_serving_chaos("spill_corrupt", seed=0)
    assert s["faults_fired"] == {"spill_corrupt": 1}
    assert s["spill"]["corrupt_discarded"] >= 1
    assert s["poisoned"] == 0
    assert s["dropped_streams"] == 0
    assert s["statuses"] == {"ok": s["n_requests"]}
    assert s["parity_ok"] == s["parity_checked"] == s["n_requests"]
    assert s["pages_conserved"]


def test_chaos_run_serve_cli_emits_one_json_line(capsys):
    """`chaos_run.py --serve` holds the one-JSON-line driver contract and
    carries the chaos verdict fields."""
    import runpy
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mod = runpy.run_path(
        os.path.join(repo, "tools", "chaos_run.py"), run_name="chaos_under_test"
    )
    argv, sys.argv = sys.argv, [
        "chaos_run.py", "--serve", "--fault", "kill_mid_decode@5",
    ]
    try:
        rc = mod["main"]()
    finally:
        sys.argv = argv
    assert rc == 0
    out = capsys.readouterr().out
    rec, problems = parse_single_json_line(out)
    assert not problems, problems
    assert rec["tool"] == "chaos_run" and rec["mode"] == "serve"
    assert rec["status"] == "ok"
    assert rec["faults_fired"] == {"kill_mid_decode": 1}
    assert rec["pages_conserved"] is True
    # the record round-trips as strict JSON (no NaN etc.)
    json.loads(out)
