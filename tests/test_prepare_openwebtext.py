"""Unit coverage for the openwebtext prep stream-writer.

The full pipeline needs HF hub egress; the piece with actual logic — the
bounded-buffer memmap writer — is tested here against a stub exposing the
same narrow dataset interface (`["n"]`, `.select_columns(...).iter(...)`),
including the buffer-flush and mega-document bypass paths.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

_SPEC = importlib.util.spec_from_file_location(
    "prepare_owt",
    os.path.join(os.path.dirname(__file__), "..", "data", "openwebtext", "prepare.py"),
)
prepare_owt = importlib.util.module_from_spec(_SPEC)
try:
    _SPEC.loader.exec_module(prepare_owt)
except SystemExit:
    prepare_owt = None  # import-gated deps missing on this host


class _FakeTokenized:
    def __init__(self, docs):
        self.docs = docs

    def __getitem__(self, key):
        assert key == "n"
        return [len(d) for d in self.docs]

    def select_columns(self, cols):
        assert cols == ["ids"]
        return self

    def iter(self, batch_size):
        for i in range(0, len(self.docs), batch_size):
            yield {"ids": self.docs[i : i + batch_size]}


@pytest.mark.skipif(prepare_owt is None, reason="datasets/tiktoken not installed")
def test_write_split_streams_exactly(tmp_path):
    rng = np.random.default_rng(0)
    docs = [list(rng.integers(0, 50257, rng.integers(1, 400))) for _ in range(57)]
    path = str(tmp_path / "train.bin")
    # tiny buffer: forces many flush cycles
    total = prepare_owt.write_split(_FakeTokenized(docs), path, buffer_tokens=512)
    expect = np.concatenate([np.asarray(d, np.uint16) for d in docs])
    got = np.memmap(path, dtype=np.uint16, mode="r")
    assert total == len(expect)
    np.testing.assert_array_equal(np.asarray(got), expect)


@pytest.mark.skipif(prepare_owt is None, reason="datasets/tiktoken not installed")
def test_write_split_mega_document_bypass(tmp_path):
    rng = np.random.default_rng(1)
    docs = [
        list(rng.integers(0, 50257, 100)),
        list(rng.integers(0, 50257, 5000)),  # larger than the buffer: bypass
        list(rng.integers(0, 50257, 100)),
    ]
    path = str(tmp_path / "train.bin")
    total = prepare_owt.write_split(_FakeTokenized(docs), path, buffer_tokens=1024)
    expect = np.concatenate([np.asarray(d, np.uint16) for d in docs])
    got = np.memmap(path, dtype=np.uint16, mode="r")
    assert total == 5200
    np.testing.assert_array_equal(np.asarray(got), expect)
