"""Split-K paged attention (kernels/attention_template.py + the gather
siblings in kernels/decode_attention.py): sequence-partitioned decode and
verify must be NUMERICALLY INTERCHANGEABLE with the unsplit pass.

Three layers of pinning, mirroring tests/test_decode_attention.py and
tests/test_quant_cache.py:

* op level — the gather split lowering (fat score matmul, partitioned
  softmax statistics, ops/online_softmax merge) vs the unsplit gather and
  the dense masked reference, decode and verify, f32 and int8, split 2/4/8;
* kernel level — the template's split grid (per-partition raw partials,
  merged outside the kernel) in interpret mode vs the gather paths;
* engine level — greedy token streams bit-identical with split-K forced
  on vs off across cache dtype, self-draft speculation, prefix cache, and
  a tp=2 serving mesh, plus the recompile pin: a forced-split engine
  compiles ONE decode program and replays request-mix changes with zero
  compiles (split_k is a static, not per-request state).

Pool geometry note: engine tests use num_pages=33, disjoint from the
25-page geometry whose compile counts tests/test_recompile_pins.py pins
from a pristine baseline and from the 29/31-page tp geometries.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import CompileCounter, jit_cache_size
from midgpt_tpu.kernels.attention_template import normalize_split_k
from midgpt_tpu.kernels.decode_attention import (
    paged_attention_gather,
    paged_attention_kernel,
    paged_verify_attention_gather,
    paged_verify_attention_kernel,
)
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.quant import quantize_q8
from midgpt_tpu.parallel.serve_tp import make_serve_mesh
from midgpt_tpu.sampling.serve import ServeEngine, _serve_decode_chunk
from midgpt_tpu.sampling.spec import self_draft

B, H, C = 3, 2, 128  # C spans the full Mosaic lane dim
PS, NP, MP = 8, 7, 4  # page_size, pool pages, max logical pages/slot

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


# ----------------------------------------------------------------------
# normalize_split_k: the static-factor contract every caller leans on
# ----------------------------------------------------------------------


def test_normalize_split_k():
    # identity on pow2 divisors
    assert normalize_split_k(1, 8) == 1
    assert normalize_split_k(4, 8) == 4
    assert normalize_split_k(8, 8) == 8
    # pow2 floor of a non-pow2 request
    assert normalize_split_k(6, 8) == 4
    # clamped to the table width BEFORE the pow2 floor (8 > 6 must give a
    # divisor of 6, not a stale pow2 of the request)
    assert normalize_split_k(8, 6) == 2
    # halves until it divides an odd width
    assert normalize_split_k(4, 7) == 1
    assert normalize_split_k(4, 12) == 4
    # floor at 1 for degenerate requests
    assert normalize_split_k(0, 8) == 1
    assert normalize_split_k(-3, 8) == 1


def test_split_bucket_rule():
    """The auto rule (docs/SERVING.md "Split-K decode"): one doubling per
    page-bucket doubling past 512 tokens, so every partition sweeps >= 512
    tokens; <= 512 stays on the unsplit program."""
    cfg = GPTConfig(
        block_size=4096, vocab_size=96, n_layer=1, n_head=1, n_embd=32
    )
    params = GPT.init(cfg, jax.random.PRNGKey(0))
    eng = ServeEngine(
        cfg, params, max_slots=1, page_size=8, num_pages=9,
        prefill_chunk=8, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    assert [eng._split_bucket(t) for t in (64, 512, 1024, 2048, 4096)] == [
        1, 1, 2, 4, 8
    ]
    forced = ServeEngine(
        cfg, params, max_slots=1, page_size=8, num_pages=9,
        prefill_chunk=8, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32, split_k=4,
    )
    assert forced._split_bucket(64) == 4  # forced engines skip the rule
    with pytest.raises(ValueError, match="split_k"):
        ServeEngine(
            cfg, params, max_slots=1, page_size=8, num_pages=9,
            prefill_chunk=8, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, split_k=0,
        )


# ----------------------------------------------------------------------
# Op level: gather split vs unsplit vs dense reference
# ----------------------------------------------------------------------


def _problem(seed=0, max_pages=8):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (B, H, C), jnp.float32)
    k_pages = jax.random.normal(keys[1], (H, NP, PS, C), jnp.float32)
    v_pages = jax.random.normal(keys[2], (H, NP, PS, C), jnp.float32)
    rng = np.random.default_rng(seed)
    page_table = jnp.asarray(
        rng.integers(0, NP, (B, max_pages)), jnp.int32
    )
    # ragged: an inactive slot, a page-unaligned length, a full slot
    lengths = jnp.asarray([0, 19, max_pages * PS], jnp.int32)
    return q, k_pages, v_pages, page_table, lengths


def _quantize(pages):
    qp, s = quantize_q8(pages.transpose(1, 0, 2, 3))
    return qp.transpose(1, 0, 2, 3), s


def _dense_decode(q, k_pages, v_pages, page_table, lengths):
    out = []
    for b in range(q.shape[0]):
        kb = np.concatenate(
            [np.asarray(k_pages)[:, p] for p in np.asarray(page_table)[b]],
            axis=1,
        )
        vb = np.concatenate(
            [np.asarray(v_pages)[:, p] for p in np.asarray(page_table)[b]],
            axis=1,
        )
        n = int(lengths[b])
        if n == 0:
            out.append(np.zeros((H, C), np.float32))
            continue
        s = np.einsum("hc,hkc->hk", np.asarray(q)[b], kb) / math.sqrt(C)
        s[:, n:] = -np.inf
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out.append(np.einsum("hk,hkc->hc", p, vb))
    return np.stack(out)


@pytest.mark.parametrize("split", [2, 4, 8])
def test_gather_split_matches_unsplit_and_dense(split):
    q, kp, vp, pt, ln = _problem()
    base = np.asarray(paged_attention_gather(q, kp, vp, pt, ln, split_k=1))
    got = np.asarray(paged_attention_gather(q, kp, vp, pt, ln, split_k=split))
    # the unsplit pass NaNs the length-0 slot (masked downstream); the
    # split merge's l==0 finalize emits finite zeros there instead
    np.testing.assert_allclose(got[1:], base[1:], atol=3e-6, rtol=3e-6)
    assert np.isfinite(got).all() and not np.abs(got[0]).any()
    dense = _dense_decode(q, kp, vp, pt, ln)
    np.testing.assert_allclose(got[1:], dense[1:], atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("split", [2, 4])
def test_gather_split_matches_unsplit_int8(split):
    q, kp, vp, pt, ln = _problem(seed=1)
    kq, ks = _quantize(kp)
    vq, vs = _quantize(vp)
    base = np.asarray(
        paged_attention_gather(q, kq, vq, pt, ln, ks, vs, split_k=1)
    )
    got = np.asarray(
        paged_attention_gather(q, kq, vq, pt, ln, ks, vs, split_k=split)
    )
    np.testing.assert_allclose(got[1:], base[1:], atol=3e-6, rtol=3e-6)


@pytest.mark.parametrize("quant", [False, True], ids=["f32", "int8"])
@pytest.mark.parametrize("split", [2, 4])
def test_verify_gather_split_matches_unsplit(split, quant):
    T = 5
    q, kp, vp, pt, ln = _problem(seed=2)
    qv = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, C), jnp.float32)
    counts = jnp.minimum(ln[:, None] + jnp.arange(T)[None] + 1, MP * PS * 2)
    counts = jnp.where(ln[:, None] > 0, counts, 0)
    args = (qv, kp, vp, pt, counts)
    kw = {}
    if quant:
        kq, ks = _quantize(kp)
        vq, vs = _quantize(vp)
        args = (qv, kq, vq, pt, counts)
        kw = dict(k_scale=ks, v_scale=vs)
    base = np.asarray(paged_verify_attention_gather(*args, split_k=1, **kw))
    got = np.asarray(paged_verify_attention_gather(*args, split_k=split, **kw))
    np.testing.assert_allclose(got[1:], base[1:], atol=3e-6, rtol=3e-6)
    assert np.isfinite(got).all() and not np.abs(got[0]).any()


# ----------------------------------------------------------------------
# Kernel level: template split grid in interpret mode
# ----------------------------------------------------------------------


@pytest.mark.parametrize("quant", [False, True], ids=["f32", "int8"])
@pytest.mark.parametrize("split", [1, 4])
def test_kernel_split_matches_gather_decode(split, quant):
    q, kp, vp, pt, ln = _problem(seed=3)
    kw, args = {}, (q, kp, vp, pt, ln)
    if quant:
        kq, ks = _quantize(kp)
        vq, vs = _quantize(vp)
        args = (q, kq, vq, pt, ln)
        kw = dict(k_scale=ks, v_scale=vs)
    want = np.asarray(paged_attention_gather(*args, split_k=1, **kw))
    got = np.asarray(paged_attention_kernel(*args, split_k=split, **kw))
    np.testing.assert_allclose(got[1:], want[1:], atol=2e-5, rtol=2e-5)
    assert np.isfinite(got).all() and not np.abs(got[0]).any()


@pytest.mark.parametrize("quant", [False, True], ids=["f32", "int8"])
@pytest.mark.parametrize("split", [1, 4])
def test_kernel_split_matches_gather_verify(split, quant):
    T = 3
    _, kp, vp, pt, ln = _problem(seed=4)
    qv = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, C), jnp.float32)
    counts = jnp.minimum(ln[:, None] + jnp.arange(T)[None] + 1, MP * PS * 2)
    counts = jnp.where(ln[:, None] > 0, counts, 0)
    kw, args = {}, (qv, kp, vp, pt, counts)
    if quant:
        kq, ks = _quantize(kp)
        vq, vs = _quantize(vp)
        args = (qv, kq, vq, pt, counts)
        kw = dict(k_scale=ks, v_scale=vs)
    want = np.asarray(paged_verify_attention_gather(*args, split_k=1, **kw))
    got = np.asarray(paged_verify_attention_kernel(*args, split_k=split, **kw))
    np.testing.assert_allclose(got[1:], want[1:], atol=2e-5, rtol=2e-5)
    assert np.isfinite(got).all() and not np.abs(got[0]).any()


# ----------------------------------------------------------------------
# Engine level: greedy streams bit-identical, split on vs off
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _trace(seed, n=4):
    rng = np.random.default_rng(seed)
    lens = rng.integers(5, 30, size=n)
    return (
        [rng.integers(1, CFG.vocab_size, size=int(l)).tolist() for l in lens],
        [int(b) for b in rng.integers(5, 18, size=n)],
    )


def _run(params, split_k, *, dtype=jnp.float32, prefix=False, spec=False,
         mesh=None, seed=0, num_pages=33):
    skw = {}
    if spec:
        dcfg, dparams = self_draft(CFG, params, 1)
        skw = dict(draft_params=dparams, draft_config=dcfg,
                   draft_shares_cache=True, spec_k_max=4)
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=num_pages,
        prefill_chunk=8, decode_chunk=8, temperature=0.0, cache_dtype=dtype,
        prefix_cache=prefix, mesh=mesh, split_k=split_k, **skw,
    )
    prompts, budgets = _trace(seed)
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    done = eng.run()
    return [done[u].tokens.tolist() for u in uids]


# int8, spec, and tp variants carry the tier-1 suite's heaviest compiles;
# the f32 plain/prefix rows keep split-parity coverage inside the 870 s
# gate (plain is the documented keeper) and the marked rows still run in
# the full (unfiltered) suite.
@pytest.mark.parametrize(
    "dtype",
    [jnp.float32, pytest.param("int8", marks=pytest.mark.slow)],
    ids=["f32", "int8"],
)
@pytest.mark.parametrize(
    "feature",
    [
        "plain",
        pytest.param("spec", marks=pytest.mark.slow),
        "prefix",
        pytest.param("tp", marks=pytest.mark.slow),
    ],
)
def test_engine_greedy_streams_identical_split_on_off(params, dtype, feature):
    """The acceptance pin: forcing split_k=4 changes WHICH program decodes
    but not one emitted token, under every serving feature it composes
    with. Exact list equality — split-K is a lowering choice, not a
    numeric mode (same f32 softmax, same merge identity,
    tests/test_online_softmax.py)."""
    kw = dict(dtype=dtype)
    if feature == "spec":
        kw["spec"] = True
    elif feature == "prefix":
        kw["prefix"] = True
    elif feature == "tp":
        kw["mesh"] = make_serve_mesh(tp_size=2)
    base = _run(params, 1, **kw)
    split = _run(params, 4, **kw)
    assert split == base
    auto = _run(params, "auto", **kw)
    assert auto == base  # <= 512-token traffic: auto IS the unsplit program


def test_forced_split_engine_compiles_one_decode_program(params):
    """Recompile pin: split_k is a static jit arg, so a forced-split
    engine compiles exactly ONE new decode program (the split_k=4
    instantiation) on its first mix, and three further distinct request
    mixes compile NOTHING — request lengths stay plain data under
    split-K. Mix design follows tests/test_recompile_pins.py: prompts
    25..47 with max_new ≡ 1 (mod 8) pin the pow2 page bucket at the 8-page
    cap from the first decode round, so "one program" means one — not one
    per bucket the trace wanders through. Geometry (35-page pool) is
    disjoint from this file's other engine runs (33) and from the pristine
    25-page pins, so the count starts cold."""

    def mix(lengths, max_new, seed):
        eng = ServeEngine(
            CFG, params, max_slots=3, page_size=8, num_pages=35,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32, split_k=4,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
            for n, m in zip(lengths, max_new)
        }
        assert set(eng.run()) == uids

    d0 = jit_cache_size(_serve_decode_chunk)
    mix((25, 34, 47), (9, 17, 17), seed=0)
    assert jit_cache_size(_serve_decode_chunk) - d0 == 1
    with CompileCounter() as cc:
        mix((26, 33, 40), (9, 17, 9), seed=1)
        mix((29, 41, 45), (17, 9, 17), seed=2)
        mix((31, 38, 47), (17, 17, 9), seed=3)
    assert cc.count == 0, f"split-K mix change recompiled {cc.count}"
    assert jit_cache_size(_serve_decode_chunk) - d0 == 1
