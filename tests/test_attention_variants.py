"""Attention variants (docs/SERVING.md "Attention variants"): GQA/MQA
grouped KV heads and sliding-window(+sinks) masking as first-class config
knobs, train-to-serve.

Three layers of pinning, mirroring tests/test_split_k.py:

* kernel level — the unified template (kernels/attention_template.py) over
  the full variant matrix {MHA, GQA, MQA, window+sinks} x {f32, int8} x
  {split_k 1/2/4} x {decode, multi-row verify}, in interpret mode, against
  an independent dense einsum oracle (the mask spelled out from the spec,
  not imported from ops/attention.visible_mask);
* engine level — a GQA ServeEngine's greedy streams bit-match
  engine.generate under int8, forced split-K, a tp=2 mesh, and a sliding
  window with sinks; window page reclamation keeps the resident page set
  bounded while the conservation law holds; and a GQA config survives the
  full train -> checkpoint -> restore_for_sampling -> serve loop;
* contract level — config validation negative paths, and the recompile
  pin: variant geometry is a PROGRAM key (an MHA and a GQA engine compile
  disjoint programs) while request-mix changes compile nothing.

Pool geometry note: engine tests use num_pages=37/39/43/45, disjoint from the
pristine 25-page pins (tests/test_recompile_pins.py), the 29/31-page tp
geometries, and split-K's 33/35.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import CompileCounter
from midgpt_tpu.kernels.attention_template import paged_attention_template
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.quant import quantize_q8
from midgpt_tpu.parallel.serve_tp import make_serve_mesh
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.ops import assert_conserved
from midgpt_tpu.sampling.serve import ServeEngine

B, C = 2, 128  # C spans the full Mosaic lane dim
PS, NP, MP = 8, 7, 4  # page_size, pool pages, max logical pages/slot

# Every variant is a (query heads, KV heads, window, sinks) spec over ONE
# template — the module's design claim. MQA is the extreme grouping (any
# head-fold indexing bug surfaces), window+sinks rides on GQA so masking
# and grouping are exercised together.
VARIANTS = {
    "mha": dict(hq=2, hkv=2, window=0, sinks=0),
    "gqa": dict(hq=4, hkv=2, window=0, sinks=0),
    "mqa": dict(hq=4, hkv=1, window=0, sinks=0),
    "window": dict(hq=4, hkv=2, window=10, sinks=3),
}


# ----------------------------------------------------------------------
# Kernel level: template variant matrix vs dense oracle
# ----------------------------------------------------------------------


def _problem(hkv, hq, n_rows, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (B, hq, n_rows, C), jnp.float32)
    k_pages = jax.random.normal(keys[1], (hkv, NP, PS, C), jnp.float32)
    v_pages = jax.random.normal(keys[2], (hkv, NP, PS, C), jnp.float32)
    rng = np.random.default_rng(seed)
    page_table = jnp.asarray(rng.integers(0, NP, (B, MP)), jnp.int32)
    # ragged, page-unaligned base lengths; verify rows extend one key each
    base = jnp.asarray([19, MP * PS - n_rows], jnp.int32)
    counts = base[:, None] + jnp.arange(n_rows)[None] + 1
    return q, k_pages, v_pages, page_table, counts


def _quantize(pages):
    qp, s = quantize_q8(pages.transpose(1, 0, 2, 3))
    return qp.transpose(1, 0, 2, 3), s


def _dense_oracle(q, k_pages, v_pages, page_table, counts, window, sinks):
    """Per-(slot, head, row) masked softmax attention, the mask written out
    from the spec: visible = [0, n) ∩ ([n - W, n) ∪ [0, sinks))."""
    Bq, HQ, R, Cd = q.shape
    groups = HQ // k_pages.shape[0]
    out = np.zeros((Bq, HQ, R, Cd), np.float32)
    for b in range(Bq):
        kb = np.concatenate(
            [np.asarray(k_pages)[:, p] for p in np.asarray(page_table)[b]],
            axis=1,
        )  # (H_kv, MP*PS, C)
        vb = np.concatenate(
            [np.asarray(v_pages)[:, p] for p in np.asarray(page_table)[b]],
            axis=1,
        )
        col = np.arange(kb.shape[1])
        for h in range(HQ):
            kv = h // groups
            for r in range(R):
                n = int(counts[b, r])
                keep = col < n
                if window:
                    w = col >= n - window
                    if sinks:
                        w |= col < sinks
                    keep &= w
                s = (np.asarray(q)[b, h, r] @ kb[kv].T) / math.sqrt(Cd)
                s = np.where(keep, s, -np.inf)
                p = np.exp(s - s.max())
                p /= p.sum()
                out[b, h, r] = p @ vb[kv]
    return out


# int8 and split_k=4 are the heavy long tail (every cell is an interpret-
# mode pallas run); the f32 x split {1,2} slice keeps full variant x mode
# coverage inside the tier-1 870 s gate and the marked cells still run in
# the unfiltered suite.
@pytest.mark.parametrize("mode", ["decode", "verify"])
@pytest.mark.parametrize(
    "split", [1, 2, pytest.param(4, marks=pytest.mark.slow)]
)
@pytest.mark.parametrize(
    "quant",
    [False, pytest.param(True, marks=pytest.mark.slow)],
    ids=["f32", "int8"],
)
@pytest.mark.parametrize("variant", list(VARIANTS))
def test_template_variant_matrix_matches_dense_oracle(
    variant, quant, split, mode
):
    """The acceptance matrix: every (variant, dtype, split, row-count) spec
    instantiated from the ONE template agrees with the dense oracle. The
    kernel body never sees query heads or window state — grouping folds
    into the row axis, the window is a static mask — so a pass here pins
    that the folds/masks compose rather than special-case."""
    v = VARIANTS[variant]
    n_rows = 1 if mode == "decode" else 3
    q, kp, vp, pt, cnt = _problem(v["hkv"], v["hq"], n_rows)
    kw = {}
    if quant:
        kq, ks = _quantize(kp)
        vq, vs = _quantize(vp)
        kp_in, vp_in = kq, vq
        kw = dict(k_scale=ks, v_scale=vs)
        # oracle runs on the dequantized pools — quantization error is the
        # representation's, not the kernel's, so it must cancel exactly
        kp = kq.astype(jnp.float32) * ks.transpose(1, 0, 2)[:, :, :, None]
        vp = vq.astype(jnp.float32) * vs.transpose(1, 0, 2)[:, :, :, None]
    else:
        kp_in, vp_in = kp, vp
    got = np.asarray(
        paged_attention_template(
            q, kp_in, vp_in, pt, cnt, split_k=split,
            sliding_window=v["window"], attn_sinks=v["sinks"], **kw,
        )
    )
    want = _dense_oracle(q, kp, vp, pt, cnt, v["window"], v["sinks"])
    assert got.shape == (B, v["hq"], n_rows, C)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_template_full_window_is_bit_identical_to_windowless():
    """window >= every count must lower to the same math as no window at
    all (the mask predicate is vacuously true) — the guarantee that lets
    the engine keep ONE template with window as a static parameter."""
    q, kp, vp, pt, cnt = _problem(hkv=2, hq=4, n_rows=1, seed=5)
    base = np.asarray(paged_attention_template(q, kp, vp, pt, cnt))
    wide = np.asarray(
        paged_attention_template(
            q, kp, vp, pt, cnt, sliding_window=MP * PS, attn_sinks=0
        )
    )
    np.testing.assert_array_equal(wide, base)


# ----------------------------------------------------------------------
# Engine level: GQA/window serving, bit-exact and page-bounded
# ----------------------------------------------------------------------

GQA_CFG = GPTConfig(
    block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    n_kv_heads=2,
)
WIN_CFG = GPTConfig(
    block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    n_kv_heads=2, sliding_window=16, attn_sinks=4,
)


def _trace(cfg, seed=0, n=4, lo=5, hi=30, budget_hi=18):
    rng = np.random.default_rng(seed)
    lens = rng.integers(lo, hi, size=n)
    return (
        [rng.integers(1, cfg.vocab_size, size=int(l)).tolist() for l in lens],
        [int(b) for b in rng.integers(5, budget_hi, size=n)],
    )


def _serve_vs_generate(cfg, params, *, dtype=jnp.float32, split_k=1,
                       mesh=None, num_pages=37, trace=None):
    eng = ServeEngine(
        cfg, params, max_slots=3, page_size=8, num_pages=num_pages,
        prefill_chunk=16, decode_chunk=8, temperature=0.0,
        cache_dtype=dtype, split_k=split_k, mesh=mesh,
    )
    prompts, budgets = trace or _trace(cfg)
    uids = [eng.submit(p, m) for p, m in zip(prompts, budgets)]
    done = eng.run()
    for uid, p, m in zip(uids, prompts, budgets):
        ref = generate(
            cfg, params, jnp.asarray(p, jnp.int32)[None], m, temperature=0.0
        )[0]
        np.testing.assert_array_equal(
            np.asarray(done[uid].tokens), np.asarray(ref)
        )
    return eng


@pytest.fixture(scope="module")
def gqa_params():
    return GPT.init(GQA_CFG, jax.random.PRNGKey(0))


# Every feature cell pays a full engine + generate-oracle compile
# (~13 s each on the 1-core host), so the whole parametrization is
# slow-tier; the cheap tier-1 engine representative for this subsystem
# is the recompile-pin test below (runs real MHA and GQA traffic), and
# the f32 template-matrix cells keep the kernel parity gate non-slow.
@pytest.mark.parametrize(
    "feature",
    [
        pytest.param("plain", marks=pytest.mark.slow),
        pytest.param("int8", marks=pytest.mark.slow),
        pytest.param("split", marks=pytest.mark.slow),
        pytest.param("tp", marks=pytest.mark.slow),
        pytest.param("window", marks=pytest.mark.slow),
    ],
)
def test_gqa_engine_greedy_matches_generate(gqa_params, feature):
    """The serving acceptance pin: a GQA engine's paged streams are
    bit-identical to the dense-cache generate path — grouping changes the
    pool geometry, never a token — and the property composes with int8
    pools, forced split-K, a tp=2 mesh (whole query groups per shard), and
    window+sinks masking."""
    kw = {}
    cfg, params = GQA_CFG, gqa_params
    if feature == "int8":
        kw["dtype"] = "int8"
    elif feature == "split":
        kw["split_k"] = 4
    elif feature == "tp":
        kw["mesh"] = make_serve_mesh(tp_size=2)
    elif feature == "window":
        cfg = WIN_CFG
        params = GPT.init(WIN_CFG, jax.random.PRNGKey(0))
    _serve_vs_generate(cfg, params, **kw)


@pytest.mark.slow  # long stream + generate oracle: ~14 s on the 1-core host
def test_window_engine_reclaims_pages_and_stays_bounded(monkeypatch):
    """Unbounded-session decode: a windowed engine streams far past
    sliding_window with (a) greedy parity against generate — reclamation
    must never free a page the mask can still see, conservative-by-one
    rule included; (b) a RESIDENT page bound at every append — the live
    (non-sentinel) page set never exceeds sink pages + window pages + the
    active page + the one-token conservatism; (c) the allocator
    conservation law intact afterwards (reclaimed pages really returned);
    (d) a nonzero window_reclaimed_pages counter on stats()."""
    params = GPT.init(WIN_CFG, jax.random.PRNGKey(0))
    W, sinks, ps = WIN_CFG.sliding_window, WIN_CFG.attn_sinks, 8
    bound = -(-sinks // ps) + -(-W // ps) + 2
    live_high = []
    orig = ServeEngine._append_token

    def spy(self, slot_i, slot, tok, t):
        ok = orig(self, slot_i, slot, tok, t)
        live_high.append(sum(p >= 0 for p in slot.pages))
        return ok

    monkeypatch.setattr(ServeEngine, "_append_token", spy)
    # one long stream: 12-token prompt + 56 new tokens = 4x+ the window
    eng = _serve_vs_generate(
        WIN_CFG, params, num_pages=39,
        trace=([list(range(1, 13))], [56]),
    )
    assert live_high, "spy never fired — decode path changed?"
    assert max(live_high) <= bound, (
        f"resident pages peaked at {max(live_high)} > bound {bound} — "
        "reclamation is not keeping up with the window"
    )
    assert eng.stats()["window_reclaimed_pages"] > 0
    assert_conserved(eng, "after windowed run")


@pytest.mark.slow  # full train-step compile: heavy long-tail, full suite only
def test_gqa_trains_checkpoints_restores_and_serves(tmp_path):
    """The end-to-end acceptance loop: a GQA config takes real optimizer
    steps on the training mesh, checkpoints, restores through the sampling
    path (restore_for_sampling), and the restored params serve greedy
    bit-exact against generate. Pins that the wkv leaf survives the
    save/restore round-trip — a pytree-structure regression here would
    silently drop the K/V projection."""
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.sampling.engine import restore_for_sampling
    from midgpt_tpu.training.checkpoint import CheckpointManager
    from midgpt_tpu.training.train import init_state, make_train_step

    mc = GPTConfig(
        block_size=32, vocab_size=64, n_layer=2, n_head=4, n_embd=32,
        n_kv_heads=2,
    )
    cfg = ExperimentConfig(
        rundir="", data_dir="", learning_rate=1e-2, batch_size=8,
        warmup_steps=2, min_lr=1e-3, lr_decay_steps=10, max_steps=10,
        beta2=0.99, weight_decay=0.0, eval_interval=5, param_dtype="float32",
        compute_dtype="float32", g_accum_iters=1, shard_model=True,
        fsdp_min_size=0, mesh=MeshConfig(data=2, fsdp=4, sp=1),
        model_config=mc,
    )
    mesh = make_mesh(cfg.mesh)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    step, *_ = make_train_step(cfg, optimizer, mesh, specs)
    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    for i in range(3):
        x = rng.integers(0, mc.vocab_size, (1, 8, 32), dtype=np.int32)
        y = np.roll(x, -1, axis=-1)
        key, k = jax.random.split(key)
        params, opt_state, loss = step(
            params, opt_state,
            make_global_batch(x, mesh, batch_spec()),
            make_global_batch(y, mesh, batch_spec()), k,
        )
    assert np.isfinite(float(loss))

    mngr = CheckpointManager(str(tmp_path), max_to_keep=1, save_interval_steps=1)
    mngr.save(3, {"params": params}, force=True)
    mngr.wait()
    mngr.close()
    restored, ckpt_step = restore_for_sampling(str(tmp_path), cfg)
    assert ckpt_step == 3
    assert jax.tree.structure(restored) == jax.tree.structure(params)
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    host = jax.device_get(restored)
    _serve_vs_generate(
        mc, host, num_pages=43,
        trace=_trace(mc, seed=1, n=3, lo=4, hi=12, budget_hi=10),
    )


# ----------------------------------------------------------------------
# Contract level: validation negative paths + the recompile pin
# ----------------------------------------------------------------------


def test_config_validation_negative_paths():
    base = dict(block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32)
    with pytest.raises(ValueError, match="n_kv_heads"):
        GPTConfig(**base, n_kv_heads=3)  # not a divisor of n_head
    with pytest.raises(ValueError, match="n_kv_heads"):
        GPTConfig(**base, n_kv_heads=0)
    with pytest.raises(ValueError, match="sliding_window"):
        GPTConfig(**base, sliding_window=64)  # must be < block_size
    with pytest.raises(ValueError, match="sliding_window"):
        GPTConfig(**base, sliding_window=-8)
    with pytest.raises(ValueError, match="attn_sinks"):
        GPTConfig(**base, attn_sinks=4)  # sinks require a window
    with pytest.raises(ValueError, match="exceeds"):
        GPTConfig(**base, sliding_window=60, attn_sinks=8)


def test_tp_divisibility_negative_paths():
    from midgpt_tpu.config import ExperimentConfig, MeshConfig

    mqa = GPTConfig(
        block_size=32, vocab_size=64, n_layer=2, n_head=4, n_embd=32,
        n_kv_heads=1,
    )
    with pytest.raises(ValueError, match="KV heads"):
        ExperimentConfig(
            rundir="", data_dir="", learning_rate=1e-3, batch_size=8,
            warmup_steps=1, min_lr=1e-4, lr_decay_steps=10, max_steps=10,
            beta2=0.99, weight_decay=0.0, eval_interval=5,
            param_dtype="float32", compute_dtype="float32", g_accum_iters=1,
            shard_model=True, fsdp_min_size=0,
            mesh=MeshConfig(data=1, fsdp=1, tp=2), model_config=mqa,
        )
    params = GPT.init(mqa, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="KV heads"):
        ServeEngine(
            mqa, params, max_slots=2, page_size=8, num_pages=9,
            prefill_chunk=8, decode_chunk=4, temperature=0.0,
            cache_dtype=jnp.float32, mesh=make_serve_mesh(tp_size=2),
        )


def test_variant_geometry_is_a_program_key_mix_changes_compile_nothing(
    gqa_params,
):
    """The recompile pin, extended per docs/SERVING.md: MHA and GQA pools
    have different shapes, so an MHA engine and a GQA engine compile
    DISJOINT decode programs (geometry is a static program key, never
    runtime state) — and once both are warm, any further mix of requests
    through either engine compiles NOTHING. Mix design follows
    tests/test_split_k.py's forced-split pin: prompts 25..47 with
    max_new ≡ 1 (mod 8) pin the pow2 page bucket at the 8-page cap from
    the first decode round, so mix changes exercise only data. Pool
    geometry 45 is this test's own (cold for BOTH variants regardless of
    run order — the parity tests above warm the 37-page programs)."""
    mha_cfg = GPTConfig(
        block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=32
    )
    mha_params = GPT.init(mha_cfg, jax.random.PRNGKey(0))

    def run_mix(cfg, params, lengths, max_new, seed):
        eng = ServeEngine(
            cfg, params, max_slots=3, page_size=8, num_pages=45,
            prefill_chunk=16, decode_chunk=8, temperature=0.0,
            cache_dtype=jnp.float32,
        )
        rng = np.random.default_rng(seed)
        uids = {
            eng.submit(rng.integers(0, cfg.vocab_size, n).astype(np.int32), m)
            for n, m in zip(lengths, max_new)
        }
        assert set(eng.run()) == uids

    # warm the MHA programs at this geometry
    run_mix(mha_cfg, mha_params, (25, 34, 47), (9, 17, 17), seed=0)
    with CompileCounter() as cc:
        run_mix(GQA_CFG, gqa_params, (25, 34, 47), (9, 17, 17), seed=0)
    assert cc.count > 0, (
        "GQA first run compiled nothing — it reused an MHA program? "
        "pool geometry must be a program key"
    )
    with CompileCounter() as cc:
        run_mix(mha_cfg, mha_params, (26, 33, 40), (9, 17, 9), seed=1)
        run_mix(GQA_CFG, gqa_params, (29, 41, 45), (17, 9, 17), seed=2)
        run_mix(mha_cfg, mha_params, (31, 38, 47), (17, 17, 9), seed=3)
    assert cc.count == 0, (
        f"request-mix change recompiled {cc.count} program(s) — variant "
        "mix must be free once both geometries are warm"
    )
