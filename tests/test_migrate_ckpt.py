"""tools/migrate_ckpt_v2_v3.py: the v2 (flat head-major) -> v3 ((3,D,D))
wqkv permutation, verified end to end against a from-scratch construction."""

import importlib.util
import os
import sys

import jax
import numpy as np

_spec = importlib.util.spec_from_file_location(
    "migrate_ckpt",
    os.path.join(os.path.dirname(__file__), "..", "tools", "migrate_ckpt_v2_v3.py"),
)
mig = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mig)


def test_wqkv_permutation_matches_semantics():
    """Row r of the v2 layout holds head h=(r//(3C)), slot j=(r//C)%3,
    channel c=r%C; the migrated (3, D, D) must hold that row at
    [j, h*C + c]."""
    L, H, C, D = 2, 3, 4, 12
    rng = np.random.default_rng(0)
    v2 = rng.normal(size=(L, 3 * D, D)).astype(np.float32)
    out = mig.migrate_tree({"wqkv": v2}, n_head=H)["wqkv"]
    assert out.shape == (L, 3, D, D)
    for r in range(3 * D):
        h, j, c = r // (3 * C), (r // C) % 3, r % C
        np.testing.assert_array_equal(out[:, j, h * C + c], v2[:, r])


def test_migrate_tree_touches_only_wqkv():
    tree = {
        "blocks": {"attn": {"wqkv": np.zeros((1, 12, 4)), "wo": np.ones((1, 4, 4))}},
        "mu": {"blocks": {"attn": {"wqkv": np.zeros((1, 12, 4))}}},
    }
    out = mig.migrate_tree(tree, n_head=2)
    assert out["blocks"]["attn"]["wqkv"].shape == (1, 3, 4, 4)
    assert out["mu"]["blocks"]["attn"]["wqkv"].shape == (1, 3, 4, 4)
    np.testing.assert_array_equal(out["blocks"]["attn"]["wo"], np.ones((1, 4, 4)))


def test_migrate_checkpoint_end_to_end(tmp_path, monkeypatch):
    """Save a v2-format checkpoint (old flat layout + v2 marker), migrate via
    the CLI, and restore it through the current CheckpointManager."""
    from midgpt_tpu.training import checkpoint as ckpt_mod

    H, C = 2, 4
    D = H * C
    v2_params = {
        "blocks": {"attn": {"wqkv": np.arange(2 * 3 * D * D, dtype=np.float32).reshape(2, 3 * D, D)}}
    }
    v2_opt = {"mu": v2_params, "count": np.zeros(())}

    src = tmp_path / "src"
    monkeypatch.setattr(ckpt_mod, "FORMAT", {"version": 2, "qkv_layout": "head_major"})
    w = ckpt_mod.CheckpointManager(str(src), save_interval_steps=1)
    w.save(5, {"params": v2_params, "opt_state": v2_opt})
    w.wait()
    w.close()
    monkeypatch.undo()

    dst = tmp_path / "dst"
    # In-process (NOT a subprocess): a bare python child would initialize
    # the real axon TPU backend — conftest's CPU selection is per-process.
    monkeypatch.setattr(
        sys, "argv", ["migrate", str(src), str(dst), "--n-head", str(H)]
    )
    mig.main()

    r = ckpt_mod.CheckpointManager(str(dst), save_interval_steps=1)
    like = {
        "params": {
            "blocks": {
                "attn": {
                    "wqkv": jax.ShapeDtypeStruct((2, 3, D, D), np.float32)
                }
            }
        }
    }
    restored = r.restore(5, like)  # v3 marker: restore must ACCEPT it
    r.close()
    got = np.asarray(restored["params"]["blocks"]["attn"]["wqkv"])
    want = mig.migrate_tree(v2_params, n_head=H)["blocks"]["attn"]["wqkv"]
    np.testing.assert_array_equal(got, want)
