"""Multi-process distributed test: 2 local processes, jax.distributed on CPU.

Exercises the code paths no single-process test can: per-process dataset
sharding (TokenDataset shard_by_process), cross-process global-array assembly
(make_global_batch under process_count() > 1), and a compiled SPMD train step
spanning both processes. The reference has no equivalent — its distributed
smoke scripts require a real TPU pod (reference scripts/test_jax.py,
test_ckpt.py).
"""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Multi-process SPMD on the CPU backend needs the cross-process CPU
# collectives that landed after this container's jax; on older builds the
# worker dies with "Multiprocess computations aren't implemented on the CPU
# backend" — an environment gap, not a repo regression, so skip cleanly.
_JAX = tuple(int(x) for x in jax.__version__.split(".")[:2])
requires_multiproc_cpu = pytest.mark.skipif(
    _JAX < (0, 5),
    reason=f"multi-process CPU collectives unsupported on jax {jax.__version__}",
)


def test_skip_pin_is_version_agnostic():
    """The env-gap skip must track the real jax version — on this image's
    jax (<0.5) the SPMD tests skip; the moment the image moves to >=0.5
    they run again with no edit here. Cross-process SERVING never hides
    behind this pin: sampling/fleet_proc.py deliberately uses no
    jax.distributed (plain sockets, zero collectives — replicas share no
    arrays), so tests/test_fleet_proc.py runs its process-boundary gates
    on this same jax."""
    assert requires_multiproc_cpu.args[0] == (_JAX < (0, 5))
    # and the serving transport really carries no distributed dependency
    # (AST, not text: the module docstring SAYS "no jax.distributed")
    import ast
    import inspect

    import midgpt_tpu.sampling.fleet_proc as fleet_proc

    tree = ast.parse(inspect.getsource(fleet_proc))
    refs = [
        node
        for node in ast.walk(tree)
        if isinstance(node, ast.Attribute) and node.attr == "distributed"
    ]
    assert not refs, "fleet_proc.py grew a jax.distributed dependency"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@requires_multiproc_cpu
def test_two_process_train_step(tmp_path):
    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        rng.integers(0, 64, 4096, dtype=np.uint16).astype(np.uint16).tofile(
            tmp_path / f"{split}.bin"
        )

    coordinator = f"localhost:{_free_port()}"
    env = {
        **os.environ,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # the worker forces the CPU platform itself (axon plugin ignores env)
    }
    worker = os.path.join(REPO, "tests", "multiproc_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(i), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"

    losses = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("LOSS ")]
        assert lines, f"no LOSS line in:\n{out}"
        losses.append(float(lines[0].split()[1]))
    assert np.isfinite(losses[0])
    # SPMD: every process computes the identical global loss
    assert abs(losses[0] - losses[1]) < 1e-6, losses


def _run_workers(tmp_path, mode, rundir=""):
    coordinator = f"localhost:{_free_port()}"
    env = {
        **os.environ,
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    worker = os.path.join(REPO, "tests", "multiproc_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(i), str(tmp_path), mode, rundir],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} ({mode}) failed:\n{out}"
    vals = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("CONT ")]
        assert lines, f"no CONT line in:\n{out}"
        vals.append(float(lines[0].split()[1]))
    return vals


@requires_multiproc_cpu
def test_two_process_checkpoint_roundtrip(tmp_path):
    """Sharded checkpoint round-trip across process restarts: 2 processes
    train 2 steps and save (each writing its own shards), a FRESH pair of
    processes restores and continues — the continued-training loss must
    equal the oracle that never stopped. A no-op or partial restore would
    diverge (2-step-trained params differ from init)."""
    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        rng.integers(0, 64, 4096, dtype=np.uint16).astype(np.uint16).tofile(
            tmp_path / f"{split}.bin"
        )
    rundir = str(tmp_path / "ckpt")

    oracle = _run_workers(tmp_path, "ckpt_save", rundir)
    resumed = _run_workers(tmp_path, "ckpt_restore", rundir)

    assert np.isfinite(oracle[0])
    assert abs(oracle[0] - oracle[1]) < 1e-6, oracle
    assert abs(resumed[0] - resumed[1]) < 1e-6, resumed
    assert abs(oracle[0] - resumed[0]) < 1e-6, (oracle, resumed)
