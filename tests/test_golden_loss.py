"""Golden-loss regression: a committed 200-step fp32 trajectory must
reproduce within tolerance (VERDICT r4 weak #6 — `loss/final < 1.0` alone
would pass a wrong-eps / swapped-beta / init-drift regression).

Calibration (measured, r5): re-running on the same stack reproduces the
fixture to 0.0 abs diff; seeded regressions deflect it by 6e-4 (RMSNorm eps
1e-6 -> 1e-4) to 1.9e-2 (init scale * 1.05). atol 1e-4 sits between.

If this fails after a DELIBERATE numerics/spec change (or a JAX upgrade —
the fixture records the version), verify the new trajectory is sane and
regenerate with `python tools/make_golden_fixture.py`. Never regenerate to
silence an unexplained shift.
"""

import json
import os

import numpy as np

import golden_runner

FIXTURE = os.path.join(os.path.dirname(__file__), "golden", "tiny_fp32.json")


def test_golden_loss_trajectory(tmp_path):
    with open(FIXTURE) as f:
        fixture = json.load(f)
    assert fixture["spec"] == golden_runner.GOLDEN_SPEC, (
        "GOLDEN_SPEC changed without regenerating the fixture — run "
        "python tools/make_golden_fixture.py (only for deliberate changes)"
    )
    golden_runner.make_stream(str(tmp_path))
    losses = golden_runner.run_trajectory(str(tmp_path))
    np.testing.assert_allclose(
        np.array(losses),
        np.array(fixture["losses"]),
        rtol=0,
        atol=1e-4,
        err_msg=(
            "training trajectory drifted from the golden fixture "
            f"(generated on {fixture['versions']}) — a numerics "
            "regression in init/optimizer/loss, a software-stack change "
            "(jax math, numpy Generator streams, optax internals), OR a "
            "different host platform/CPU than the fixture's: XLA:CPU "
            "vectorizes reductions per ISA, so the same program can give "
            "ulp-different f32 sums on another machine — compare the "
            "fixture's platform/machine/processor fields against this "
            "host before suspecting the code; "
            "see tests/test_golden_loss.py docstring"
        ),
    )
