"""Native C batcher: build, bit-parity with numpy, and dataset integration."""

import numpy as np
import pytest

from midgpt_tpu import native
from midgpt_tpu.data.dataset import sample_batch


def _stream(n=100_000, seed=0):
    return np.random.default_rng(seed).integers(0, 50304, n).astype(np.uint16)


def test_native_builds_and_matches_numpy():
    if not native.native_available():
        pytest.skip("no C toolchain on this host (numpy fallback covers it)")
    data = _stream()
    starts = np.random.default_rng(1).integers(0, len(data) - 257, size=64)
    x, y = native.sample_windows(data, starts, 256)
    offsets = np.arange(256)
    np.testing.assert_array_equal(x, data[starts[:, None] + offsets].astype(np.int32))
    np.testing.assert_array_equal(
        y, data[starts[:, None] + offsets + 1].astype(np.int32)
    )


def test_native_single_window_and_single_thread():
    if not native.native_available():
        pytest.skip("no C toolchain on this host")
    data = _stream(5000)
    starts = np.asarray([17], dtype=np.int64)
    x, y = native.sample_windows(data, starts, 64, n_threads=1)
    np.testing.assert_array_equal(x[0], data[17:81].astype(np.int32))
    np.testing.assert_array_equal(y[0], data[18:82].astype(np.int32))


def test_sample_batch_deterministic_across_paths(monkeypatch):
    """sample_batch yields identical batches whether or not the native
    library loads — the RNG lives in numpy, the gather is mechanical."""
    data = _stream()
    rng1 = np.random.default_rng([7, 0, 3])
    x1, y1 = sample_batch(data, 128, 4, 2, rng=rng1)

    monkeypatch.setattr(native, "sample_windows", lambda *a, **k: None)
    rng2 = np.random.default_rng([7, 0, 3])
    x2, y2 = sample_batch(data, 128, 4, 2, rng=rng2)

    assert x1.shape == (2, 4, 128) and x1.dtype == np.int32
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_array_equal(x1[..., 1:], y1[..., :-1])
