"""Fault-tolerant training, end to end on the virtual CPU mesh: supervisor
rollback with data-window skip, verified checkpointing (manifests, retry,
verified-only GC), preemption emergency saves, exact-continuation resume,
and one pin per fault in the robustness/faults.py registry.

Compile discipline: every train() in this module shares ONE module-scoped
TrainRuntime, which is both the wall-clock lever (one step compile for the
whole file) and the acceptance pin — the supervisor's rollback/resume path
must reuse the compiled train step (test_recompile_pins.py methodology).
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import jit_cache_size
from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPTConfig
from midgpt_tpu.robustness import faults, preempt
from midgpt_tpu.robustness.errors import (
    CheckpointCorruptError,
    CheckpointWriteError,
    DivergenceError,
    SimulatedPreemption,
)
from midgpt_tpu.robustness.supervisor import supervise
from midgpt_tpu.training.checkpoint import MANIFEST_NAME, CheckpointManager
from midgpt_tpu.training.train import make_runtime, train

CFG = GPTConfig(block_size=32, vocab_size=64, n_layer=1, n_head=2, n_embd=32)


def base_config(data_dir, **overrides) -> ExperimentConfig:
    base = dict(
        rundir="",
        data_dir=str(data_dir),
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=60,
        max_steps=16,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=8,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        mesh=MeshConfig(data=2, fsdp=4, sp=1),
        eval_steps=2,
        log_interval=1,
        fsdp_min_size=0,
        model_config=CFG,
        restart_backoff_sec=0.0,
        ckpt_retry_backoff_sec=0.0,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    preempt.reset()
    yield
    faults.clear()
    preempt.reset()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    stream = (np.arange(20000) % 17).astype(np.uint16)
    stream.tofile(d / "train.bin")
    stream[:4000].tofile(d / "val.bin")
    return d


@pytest.fixture(scope="module")
def runtime(data_dir):
    """ONE compiled runtime for every train() in this module — rundir,
    max_steps, fault_plan, and data_step_offset are host-side and may vary
    per test (training/train.py TrainRuntime)."""
    return make_runtime(base_config(data_dir))


@pytest.fixture(scope="module")
def straight16(data_dir, runtime, tmp_path_factory):
    """The uninterrupted 16-step trajectory every resume test compares to."""
    rundir = tmp_path_factory.mktemp("straight")
    result = train(base_config(data_dir, rundir=str(rundir)), runtime=runtime)
    return result, str(rundir)


def _logged_losses(rundir) -> dict:
    """step -> loss/optimized from a run's metrics.jsonl."""
    out = {}
    with open(os.path.join(rundir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "loss/optimized" in rec:
                out[rec["step"]] = rec["loss/optimized"]
    return out


# ----------------------------------------------------------------------
# fault registry
# ----------------------------------------------------------------------


def test_fault_registry_semantics():
    faults.activate_plan("nan_grad@12,ckpt_io_error*2")
    assert not faults.should_fire("nan_grad", step=11)  # wrong step
    assert faults.should_fire("nan_grad", step=12)
    assert not faults.should_fire("nan_grad", step=12)  # consumed
    assert faults.should_fire("ckpt_io_error")
    assert faults.should_fire("ckpt_io_error")
    assert not faults.should_fire("ckpt_io_error")  # times=2 exhausted
    assert faults.fired_counts() == {"nan_grad": 1, "ckpt_io_error": 2}
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.activate("reboot")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.activate_plan("nan_grad@@3")


def test_stepless_hook_does_not_fire_step_scoped_fault():
    faults.activate("ckpt_io_error", step=5)
    assert not faults.should_fire("ckpt_io_error")  # scoped fault, stepless hook
    assert faults.should_fire("ckpt_io_error", step=5)


# ----------------------------------------------------------------------
# verified checkpointing (numpy trees: no model in the loop)
# ----------------------------------------------------------------------


def _np_state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {"w": rng.normal(size=(16, 8)).astype(np.float32)},
        "opt_state": {"mu": rng.normal(size=(16, 8)).astype(np.float32)},
    }


def _like(state):
    return {
        k: {n: jax.ShapeDtypeStruct(a.shape, a.dtype) for n, a in v.items()}
        for k, v in state.items()
    }


def test_manifest_written_and_verified(tmp_path):
    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    state = _np_state()
    mngr.save(3, state)
    mngr.wait()
    step_dir = mngr._step_dir(3)
    assert step_dir is not None and os.path.exists(
        os.path.join(step_dir, MANIFEST_NAME)
    )
    assert mngr.is_verified(3) and mngr.latest_verified_step() == 3
    restored = mngr.restore(3, _like(state))
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    mngr.close()


def test_corrupted_item_fails_verification_and_restore(tmp_path):
    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    state = _np_state()
    mngr.save(0, state)
    mngr.wait()
    # flip bytes in the largest file under the step dir
    files = []
    for root, _, names in os.walk(tmp_path / "0"):
        files += [os.path.join(root, n) for n in names if n != MANIFEST_NAME]
    victim = max(files, key=os.path.getsize)
    with open(victim, "rb+") as fh:
        fh.truncate(max(1, os.path.getsize(victim) // 2))
    problems = mngr.verify(0)
    assert problems and any("truncated" in p or "mismatch" in p for p in problems)
    assert mngr.latest_verified_step() is None  # manifests exist, none verify
    with pytest.raises(CheckpointCorruptError, match="integrity"):
        mngr.restore(0, _like(state))
    mngr.close()


def test_ckpt_io_error_retry_succeeds(tmp_path):
    """Acceptance (c): transient write IOError -> retry succeeds and the
    manifest verifies."""
    faults.activate("ckpt_io_error", times=2)
    mngr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, write_retries=3,
        retry_backoff_sec=0.0,
    )
    assert mngr.save(0, _np_state()) is True
    mngr.wait()
    assert faults.fired_counts()["ckpt_io_error"] == 2
    assert mngr.is_verified(0)
    mngr.close()


def test_ckpt_io_error_exhausts_budget(tmp_path):
    faults.activate("ckpt_io_error", times=3)
    mngr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, write_retries=3,
        retry_backoff_sec=0.0,
    )
    with pytest.raises(CheckpointWriteError, match="3 attempt"):
        mngr.save(0, _np_state())
    mngr.close()


def test_kill_mid_save_previous_verified_survives(tmp_path):
    """Acceptance (b): a save killed between the TensorStore write and the
    manifest commit leaves the PREVIOUS verified checkpoint as the resume
    point; the half-written step is skipped, and a later save may reuse its
    step number."""
    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    state = _np_state()
    mngr.save(1, state)
    mngr.wait()
    faults.activate("kill_mid_save", step=2)
    with pytest.raises(SimulatedPreemption):
        mngr.save(2, _np_state(seed=1))
    mngr.close()

    resumed = CheckpointManager(str(tmp_path), save_interval_steps=1)
    assert resumed.latest_verified_step() == 1
    restored = resumed.restore(1, _like(state))
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    # the crashed step's leftovers must not block re-saving step 2 (the
    # manager clears the unverified remnant; force bypasses orbax's
    # step-already-known interval filter)
    resumed.save(2, _np_state(seed=2), force=True)
    resumed.wait()
    assert resumed.latest_verified_step() == 2
    resumed.close()


def test_truncate_after_manifest_detected(tmp_path):
    """Bit-rot fault: corruption AFTER the manifest committed is caught by
    re-verification at resume time."""
    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    mngr.save(1, _np_state())
    mngr.wait()
    faults.activate("truncate_ckpt_item", step=2)
    mngr.save(2, _np_state(seed=1))
    mngr.wait()  # finalize writes the manifest, THEN the fault truncates
    assert mngr.verify(2)  # problems found
    assert mngr.latest_verified_step() == 1
    mngr.close()


def test_gc_only_after_newer_verifies(tmp_path):
    """max_to_keep=2 with verified-only GC: old steps are deleted only once
    two newer VERIFIED steps exist; an unverified newest save triggers no
    GC at all."""
    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1, max_to_keep=2)
    for s in range(3):
        mngr.save(s, _np_state(seed=s))
    mngr.wait()
    assert mngr.all_steps() == [1, 2]  # 0 GC'd after 2 verified
    faults.activate("truncate_ckpt_item", step=3)
    mngr.save(3, _np_state(seed=3))
    mngr.wait()
    # 3 is unverified: nothing new was GC'd, and resume still points at 2.
    assert set(mngr.all_steps()) >= {1, 2, 3}
    assert mngr.latest_verified_step() == 2
    mngr.close()


def test_restore_diagnostics(tmp_path):
    """Satellite: missing step lists available steps; a v2 marker mismatch
    names found vs expected and points at the migration tool."""
    from midgpt_tpu.training import checkpoint as ckpt_mod

    mngr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    state = _np_state()
    mngr.save(4, state)
    mngr.wait()
    with pytest.raises(ValueError, match=r"available\s+steps: \[4\]"):
        mngr.restore(9, _like(state))
    mngr.close()

    v2 = {"version": 2, "qkv_layout": "head_major"}
    src = tmp_path / "v2"
    orig = ckpt_mod.FORMAT
    ckpt_mod.FORMAT = v2
    try:
        w = CheckpointManager(str(src), save_interval_steps=1)
        w.save(0, state)
        w.close()
    finally:
        ckpt_mod.FORMAT = orig
    r = CheckpointManager(str(src), save_interval_steps=1)
    with pytest.raises(ValueError) as ei:
        r.restore(0, _like(state))
    msg = str(ei.value)
    assert "format" in msg and "'version': 2" in msg and "'version': 3" in msg
    assert "migrate_ckpt_v2_v3" in msg
    r.close()


# ----------------------------------------------------------------------
# supervisor: rollback, skip, budget — and the recompile pin
# ----------------------------------------------------------------------


def test_supervisor_nan_rollback_completes(data_dir, runtime, tmp_path):
    """Acceptance (a): injected NaN at data step 13 -> rollback to the last
    verified checkpoint (step 8), the window is skipped, the run completes
    with finite loss — and the rollback/resume path reuses the compiled
    train step (zero growth of its jit cache)."""
    cfg = base_config(
        data_dir, rundir=str(tmp_path), max_steps=16, fault_plan="nan_grad@13",
    )
    result = supervise(cfg, runtime=runtime)
    sup = result["supervisor"]
    assert sup["restarts"] == 1
    assert sup["windows_skipped"] == [[9, 13]]
    assert sup["faults_fired"] == {"nan_grad": 1}
    assert np.isfinite(result["metrics"]["loss/final"])
    # Recompile pin (test_recompile_pins.py methodology): every train() in
    # this module — including this rollback + resume — shares one runtime,
    # so its step must have compiled exactly ONE program, ever.
    assert jit_cache_size(runtime.step) == 1
    # rollback ledger persisted for cross-process relaunches
    ledger = json.load(open(os.path.join(str(tmp_path), "supervisor_state.json")))
    assert ledger["data_step_offset"] == sup["data_step_offset"] > 0


def test_supervisor_budget_exhaustion_diagnosis(data_dir, runtime, tmp_path):
    cfg = base_config(
        data_dir, rundir=str(tmp_path), fault_plan="nan_grad@13",
        max_restarts=0,
    )
    with pytest.raises(RuntimeError, match="budget"):
        supervise(cfg, runtime=runtime)


def test_supervisor_no_checkpoint_fails_loudly(data_dir, runtime):
    """Divergence with nothing saved (no rundir): nothing to roll back to."""
    cfg = base_config(data_dir, rundir="", fault_plan="nan_grad@3", debug=False)
    with pytest.raises(RuntimeError, match="NO verified checkpoint"):
        supervise(cfg, runtime=runtime)


def test_divergence_error_carries_structure(data_dir, runtime, tmp_path):
    cfg = base_config(data_dir, rundir=str(tmp_path), fault_plan="nan_grad@10")
    faults.activate_plan(cfg.fault_plan)
    with pytest.raises(DivergenceError) as ei:
        train(cfg, runtime=runtime)
    e = ei.value
    assert e.step == 10 and e.last_good_step == 8 and e.rundir == str(tmp_path)
    assert isinstance(e, FloatingPointError)  # legacy guard contract


# ----------------------------------------------------------------------
# exact continuation + preemption
# ----------------------------------------------------------------------


def test_exact_continuation_resume(data_dir, runtime, straight16, tmp_path):
    """Satellite: train 2N straight vs train N, kill, resume to 2N — the
    loss trajectories and final eval match (stateless positional sampler +
    step-folded keys + exact checkpoint round-trip)."""
    straight, straight_dir = straight16
    rundir = str(tmp_path)
    train(base_config(data_dir, rundir=rundir, max_steps=8), runtime=runtime)
    resumed = train(base_config(data_dir, rundir=rundir, max_steps=16), runtime=runtime)

    a, b = _logged_losses(straight_dir), _logged_losses(rundir)
    overlap = sorted(set(a) & set(b) & set(range(8, 16)))
    assert len(overlap) >= 7, (sorted(a), sorted(b))
    np.testing.assert_allclose(
        [a[s] for s in overlap], [b[s] for s in overlap], rtol=1e-6
    )
    np.testing.assert_allclose(
        resumed["metrics"]["loss/final"], straight["metrics"]["loss/final"],
        rtol=1e-6,
    )


def test_preemption_emergency_save_and_exact_resume(
    data_dir, runtime, straight16, tmp_path
):
    """Acceptance (d): SIGTERM (the `preempt` fault models its arrival
    mid-step) -> emergency save lands at the step boundary, verified; the
    resumed run continues the exact straight-run trajectory."""
    straight, straight_dir = straight16
    rundir = str(tmp_path)
    cfg = base_config(data_dir, rundir=rundir, fault_plan="preempt@5")
    interrupted = supervise(cfg, runtime=runtime)
    assert interrupted["metrics"].get("preempted") is True
    assert "loss/final" not in interrupted["metrics"]

    mngr = CheckpointManager(rundir)
    assert mngr.latest_verified_step() == 5  # emergency save, manifest-verified
    mngr.close()

    preempt.reset()
    resumed = train(base_config(data_dir, rundir=rundir), runtime=runtime)
    a, b = _logged_losses(straight_dir), _logged_losses(rundir)
    overlap = sorted(set(a) & set(b) & set(range(6, 16)))
    assert len(overlap) >= 9
    np.testing.assert_allclose(
        [a[s] for s in overlap], [b[s] for s in overlap], rtol=1e-6
    )
    np.testing.assert_allclose(
        resumed["metrics"]["loss/final"], straight["metrics"]["loss/final"],
        rtol=1e-6,
    )


def test_preempt_grace_budget_skips_save_loudly(data_dir, runtime, tmp_path, capsys):
    """Satellite: the grace budget was spent before the emergency save could
    START -> the save is SKIPPED (no step-10 checkpoint), the ledger gets a
    preempt_save_skipped note, and the flight recorder is dumped."""
    rundir = str(tmp_path)
    cfg = base_config(
        data_dir, rundir=rundir, fault_plan="preempt@10", preempt_grace_s=1e-9,
    )
    result = supervise(cfg, runtime=runtime)
    assert result["metrics"].get("preempted") is True
    mngr = CheckpointManager(rundir)
    assert mngr.latest_verified_step() == 8  # interval save only, no emergency
    mngr.close()
    ledger = json.load(open(os.path.join(rundir, "supervisor_state.json")))
    assert any(
        n.get("event") == "preempt_save_skipped" and n.get("step") == 10
        for n in ledger.get("notes", [])
    ), ledger
    assert os.path.exists(os.path.join(rundir, "flight_recorder.json"))
    assert "skipping the emergency save" in capsys.readouterr().out


def test_sigterm_handler_sets_flag():
    """The real signal path (not the fault): SIGTERM flips the replicated
    flag; install is one-shot so a second signal would reach the previous
    handler."""
    preempt.install_handlers((signal.SIGTERM,))
    try:
        assert not preempt.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert preempt.requested()
        assert preempt.requested_at() is not None  # grace clock armed
        assert preempt.any_host_requested()  # single-process: local flag
        assert signal.getsignal(signal.SIGTERM) is not preempt.request  # one-shot
    finally:
        preempt.reset()
    assert not preempt.requested()
    assert preempt.requested_at() is None


# ----------------------------------------------------------------------
# elastic resume & hung-step watchdog
# (docs/ROBUSTNESS.md "Elastic resume & watchdog")
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def rt4(data_dir):
    """The elastic-resume runtime on HALF the mesh: make_runtime re-derives
    the data axis for 4 devices (data=1, fsdp=4). Module-scoped so the
    cross-mesh tests pay ONE extra compile, total."""
    return make_runtime(base_config(data_dir), devices=jax.devices()[:4])


def test_make_runtime_rederives_data_axis(rt4):
    shape = dict(rt4.mesh.shape)
    assert shape["data"] == 1 and shape["fsdp"] == 4
    assert len(rt4.mesh.devices.flatten()) == 4


def test_cross_mesh_reshard_resume_8_4_8(
    data_dir, runtime, rt4, straight16, tmp_path
):
    """Tentpole acceptance: train on 8 devices, checkpoint, resume on 4,
    checkpoint again, resume back on 8 — the loss trajectory matches the
    uninterrupted run (rtol covers only the f32 reassociation of the
    re-derived data-axis all-reduce; the batch order is positional and
    exact), the ledger records every mesh the run touched, and each mesh
    compiled exactly ONE step program, ever (warm-then-count: the module
    fixtures are the warm, the jit cache sizes are the count)."""
    straight, straight_dir = straight16
    rundir = str(tmp_path)
    # phase 1 on 8 devices: the reshard fault ends the attempt like a
    # preemption at step 5 (emergency save verified)
    faults.activate("resume_reshard", step=5)
    r1 = supervise(base_config(data_dir, rundir=rundir), runtime=runtime)
    assert r1["metrics"].get("preempted") is True
    # phase 2 on 4 devices: the 8-device checkpoint restores through the
    # NEW mesh's shardings (on_resume_mesh="any"); preempted again at 10
    preempt.reset()
    faults.clear()
    faults.activate("preempt", step=10)
    r2 = supervise(
        base_config(data_dir, rundir=rundir, on_resume_mesh="any"), runtime=rt4
    )
    assert r2["metrics"].get("preempted") is True
    assert [m["n_devices"] for m in r2["supervisor"]["mesh_history"]] == [8, 4]
    # phase 3 back on 8 devices: the 4-device checkpoint reshards UP again
    preempt.reset()
    faults.clear()
    r3 = supervise(
        base_config(data_dir, rundir=rundir, on_resume_mesh="any"),
        runtime=runtime,
    )
    assert [m["n_devices"] for m in r3["supervisor"]["mesh_history"]] == [8, 4, 8]
    # trajectory parity across BOTH mesh moves
    a, b = _logged_losses(straight_dir), _logged_losses(rundir)
    overlap = sorted(set(a) & set(b))
    assert len(overlap) >= 15, (sorted(a), sorted(b))
    np.testing.assert_allclose(
        [a[s] for s in overlap], [b[s] for s in overlap], rtol=1e-6
    )
    np.testing.assert_allclose(
        r3["metrics"]["loss/final"], straight["metrics"]["loss/final"],
        rtol=1e-6,
    )
    # one program per mesh: neither resume recompiled the other's step
    assert jit_cache_size(runtime.step) == 1
    assert jit_cache_size(rt4.step) == 1


def test_on_resume_mesh_same_refuses_topology_change(data_dir, runtime, tmp_path):
    """Default policy: a resume that sees a different device count than the
    ledger recorded fails loudly BEFORE training starts."""
    from midgpt_tpu.robustness import supervisor as sup_mod

    sup_mod._save_state(
        str(tmp_path),
        {"mesh": {"n_devices": 4, "axes": {"data": 1, "fsdp": 4, "sp": 1}}},
    )
    with pytest.raises(RuntimeError, match="on_resume_mesh"):
        supervise(base_config(data_dir, rundir=str(tmp_path)), runtime=runtime)


def test_supervisor_hang_step_restart_completes(
    data_dir, runtime, straight16, tmp_path
):
    """Watchdog acceptance: hang_step@12 wedges the step's device sync; the
    0.3s watchdog ends the wait, dumps the postmortem artifacts, the
    supervisor marks the step HUNG (data offset UNTOUCHED — a hang is not a
    data problem) and the restart completes with exact-continuation
    parity, all on the one compiled step program."""
    straight, _ = straight16
    rundir = str(tmp_path)
    cfg = base_config(
        data_dir, rundir=rundir, fault_plan="hang_step@12",
        watchdog_deadline_s=0.3,
    )
    result = supervise(cfg, runtime=runtime)
    sup = result["supervisor"]
    assert sup["hung_steps"] == [12] and sup["restarts"] == 1
    assert sup["faults_fired"] == {"hang_step": 1}
    assert sup["data_step_offset"] == 0
    assert os.path.exists(os.path.join(rundir, "flight_recorder.json"))
    assert os.path.exists(os.path.join(rundir, "flight_recorder.prom"))
    ledger = json.load(open(os.path.join(rundir, "supervisor_state.json")))
    assert ledger["hung_steps"] == [12]
    np.testing.assert_allclose(
        result["metrics"]["loss/final"], straight["metrics"]["loss/final"],
        rtol=1e-6,
    )
    assert jit_cache_size(runtime.step) == 1


def test_watchdog_armed_is_invisible(data_dir, runtime, straight16, tmp_path):
    """An armed-but-never-expiring watchdog changes NOTHING: bit-identical
    logged losses vs the straight run (same runtime, deterministic step)
    and zero extra XLA programs — the guard is pure host machinery."""
    straight, straight_dir = straight16
    rundir = str(tmp_path)
    train(
        base_config(data_dir, rundir=rundir, watchdog_deadline_s=60.0),
        runtime=runtime,
    )
    a, b = _logged_losses(straight_dir), _logged_losses(rundir)
    assert sorted(a) == sorted(b)
    np.testing.assert_array_equal(
        [a[s] for s in sorted(a)], [b[s] for s in sorted(b)]
    )
    assert jit_cache_size(runtime.step) == 1


def test_ckpt_enospc_retry_recovers(tmp_path):
    """Degraded IO: ENOSPC with partial bytes left mid-write, twice — the
    retry sweeps the partial and the third attempt lands verified."""
    faults.activate("ckpt_enospc", times=2)
    mngr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, write_retries=3,
        retry_backoff_sec=0.0,
    )
    assert mngr.save(0, _np_state()) is True
    mngr.wait()
    assert faults.fired_counts()["ckpt_enospc"] == 2
    assert mngr.is_verified(0) and not mngr.verify(0)
    mngr.close()


def test_ckpt_enospc_budget_exhaustion_leaves_no_partial(tmp_path):
    """Acceptance: ENOSPC through the whole retry budget — the save fails
    loudly, NO partial step is left on disk or visible to
    latest_verified_step, and the earlier verified checkpoint survives
    (verified-only GC never touched it)."""
    mngr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, write_retries=2,
        retry_backoff_sec=0.0,
    )
    state = _np_state()
    mngr.save(1, state)
    mngr.wait()
    assert mngr.latest_verified_step() == 1
    faults.activate("ckpt_enospc", times=5)
    with pytest.raises(CheckpointWriteError, match="2 attempt"):
        mngr.save(2, _np_state(seed=1))
    # the partial step-2 bytes were swept on the failure path
    assert not os.path.exists(os.path.join(str(tmp_path), "2"))
    assert mngr.latest_verified_step() == 1
    restored = mngr.restore(1, _like(state))
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    mngr.close()


def test_corrupt_supervisor_state_quarantined(tmp_path, capsys):
    """Satellite regression: a torn/garbage ledger is quarantined to
    `.corrupt` with a warning and a fresh ledger takes over — a damaged
    sidecar must never brick a resume whose checkpoints are intact."""
    from midgpt_tpu.robustness import supervisor as sup_mod

    path = os.path.join(str(tmp_path), "supervisor_state.json")
    with open(path, "w") as fh:
        fh.write('{"data_step_offset": 3, "windo')  # torn mid-write
    assert sup_mod._load_state(str(tmp_path)) == {}
    assert os.path.exists(path + ".corrupt") and not os.path.exists(path)
    assert "quarantined" in capsys.readouterr().out
    # the fresh ledger works on top of the quarantine
    sup_mod.append_note(str(tmp_path), {"event": "x"})
    assert sup_mod._load_state(str(tmp_path))["notes"] == [{"event": "x"}]
    # non-object JSON is corrupt too (the ledger is always a dict)
    with open(path, "w") as fh:
        fh.write("[1, 2]")
    assert sup_mod._load_state(str(tmp_path)) == {}
    assert "quarantined" in capsys.readouterr().out
