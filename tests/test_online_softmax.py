"""Direct unit tests for ops/online_softmax.py — the shared combine math
under ring attention, the paged kernel template, and split-K merging.

Covers the numerical edge cases the call sites rely on: an all-masked
partition contributing exactly 0, true -inf score rows finalizing to 0
(not NaN), and bf16 normalized partials merging in f32.
"""

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.ops.online_softmax import (
    MASK,
    M_INIT,
    finalize,
    merge_normalized,
    merge_partials,
    online_block,
)


def _sweep(s_parts):
    """Run the online update over a list of score blocks, like a kernel's
    page sweep: returns raw (m, l, acc) with V = identity-weighted probs
    (acc accumulates the probabilities themselves, so the finalized output
    is the softmax over the concatenated scores)."""
    lead = s_parts[0].shape[:-1]
    m = jnp.full(lead, M_INIT, jnp.float32)
    l = jnp.zeros(lead, jnp.float32)
    acc = jnp.zeros((*lead, sum(p.shape[-1] for p in s_parts)), jnp.float32)
    col = 0
    for s in s_parts:
        w = s.shape[-1]
        m, alpha, p, l = online_block(m, l, s)
        pv = jnp.zeros_like(acc).at[..., col : col + w].set(p)
        acc = acc * alpha[..., None] + pv
        col += w
    return m, l, acc


def test_online_block_matches_direct_softmax():
    rng = np.random.default_rng(0)
    s = jnp.asarray(rng.normal(size=(3, 4, 32)), jnp.float32)
    m, l, acc = _sweep([s[..., :8], s[..., 8:20], s[..., 20:]])
    out, lse = finalize(m, l, acc)
    ref = jax.nn.softmax(s, axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6, rtol=1e-6)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=1e-5, rtol=1e-5)


def test_merge_partials_matches_single_sweep():
    """Splitting the key axis into independent sweeps and merging the raw
    partials must recover the softmax over the union of the spans."""
    rng = np.random.default_rng(1)
    s = jnp.asarray(rng.normal(size=(2, 3, 24)), jnp.float32)
    parts = [_sweep([s[..., i * 8 : (i + 1) * 8]]) for i in range(3)]
    # each partition's acc only spans its own 8 columns; re-embed into S=24
    accs = []
    for i, (_, _, acc) in enumerate(parts):
        full = jnp.zeros((2, 3, 24), jnp.float32)
        accs.append(full.at[..., i * 8 : (i + 1) * 8].set(acc[..., :8]))
    m = jnp.stack([p[0] for p in parts], axis=1)  # (2, split, 3)
    l = jnp.stack([p[1] for p in parts], axis=1)
    acc = jnp.stack(accs, axis=1)  # (2, split, 3, 24)
    mm, lm, am = merge_partials(m, l, acc, axis=1)
    out, _ = finalize(mm, lm, am)
    ref = jax.nn.softmax(s, axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6, rtol=1e-6)


def test_merge_partials_all_masked_partition_contributes_zero():
    """A partition whose every key was masked carries exactly (M_INIT, 0, 0)
    and must not perturb the merged result at all (bitwise: its weight
    underflows to 0)."""
    rng = np.random.default_rng(2)
    s = jnp.asarray(rng.normal(size=(2, 2, 8)), jnp.float32)
    m1, l1, acc1 = _sweep([s])
    masked = jnp.full_like(s, MASK)
    m2, l2, acc2 = _sweep([masked])
    assert float(l2.max()) == 0.0 and float(m2.min()) == float(np.float32(M_INIT))
    m = jnp.stack([m1, m2], axis=0)
    l = jnp.stack([l1, l2], axis=0)
    acc = jnp.stack([acc1, acc2], axis=0)
    mm, lm, am = merge_partials(m, l, acc, axis=0)
    out, lse = finalize(mm, lm, am)
    ref, ref_lse = finalize(m1, l1, acc1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(lse), np.asarray(ref_lse))


def test_finalize_neg_inf_rows_emit_zero_not_nan():
    """True -inf scores (not just the finite MASK) must flow through the
    sweep and finalize to a 0 output row with lse == MASK — the inactive
    slot / length-0 contract of the paged kernels."""
    s = jnp.full((2, 4, 16), -jnp.inf, jnp.float32)
    m, l, acc = _sweep([s[..., :8], s[..., 8:]])
    out, lse = finalize(m, l, acc)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out), np.zeros_like(np.asarray(out)))
    np.testing.assert_array_equal(
        np.asarray(lse), np.full(lse.shape, MASK, dtype=np.float32)
    )
    # merging an all -inf partition with a live one is equally inert
    s_live = jnp.asarray(np.random.default_rng(3).normal(size=(2, 4, 8)), jnp.float32)
    m1, l1, acc1 = _sweep([s_live])
    mm, lm, am = merge_partials(
        jnp.stack([m1, m]), jnp.stack([l1, l]),
        jnp.stack([jnp.pad(acc1, ((0, 0), (0, 0), (0, 8))), acc]),
    )
    out2, _ = finalize(mm, lm, am)
    ref, _ = finalize(m1, l1, acc1)
    np.testing.assert_array_equal(np.asarray(out2[..., :8]), np.asarray(ref[..., :8]))


def test_merge_normalized_bf16_partials():
    """Ring-style merge of NORMALIZED bf16 partials: statistics stay f32,
    the bf16 output shard is upcast once, and merging two halves of a key
    axis reproduces the full softmax to bf16 tolerance."""
    rng = np.random.default_rng(4)
    s = jnp.asarray(rng.normal(size=(2, 3, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 3, 16, 8)), jnp.float32)
    ref = jnp.einsum("bhk,bhkc->bhc", jax.nn.softmax(s, axis=-1), v)

    halves = []
    for sl in (slice(0, 8), slice(8, 16)):
        p = jax.nn.softmax(s[..., sl], axis=-1)
        out = jnp.einsum("bhk,bhkc->bhc", p, v[..., sl, :]).astype(jnp.bfloat16)
        lse = jax.scipy.special.logsumexp(s[..., sl], axis=-1)
        halves.append((out, lse))
    (o0, lse0), (o1, lse1) = halves
    m, l, acc = lse0, jnp.ones_like(lse0), o0.astype(jnp.float32)
    m, l, acc = merge_normalized(m, l, acc, o1, lse1)
    out, lse = finalize(m, l, acc)
    assert acc.dtype == jnp.float32 and m.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-2, rtol=3e-2)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=1e-2, rtol=1e-2)


def test_merge_normalized_masked_shard_is_inert():
    """lse_s == MASK (ring's 'future shard' case) leaves (m, l, acc)
    numerically unchanged."""
    rng = np.random.default_rng(5)
    m = jnp.asarray(rng.normal(size=(2, 4)), jnp.float32)
    l = jnp.asarray(rng.uniform(1.0, 2.0, size=(2, 4)), jnp.float32)
    acc = jnp.asarray(rng.normal(size=(2, 4, 8)), jnp.float32)
    junk = jnp.asarray(rng.normal(size=(2, 4, 8)), jnp.bfloat16)
    m2, l2, acc2 = merge_normalized(m, l, acc, junk, jnp.full_like(m, MASK))
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(m))
    np.testing.assert_array_equal(np.asarray(l2), np.asarray(l))
    np.testing.assert_array_equal(np.asarray(acc2), np.asarray(acc))
