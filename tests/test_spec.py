"""Speculative decoding (sampling/spec.py + serve engine wiring): greedy
token parity with the plain engine (the acceptance pin), exactness of the
rejection sampler against a deliberately wrong draft (statistical), the
page-aligned rollback invariants, and the zero-in-loop-pool-copy HLO pin
on the compiled verify program."""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
from midgpt_tpu.sampling.engine import generate, warp_logits
from midgpt_tpu.sampling.serve import ServeEngine
from midgpt_tpu.sampling.spec import self_draft, speculative_accept

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=4, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft(params):
    return self_draft(CFG, params, 1)


def _trace(seed=0, lengths=(5, 23, 11, 37), max_new=(10, 12, 20, 8)):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in zip(lengths, max_new)
    ]


def test_self_draft_shares_embeddings(params):
    dcfg, dparams = self_draft(CFG, params, 2)
    assert dcfg.n_layer == 2 and dcfg.block_size == CFG.block_size
    assert dparams.wte is params.wte and dparams.lm_head is params.lm_head
    np.testing.assert_array_equal(
        np.asarray(dparams.blocks.attn.wqkv),
        np.asarray(params.blocks.attn.wqkv[:2]),
    )
    for bad in (0, CFG.n_layer):
        with pytest.raises(ValueError, match="n_draft_layers"):
            self_draft(CFG, params, bad)


def test_verify_step_paged_matches_sequential_decode(params):
    """The verify forward (k+1 positions per slot, one batched paged
    forward) must produce the same logits and cache writes as k+1
    sequential decode_step_paged calls — it IS the target's scoring of the
    speculative chain."""
    ps, n_pages, mp, K1 = 8, 25, 8, 4
    cache = PagedKVCache.init(CFG, num_pages=n_pages, page_size=ps, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 96, 11), rng.integers(0, 96, 7)]
    pages = [[1, 2, 3], [4, 5]]
    for pr, pg in zip(prompts, pages):
        row = np.zeros((1, mp), np.int32)
        row[0, : len(pg)] = pg
        chunk = np.zeros((1, 16), np.int32)
        chunk[0, : len(pr)] = pr
        _, cache = GPT.prefill_paged_chunk(
            CFG, params, jnp.asarray(chunk), jnp.asarray(0, jnp.int32),
            jnp.asarray(len(pr), jnp.int32), cache, jnp.asarray(row),
        )
    table = np.zeros((2, mp), np.int32)
    table[0, :3] = pages[0]
    table[1, :2] = pages[1]
    lengths = np.asarray([11, 7], np.int32)
    tokens = np.concatenate(
        [np.asarray([[p[-1]] for p in prompts], np.int32),
         rng.integers(0, 96, (2, K1 - 1)).astype(np.int32)],
        axis=1,
    )
    act = jnp.asarray([True, True])

    ref_logits, c, lens = [], cache, jnp.asarray(lengths)
    for t in range(K1):
        lg, c = GPT.decode_step_paged(
            CFG, params, jnp.asarray(tokens[:, t]), c, jnp.asarray(table),
            lens, act, attn_impl="gather",
        )
        ref_logits.append(lg)
        lens = lens + 1
    ref = jnp.stack(ref_logits, axis=1)

    v_logits, v_cache = GPT.verify_step_paged(
        CFG, params, jnp.asarray(tokens), cache, jnp.asarray(table),
        jnp.asarray(lengths), act, attn_impl="gather",
    )
    np.testing.assert_allclose(
        np.asarray(v_logits), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(v_cache.k), np.asarray(c.k), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(v_cache.v), np.asarray(c.v), atol=2e-5, rtol=2e-5
    )


@pytest.mark.slow  # both rows are among the suite's slowest compiles
# (~56 s dedicated, more shared); full suite only per the tier-1 870 s
# gate budget — the cheaper spec unit tests keep tier-1 coverage
@pytest.mark.parametrize(
    "shared",
    (True, False),
    ids=("shared", "dedicated"),
)
def test_spec_greedy_parity_with_generate(params, draft, shared):
    """THE acceptance pin: greedy speculative serving is token-for-token
    identical to engine.generate across a mixed-length trace — chunked
    prefill, draft/verify rounds, adaptive k, rollback and slot churn
    included — in both draft-cache modes (prefix layers sharing the target
    pool, and a dedicated draft pool)."""
    dcfg, dparams = draft
    trace = _trace()
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, prefill_chunk=16,
        temperature=0.0, cache_dtype=jnp.float32,
        draft_params=dparams, draft_config=dcfg, draft_shares_cache=shared,
        spec_k_max=4,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(
            done[u].tokens, np.asarray(ref[0]), err_msg=f"request {u}"
        )
    stats = eng.spec_stats()
    assert stats["rounds"] > 0 and stats["tokens_per_verify"] >= 1.0
    assert eng.allocator.free_count == eng.allocator.num_pages - 1


@pytest.mark.slow
def test_spec_greedy_parity_separate_draft_model(params):
    """A draft with DIFFERENT weights (an independently initialized model —
    a deliberately wrong draft) must still produce exactly the target's
    greedy tokens: the draft only proposes, the verify forward decides."""
    dcfg = dataclasses.replace(CFG, n_layer=1)
    dparams = GPT.init(dcfg, jax.random.PRNGKey(99))
    trace = _trace(seed=1, lengths=(9, 17), max_new=(12, 9))
    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, temperature=0.0,
        cache_dtype=jnp.float32, draft_params=dparams, draft_config=dcfg,
        spec_k_max=4,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(done[u].tokens, np.asarray(ref[0]))
    # a wrong draft shows up as low acceptance, never as wrong tokens
    assert eng.spec_stats()["accept_rate"] < 0.9


@pytest.mark.slow
def test_spec_parity_under_eviction(params, draft):
    """Pool pressure during speculative rounds forces recompute-style
    preemption; parity must survive it (same pin the plain engine has)."""
    dcfg, dparams = draft
    rng = np.random.default_rng(3)
    trace = [(rng.integers(0, 96, 8).astype(np.int32), 40) for _ in range(3)]
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=10,
        temperature=0.0, cache_dtype=jnp.float32,
        draft_params=dparams, draft_config=dcfg, draft_shares_cache=True,
    )
    uids = [eng.submit(p, m) for p, m in trace]
    done = eng.run()
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(done[u].tokens, np.asarray(ref[0]))


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_spec_rollback_is_page_aligned(params):
    """After every speculative round, a live slot holds EXACTLY
    ceil(length / page_size) pages — rejected tail pages went back to the
    free list, the partial last page keeps its stale (masked) columns, and
    nothing was rewritten on device. A wrong-weights draft forces frequent
    rejection so the rollback path actually runs."""
    dcfg = dataclasses.replace(CFG, n_layer=1)
    dparams = GPT.init(dcfg, jax.random.PRNGKey(99))
    rng = np.random.default_rng(5)
    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, prefill_chunk=16,
        temperature=0.0, cache_dtype=jnp.float32,
        draft_params=dparams, draft_config=dcfg, spec_k_max=4,
        spec_adapt=False,  # keep k at 4: maximal speculative overhang
    )
    uids = [
        eng.submit(rng.integers(0, 96, n).astype(np.int32), m)
        for n, m in ((11, 20), (19, 16))
    ]
    rejected_rounds = 0
    while not eng.idle:
        eng.step()
        held = 0
        for slot in eng.slots:
            if slot is None:
                continue
            assert len(slot.pages) == -(-slot.length // eng.page_size), (
                slot.length, slot.pages,
            )
            held += len(slot.pages)
        # conservation: every page is either free or held by a live slot
        assert eng.allocator.free_count + held == eng.allocator.num_pages - 1
        rejected_rounds += eng._spec_drafted > eng._spec_accepted
    assert rejected_rounds > 0, "draft never rejected — rollback untested"
    assert set(eng.finished) == set(uids)


def test_spec_statistical_rejection_sampler():
    """Satellite pin: with a deliberately WRONG draft distribution, the
    token the sampler emits at a position is still distributed as the
    warped TARGET softmax — 10k vectorized draws, total-variation
    tolerance. This is the Leviathan exactness guarantee as a number."""
    V, K, B = 16, 2, 10_000
    rng = np.random.default_rng(7)
    t_log = rng.normal(0.0, 1.5, (1, K + 1, V)).astype(np.float32)
    # wrong draft: an independent draw — far from the target
    q_log = rng.normal(0.0, 1.5, (1, K, V)).astype(np.float32)
    p = np.asarray(jax.nn.softmax(jnp.asarray(t_log[0]), axis=-1))
    q = np.asarray(jax.nn.softmax(jnp.asarray(q_log[0]), axis=-1))
    tv_pq = 0.5 * np.abs(p[0] - q[0]).sum()
    assert tv_pq > 0.25, f"test has no power: draft too close (TV={tv_pq})"

    # drafts sampled FROM the draft distribution (its job in the protocol)
    drafts = np.stack(
        [rng.choice(V, size=B, p=q[i]) for i in range(K)], axis=1
    ).astype(np.int32)
    n_accept, out = speculative_accept(
        jnp.asarray(np.broadcast_to(t_log, (B, K + 1, V))),
        jnp.asarray(np.broadcast_to(q[None], (B, K, V))),
        jnp.asarray(drafts),
        jax.random.PRNGKey(0),
        temperature=1.0,
    )
    out = np.asarray(out)
    first = out[:, 0]  # accepted d_1 or its correction: must be ~ p_1
    emp = np.bincount(first, minlength=V) / B
    tv = 0.5 * np.abs(emp - p[0]).sum()
    assert tv < 0.03, f"emitted dist deviates from target: TV={tv}"
    # and it must NOT follow the draft (the wrong distribution)
    tv_q = 0.5 * np.abs(emp - q[0]).sum()
    assert tv_q > 0.15, f"emitted dist tracks the DRAFT: TV={tv_q}"

    # greedy degenerates to argmax equality: emitted = target argmax chain
    n0, out0 = speculative_accept(
        jnp.asarray(np.broadcast_to(t_log, (4, K + 1, V))),
        jnp.asarray(np.broadcast_to(q[None], (4, K, V))),
        jnp.asarray(drafts[:4]),
        None,
        temperature=0.0,
    )
    first0 = np.asarray(out0)[:, 0]
    tgt0 = int(np.argmax(t_log[0, 0]))
    ok = (drafts[:4, 0] == tgt0) | (first0 == tgt0)
    assert ok.all()


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_spec_eos_finishes_mid_round(params, draft):
    """EOS inside an accepted speculative chain truncates the request at
    the EOS token, frees the slot, and discards the rest of the round."""
    dcfg, dparams = draft
    p = _trace()[0][0]
    probe = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, temperature=0.0,
        cache_dtype=jnp.float32, draft_params=dparams, draft_config=dcfg,
        draft_shares_cache=True,
    )
    u = probe.submit(p, 10)
    gen = probe.run()[u].tokens[len(p):]
    # the first token value whose occurrence index is unique-so-far keeps
    # the expected stop position well-defined (greedy chains repeat fast)
    eos_idx = next(i for i in range(len(gen)) if gen[i] not in gen[:i])
    eos = int(gen[eos_idx])

    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, temperature=0.0,
        cache_dtype=jnp.float32, draft_params=dparams, draft_config=dcfg,
        draft_shares_cache=True,
    )
    u2 = eng.submit(p, 10, eos_id=eos)
    out = eng.run()[u2].tokens
    assert out[-1] == eos and len(out) == len(p) + eos_idx + 1
    assert eng.allocator.free_count == eng.allocator.num_pages - 1
    assert eng.idle


def test_spec_engine_validation(params, draft):
    dcfg, dparams = draft
    with pytest.raises(ValueError, match="come together"):
        ServeEngine(CFG, params, draft_params=dparams)
    with pytest.raises(ValueError, match="power of two"):
        ServeEngine(
            CFG, params, draft_params=dparams, draft_config=dcfg, spec_k_max=3
        )
    with pytest.raises(ValueError, match="spec_k_min"):
        ServeEngine(
            CFG, params, draft_params=dparams, draft_config=dcfg,
            spec_k_max=2, spec_k_min=4,
        )
    with pytest.raises(ValueError, match="block_size"):
        ServeEngine(
            CFG, params, draft_params=dparams,
            draft_config=dataclasses.replace(dcfg, block_size=128),
        )
    with pytest.raises(ValueError, match="layer-prefix"):
        ServeEngine(
            CFG, params, draft_params=dparams,
            draft_config=dataclasses.replace(dcfg, n_head=1, n_embd=16),
            draft_shares_cache=True,
        )


def test_spec_config_validation():
    from midgpt_tpu.config import ExperimentConfig, MeshConfig

    base = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8,
        warmup_steps=1, min_lr=1e-4, lr_decay_steps=10, max_steps=10,
        beta2=0.99, weight_decay=0.0, eval_interval=5,
        param_dtype="float32", compute_dtype="float32", g_accum_iters=1,
        shard_model=False, mesh=MeshConfig(data=-1, fsdp=1), model_config=CFG,
    )
    ExperimentConfig(**base, spec_layers=2, spec_k_max=8)  # valid
    with pytest.raises(ValueError, match="spec_layers"):
        ExperimentConfig(**base, spec_layers=CFG.n_layer)
    with pytest.raises(ValueError, match="power of two"):
        ExperimentConfig(**base, spec_k_max=6)
    with pytest.raises(ValueError, match="spec_k_min"):
        ExperimentConfig(**base, spec_k_min=8, spec_k_max=4)


def test_verify_program_has_no_in_loop_pool_copies():
    """ISSUE acceptance HLO pin, via the shared census helper the audit CLI
    uses: the verify program's layer loop (decode_layer_scan=True — the
    lowering that HAS a while body) contains zero pool-sized copies, and
    the unrolled lowering contains zero pool-sized copies anywhere — the
    speculative writes alias through the carry exactly like decode's."""
    from midgpt_tpu.analysis.hlo_audit import while_body_pool_copies
    from midgpt_tpu.sampling import serve

    B, ps, n_pages, K = 2, 8, 12, 2
    for scan in (True, False):
        cfg = dataclasses.replace(CFG, n_layer=2, decode_layer_scan=scan)
        L, H, C = cfg.n_layer, cfg.n_head, cfg.head_dim
        mp = cfg.block_size // ps
        abstract = jax.eval_shape(
            lambda k: GPT.init(cfg, k), jax.random.PRNGKey(0)
        )
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, jnp.bfloat16), abstract
        )
        cache = jax.eval_shape(
            lambda: PagedKVCache.init(cfg, num_pages=n_pages, page_size=ps)
        )
        txt = (
            serve._spec_verify_chunk.lower(
                cfg,
                abstract,
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((K, B), jnp.int32),
                jax.ShapeDtypeStruct((K, B, cfg.vocab_size), jnp.float32),
                cache,
                jax.ShapeDtypeStruct((B, mp), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.int32),
                jax.ShapeDtypeStruct((B,), jnp.bool_),
                0.0,
                None,
                None,
                "gather",
                None,
            )
            .compile()
            .as_text()
        )
        pool = f"bf16[{L},{H},{n_pages},{ps},{C}]"
        census = while_body_pool_copies(txt, pool)
        offenders = {b: ls for b, ls in census.items() if ls}
        assert not offenders, f"scan={scan}: in-loop pool copies {offenders}"
        if scan:
            assert census, "layer scan lowered without a while body?"
        else:
            # no loop at all: the whole program must be copy-free
            n_copies = len(re.findall(rf"= {re.escape(pool)}[^=]*copy\(", txt))
            assert n_copies == 0, f"unrolled verify copies the pool {n_copies}x"
