"""Ulysses (all-to-all) sequence parallelism parity vs the oracle.

Second context-parallel schedule next to the ring (parallel/ulysses.py):
the sequence sharding is traded for a head sharding by one all-to-all and
attention runs dense per head group. Same parity bar as tests/test_ring.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from midgpt_tpu.ops.attention import naive_causal_attention
from midgpt_tpu.parallel.ulysses import ulysses_attention_sharded


def _mesh(sp: int) -> Mesh:
    devs = np.array(jax.devices()[: 2 * sp]).reshape(2, 1, sp)
    return Mesh(devs, ("data", "fsdp", "sp"))


def _qkv(B=4, H=4, T=128, C=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (B, H, T, C), dtype) for k in ks)


@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_matches_naive_forward(sp):
    q, k, v = _qkv()
    mesh = _mesh(sp)
    out = ulysses_attention_sharded(q, k, v, mesh)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match(sp=2):
    """AD through the all-to-alls (self-transposing) equals oracle AD."""
    q, k, v = _qkv(B=2, H=2, T=64, C=8)
    mesh = _mesh(sp)

    def loss_uly(q, k, v):
        return jnp.sum(jnp.sin(ulysses_attention_sharded(q, k, v, mesh)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gu, gf in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gf), atol=3e-5, rtol=3e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_ulysses_train_step_matches_naive_sp1():
    """One full training step on a (data=2, fsdp=2, sp=2) mesh with
    attn_impl='ulysses' reproduces the naive sp=1 oracle's loss."""
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPTConfig
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.train import init_state, make_train_step

    mc = GPTConfig(block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=64)
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=2,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
    )
    oracle_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=4, sp=1), model_config=mc, **base
    )
    uly_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=2, sp=2),
        model_config=dataclasses.replace(mc, attn_impl="ulysses"),
        **base,
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, mc.vocab_size, (2, 8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    for name, cfg in (("oracle", oracle_cfg), ("ulysses", uly_cfg)):
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, _, _ = make_train_step(cfg, optimizer, mesh, specs)
        shard_seq = cfg.model_config.attn_impl == "ulysses"
        xg = make_global_batch(x, mesh, batch_spec(shard_seq=shard_seq))
        yg = make_global_batch(y, mesh, batch_spec(shard_seq=shard_seq))
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["ulysses"], losses["oracle"], rtol=1e-5)


def test_ulysses_bf16_forward_parity():
    """bf16 inputs (the real training dtype) against the f32 oracle at bf16
    tolerance — same bar as the ring's T=4096 bf16 check."""
    q, k, v = _qkv(B=2, H=4, T=256, C=16, dtype=jnp.bfloat16)
    mesh = _mesh(4)
    out = ulysses_attention_sharded(q, k, v, mesh)
    ref = naive_causal_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_kernel_path_forward_parity(sp, monkeypatch):
    """The Pallas flash kernel serves the inner dense attention (what a real
    TPU slice runs): interpret mode on CPU, forced via the kernel module's
    off-TPU switch. All-to-alls wrap the kernel; parity must hold."""
    import importlib

    fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")

    monkeypatch.setattr(fa, "RUN_INTERPRET_OFF_TPU", True)
    q, k, v = _qkv(B=2, H=4, T=128, C=32)
    mesh = _mesh(sp)
    out = ulysses_attention_sharded(q, k, v, mesh)
    ref = naive_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_kernel_path_gradients(monkeypatch, sp=2):
    """Backward through all_to_all (self-transposing) + the flash kernel's
    custom VJP equals oracle AD — the exact program a TPU training step
    differentiates."""
    import importlib

    fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")

    monkeypatch.setattr(fa, "RUN_INTERPRET_OFF_TPU", True)
    q, k, v = _qkv(B=2, H=2, T=128, C=32)
    mesh = _mesh(sp)

    def loss_uly(q, k, v):
        return jnp.sum(jnp.sin(ulysses_attention_sharded(q, k, v, mesh)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(naive_causal_attention(q, k, v)))

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gu, gf, name in zip(g_uly, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gu), np.asarray(gf), atol=3e-5, rtol=3e-5, err_msg=f"d{name}"
        )


def test_ulysses_kernel_jnp_paths_agree(monkeypatch):
    """Kernel-served inner attention (interpret mode) vs the blockwise jnp
    inner attention: the all-to-all schedule is identical, so the two inner
    impls must agree."""
    import importlib

    fa = importlib.import_module("midgpt_tpu.kernels.flash_attention")

    q, k, v = _qkv(B=2, H=4, T=256, C=16)
    mesh = _mesh(4)
    monkeypatch.setattr(fa, "RUN_INTERPRET_OFF_TPU", True)
    out_k = ulysses_attention_sharded(q, k, v, mesh, impl="flash")
    monkeypatch.setattr(fa, "RUN_INTERPRET_OFF_TPU", False)
    out_j = ulysses_attention_sharded(q, k, v, mesh, impl="blockwise")
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_j), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ulysses_shard_map_fsdp_train_step_matches_gspmd():
    """Ulysses composes with the explicit shard_map ZeRO-3 schedule the same
    way the ring does (parallel/shard_map_fsdp.py): one body, weight gathers
    on 'fsdp', head<->sequence all_to_alls on 'sp'. Same loss as the GSPMD
    Ulysses step, the naive sp=1 oracle, AND the tp x sp composition
    (heads sharded over tp, then sp)."""
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPTConfig
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.train import init_state, make_train_step

    mc = GPTConfig(block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=64)
    base = dict(
        rundir="",
        data_dir="",
        learning_rate=1e-2,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-3,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.99,
        weight_decay=1e-4,
        eval_interval=25,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        eval_steps=2,
    )
    oracle_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=4, sp=1), model_config=mc, **base
    )
    uly = dataclasses.replace(mc, attn_impl="ulysses")
    gspmd_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=2, sp=2), model_config=uly, **base
    )
    sm_cfg = ExperimentConfig(
        mesh=MeshConfig(data=2, fsdp=2, sp=2), model_config=uly,
        fsdp_mode="shard_map", **base,
    )
    # Megatron-TP composition (train.py passes head_axis='tp': heads shard
    # over tp x sp, all-to-alls ride 'sp' within each head group)
    tp_cfg = ExperimentConfig(
        mesh=MeshConfig(data=1, fsdp=2, sp=2, tp=2), model_config=uly, **base
    )

    rng = np.random.default_rng(0)
    x = rng.integers(0, mc.vocab_size, (1, 8, 64), dtype=np.int32)
    y = np.roll(x, -1, axis=-1)
    losses = {}
    for name, cfg in (
        ("oracle", oracle_cfg), ("gspmd", gspmd_cfg), ("shard_map", sm_cfg),
        ("tp_sp", tp_cfg),
    ):
        mesh = make_mesh(cfg.mesh)
        params, opt_state, specs, optimizer = init_state(cfg, mesh)
        step, _, _ = make_train_step(cfg, optimizer, mesh, specs)
        shard_seq = cfg.model_config.attn_impl == "ulysses"
        xg = make_global_batch(x, mesh, batch_spec(shard_seq=shard_seq))
        yg = make_global_batch(y, mesh, batch_spec(shard_seq=shard_seq))
        _, _, loss = step(params, opt_state, xg, yg, jax.random.PRNGKey(0))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["gspmd"], losses["oracle"], rtol=1e-5)
    np.testing.assert_allclose(losses["shard_map"], losses["oracle"], rtol=1e-5)
    np.testing.assert_allclose(losses["tp_sp"], losses["oracle"], rtol=1e-5)


def test_ulysses_rejects_indivisible_heads_directly():
    """Direct ulysses_attention callers (bypassing config validation) get a
    ValueError, not an all_to_all shape error — and not an `assert` that
    python -O strips."""
    q, k, v = _qkv(B=2, H=3, T=64, C=8)  # 3 heads over sp=2
    mesh = _mesh(2)
    with pytest.raises(ValueError, match="n_head"):
        ulysses_attention_sharded(q, k, v, mesh)


def test_ulysses_config_validation():
    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.models.gpt import GPTConfig

    kw = dict(
        rundir="", data_dir="", learning_rate=1e-3, batch_size=8, warmup_steps=1,
        min_lr=1e-4, lr_decay_steps=10, max_steps=10, beta2=0.99, weight_decay=0.0,
        eval_interval=5, param_dtype="float32", compute_dtype="float32",
        g_accum_iters=1, shard_model=True,
    )
    # n_head=2 over sp=4: no whole head per device -> rejected up front
    with pytest.raises(ValueError, match="n_head"):
        ExperimentConfig(
            mesh=MeshConfig(data=2, fsdp=1, sp=4),
            model_config=GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                                   n_head=2, n_embd=64, attn_impl="ulysses"),
            **kw,
        )
