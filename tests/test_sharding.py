"""Mesh/FSDP tests on the 8-device virtual CPU mesh (test infra the
reference never had — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.data import make_global_batch
from midgpt_tpu.parallel.fsdp import constrain, fsdp_param_specs
from midgpt_tpu.parallel.mesh import batch_spec, make_mesh

CFG = GPTConfig(block_size=32, vocab_size=256, n_layer=2, n_head=2, n_embd=64)


def test_devices_available():
    assert jax.device_count() == 8


def test_make_mesh_shapes():
    mesh = make_mesh(MeshConfig(data=-1, fsdp=4, sp=1))
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4, "sp": 1, "tp": 1, "pp": 1, "ep": 1}
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sp=2))
    assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "sp": 2, "tp": 1, "pp": 1, "ep": 1}
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sp=1, tp=2))
    assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "sp": 1, "tp": 2, "pp": 1, "ep": 1}


def test_make_mesh_clamps_fsdp_on_small_counts():
    # 8 devices, fsdp=16 requested -> clamp to 8
    mesh = make_mesh(MeshConfig(data=-1, fsdp=16, sp=1))
    assert dict(mesh.shape)["fsdp"] == 8


def test_fsdp_specs_shard_large_replicate_small():
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    # Big 2D+ leaves sharded over 'fsdp' on exactly one axis:
    assert specs.wte == P(None, "fsdp")
    assert specs.lm_head == P(None, "fsdp")
    assert specs.blocks.attn.wqkv == P(None, None, None, "fsdp")
    assert specs.blocks.mlp.w_up == P(None, None, "fsdp")
    # per-head norm scales: (L, C) with C=32 not divisible by 4 on last axis?
    # C=32 divisible; but skip_leading keeps axis 1: either sharded or replicated is legal.
    # With min_size=big, everything replicated:
    specs2 = fsdp_param_specs(params, mesh, shard_model=True, min_size=2**30)
    assert all(s == P() for s in jax.tree.leaves(specs2))
    specs3 = fsdp_param_specs(params, mesh, shard_model=False)
    assert all(s == P() for s in jax.tree.leaves(specs3))


def test_fsdp_indivisible_falls_back_replicated():
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, sp=1))
    x = jnp.zeros((3, 5, 7))
    specs = fsdp_param_specs({"w": x}, mesh, shard_model=True, min_size=0)
    assert specs["w"] == P()


def test_sharded_forward_matches_single_device():
    """FSDP-sharded forward must be numerically identical to unsharded."""
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1))
    params = GPT.init(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, CFG.vocab_size)

    base = GPT.apply(CFG, params, tokens, inference=True)

    specs = fsdp_param_specs(params, mesh, shard_model=True, min_size=0)
    sharded_params = jax.jit(lambda p: constrain(p, specs, mesh))(params)
    xg = make_global_batch(np.asarray(tokens), mesh, batch_spec(with_accum=False))
    out = jax.jit(
        lambda p, t: GPT.apply(CFG, p, t, inference=True)
    )(sharded_params, xg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(base), atol=2e-5, rtol=2e-5
    )


def test_make_global_batch_sharding():
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sp=1))
    x = np.arange(16 * 8, dtype=np.int32).reshape(16, 8)
    g = make_global_batch(x, mesh, batch_spec(with_accum=False))
    assert g.shape == (16, 8)
    np.testing.assert_array_equal(np.asarray(g), x)
    # batch axis sharded over data*fsdp = 8 ways
    assert len(g.sharding.device_set) == 8
