"""Unified observability layer (midgpt_tpu/obs/): fake-clock tracer and
metrics units, the Chrome-trace export contract that tools/trace_view.py
and Perfetto consume, round-decomposition arithmetic, the engine-level
span taxonomy on a CPU mesh, the obs-on == obs-off greedy bit-parity
pin, and the chaos-path flight-recorder dump.

Pool geometry note: engine tests use num_pages=33 — disjoint from the
25-page pristine recompile-pin geometry and the 29/31-page tp/warm-pin
geometries (tests/test_recompile_pins.py); the obs-toggle compile pin
itself lives there with the other pins.
"""

import importlib.util
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import midgpt_tpu.obs as obs_mod
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.obs import (
    NULL_TRACER,
    Observability,
    Tracer,
    dump_flight_recorder,
    flight_recorder,
)
from midgpt_tpu.obs.metrics import Histogram, MetricsRegistry
from midgpt_tpu.obs.trace import _NULL_SPAN
from midgpt_tpu.robustness.chaos_serve import run_serving_chaos
from midgpt_tpu.sampling.serve import ServeEngine

_TOOLS = pathlib.Path(__file__).resolve().parents[1] / "tools"


def _load_trace_view():
    spec = importlib.util.spec_from_file_location(
        "trace_view", _TOOLS / "trace_view.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    """Deterministic injected clock: each call returns the current time
    then advances by `step` — so every clock read is visible in the
    expected timestamps below."""

    def __init__(self, start=100.0, step=1.0):
        self.t = start
        self.step = step

    def __call__(self):
        t = self.t
        self.t += self.step
        return t


# ---------------------------------------------------------------------------
# Tracer units (JAX-free, fake clock)
# ---------------------------------------------------------------------------


def test_span_nesting_records_both_levels_with_real_durations():
    clock = FakeClock(start=100.0, step=1.0)
    tr = Tracer(capacity=8, clock=clock)  # _t_base = 100.0
    with tr.span("outer", "phase", "engine"):  # t0 = 101
        with tr.span("inner", "phase", "engine"):  # t0 = 102
            pass  # inner exit reads 103
    # outer exit reads 104
    evs = tr.events()
    assert [(e[1], e[4], e[5]) for e in evs] == [
        ("inner", 102.0, 1.0),
        ("outer", 101.0, 3.0),  # closes after inner: completion order
    ]
    assert all(e[0] == "X" and e[2] == "phase" and e[3] == "engine" for e in evs)


def test_export_rebases_to_birth_and_assigns_tid_lanes():
    clock = FakeClock(start=50.0, step=1.0)
    tr = Tracer(capacity=8, clock=clock)  # birth at t=50
    tr.complete("round", "round", "engine", 52.0, 0.5)
    tr.instant("rollback", "fault", "train")
    tr.async_begin("request", "uid-7", "lifecycle", "server")
    tr.async_end("request", "uid-7", "lifecycle", "server")
    out = tr.export()
    by_name = {e["name"]: e for e in out if e["ph"] != "M"}
    # complete: ts/dur microseconds rebased to the tracer's birth
    assert by_name["round"]["ph"] == "X"
    assert by_name["round"]["ts"] == pytest.approx(2e6)
    assert by_name["round"]["dur"] == pytest.approx(0.5e6)
    # instant: thread-scoped
    assert by_name["rollback"]["ph"] == "i" and by_name["rollback"]["s"] == "t"
    # async pair shares an id, and b comes before e
    pair = [e for e in out if e.get("id") == "uid-7"]
    assert [e["ph"] for e in pair] == ["b", "e"]
    # tid strings became distinct integer lanes with thread_name metadata
    lanes = {e["args"]["name"]: e["tid"] for e in out if e["ph"] == "M"}
    assert set(lanes) == {"engine", "train", "server"}
    assert len(set(lanes.values())) == 3
    assert by_name["round"]["tid"] == lanes["engine"]
    assert by_name["rollback"]["tid"] == lanes["train"]


def test_ring_keeps_the_tail_and_counts_drops():
    tr = Tracer(capacity=4, clock=FakeClock())
    for i in range(6):
        tr.instant(f"i{i}")
    assert len(tr) == 4
    assert tr.dropped == 2
    # flight-recorder semantics: the OLDEST events fell off
    assert [e[1] for e in tr.events()] == ["i2", "i3", "i4", "i5"]
    tr.clear()
    assert len(tr) == 0 and tr.dropped == 0


def test_dump_is_loadable_by_trace_view(tmp_path):
    tv = _load_trace_view()
    tr = Tracer(capacity=8, clock=FakeClock())
    with tr.span("engine.round", "round", "engine"):
        pass
    path = tr.dump(str(tmp_path / "flight_recorder.json"))
    evs = tv.load_trace(tv.find_trace(str(tmp_path)))
    assert any(e["name"] == "engine.round" for e in evs)
    # raw json is the Chrome container
    with open(path, encoding="utf-8") as fh:
        assert set(json.load(fh)) == {"traceEvents"}


def test_trace_view_rejects_non_trace_json(tmp_path):
    tv = _load_trace_view()
    bad = tmp_path / "not_a_trace.json"
    bad.write_text(json.dumps({"hello": 1}))
    with pytest.raises(ValueError):
        tv.load_trace(str(bad))


def test_null_tracer_is_inert():
    with NULL_TRACER.span("x", "y", "z") as s:
        assert s is _NULL_SPAN  # one shared handle, no allocation
    NULL_TRACER.complete("a", "b", "c", 0.0, 1.0)
    NULL_TRACER.instant("a")
    NULL_TRACER.async_begin("a", "id")
    NULL_TRACER.async_end("a", "id")
    assert len(NULL_TRACER) == 0
    assert NULL_TRACER.events() == [] and NULL_TRACER.export() == []
    assert NULL_TRACER.dropped == 0


# ---------------------------------------------------------------------------
# Metrics units
# ---------------------------------------------------------------------------


def test_histogram_nearest_rank_percentiles():
    h = Histogram("lat")
    for v in range(1, 101):
        h.observe(float(v))
    s = h.summary()
    assert s["n"] == 100
    assert s["mean"] == pytest.approx(50.5)
    assert s["p50"] == 50.0  # nearest-rank: ceil(0.5*100)-1 -> sorted[49]
    assert s["p95"] == 95.0
    assert s["max"] == 100.0


def test_histogram_empty_summary_is_zeros():
    assert Histogram("empty").summary() == {
        "n": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0, "max": 0.0,
    }


def test_histogram_reservoir_is_bounded_but_counts_exact():
    h = Histogram("lat", maxlen=8)
    for v in range(100):
        h.observe(float(v))
    s = h.summary()
    assert s["n"] == 100  # exact count survives the bounded reservoir
    assert s["max"] == 99.0
    assert s["p50"] >= 92.0  # percentiles come from the recent tail


def test_registry_create_or_get_and_snapshot():
    reg = MetricsRegistry()
    c = reg.counter("rounds", "help text")
    c.inc()
    c.inc(2.0)
    assert reg.counter("rounds") is c  # create-or-get, no reset
    reg.gauge("backlog").set(7)
    reg.histogram("lat").observe(0.25)
    snap = reg.snapshot()
    assert snap["counters"] == {"rounds": 3.0}
    assert snap["gauges"] == {"backlog": 7.0}
    assert snap["histograms"]["lat"]["n"] == 1
    json.dumps(snap)  # the unified stats payload must stay serializable


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("rounds_decomposed", "rounds seen").inc(3)
    reg.gauge("backlog.pages").set(2)  # dot must sanitize to underscore
    reg.histogram("round_dispatch_s").observe(0.002)
    text = reg.to_prometheus()
    assert "# TYPE rounds_decomposed counter\nrounds_decomposed 3" in text
    assert "# TYPE backlog_pages gauge\nbacklog_pages 2" in text
    assert '# TYPE round_dispatch_s summary' in text
    assert 'round_dispatch_s{quantile="0.5"} 0.002' in text
    assert "round_dispatch_s_count 1" in text
    assert text.endswith("\n")


# ---------------------------------------------------------------------------
# Observability bundle: round decomposition + dump
# ---------------------------------------------------------------------------


def test_record_round_decomposition_arithmetic():
    obs = Observability(clock=FakeClock())
    # four boundary readings: dispatch 2 ms, device wait 8 ms, post 1 ms
    obs.record_round("decode", "engine", 10.000, 10.002, 10.010, 10.011)
    d = obs.round_decomp()
    assert d["rounds"] == 1
    assert d["dispatch"]["mean_ms"] == pytest.approx(2.0)
    assert d["device_wait"]["p50_ms"] == pytest.approx(8.0)
    assert d["host_post"]["max_ms"] == pytest.approx(1.0)
    # the three phase spans landed in the ring with the EXPLICIT boundary
    # timestamps — record_round must not read the clock again
    evs = obs.tracer.events()
    assert [(e[1], e[4], e[5]) for e in evs] == [
        ("decode.dispatch", 10.000, pytest.approx(0.002)),
        ("decode.device_wait", 10.002, pytest.approx(0.008)),
        ("decode.host_post", 10.010, pytest.approx(0.001)),
    ]
    assert all(e[2] == "round" and e[3] == "engine" for e in evs)
    snap = obs.snapshot()
    assert snap["enabled"] is True
    assert snap["spans"] == 3 and snap["spans_dropped"] == 0
    assert snap["round_decomp"]["rounds"] == 1


def test_observability_dump_writes_trace_and_prom(tmp_path):
    tv = _load_trace_view()
    obs = Observability(clock=FakeClock())
    obs.record_round("decode", "engine", 1.0, 2.0, 3.0, 4.0)
    path = obs.dump(str(tmp_path))
    assert path == str(tmp_path / "flight_recorder.json")
    evs = tv.load_trace(path)
    assert {e["name"] for e in evs} >= {
        "decode.dispatch", "decode.device_wait", "decode.host_post",
    }
    prom = (tmp_path / "flight_recorder.prom").read_text()
    assert "rounds_decomposed 1" in prom


def test_global_flight_recorder_lazy_and_dump_none(tmp_path, monkeypatch):
    monkeypatch.setattr(obs_mod, "_FLIGHT", None)
    # never touched -> no file, no empty lie
    assert dump_flight_recorder(str(tmp_path)) is None
    assert not list(tmp_path.iterdir())
    fr = flight_recorder()
    assert flight_recorder() is fr  # singleton
    fr.tracer.instant("supervisor.rollback", "fault", "train")
    path = dump_flight_recorder(str(tmp_path))
    tv = _load_trace_view()
    assert any(
        e["name"] == "supervisor.rollback" for e in tv.load_trace(path)
    )


# ---------------------------------------------------------------------------
# Engine-level: span taxonomy, nesting, and the obs-toggle parity pin
# ---------------------------------------------------------------------------

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def _trace(seed=0, n=4):
    rng = np.random.default_rng(seed)
    lens = rng.integers(4, 30, size=n)
    return (
        [rng.integers(1, CFG.vocab_size, size=int(l)).astype(np.int32)
         for l in lens],
        [int(b) for b in rng.integers(5, 14, size=n)],
    )


def _run(params, obs):
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=33,
        prefill_chunk=8, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32, obs=obs,
    )
    prompts, budgets = _trace()
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    done = eng.run()
    return eng, [done[u].tokens.tolist() for u in uids]


def test_engine_emits_span_taxonomy_and_rounds_contain_decode(params):
    """A served trace carries the documented span taxonomy
    (docs/OBSERVABILITY.md) and every decode phase span is time-contained
    in an engine.round envelope — one shared clock, four boundary reads."""
    obs = Observability()
    eng, toks = _run(params, obs)
    assert all(len(t) > 0 for t in toks)
    evs = obs.tracer.events()  # (kind, name, cat, tid, t, dur, ident, args)
    names = {e[1] for e in evs}
    assert {
        "engine.round", "engine.expire", "engine.admit", "engine.prefill",
        "prefill.chunk", "prefill.first_token",
        "decode.dispatch", "decode.device_wait", "decode.host_post",
        "admitted", "finish",
    } <= names
    rounds = sorted(
        (e[4], e[4] + e[5]) for e in evs
        if e[0] == "X" and e[1] == "engine.round"
    )
    assert rounds
    phases = [
        (e[4], e[4] + e[5]) for e in evs
        if e[0] == "X" and e[1].startswith("decode.")
    ]
    assert phases
    for t0, t1 in phases:
        assert any(r0 <= t0 and t1 <= r1 for r0, r1 in rounds), (
            f"decode span [{t0}, {t1}] outside every engine.round envelope"
        )
    # unified stats schema: one decomposition per DECODE round (prefill-
    # only rounds get an engine.round envelope but no decode dispatch)
    st = eng.stats()["obs"]
    assert st["enabled"] is True
    decomp = st["round_decomp"]
    assert decomp["rounds"] == len(phases) // 3 > 0
    assert decomp["rounds"] <= len(rounds)
    assert decomp["device_wait"]["n"] == decomp["rounds"]
    assert decomp["dispatch"]["p95_ms"] >= 0.0


def test_obs_toggle_preserves_greedy_token_streams(params):
    """The acceptance pin: wiring an Observability through the engine
    changes zero emitted tokens — instrumentation reads clocks and appends
    tuples, it never touches scheduling state or device buffers."""
    eng_off, base = _run(params, None)
    assert eng_off.stats()["obs"] == {"enabled": False}
    _, traced = _run(params, Observability())
    assert traced == base


def test_serving_chaos_leaves_loadable_dump(tmp_path):
    """Crash-path artifact: a chaos run with a trace_dir leaves a
    Chrome-trace flight recorder (plus .prom metrics) for the FAULT pass,
    fault instant included."""
    s = run_serving_chaos("kill_mid_decode@6", seed=0, trace_dir=str(tmp_path))
    assert s["mode"] == "serve"
    assert s["parity_ok"] == s["parity_checked"] > 0
    assert s["trace"] == str(tmp_path / "flight_recorder.json")
    tv = _load_trace_view()
    evs = tv.load_trace(tv.find_trace(str(tmp_path)))
    names = {e["name"] for e in evs}
    assert "fault.kill_mid_decode" in names
    assert "engine.round" in names
    assert (tmp_path / "flight_recorder.prom").exists()
