"""graftcheck pass-1 lint: one deliberate-violation fixture per rule
(GC001-GC006), suppression semantics, and the CLI contract (nonzero exit
with rule ID + file:line on violations; --json is one schema-conformant
line). The repo-wide "tree is clean" gate lives in tests/test_lint_clean.py.
"""

import json
import os
import subprocess
import sys

import pytest

from midgpt_tpu.analysis.bench_contract import check_bench_stdout
from midgpt_tpu.analysis.lint import lint_source, parse_suppressions

# One minimal violating snippet per rule; (rule, expected line) is asserted
# exactly so a rule that silently stops firing fails loudly here.
FIXTURES = {
    "GC001": (
        """\
import jax
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[0] = jax.lax.cond(x_ref[0] > 0, lambda: x_ref[0], lambda: x_ref[1])

def run(x):
    return pl.pallas_call(_kern, out_shape=x)(x)
""",
        5,
    ),
    "GC002": (
        """\
import jax

@jax.jit
def f(x):
    return float(x) + 1.0
""",
        5,
    ),
    "GC003": (
        """\
from jax.experimental import pallas as pl

spec = pl.BlockSpec((4, 100), lambda i: (i, 0))
""",
        3,
    ),
    "GC004": (
        """\
import functools

import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def f(buf, x):
    return buf + x

def run(buf, x):
    y = f(buf, x)
    return y + buf.sum()
""",
        11,
    ),
    "GC005": (
        """\
import time

import jax

@jax.jit
def f(x):
    return x + time.time()
""",
        7,
    ),
    "GC006": (
        """\
def attn(q):
    \"\"\"Numerical parity with the fused path is exact.\"\"\"
    return q
""",
        1,
    ),
    "GC007": (
        """\
def flush(mngr, state):
    try:
        mngr.save(0, state)
    except Exception:
        pass
""",
        4,
    ),
    "GC008": (
        """\
import jax.numpy as jnp

def quantize(x, scale):
    return (x / scale).astype(jnp.int8)
""",
        4,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_each_rule_fires_on_its_fixture(rule):
    src, line = FIXTURES[rule]
    active, suppressed = lint_source(src, f"{rule}.py")
    assert [(f.rule, f.line) for f in active] == [(rule, line)], active
    assert not suppressed


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_each_rule_suppressible_inline(rule):
    src, line = FIXTURES[rule]
    lines = src.splitlines()
    lines[line - 1] += f"  # graftcheck: disable={rule} — fixture: rule under test"
    active, suppressed = lint_source("\n".join(lines) + "\n", f"{rule}.py")
    assert active == []
    assert [(f.rule, f.line) for f in suppressed] == [(rule, line)]


def test_suppression_justification_is_captured():
    src = "x = 1  # graftcheck: disable=GC003 — spans the full array dim\n"
    (s,) = parse_suppressions(src)
    assert s.rules == ("GC003",) and s.line == 1
    assert "full array dim" in s.justification


def test_clean_code_with_traced_scopes_passes():
    src = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    n = int(x.shape[0])  # static shape math is not a host sync
    return x * n + float("-inf")
"""
    active, _ = lint_source(src, "clean.py")
    assert active == []


def test_gc004_accepts_rebinding_and_flags_loop_reuse():
    ok = """\
import functools

import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def f(buf, x):
    return buf + x

def run(buf, xs):
    for x in xs:
        buf = f(buf, x)
    return buf
"""
    active, _ = lint_source(ok, "ok.py")
    assert active == []
    bad = ok.replace("        buf = f(buf, x)", "        out = f(buf, x)").replace(
        "    return buf\n", "    return out\n"
    )
    active, _ = lint_source(bad, "bad.py")
    assert [f.rule for f in active] == ["GC004"]


def test_gc008_accepts_rounded_cast_and_string_dtype():
    """The blessed quantization shape — round (possibly under clip) before
    the int8 cast — passes; a truncating cast via the STRING dtype
    spelling is still caught."""
    ok = """\
import jax.numpy as jnp

def quantize(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
"""
    active, _ = lint_source(ok, "ok.py")
    assert active == []
    bad = 'def f(x):\n    return x.astype("int8")\n'
    active, _ = lint_source(bad, "bad.py")
    assert [(f.rule, f.line) for f in active] == [("GC008", 2)]
    # clip alone is NOT rounding evidence (it still truncates)
    clip_only = """\
import jax.numpy as jnp

def f(x):
    return jnp.clip(x, -127, 127).astype(jnp.int8)
"""
    active, _ = lint_source(clip_only, "clip.py")
    assert [f.rule for f in active] == ["GC008"]


def test_gc006_accepts_reference_or_test_citation():
    for cite in ("reference model.py:76", "tests/test_flash.py"):
        src = f'def f(q):\n    """Parity pinned ({cite})."""\n    return q\n'
        active, _ = lint_source(src, "cited.py")
        assert active == [], cite


# ----------------------------------------------------------------------
# CLI contract
# ----------------------------------------------------------------------


def _run_cli(*args):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    return subprocess.run(
        [sys.executable, "-m", "midgpt_tpu.analysis", *args],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )


def test_cli_nonzero_with_rule_id_and_location_per_fixture(tmp_path):
    """The acceptance pin: the CLI exits nonzero on the fixture violations
    and names each one by rule ID and file:line."""
    expected = []
    for rule, (src, line) in FIXTURES.items():
        p = tmp_path / f"fixture_{rule.lower()}.py"
        p.write_text(src)
        expected.append((rule, str(p), line))
    proc = _run_cli("--json", str(tmp_path))
    assert proc.returncode == 1, proc.stderr
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert rec["count"] == len(FIXTURES)
    got = {(f["rule"], f["path"], f["line"]) for f in rec["findings"]}
    for rule, path, line in expected:
        assert (rule, path, line) in got, (rule, got)


def test_cli_exit_zero_on_clean_file(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x + 1\n")
    proc = _run_cli(str(p))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rules_subset(tmp_path):
    """--rules narrows the run; unknown rules are a usage error."""
    p = tmp_path / "two.py"
    p.write_text(FIXTURES["GC003"][0] + FIXTURES["GC006"][0])
    proc = _run_cli("--json", "--rules", "GC006", str(p))
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert [f["rule"] for f in rec["findings"]] == ["GC006"]
    assert _run_cli("--rules", "GC999", str(p)).returncode == 2
