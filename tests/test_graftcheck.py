"""graftcheck pass-1 lint + pass-3 lifecycle + pass-4 concurrency: one
deliberate-violation fixture per rule (GC001-GC011, GC013-GC016;
path-scoped GC012 gets dedicated tests below — it cannot live in FIXTURES
because it only fires under `sampling/` / `robustness/` paths),
suppression semantics, the jit-surface census/diff, and the CLI contract
(nonzero exit with rule ID + file:line on violations; --json is one
schema-conformant line; --fail-on-new gates on the committed baselines).
The repo-wide "tree is clean" gate lives in tests/test_lint_clean.py.
"""

import json
import os
import subprocess
import sys

import pytest

from midgpt_tpu.analysis.bench_contract import check_bench_stdout
from midgpt_tpu.analysis.concurrency import concurrency_source
from midgpt_tpu.analysis.jit_surface import diff_surface, jit_surface
from midgpt_tpu.analysis.lifecycle import lifecycle_source
from midgpt_tpu.analysis.lint import lint_source, parse_suppressions


def check_source(src, path):
    """All three JAX-free passes merged — every fixture must trip exactly
    its own rule and stay clean under the other passes."""
    active, suppressed = lint_source(src, path)
    a3, s3 = lifecycle_source(src, path)
    a4, s4 = concurrency_source(src, path)
    merged = sorted(active + a3 + a4, key=lambda f: (f.line, f.col, f.rule))
    return merged, suppressed + s3 + s4

# One minimal violating snippet per rule; (rule, expected line) is asserted
# exactly so a rule that silently stops firing fails loudly here.
FIXTURES = {
    "GC001": (
        """\
import jax
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[0] = jax.lax.cond(x_ref[0] > 0, lambda: x_ref[0], lambda: x_ref[1])

def run(x):
    return pl.pallas_call(_kern, out_shape=x)(x)
""",
        5,
    ),
    "GC002": (
        """\
import jax

@jax.jit
def f(x):
    return float(x) + 1.0
""",
        5,
    ),
    "GC003": (
        """\
from jax.experimental import pallas as pl

spec = pl.BlockSpec((4, 100), lambda i: (i, 0))
""",
        3,
    ),
    "GC004": (
        """\
import functools

import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def f(buf, x):
    return buf + x

def run(buf, x):
    y = f(buf, x)
    return y + buf.sum()
""",
        11,
    ),
    "GC005": (
        """\
import time

import jax

@jax.jit
def f(x):
    return x + time.time()
""",
        7,
    ),
    "GC006": (
        """\
def attn(q):
    \"\"\"Numerical parity with the fused path is exact.\"\"\"
    return q
""",
        1,
    ),
    "GC007": (
        """\
def flush(mngr, state):
    try:
        mngr.save(0, state)
    except Exception:
        pass
""",
        4,
    ),
    "GC008": (
        """\
import jax.numpy as jnp

def quantize(x, scale):
    return (x / scale).astype(jnp.int8)
""",
        4,
    ),
    # exception-edge leak: pages acquired, then a raise with no cleanup
    "GC009": (
        """\
def handoff(allocator, n):
    pages = allocator.alloc(n)
    if pages is None:
        return None
    if n > 8:
        raise ValueError(n)
    allocator.free(pages)
    return n
""",
        6,
    ),
    # await interleaved inside a mutation-in-progress region
    "GC010": (
        """\
import asyncio

class Server:
    async def rotate(self, item):
        self.slots = []
        await asyncio.sleep(0)
        self.slots = [item]
""",
        6,
    ),
    # unbounded request-derived value at a static jit position
    "GC011": (
        """\
import functools

import jax

@functools.partial(jax.jit, static_argnums=(1,))
def step(x, n):
    return x * n

def drive(x, requests):
    for r in requests:
        x = step(x, r)
    return x
""",
        11,
    ),
    # thread-escape mutation of engine-owned state
    "GC013": (
        """\
import threading

class Serve:
    def start(self):
        threading.Thread(target=self._worker, daemon=True).start()

    def _worker(self):
        self.engine.temperature = 0.0
""",
        8,
    ),
    # allocating (IO-performing) signal handler
    "GC014": (
        """\
import signal

def _on_term(signum, frame):
    with open("/tmp/flag", "w") as fh:
        fh.write("x")

def install():
    signal.signal(signal.SIGTERM, _on_term)
""",
        4,
    ),
    # a lock riding a handoff payload
    "GC015": (
        """\
class Disagg:
    def enqueue(self, uid):
        item = HandoffItem(uid=uid, lock=self._lock)
        self.handoff_queue.push(item)
""",
        3,
    ),
    # structured error raised without its declared fields
    "GC016": (
        """\
def give_up(step):
    raise CheckpointWriteError(f"save at {step} failed")
""",
        2,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_each_rule_fires_on_its_fixture(rule):
    src, line = FIXTURES[rule]
    active, suppressed = check_source(src, f"{rule}.py")
    assert [(f.rule, f.line) for f in active] == [(rule, line)], active
    assert not suppressed


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_each_rule_suppressible_inline(rule):
    src, line = FIXTURES[rule]
    lines = src.splitlines()
    lines[line - 1] += f"  # graftcheck: disable={rule} — fixture: rule under test"
    active, suppressed = check_source("\n".join(lines) + "\n", f"{rule}.py")
    assert active == []
    assert [(f.rule, f.line) for f in suppressed] == [(rule, line)]


def test_suppression_justification_is_captured():
    src = "x = 1  # graftcheck: disable=GC003 — spans the full array dim\n"
    (s,) = parse_suppressions(src)
    assert s.rules == ("GC003",) and s.line == 1
    assert "full array dim" in s.justification


def test_clean_code_with_traced_scopes_passes():
    src = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    n = int(x.shape[0])  # static shape math is not a host sync
    return x * n + float("-inf")
"""
    active, _ = lint_source(src, "clean.py")
    assert active == []


def test_gc004_accepts_rebinding_and_flags_loop_reuse():
    ok = """\
import functools

import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def f(buf, x):
    return buf + x

def run(buf, xs):
    for x in xs:
        buf = f(buf, x)
    return buf
"""
    active, _ = lint_source(ok, "ok.py")
    assert active == []
    bad = ok.replace("        buf = f(buf, x)", "        out = f(buf, x)").replace(
        "    return buf\n", "    return out\n"
    )
    active, _ = lint_source(bad, "bad.py")
    assert [f.rule for f in active] == ["GC004"]


def test_gc008_accepts_rounded_cast_and_string_dtype():
    """The blessed quantization shape — round (possibly under clip) before
    the int8 cast — passes; a truncating cast via the STRING dtype
    spelling is still caught."""
    ok = """\
import jax.numpy as jnp

def quantize(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
"""
    active, _ = lint_source(ok, "ok.py")
    assert active == []
    bad = 'def f(x):\n    return x.astype("int8")\n'
    active, _ = lint_source(bad, "bad.py")
    assert [(f.rule, f.line) for f in active] == [("GC008", 2)]
    # clip alone is NOT rounding evidence (it still truncates)
    clip_only = """\
import jax.numpy as jnp

def f(x):
    return jnp.clip(x, -127, 127).astype(jnp.int8)
"""
    active, _ = lint_source(clip_only, "clip.py")
    assert [f.rule for f in active] == ["GC008"]


def test_gc006_accepts_reference_or_test_citation():
    for cite in ("reference model.py:76", "tests/test_flash.py"):
        src = f'def f(q):\n    """Parity pinned ({cite})."""\n    return q\n'
        active, _ = lint_source(src, "cited.py")
        assert active == [], cite


def test_gc012_bare_clock_call_fires_only_in_scope():
    """Path-scoped: a bare clock CALL flags under sampling/ and
    robustness/ components, and nowhere else."""
    src = """\
import time

class Engine:
    def step(self):
        t0 = time.perf_counter()
        return t0
"""
    active, _ = check_source(src, "midgpt_tpu/sampling/serve.py")
    assert [(f.rule, f.line) for f in active] == [("GC012", 5)]
    active, _ = check_source(
        src.replace("perf_counter", "time"),
        "midgpt_tpu/robustness/supervisor.py",
    )
    assert [(f.rule, f.line) for f in active] == [("GC012", 5)]
    # the SAME source outside injectable-clock territory never flags
    for path in ("midgpt_tpu/training/train.py", "tools/loadgen.py"):
        active, _ = check_source(src, path)
        assert active == [], path


def test_gc012_plumbing_and_sleep_are_exempt():
    """`clock=time.perf_counter` is a reference (the plumbing itself, not
    a read) and `time.sleep` is a delay, not a measurement — the exact
    shapes sampling/serve.py and robustness/supervisor.py use."""
    src = """\
import time

class Engine:
    def __init__(self, clock=time.perf_counter, sleep_fn=time.sleep):
        self._clock = clock
        self._sleep = sleep_fn

    def step(self):
        time.sleep(0.01)
        return self._clock()
"""
    active, _ = check_source(src, "midgpt_tpu/sampling/serve.py")
    assert active == []


def test_gc012_suppressible_inline():
    src = """\
import time

def arrival_stamp():
    return time.time()  # graftcheck: disable=GC012 — wall-anchored arrival timestamp for logs
"""
    active, suppressed = check_source(src, "midgpt_tpu/sampling/server.py")
    assert active == []
    assert [(f.rule, f.line) for f in suppressed] == [("GC012", 4)]


# ----------------------------------------------------------------------
# Pass 3: clean counterparts and extra triggering shapes
# ----------------------------------------------------------------------


def test_gc009_clean_when_every_path_releases():
    """The disagg handoff shape: guarded raise cleans up in the handler,
    falsy acquisition carries no obligation, free(release(...)) retires
    the trie pages inline."""
    src = """\
def gather(prefill, allocator, tokens):
    pc = prefill.prefix_cache
    mr = pc.match(tokens)
    if mr is None:
        return None
    try:
        stage(mr)
    except Exception:
        allocator.free(pc.release(tokens, mr.pages, 0))
        raise
    allocator.free(pc.release(tokens, mr.pages, 0))
    return mr
"""
    active, _ = check_source(src, "clean_gc009.py")
    assert active == []


def test_gc009_double_release_and_discard():
    src = """\
def twice(allocator, n):
    pages = allocator.alloc(n)
    allocator.free(pages)
    allocator.free(pages)
"""
    active, _ = check_source(src, "double.py")
    assert [(f.rule, f.line) for f in active] == [("GC009", 4)]
    assert "released again" in active[0].message
    src = """\
def drop(prefill, tokens):
    prefill.prefix_cache.evict(tokens)
"""
    active, _ = check_source(src, "discard.py")
    assert [(f.rule, f.line) for f in active] == [("GC009", 2)]
    assert "discarded" in active[0].message


def test_gc009_transfer_into_container_is_a_release_funnel():
    """slot.pages.extend(got) moves ownership into engine state — the
    canonical adoption shape must not flag."""
    src = """\
def adopt(allocator, slot, n):
    got = allocator.alloc(n)
    if got is None:
        return False
    slot.pages.extend(got)
    return True
"""
    active, _ = check_source(src, "adopt.py")
    assert active == []


def test_gc009_refs_protocol():
    trie_src = """\
class _Node:
    def dec(self):
        self.refs -= 1
"""
    # outside the trie module: ANY .refs mutation is a protocol breach
    active, _ = check_source(trie_src, "server.py")
    assert [(f.rule, f.line) for f in active] == [("GC009", 3)]
    # inside it: a decrement still needs the adjacent underflow guard
    active, _ = check_source(trie_src, "prefix_cache.py")
    assert [(f.rule, f.line) for f in active] == [("GC009", 3)]
    assert "underflow" in active[0].message
    guarded = """\
class _Node:
    def dec(self):
        self.refs -= 1
        assert self.refs >= 0
"""
    active, _ = check_source(guarded, "prefix_cache.py")
    assert active == []


def test_gc010_direct_engine_call_flags_queued_command_clean():
    bad = """\
class Server:
    async def status(self):
        return self.engine.stats()
"""
    active, _ = check_source(bad, "srv.py")
    assert [(f.rule, f.line) for f in active] == [("GC010", 3)]
    # the blessed shape: mutation happens inside a queued command (nested
    # def) drained by the driver loop, not in the event-loop context
    ok = """\
import asyncio

class Server:
    async def submit(self, req):
        def do_submit():
            return self.engine.submit(req)
        return await asyncio.to_thread(do_submit)
"""
    active, _ = check_source(ok, "srv_ok.py")
    assert active == []


def test_gc010_single_mutation_with_await_is_clean():
    src = """\
import asyncio

class Server:
    async def run(self):
        self.running = True
        await asyncio.sleep(0)
        self.stopped = True
"""
    active, _ = check_source(src, "srv2.py")
    assert active == []


def test_gc011_bounded_domains_pass():
    """pow2 ladder, bucket normalizer, bool compare, literal menu — every
    blessed static-domain shape proves bounded."""
    src = """\
import functools

import jax

@functools.partial(jax.jit, static_argnums=(1, 2))
def step(x, n, flag):
    return x * n if flag else x

def _split_bucket(t):
    return 1 if t < 4096 else 4

def drive(x, budget, t):
    n = 1 << (budget.bit_length() - 1)
    x = step(x, n, budget > 0)
    return step(x, _split_bucket(t), False)
"""
    active, _ = check_source(src, "bounded.py")
    assert active == []


def test_gc011_init_frozen_self_attr_passes_late_store_flags():
    frozen = """\
import functools

import jax

@functools.partial(jax.jit, static_argnums=(1,))
def step(x, n):
    return x * n

class Engine:
    def __init__(self, chunk):
        self.chunk = chunk

    def decode(self, x):
        return step(x, self.chunk)
"""
    active, _ = check_source(frozen, "eng.py")
    assert active == []
    thawed = frozen.replace(
        "    def decode(self, x):",
        "    def retune(self, c):\n        self.chunk = c\n\n    def decode(self, x):",
    )
    active, _ = check_source(thawed, "eng2.py")
    assert [(f.rule) for f in active] == ["GC011"]


# ----------------------------------------------------------------------
# Pass 4: clean counterparts and extra triggering shapes
# ----------------------------------------------------------------------


def test_gc013_queued_command_worker_is_clean():
    """The blessed worker shape: results travel back through driver-owned
    queues/events; the worker never touches engine state directly."""
    src = """\
import threading

class Serve:
    def start(self):
        threading.Thread(target=self._worker, daemon=True).start()

    def _worker(self):
        self._cmds.append(("set_temperature", 0.0))
        self._landed.set()
"""
    active, _ = check_source(src, "srv.py")
    assert active == []


def test_gc013_blessed_to_thread_step_funnel_passes_others_flag():
    """`await asyncio.to_thread(self.engine.step)` is the ONE blessed
    off-loop engine touch (sampling/server.py driver); shipping any other
    callee to the thread pool makes it a worker context."""
    ok = """\
import asyncio

class Server:
    async def drive(self):
        await asyncio.to_thread(self.engine.step)
"""
    active, _ = check_source(ok, "ok.py")
    assert active == []
    bad = """\
import asyncio

class Server:
    async def drive(self):
        await asyncio.to_thread(self._drain)

    def _drain(self):
        self.pool.resize(4)
"""
    active, _ = check_source(bad, "bad.py")
    assert [(f.rule, f.line) for f in active] == [("GC013", 8)]


def test_gc013_on_expire_callback_is_a_worker_context():
    src = """\
class Train:
    def arm(self, wd):
        wd.sync(self._force, on_expire=self._expired)

    def _expired(self, step, waited):
        self.engine.abort()
"""
    active, _ = check_source(src, "wd.py")
    assert [(f.rule, f.line) for f in active] == [("GC013", 6)]


def test_gc014_one_shot_flag_handler_is_clean():
    """The robustness/preempt.py pattern: set pre-existing module flags,
    stamp via an injected clock parameter, restore the previous
    disposition one-shot — all blessed."""
    src = """\
import signal

_requested = False


def _on_term(signum, frame, _clock=None):
    global _requested
    _requested = True
    stamp = _clock() if _clock else None
    signal.signal(signum, signal.SIG_DFL)
    return stamp


def install():
    signal.signal(signal.SIGTERM, _on_term)
"""
    active, _ = check_source(src, "preempt_ok.py")
    assert active == []


def test_gc014_checkpoint_call_and_lock_in_handler_flag():
    src = """\
import signal

def _on_term(signum, frame):
    mngr.save(0, state)
    guard.acquire()

def install():
    signal.signal(signal.SIGTERM, _on_term)
"""
    active, _ = check_source(src, "preempt_bad.py")
    assert [(f.rule, f.line) for f in active] == [("GC014", 4), ("GC014", 5)]
    assert "checkpoint" in active[0].message
    assert "lock" in active[1].message


def test_gc015_quantized_page_tuple_is_clean():
    """The `_gather_pages` idiom (sampling/disagg.py): host-landed
    np.asarray pages under the blessed {k, v, k_scale, v_scale} keys and
    plain scalars everywhere else."""
    src = """\
import jax.numpy as jnp
import numpy as np

class Disagg:
    def gather(self, cache, idx, uid):
        blocks = {}
        blocks["k"] = np.asarray(jnp.take(cache.k, idx, axis=2))
        blocks["k_scale"] = np.asarray(jnp.take(cache.k_scale, idx, axis=2))
        item = HandoffItem(uid=uid, deadline=self._clock() + 1.0,
                           blocks=blocks, n_pages=2)
        self.handoff_queue.push(item)
"""
    active, _ = check_source(src, "disagg_ok.py")
    assert active == []


def test_gc015_device_array_and_bad_block_key_flag():
    src = """\
import jax.numpy as jnp

class Disagg:
    def gather(self, cache, idx, uid):
        blocks = {}
        blocks["k"] = jnp.take(cache.k, idx, axis=2)
        blocks["raw_logits"] = cache.logits
        self.handoff_queue.push(HandoffItem(uid=uid, blocks=blocks))
"""
    active, _ = check_source(src, "disagg_bad.py")
    assert [(f.rule, f.line) for f in active] == [
        ("GC015", 6),
        ("GC015", 7),
    ]
    assert "device array" in active[0].message
    assert "raw_logits" in active[1].message


def test_gc015_tracks_queue_constructor_assignment():
    """A queue bound from PageHandoffQueue(...) is a wire queue even when
    the attribute name carries no handoff/failover/spill hint."""
    src = """\
class Disagg:
    def __init__(self):
        self.queue = PageHandoffQueue(retries=3)

    def enqueue(self, uid):
        self.queue.push(HandoffItem(uid=uid, clock=self._clock))
"""
    active, _ = check_source(src, "q.py")
    assert [(f.rule, f.line) for f in active] == [("GC015", 6)]
    assert "clock callable" in active[0].message


def test_gc016_complete_raise_is_clean_undeclared_field_flags():
    ok = """\
def give_up(step, retries, d):
    raise CheckpointWriteError(
        f"save at {step} failed",
        step=step,
        attempts=retries,
        directory=d,
    )
"""
    active, _ = check_source(ok, "ok.py")
    assert active == []
    bad = """\
def shed(self, needed):
    raise BackpressureError(
        "no pages",
        needed_pages=needed,
        backlog_pages=0,
        budget_pages=1,
        retryable=True,
        retry_after_pages=needed,
    )
"""
    active, _ = check_source(bad, "bad.py")
    assert [(f.rule) for f in active] == ["GC016"]
    assert "retry_after_pages" in active[0].message


def test_gc016_registry_matches_live_class_signatures():
    """The declarative registry (analysis/error_contracts.py) must track
    the real constructors: every declared field is a keyword parameter of
    the class __init__, required fields have no default, optional fields
    do. A registry/class drift fails here, not at triage time."""
    import inspect

    from midgpt_tpu.analysis.error_contracts import ERROR_CONTRACTS
    from midgpt_tpu.robustness.errors import (
        CheckpointCorruptError,
        CheckpointWriteError,
        DivergenceError,
        StepHangError,
    )
    from midgpt_tpu.sampling.disagg import HandoffRetryExhausted
    from midgpt_tpu.sampling.fleet_proc import (
        ReplicaGoneError,
        TransportError,
        WireFrameError,
    )
    from midgpt_tpu.sampling.ops import HotSwapError, PoolResizeError
    from midgpt_tpu.sampling.serve import BackpressureError

    classes = {
        "DivergenceError": DivergenceError,
        "StepHangError": StepHangError,
        "CheckpointCorruptError": CheckpointCorruptError,
        "CheckpointWriteError": CheckpointWriteError,
        "HotSwapError": HotSwapError,
        "PoolResizeError": PoolResizeError,
        "BackpressureError": BackpressureError,
        "HandoffRetryExhausted": HandoffRetryExhausted,
        "TransportError": TransportError,
        "WireFrameError": WireFrameError,
        "ReplicaGoneError": ReplicaGoneError,
    }
    assert set(classes) == set(ERROR_CONTRACTS)
    for name, cls in classes.items():
        contract = ERROR_CONTRACTS[name]
        params = inspect.signature(cls.__init__).parameters
        for field in contract.required + contract.optional:
            assert field in params, f"{name}: `{field}` not a constructor param"
        declared = set(contract.required) | set(contract.optional)
        for pname, p in params.items():
            if pname in ("self", "message") or p.kind is not p.KEYWORD_ONLY:
                continue
            assert pname in declared, f"{name}: `{pname}` missing from registry"


# ----------------------------------------------------------------------
# jit-surface census + baseline diff
# ----------------------------------------------------------------------


_SURFACE_SRC = """\
import functools

import jax


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def step(x, n):
    return x * n


def plain(x):
    return x + 1


fwd = jax.jit(plain)
params = jax.jit(lambda k: k * 2)(3)


def drive(x):
    return step(x, 1 if x.ndim > 1 else 2)
"""


def _census(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(_SURFACE_SRC)
    return jit_surface([str(tmp_path)], rel_to=str(tmp_path))


def test_jit_surface_census_records_all_three_forms(tmp_path):
    entries = {e["name"]: e for e in _census(tmp_path)}
    assert set(entries) == {"step", "fwd", "<inline:lambda#0>"}
    assert entries["step"]["form"] == "decorator"
    assert entries["step"]["static_argnums"] == [1]
    assert entries["step"]["donate_argnums"] == [0]
    # the only callsite passes a literal-menu IfExp: provably bounded
    assert entries["step"]["static_verdicts"] == {"n": "bounded"}
    assert entries["fwd"]["form"] == "rebinding"
    assert entries["<inline:lambda#0>"]["form"] == "inline"


def test_jit_surface_diff_flags_new_and_changed_allows_removed(tmp_path):
    entries = _census(tmp_path)
    assert diff_surface(entries, entries) == []
    # a brand-new wrapper fails until re-pinned
    missing_one = [e for e in entries if e["name"] != "fwd"]
    problems = diff_surface(entries, missing_one)
    assert any("new jit wrapper `fwd`" in p for p in problems)
    # a widened static set on a pinned wrapper fails
    import copy

    widened = copy.deepcopy(entries)
    for e in widened:
        if e["name"] == "step":
            e["static_argnums"] = [1, 2]
    problems = diff_surface(widened, entries)
    assert any("static_argnums" in p for p in problems)
    # removal is allowed (shrinking the compile surface needs no ceremony)
    assert diff_surface(missing_one, entries) == []


def test_jit_surface_verdict_degrades_on_unbounded_callsite(tmp_path):
    src = _SURFACE_SRC.replace(
        "    return step(x, 1 if x.ndim > 1 else 2)",
        "    return step(x, x.tolist().pop())",
    )
    p = tmp_path / "mod.py"
    p.write_text(src)
    entries = {e["name"]: e for e in jit_surface([str(p)])}
    assert entries["step"]["static_verdicts"] == {"n": "unproven"}


# ----------------------------------------------------------------------
# CLI contract
# ----------------------------------------------------------------------


def _run_cli(*args):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    return subprocess.run(
        [sys.executable, "-m", "midgpt_tpu.analysis", *args],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )


def test_cli_nonzero_with_rule_id_and_location_per_fixture(tmp_path):
    """The acceptance pin: the CLI exits nonzero on the fixture violations
    and names each one by rule ID and file:line."""
    expected = []
    for rule, (src, line) in FIXTURES.items():
        p = tmp_path / f"fixture_{rule.lower()}.py"
        p.write_text(src)
        expected.append((rule, str(p), line))
    proc = _run_cli("--json", str(tmp_path))
    assert proc.returncode == 1, proc.stderr
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert rec["count"] == len(FIXTURES)
    got = {(f["rule"], f["path"], f["line"]) for f in rec["findings"]}
    for rule, path, line in expected:
        assert (rule, path, line) in got, (rule, got)


def test_cli_exit_zero_on_clean_file(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x + 1\n")
    proc = _run_cli(str(p))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rules_subset(tmp_path):
    """--rules narrows the run; unknown rules are a usage error."""
    p = tmp_path / "two.py"
    p.write_text(FIXTURES["GC003"][0] + FIXTURES["GC006"][0])
    proc = _run_cli("--json", "--rules", "GC006", str(p))
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert [f["rule"] for f in rec["findings"]] == ["GC006"]
    assert _run_cli("--rules", "GC999", str(p)).returncode == 2


def test_cli_rules_subset_can_select_pass3_only(tmp_path):
    p = tmp_path / "life.py"
    p.write_text(FIXTURES["GC009"][0] + FIXTURES["GC006"][0])
    proc = _run_cli("--json", "--rules", "GC009", str(p))
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert [f["rule"] for f in rec["findings"]] == ["GC009"]
    assert rec["count"] == rec["pass3_count"] == 1


def test_cli_rules_subset_can_select_pass4_only(tmp_path):
    p = tmp_path / "conc.py"
    p.write_text(FIXTURES["GC016"][0] + FIXTURES["GC006"][0])
    proc = _run_cli("--json", "--rules", "GC016", str(p))
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert [f["rule"] for f in rec["findings"]] == ["GC016"]
    assert rec["count"] == rec["pass4_count"] == 1
    assert rec["pass3_count"] == 0


def test_cli_fail_on_new_reports_jit_surface_changes(tmp_path):
    """A jit wrapper absent from the committed manifest fails
    --fail-on-new even with zero findings: compile-surface growth is a
    reviewed artifact, not a drive-by."""
    p = tmp_path / "new_wrapper.py"
    p.write_text(
        "import jax\n\n@jax.jit\ndef brand_new_wrapper(x):\n    return x + 1\n"
    )
    proc = _run_cli("--json", "--fail-on-new", str(p))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert rec["count"] == rec["new_count"] == 0
    assert rec["jit_surface_count"] == 1 and rec["jit_surface_new"] == 1
    # without --fail-on-new the same file is informational only: exit 0
    assert _run_cli(str(p)).returncode == 0


def test_cli_fail_on_new_flags_findings_absent_from_baseline(tmp_path):
    """The committed baseline is empty (the tree is clean), so any fixture
    finding is NEW: --fail-on-new exits nonzero and reports new_count."""
    p = tmp_path / "leak.py"
    p.write_text(FIXTURES["GC009"][0])
    proc = _run_cli("--json", "--fail-on-new", str(p))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert rec["new_count"] == rec["count"] == 1


def test_cli_json_reports_pass3_stats(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text("x = 1\n")
    proc = _run_cli("--json", str(p))
    rec, problems = check_bench_stdout(proc.stdout, "graftcheck")
    assert not problems, problems
    assert rec["pass3_count"] == 0 and rec["pass3_suppressed"] == 0
    assert rec["pass3_wall_ms"] >= 0
