"""The permanent tier-1 gate: the shipped tree is graftcheck-clean.

Every future PR that introduces a lax.cond-in-kernel, a host sync in a jit
scope, an untiled BlockSpec literal, a use-after-donate, trace-time RNG/
clock, or an uncited parity claim fails HERE with a rule ID and file:line
— and any suppression added to get past it must carry a justification.
"""

import json
import os

from midgpt_tpu.analysis.__main__ import BASELINE_PATH, _default_paths, _repo_root
from midgpt_tpu.analysis.concurrency import concurrency_paths
from midgpt_tpu.analysis.jit_surface import (
    JIT_SURFACE_BASELINE_PATH,
    jit_surface,
    load_baseline,
)
from midgpt_tpu.analysis.lifecycle import lifecycle_paths
from midgpt_tpu.analysis.lint import iter_python_files, lint_paths, parse_suppressions


def test_tree_is_violation_free():
    active, _suppressed, n_files = lint_paths(_default_paths())
    assert n_files > 50, "lint roots resolved to almost nothing — path bug?"
    assert active == [], "\n" + "\n".join(f.format() for f in active)


def test_tree_is_lifecycle_clean():
    """Pass 3 (GC009/GC010/GC011) on the whole tree: zero unsuppressed
    findings. A page-lifecycle leak, an engine touch from the event loop,
    or an unbounded static-arg domain fails here with file:line."""
    active, _suppressed, n_files = lifecycle_paths(_default_paths())
    assert n_files > 50, "lifecycle roots resolved to almost nothing — path bug?"
    assert active == [], "\n" + "\n".join(f.format() for f in active)


def test_tree_is_concurrency_clean():
    """Pass 4 (GC013-GC016) on the whole tree: zero unsuppressed findings.
    A thread-escape engine mutation, an allocating signal handler, a
    non-plain-data handoff payload, or a field-dropping structured raise
    fails here with file:line."""
    active, _suppressed, n_files = concurrency_paths(_default_paths())
    assert n_files > 50, "concurrency roots resolved to almost nothing — path bug?"
    assert active == [], "\n" + "\n".join(f.format() for f in active)


def test_jit_surface_baseline_pins_clean_tree():
    """The committed jit-surface manifest must match the live census
    exactly (and be non-empty — the tree HAS jit wrappers): a new wrapper,
    a widened static-arg set, or a regressed GC011 verdict fails here
    until the baseline is deliberately re-pinned via --update-baseline."""
    current = jit_surface(_default_paths(), rel_to=_repo_root())
    baseline = load_baseline(JIT_SURFACE_BASELINE_PATH)
    assert len(baseline) > 0, "committed jit_surface_baseline.json is empty"
    cur = {(e["path"], e["name"]): e for e in current}
    base = {(e["path"], e["name"]): e for e in baseline}
    assert cur == base, (
        "jit surface drifted from the committed baseline; review the "
        "change, then run `python -m midgpt_tpu.analysis --update-baseline`"
    )


def test_baseline_matches_clean_tree():
    """The committed --fail-on-new baseline must be empty while the tree is
    clean; a stale non-empty baseline would mask reintroduced findings."""
    with open(BASELINE_PATH, encoding="utf-8") as fh:
        assert json.load(fh) == []


def test_every_suppression_is_justified():
    """`# graftcheck: disable=GCnnn` alone is not an explanation. Require a
    justification clause long enough to say *why* the rule does not apply
    (the satellite contract: zero unexplained findings at merge)."""
    bare = []
    for path in iter_python_files(_default_paths()):
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        for s in parse_suppressions(src):
            text = s.justification.strip(" -—:—")
            if len(text) < 10:
                bare.append(f"{path}:{s.line}: disable={','.join(s.rules)}")
    assert not bare, "unjustified suppressions:\n" + "\n".join(bare)


def test_default_roots_exclude_tests():
    """tests/ holds deliberate-violation fixtures; the default scan must
    never pull them in (it would make the clean gate unsatisfiable)."""
    for path in iter_python_files(_default_paths()):
        assert os.sep + "tests" + os.sep not in path, path
