"""Hung-step watchdog unit pins (robustness/watchdog.py): deadline
arithmetic on a fake clock, passthrough/exception transparency, both
escalation modes, and the disabled-is-free contract. JAX-free — the
watchdog is pure host machinery, so these run in milliseconds."""

import threading

import pytest

from midgpt_tpu.robustness import watchdog as wd_mod
from midgpt_tpu.robustness.errors import StepHangError
from midgpt_tpu.robustness.watchdog import EXIT_CODE, StepWatchdog


class FakeClock:
    """Injected monotonic clock the hang closures can advance."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _hang_forever(clock, at=100.0):
    """A sync that never lands: advance the fake clock past any deadline,
    then park on a never-set event (the tunnel-down model)."""

    def fn():
        clock.t = at
        threading.Event().wait()

    return fn


def test_disabled_is_a_plain_call():
    calls = []
    wd = StepWatchdog(0.0, clock=lambda: calls.append(1) or 0.0)
    assert not wd.enabled
    assert wd.sync(lambda: "ok") == "ok"
    # no thread, no clock read, no counter: zero machinery when disabled
    assert calls == [] and wd.syncs == 0 and wd.expiries == 0


def test_passthrough_returns_value_and_counts():
    clock = FakeClock()
    wd = StepWatchdog(5.0, clock=clock, poll_s=0.001)
    assert wd.sync(lambda: 42) == 42
    assert wd.sync(lambda: None) is None
    assert wd.syncs == 2 and wd.expiries == 0


def test_worker_exception_propagates_unchanged():
    clock = FakeClock()
    wd = StepWatchdog(5.0, clock=clock, poll_s=0.001)

    def boom():
        raise FloatingPointError("divergence guard fired inside the sync")

    with pytest.raises(FloatingPointError, match="divergence guard"):
        wd.sync(boom)
    assert wd.expiries == 0  # an exception is a LANDED sync, not a hang


def test_expiry_raises_structured_steph_hang_error(tmp_path):
    clock = FakeClock()
    seen = []
    wd = StepWatchdog(
        5.0, clock=clock, poll_s=0.001, rundir=str(tmp_path),
        on_expire=lambda step, waited: seen.append((step, waited)),
    )
    with pytest.raises(StepHangError) as ei:
        wd.sync(_hang_forever(clock), step=12, label="train.loss_sync")
    e = ei.value
    assert e.step == 12 and e.waited_s >= 5.0 and e.rundir == str(tmp_path)
    assert "train.loss_sync" in str(e)
    assert wd.expiries == 1
    # the supervisor's HUNG-mark hook saw the expiry
    assert seen == [(12, e.waited_s)]
    # postmortem artifacts landed in the rundir
    assert (tmp_path / "flight_recorder.json").exists()
    assert (tmp_path / "flight_recorder.prom").exists()


def test_deadline_not_reached_is_not_an_expiry():
    """A slow-but-landing sync under the deadline returns normally: the
    fake clock advances to just UNDER the deadline before landing."""
    clock = FakeClock()
    wd = StepWatchdog(5.0, clock=clock, poll_s=0.001)

    def slow():
        clock.t = 4.9
        return "landed"

    assert wd.sync(slow) == "landed"
    assert wd.expiries == 0


def test_escalate_exit_hard_exits_with_exit_code(monkeypatch, capsys):
    clock = FakeClock()
    exited = []
    # os._exit cannot be caught; intercept it to observe the code
    monkeypatch.setattr(
        wd_mod.os, "_exit", lambda code: exited.append(code) or (_ for _ in ()).throw(SystemExit(code))
    )
    wd = StepWatchdog(5.0, escalate="exit", clock=clock, poll_s=0.001)
    with pytest.raises(SystemExit):
        wd.sync(_hang_forever(clock), step=3)
    assert exited == [EXIT_CODE]
    assert "hard-exiting" in capsys.readouterr().out


def test_escalate_validation():
    with pytest.raises(ValueError, match="escalate"):
        StepWatchdog(1.0, escalate="reboot")


def test_hang_error_is_runtime_error():
    """The supervisor (and chaos_run's catch) depend on the hierarchy."""
    e = StepHangError("x", step=1, waited_s=2.0, rundir="/r")
    assert isinstance(e, RuntimeError)
    assert e.step == 1 and e.waited_s == 2.0 and e.rundir == "/r"
