import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.loss import cross_entropy_loss
from midgpt_tpu.utils.precision import cast_floating

CFG = GPTConfig(block_size=32, vocab_size=96, n_layer=2, n_head=2, n_embd=32, dropout=0.0)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def test_init_shapes(params):
    D, C, L, V = CFG.n_embd, CFG.head_dim, CFG.n_layer, CFG.vocab_size
    assert params.wte.shape == (V, D)
    assert params.lm_head.shape == (V, D)
    assert params.blocks.attn.wqkv.shape == (L, 3, D, D)
    assert params.blocks.attn.wo.shape == (L, D, D)
    assert params.blocks.attn.q_scale.shape == (L, C)
    assert params.blocks.mlp.w_up.shape == (L, 4 * D, D)
    assert params.blocks.mlp.w_down.shape == (L, D, 4 * D)


def test_init_weight_tying_init_only(params):
    np.testing.assert_array_equal(np.asarray(params.wte), np.asarray(params.lm_head))
    # but they are independent leaves:
    leaves = jax.tree.leaves(params)
    assert sum(1 for x in leaves if x.shape == (CFG.vocab_size, CFG.n_embd)) == 2


def test_count_params(params):
    D, C, L, V = CFG.n_embd, CFG.head_dim, CFG.n_layer, CFG.vocab_size
    expected = V * D + L * (3 * D * D + D * D + 2 * C + 8 * D * D)
    assert GPT.count_params(params) == expected


def test_forward_shape_and_finite(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 16), 0, CFG.vocab_size)
    logits = GPT.apply(CFG, params, tokens, inference=True)
    assert logits.shape == (3, 16, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_forward_causal(params):
    """Perturbing token t must not change logits before t."""
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, 16), 0, CFG.vocab_size)
    logits1 = GPT.apply(CFG, params, tokens, inference=True)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % CFG.vocab_size)
    logits2 = GPT.apply(CFG, params, tokens2, inference=True)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))


def test_remat_matches_no_remat(params):
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, CFG.vocab_size)
    cfg_noremat = dataclasses.replace(CFG, remat=False)
    l1 = GPT.apply(CFG, params, tokens, inference=True)
    l2 = GPT.apply(cfg_noremat, params, tokens, inference=True)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)


def test_attn_impl_parity(params):
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 32), 0, CFG.vocab_size)
    base = GPT.apply(CFG, params, tokens, inference=True)
    cfg_blk = dataclasses.replace(CFG, attn_impl="blockwise", attn_block_size=16)
    blk = GPT.apply(cfg_blk, params, tokens, inference=True)
    np.testing.assert_allclose(np.asarray(base), np.asarray(blk), atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_rope_split_style_exact(params):
    """rope_style='split' (in-graph q/k row permutation + rotate-half,
    models/gpt.py _project_qkv) computes the SAME function of the SAME
    params as the reference interleaved rotation: logits AND grads match.
    This is what lets perf configs flip the style without touching
    checkpoints or val-loss parity."""
    cfg_split = dataclasses.replace(CFG, rope_style="split")
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 32), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size
    l_ref = GPT.apply(CFG, params, tokens, inference=True)
    l_split = GPT.apply(cfg_split, params, tokens, inference=True)
    np.testing.assert_allclose(
        np.asarray(l_ref), np.asarray(l_split), atol=2e-5, rtol=2e-5
    )

    def loss(cfg, p):
        return cross_entropy_loss(GPT.apply(cfg, p, tokens, inference=True), labels)

    g_ref = jax.grad(lambda p: loss(CFG, p))(params)
    g_split = jax.grad(lambda p: loss(cfg_split, p))(params)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_split)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_rope_split_style_decode_consistent(params):
    """Prefill + decode under rope_style='split' agree with the full
    forward (the permuted-order keys live in the KV cache; consistent
    within a run because the style is config-recorded)."""
    from midgpt_tpu.models.gpt import KVCache

    cfg = dataclasses.replace(CFG, rope_style="split")
    tokens = jax.random.randint(jax.random.PRNGKey(9), (2, 12), 0, CFG.vocab_size)
    full = GPT.apply(cfg, params, tokens, inference=True)
    cache = KVCache.init(cfg, 2, dtype=jnp.float32)
    logits, cache = GPT.prefill(cfg, params, tokens[:, :8], cache)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :8]), atol=1e-4, rtol=1e-4
    )
    for t in range(8, 12):
        step_logits, cache = GPT.decode_step(cfg, params, tokens[:, t], cache)
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(full[:, t]), atol=1e-4, rtol=1e-4
        )


def test_attn_layout_head_matches_seq(params):
    """attn_layout='head' (direct (B,H,T,C) projection + fused merge,
    models/gpt.py) is the same math as the seq layout — logits and grads
    match on the flash path it accelerates. Uses blockwise impl via attn_fn?
    No: flash needs TPU; on CPU the head path activates via attn_fn
    injection, so test through a trivial head-major attn_fn."""
    from midgpt_tpu.ops.attention import multihead_attention

    # head-major oracle attention fn (what ring/ulysses/flash present)
    attn_fn = lambda q, k, v: multihead_attention(
        q, k, v, impl="naive", inference=True, layout="bhtc"
    )
    cfg_head = dataclasses.replace(CFG, attn_layout="head")
    tokens = jax.random.randint(jax.random.PRNGKey(10), (2, 32), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size

    def loss(cfg, p, use_fn):
        h = GPT.hidden(
            cfg, p, tokens, inference=True, attn_fn=attn_fn if use_fn else None
        )
        logits = jnp.einsum("btd,vd->btv", h, p.lm_head)
        return cross_entropy_loss(logits, labels)

    l_seq, g_seq = jax.value_and_grad(lambda p: loss(CFG, p, False))(params)
    l_head, g_head = jax.value_and_grad(lambda p: loss(cfg_head, p, True))(params)
    np.testing.assert_allclose(float(l_head), float(l_seq), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_head)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    # and with split rope on top (the shipped fast-path combination)
    cfg_both = dataclasses.replace(CFG, attn_layout="head", rope_style="split")
    l_both = loss(cfg_both, params, True)
    np.testing.assert_allclose(float(l_both), float(l_seq), rtol=1e-5)


def test_grad_flows_everywhere(params):
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, CFG.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, CFG.vocab_size)

    def loss(p):
        return cross_entropy_loss(GPT.apply(CFG, p, tokens, inference=True), labels)

    grads = jax.grad(loss)(params)
    for path, g in jax.tree_util.tree_leaves_with_path(grads):
        assert bool(jnp.isfinite(g).all()), path
        assert float(jnp.abs(g).max()) > 0, f"zero grad at {jax.tree_util.keystr(path)}"


def test_bf16_compute_close_to_fp32(params):
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size
    l32 = cross_entropy_loss(GPT.apply(CFG, params, tokens, inference=True), labels)
    pbf = cast_floating(params, jnp.bfloat16)
    lbf = cross_entropy_loss(GPT.apply(CFG, pbf, tokens, inference=True), labels)
    assert abs(float(l32) - float(lbf)) < 0.1


def test_dropout_needs_key(params):
    cfg = dataclasses.replace(CFG, dropout=0.1)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    with pytest.raises(ValueError):
        GPT.apply(cfg, params, tokens, inference=False, key=None)
    out = GPT.apply(cfg, params, tokens, inference=False, key=jax.random.PRNGKey(0))
    assert bool(jnp.isfinite(out).all())
