import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.ops.loss import cross_entropy_loss
from midgpt_tpu.utils.precision import cast_floating

CFG = GPTConfig(block_size=32, vocab_size=96, n_layer=2, n_head=2, n_embd=32, dropout=0.0)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


def test_init_shapes(params):
    D, C, L, V = CFG.n_embd, CFG.head_dim, CFG.n_layer, CFG.vocab_size
    assert params.wte.shape == (V, D)
    assert params.lm_head.shape == (V, D)
    assert params.blocks.attn.wqkv.shape == (L, 3, D, D)
    assert params.blocks.attn.wo.shape == (L, D, D)
    assert params.blocks.attn.q_scale.shape == (L, C)
    assert params.blocks.mlp.w_up.shape == (L, 4 * D, D)
    assert params.blocks.mlp.w_down.shape == (L, D, 4 * D)


def test_init_weight_tying_init_only(params):
    np.testing.assert_array_equal(np.asarray(params.wte), np.asarray(params.lm_head))
    # but they are independent leaves:
    leaves = jax.tree.leaves(params)
    assert sum(1 for x in leaves if x.shape == (CFG.vocab_size, CFG.n_embd)) == 2


def test_count_params(params):
    D, C, L, V = CFG.n_embd, CFG.head_dim, CFG.n_layer, CFG.vocab_size
    expected = V * D + L * (3 * D * D + D * D + 2 * C + 8 * D * D)
    assert GPT.count_params(params) == expected


def test_forward_shape_and_finite(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 16), 0, CFG.vocab_size)
    logits = GPT.apply(CFG, params, tokens, inference=True)
    assert logits.shape == (3, 16, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_forward_causal(params):
    """Perturbing token t must not change logits before t."""
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (1, 16), 0, CFG.vocab_size)
    logits1 = GPT.apply(CFG, params, tokens, inference=True)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % CFG.vocab_size)
    logits2 = GPT.apply(CFG, params, tokens2, inference=True)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))


def test_remat_matches_no_remat(params):
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, CFG.vocab_size)
    cfg_noremat = dataclasses.replace(CFG, remat=False)
    l1 = GPT.apply(CFG, params, tokens, inference=True)
    l2 = GPT.apply(cfg_noremat, params, tokens, inference=True)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)


def test_attn_impl_parity(params):
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 32), 0, CFG.vocab_size)
    base = GPT.apply(CFG, params, tokens, inference=True)
    cfg_blk = dataclasses.replace(CFG, attn_impl="blockwise", attn_block_size=16)
    blk = GPT.apply(cfg_blk, params, tokens, inference=True)
    np.testing.assert_allclose(np.asarray(base), np.asarray(blk), atol=2e-5, rtol=2e-5)


def test_grad_flows_everywhere(params):
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, CFG.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, CFG.vocab_size)

    def loss(p):
        return cross_entropy_loss(GPT.apply(CFG, p, tokens, inference=True), labels)

    grads = jax.grad(loss)(params)
    for path, g in jax.tree_util.tree_leaves_with_path(grads):
        assert bool(jnp.isfinite(g).all()), path
        assert float(jnp.abs(g).max()) > 0, f"zero grad at {jax.tree_util.keystr(path)}"


def test_bf16_compute_close_to_fp32(params):
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, CFG.vocab_size)
    labels = (tokens + 1) % CFG.vocab_size
    l32 = cross_entropy_loss(GPT.apply(CFG, params, tokens, inference=True), labels)
    pbf = cast_floating(params, jnp.bfloat16)
    lbf = cross_entropy_loss(GPT.apply(CFG, pbf, tokens, inference=True), labels)
    assert abs(float(l32) - float(lbf)) < 0.1


def test_dropout_needs_key(params):
    cfg = dataclasses.replace(CFG, dropout=0.1)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    with pytest.raises(ValueError):
        GPT.apply(cfg, params, tokens, inference=False, key=None)
    out = GPT.apply(cfg, params, tokens, inference=False, key=jax.random.PRNGKey(0))
    assert bool(jnp.isfinite(out).all())
