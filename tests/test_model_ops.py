"""Zero-downtime model ops (sampling/ops.py, docs/ROBUSTNESS.md): the
blue/green hot-swap protocol, the elastic pool resize, and the SLO policy
controller, exercised directly on ServeEngine / DisaggServe.

The chaos gates (tests/test_chaos_serve.py hot_swap_mid_decode /
pool_resize) hold the end-to-end mid-trace invariants; this file pins the
protocol edges those scenarios drive through: structured rejections,
idle-flip semantics, admission pause while staged, shrink refusal fields,
int8 scale migration, and the clock-injected controller's decision table.

Pool geometries here (33/35/41/47/53/59/63) are fresh — num_pages is a
program-shape key, and the recompile pins (tests/test_recompile_pins.py)
count compiles on THEIR geometries in this same process.
"""

import dataclasses
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.robustness import faults
from midgpt_tpu.sampling.engine import restore_for_sampling
from midgpt_tpu.sampling.ops import (
    HotSwapError,
    ModelOps,
    PoolResizeError,
    _pow2_bucket,
    assert_conserved,
)
from midgpt_tpu.sampling.serve import BackpressureError, ServeEngine
from midgpt_tpu.training.checkpoint import CheckpointManager

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def params_new():
    return GPT.init(CFG, jax.random.PRNGKey(11))


def _engine(params, num_pages, **kw):
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("max_slots", 3)
    return ServeEngine(
        CFG, params, page_size=8, num_pages=num_pages,
        prefill_chunk=16, decode_chunk=8, temperature=0.0, **kw,
    )


def _trace(seed, n=3, lo=18, hi=30, bl=8, bh=14):
    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, CFG.vocab_size, int(m)).astype(np.int32)
        for m in rng.integers(lo, hi, size=n)
    ]
    return prompts, [int(b) for b in rng.integers(bl, bh, size=n)]


def _cold(params, num_pages, prompts, budgets, **kw):
    eng = _engine(params, num_pages, **kw)
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    done = eng.run()
    return [done[u].tokens.tolist() for u in uids]


def test_fault_descriptions_cover_every_kind():
    """`chaos_run.py --list-faults` renders DESCRIPTIONS — every
    registered kind must have a non-empty one-liner (and no strays)."""
    assert set(faults.DESCRIPTIONS) == set(faults.KINDS)
    for kind, desc in faults.DESCRIPTIONS.items():
        assert desc.strip() and "\n" not in desc, kind


def test_hot_swap_rejections_are_structured_and_touch_nothing(
    params, params_new
):
    """Every rejection raises HotSwapError with machine-readable fields
    BEFORE the live engine changes — no staged state, no version bump.
    Validation never dispatches a program, so this engine never serves."""
    eng = _engine(params, 33)

    # shape mismatch: same tree, wrong leaf shapes (a different width is
    # a new engine, not a swap)
    wide = GPT.init(
        dataclasses.replace(CFG, n_embd=48), jax.random.PRNGKey(1)
    )
    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap(wide)
    assert ei.value.reason == "shape"
    assert ei.value.path  # names the offending leaf
    assert ei.value.expected != ei.value.got

    # dtype mismatch: a dtype change is a recompile, not a swap
    bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params_new)
    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap(bf16)
    assert ei.value.reason == "dtype"
    assert ei.value.path

    # tree-structure mismatch: a different model family
    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap({"stray": jnp.zeros(())})
    assert ei.value.reason == "tree_structure"

    # config mismatch (checked before leaves: the config IS the identity)
    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap(
            params_new, config=dataclasses.replace(CFG, block_size=128)
        )
    assert ei.value.reason == "config"

    # draft weights offered to a draft-less engine
    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap(params_new, draft_params=params_new)
    assert ei.value.reason == "draft_unexpected"

    assert eng.hot_swaps == 0
    assert eng.weights_version == "inline"
    assert eng.stats()["swap_pending"] is False


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_hot_swap_idle_engine_flips_immediately(params, params_new):
    """An idle engine has nothing to drain: stage_hot_swap flips in the
    same call, and everything served afterwards is bit-identical to a
    cold engine built from the new weights."""
    eng = _engine(params, 33)
    s = eng.hot_swap(params_new, version="v2", config=CFG)
    assert s["staged"] and s["flipped"]
    assert s["in_flight_at_stage"] == []
    assert eng.hot_swaps == 1 and eng.weights_version == "v2"
    rec = eng.swap_history[-1]
    assert rec["from_version"] == "inline" and rec["version"] == "v2"
    assert rec["flip_round"] == rec["staged_round"]
    assert rec["swap_latency_s"] >= 0.0

    prompts, budgets = _trace(seed=3)
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    done = eng.run()
    got = [done[u].tokens.tolist() for u in uids]
    assert got == _cold(params_new, 33, prompts, budgets)
    assert_conserved(eng, "after idle-flip serving")


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_hot_swap_staged_pauses_admissions_blue_green(params, params_new):
    """Mid-trace protocol on the engine API: while a swap is staged the
    engine is not idle, a second stage is refused (swap_pending), fresh
    arrivals wait in the queue, and the flip lands only after the old
    side drains — pre-flip streams match the OLD-weights cold engine,
    the queued arrival matches the NEW-weights one."""
    prompts, budgets = _trace(seed=4)
    eng = _engine(params, 35)
    uids1 = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    for _ in range(3):
        eng.step()
    assert any(s is not None for s in eng.slots)

    s = eng.hot_swap(params_new, version="v2")
    assert s["staged"] and not s["flipped"]
    assert sorted(s["in_flight_at_stage"]) == sorted(
        sl.request.uid for sl in eng.slots if sl is not None
    )
    assert eng.stats()["swap_pending"] is True
    assert not eng.idle  # a staged swap holds the engine alive to flip

    with pytest.raises(HotSwapError) as ei:
        eng.hot_swap(params_new, version="v3")
    assert ei.value.reason == "swap_pending"
    assert ei.value.got == "v3"

    p2, b2 = _trace(seed=5, n=1)
    uid2 = eng.submit(p2[0], b2[0])
    done = eng.run()
    assert eng.hot_swaps == 1 and eng.weights_version == "v2"
    rec = eng.swap_history[-1]
    assert rec["flip_round"] > rec["staged_round"]
    # the queued arrival was NOT served before the flip
    assert uid2 not in rec["served_uids_at_flip"]
    assert sorted(uids1) == rec["served_uids_at_flip"]

    got1 = [done[u].tokens.tolist() for u in uids1]
    assert got1 == _cold(params, 35, prompts, budgets)
    assert [done[uid2].tokens.tolist()] == _cold(params_new, 35, p2, b2)
    assert all(done[u].status == "ok" for u in uids1 + [uid2])
    assert_conserved(eng, "after staged swap drain")


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_resize_refusals_and_int8_scale_migration(params):
    """The elastic-resize protocol on one int8 engine: shrinking below
    the resident working set (or the live slot count) is a structured,
    retryable refusal; a grow-then-shrink migration carries the int8
    scales with their pages, so the final streams stay greedy-bit-exact
    vs a never-resized engine."""
    # budgets long enough that all three streams are still decoding at
    # round 3 (short budgets drain slots before the refusal can see them)
    prompts, budgets = _trace(seed=6, lo=20, hi=31, bl=16, bh=24)
    eng = _engine(params, 41, cache_dtype="int8")
    uids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    for _ in range(3):
        eng.step()
    live = sum(s is not None for s in eng.slots)
    assert live >= 2

    with pytest.raises(PoolResizeError) as ei:
        eng.resize(2)
    e = ei.value
    assert e.requested_pages == 2 and e.num_pages == 41
    assert e.resident_pages >= live  # >= one page per live slot
    assert e.retryable is True

    with pytest.raises(PoolResizeError) as ei:
        eng.resize(max_slots=live - 1)
    e = ei.value
    assert e.requested_slots == live - 1 and e.live_slots == live
    assert e.retryable is True
    assert eng.allocator.num_pages == 41 and eng.resizes == 0

    grow = eng.resize(47)
    assert (grow["from_pages"], grow["to_pages"]) == (41, 47)
    assert grow["pages_migrated"] >= live
    assert grow["gather_bucket"] == _pow2_bucket(grow["pages_migrated"])
    eng.step()
    shrink = eng.resize(41)
    assert (shrink["from_pages"], shrink["to_pages"]) == (47, 41)
    assert shrink["pages_migrated"] >= 1
    assert eng.resizes == 2 and eng.allocator.num_pages == 41

    done = eng.run()
    got = [done[u].tokens.tolist() for u in uids]
    assert got == _cold(params, 41, prompts, budgets, cache_dtype="int8")
    assert_conserved(eng, "after grow/shrink drain")


def test_model_ops_controller_decision_table(params):
    """The clock-injected policy loop, one branch at a time on an idle
    engine (idle resizes migrate zero pages, so nothing dispatches):
    shed_threshold -> interval gate -> grow on TTFT breach -> shrink on
    surplus -> in_band. Decisions carry machine-readable args and the
    actuations really land (budget loosened, pool resized)."""
    t = {"now": 100.0}
    eng = _engine(params, 59, max_backlog_pages=1)
    mops = ModelOps(
        eng, clock=lambda: t["now"], min_interval_s=10.0,
        ttft_budget_ms=200.0,
    )

    # one shed (the 1-page budget refuses any real request) -> loosen
    with pytest.raises(BackpressureError):
        eng.submit(np.zeros(24, np.int32), 8)
    d = mops.tick()
    assert d.kind == "shed_threshold" and d.reason == "shed_frac_over_budget"
    assert d.applied and eng.max_backlog_pages > 1
    assert d.args["to_budget"] == eng.max_backlog_pages

    t["now"] += 1.0  # inside min_interval_s: the tick is a no-op
    assert mops.tick().kind == "none"
    assert mops.decisions[-1].reason == "interval"

    t["now"] += 100.0  # caller-measured TTFT over budget -> grow
    d = mops.tick(ttft_p95_ms=500.0)
    assert d.kind == "grow" and d.reason == "ttft_over_budget"
    assert d.applied and eng.allocator.num_pages == d.args["to_pages"]
    assert d.args["to_pages"] > d.args["from_pages"] == 59

    # the shed counter is cumulative and nothing admitted since, so the
    # shed branch would keep loosening; turn the budget off (the same
    # actuator, set_backlog_budget) to expose the shrink branch
    from midgpt_tpu.sampling.scheduler import set_backlog_budget

    set_backlog_budget(eng, None)
    t["now"] += 100.0  # all-free pool, empty backlog -> shrink
    d = mops.tick()
    assert d.kind == "shrink" and d.reason == "free_pages_high"
    assert d.applied and eng.allocator.num_pages == d.args["to_pages"]
    assert eng.resizes == 2

    t["now"] += 100.0  # widen the band: healthy pool is a "none" tick
    mops.high_free_frac = 1.1
    d = mops.tick()
    assert d.kind == "none" and d.reason == "in_band"
    assert [x.kind for x in mops.decisions] == [
        "shed_threshold", "none", "grow", "shrink", "none",
    ]


def test_model_ops_re_roles_disagg_pair(params):
    """The re-role actuator: DisaggServe.rebalance moves page BUDGET
    between roles (shrink-first, so a refusal changes nothing), and the
    controller's deep-handoff branch drives it. Idle engines migrate
    zero pages — this is pure pool-geometry bookkeeping."""
    from midgpt_tpu.sampling.disagg import DisaggServe

    d = DisaggServe(
        CFG, params, max_slots=2, page_size=8, num_pages=63,
        prefill_chunk=16, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    rec = d.rebalance(4)
    assert (rec["src"], rec["dst"]) == ("prefill", "decode")
    assert d.prefill.allocator.num_pages == 59
    assert d.decode.allocator.num_pages == 67
    assert d.re_roles == 1
    assert rec["src_resize"]["to_pages"] == 59
    assert rec["dst_resize"]["to_pages"] == 67

    # the controller's deep-backlog branch (threshold forced under the
    # empty queue so the branch fires without traffic)
    mops = ModelOps(
        d, clock=lambda: 0.0, handoff_backlog_high=-1, rebalance_pages=2,
    )
    dec = mops.tick()
    assert dec.kind == "re_role" and dec.reason == "handoff_backlog_deep"
    assert dec.applied and d.re_roles == 2
    assert d.prefill.allocator.num_pages == 57
    assert d.decode.allocator.num_pages == 69
    assert_conserved(d.prefill, "after re-role")
    assert_conserved(d.decode, "after re-role")


@pytest.mark.slow
def test_restored_checkpoint_into_running_tp2_engine_bit_exact(
    params, tmp_path
):
    """The deploy path end to end: a verified checkpoint restored via
    restore_for_sampling is hot-swapped into a RUNNING tp=2 engine; the
    in-flight wave drains on the old weights, and the post-flip wave is
    greedy-bit-exact vs a cold single-chip engine from the same step.
    The version string is the manifest's '<step>:<sha12>'."""
    from midgpt_tpu.parallel.serve_tp import make_serve_mesh

    new_params = GPT.init(CFG, jax.random.PRNGKey(21))
    ckpt = str(tmp_path / "ckpt")
    mgr = CheckpointManager(ckpt, save_interval_steps=1)
    mgr.save(5, {"params": new_params}, force=True)
    mgr.wait()
    version = mgr.weights_version(5)
    mgr.close()
    assert version.startswith("5:") and len(version.split(":")[1]) == 12

    shim = types.SimpleNamespace(
        model_config=CFG, fsdp_min_size=1 << 60, param_dtype="float32"
    )
    restored, step = restore_for_sampling(ckpt, shim)
    assert step == 5

    mesh = make_serve_mesh(tp_size=2)
    eng = _engine(params, 53, mesh=mesh, max_slots=2)
    prompts, budgets = _trace(seed=8, n=2)
    uids1 = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    for _ in range(2):
        eng.step()
    assert any(s is not None for s in eng.slots)

    s = eng.hot_swap(restored, version=version, config=CFG)
    assert s["staged"] and not s["flipped"]
    done = eng.run()  # old side drains, then the flip
    assert eng.hot_swaps == 1 and eng.weights_version == version
    got1 = [done[u].tokens.tolist() for u in uids1]
    assert got1 == _cold(params, 53, prompts, budgets)

    p2, b2 = _trace(seed=9, n=2)
    uids2 = [eng.submit(p, b) for p, b in zip(p2, b2)]
    done = eng.run()
    got2 = [done[u].tokens.tolist() for u in uids2]
    assert got2 == _cold(restored, 53, p2, b2)
    assert_conserved(eng, "after tp swap drain")
