"""Direct unit tests for the utils/hlo.py parser (previously exercised only
through the structural pins that consume it) and the analysis/hlo_audit.py
text-level audits built on top of it."""

import jax
import jax.numpy as jnp
import pytest

from midgpt_tpu.analysis.hlo_audit import (
    CompileCounter,
    assert_fp32_master_params,
    assert_no_while_body_collectives,
    entry_parameter_dtypes,
    fp32_master_param_audit,
    jit_cache_size,
    while_body_collectives,
)
from midgpt_tpu.utils.hlo import hlo_computations, while_body_names

# Shaped like a post-optimization dump: layout annotations and a nested-brace
# constant inside instruction lines, an indented closing brace, and a while
# whose body computation calls a fusion holding an all-gather.
SAMPLE_HLO = """\
HloModule test, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

%fused_computation (param_0: f32[4]) -> f32[4] {
  %param_0 = f32[4]{0} parameter(0)
  %c = f32[2,2]{1,0} constant({ {1, 2}, {3, 4} })
  ROOT %ag = f32[4]{0} all-gather(f32[4]{0} %param_0), replica_groups={}
  }

%region_0.22 (arg_tuple.23: (s32[], f32[4])) -> (s32[], f32[4]) {
  %arg_tuple.23 = (s32[], f32[4]{0}) parameter(0)
  %f = f32[4]{0} fusion(f32[4]{0} %gte), kind=kLoop, calls=%fused_computation
}

%region_2.47 (arg_tuple.48: (s32[], f32[4])) -> pred[] {
  %arg_tuple.48 = (s32[], f32[4]{0}) parameter(0)
}

ENTRY %main.62 (Arg_0.1: f32[4], Arg_1.2: bf16[4], Arg_2.3: s32[]) -> f32[4] {
  %w = (s32[], f32[4]{0}) while((s32[], f32[4]{0}) %t), condition=%region_2.47, body=%region_0.22
}
"""


def test_hlo_computations_parses_bodies_and_nested_braces():
    comps = hlo_computations(SAMPLE_HLO)
    assert set(comps) == {"fused_computation", "region_0.22", "region_2.47", "main.62"}
    # the nested-brace constant is ONE instruction line, not a scope change
    assert any("constant({ {1, 2}, {3, 4} })" in l for l in comps["fused_computation"])
    assert len(comps["region_0.22"]) == 2
    # indented closing brace (fused_computation) still closed the scope
    assert all("parameter(0)" not in l for l in comps["region_2.47"][1:])


def test_hlo_computations_malformed_missing_close():
    """A header met while a computation is still open (truncated/malformed
    dump) starts the new computation instead of glomming instructions."""
    txt = (
        "%a (x: f32[]) -> f32[] {\n"
        "  %i1 = f32[] parameter(0)\n"
        "%b (y: f32[]) -> f32[] {\n"
        "  %i2 = f32[] parameter(0)\n"
        "}\n"
    )
    comps = hlo_computations(txt)
    assert [l for l in comps["a"]] == ["%i1 = f32[] parameter(0)"]
    assert [l for l in comps["b"]] == ["%i2 = f32[] parameter(0)"]


def test_hlo_computations_header_without_brace_is_not_a_computation():
    txt = "%notacomp (x: f32[])\n%real (y: f32[]) -> f32[] {\n  %i = f32[] parameter(0)\n}\n"
    comps = hlo_computations(txt)
    assert set(comps) == {"real"}


def test_while_body_names_and_census():
    assert while_body_names(SAMPLE_HLO) == {"region_0.22"}
    census = while_body_collectives(SAMPLE_HLO)
    # transitive: the all-gather hides inside a fusion the body calls
    assert [l for l in census["region_0.22"] if "all-gather" in l]
    with pytest.raises(AssertionError, match="all-gather"):
        assert_no_while_body_collectives(SAMPLE_HLO)
    assert_no_while_body_collectives(SAMPLE_HLO, ops=("all-to-all",))


def test_entry_parameter_dtypes_and_fp32_audit():
    assert entry_parameter_dtypes(SAMPLE_HLO) == ["f32", "bf16", "s32"]
    audit = fp32_master_param_audit(SAMPLE_HLO)
    assert audit == {"n_params": 3, "n_f32": 1, "n_reduced": 1, "has_bf16_compute": 1}
    with pytest.raises(AssertionError, match="fp32"):
        assert_fp32_master_params(SAMPLE_HLO)
    with pytest.raises(ValueError, match="ENTRY"):
        entry_parameter_dtypes("HloModule empty\n")


def test_parser_roundtrip_on_real_lowering():
    """End-to-end sanity on an actual compiled scan: the while body exists,
    parses, and is collective-free on one device."""

    @jax.jit
    def f(x):
        def body(c, _):
            return c * 1.5 + 1.0, None

        c, _ = jax.lax.scan(body, x, None, length=4)
        return c

    txt = f.lower(jnp.ones((8,), jnp.float32)).compile().as_text()
    comps = hlo_computations(txt)
    bodies = while_body_names(txt)
    assert bodies and bodies <= set(comps)
    assert_no_while_body_collectives(txt)
    assert entry_parameter_dtypes(txt) == ["f32"]


def test_compile_counter_and_cache_size():
    f = jax.jit(lambda x: x * 3 + 2)
    assert jit_cache_size(f) == 0
    with CompileCounter() as cc:
        f(jnp.ones((5, 3)))
    assert cc.count >= 1
    assert jit_cache_size(f) == 1
    with CompileCounter() as cc2:
        f(jnp.zeros((5, 3)))  # same shape/dtype: cache hit
    assert cc2.count == 0
    assert jit_cache_size(f) == 1
    with CompileCounter() as cc3:
        f(jnp.ones((2, 9)))  # new shape: recompile
    assert cc3.count >= 1
    assert jit_cache_size(f) == 2
