"""Checkpoint save/restore round-trips to tmpdirs, including partial
(params-only) restore — the named-item layout that frees the sampler from
rebuilding an optimizer skeleton (unlike reference sample.py:111-137)."""

import jax
import numpy as np

from midgpt_tpu.config import ExperimentConfig, MeshConfig
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.parallel.mesh import make_mesh
from midgpt_tpu.training.checkpoint import CheckpointManager
from midgpt_tpu.training.train import init_state

CFG = GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2, n_embd=32)


def make_config(mesh=MeshConfig(data=2, fsdp=4, sp=1)) -> ExperimentConfig:
    return ExperimentConfig(
        rundir="",
        data_dir="",
        learning_rate=1e-3,
        batch_size=8,
        warmup_steps=5,
        min_lr=1e-4,
        lr_decay_steps=50,
        max_steps=50,
        beta2=0.95,
        weight_decay=1e-4,
        eval_interval=10,
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        fsdp_min_size=0,
        mesh=mesh,
        model_config=CFG,
    )


def test_roundtrip_sharded_state(tmp_path):
    config = make_config()
    mesh = make_mesh(config.mesh)
    params, opt_state, _, _ = init_state(config, mesh)

    mngr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
    assert mngr.latest_step() is None
    mngr.save(3, {"params": params, "opt_state": opt_state})
    mngr.wait()
    assert mngr.latest_step() == 3

    # Restore into fresh differently-valued state: values must come back.
    config2 = config.replace(seed=123)
    params2, opt2, _, _ = init_state(config2, mesh)
    restored = mngr.restore(3, {"params": params2, "opt_state": opt2})
    for a, b in zip(jax.tree.leaves(restored["params"]), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # shardings preserved
    assert restored["params"].wte.sharding == params.wte.sharding
    mngr.close()


def test_partial_restore_params_only(tmp_path):
    config = make_config()
    mesh = make_mesh(config.mesh)
    params, opt_state, _, _ = init_state(config, mesh)
    mngr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
    mngr.save(7, {"params": params, "opt_state": opt_state})
    mngr.wait()

    abstract = jax.eval_shape(lambda k: GPT.init(CFG, k), jax.random.PRNGKey(0))
    restored = mngr.restore(7, {"params": abstract})
    assert set(restored.keys()) == {"params"}
    np.testing.assert_array_equal(
        np.asarray(restored["params"].wte), np.asarray(params.wte)
    )
    mngr.close()


def test_save_interval_filtering_and_force(tmp_path):
    config = make_config(MeshConfig(data=1, fsdp=1, sp=1))
    mesh = make_mesh(config.mesh, devices=jax.devices()[:1])
    params, opt_state, _, _ = init_state(config, mesh)
    state = {"params": params, "opt_state": opt_state}
    mngr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=10)
    assert mngr.save(0, state) is True
    assert mngr.save(3, state) is False  # filtered
    assert mngr.save(10, state) is True
    assert mngr.save(13, state, force=True) is True
    mngr.wait()
    assert mngr.latest_step() == 13
    mngr.close()


def test_format_marker_rejects_mismatched_checkpoint(tmp_path, monkeypatch):
    """A checkpoint whose format marker doesn't match this build (e.g. the
    pre-v2 stacked-qkv layout) must refuse to restore rather than silently
    reinterpret the arrays (training/checkpoint.py FORMAT)."""
    import pytest

    from midgpt_tpu.training import checkpoint as ckpt_mod

    config = make_config(MeshConfig(data=1, fsdp=1, sp=1))
    mesh = make_mesh(config.mesh, devices=jax.devices()[:1])
    params, opt_state, _, _ = init_state(config, mesh)
    mngr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
    mngr.save(0, {"params": params})
    mngr.wait()
    mngr.close()

    # A build with a different format must refuse this checkpoint.
    monkeypatch.setattr(
        ckpt_mod, "FORMAT", {"version": 99, "qkv_layout": "other"}
    )
    mngr2 = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
    abstract = jax.eval_shape(lambda k: GPT.init(CFG, k), jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="format"):
        mngr2.restore(0, {"params": abstract})
    mngr2.close()
