"""Unit tests for the fully-offline BPE prep pipeline (data/local_text).

Runs the real HF `tokenizers` trainer on a tiny corpus — everything here is
offline by construction, which is the pipeline's point.
"""

import importlib.util
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

pytest.importorskip("tokenizers")

_spec = importlib.util.spec_from_file_location(
    "prepare_local_text",
    os.path.join(os.path.dirname(__file__), "..", "data", "local_text", "prepare.py"),
)
prep = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(prep)


@pytest.fixture()
def corpus(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.py").write_text("def add(a, b):\n    return a + b\n" * 40)
    (d / "b.md").write_text("# Title\n\nSome prose about the add function.\n" * 40)
    (d / "dup.py").write_text("def add(a, b):\n    return a + b\n" * 40)  # exact dup of a.py
    (d / "small.txt").write_text("x")  # under min_bytes -> skipped
    (d / "bin.txt").write_bytes(b"\xff\xfe" + os.urandom(600))  # not utf-8 -> skipped
    (d / "skip.cfg").write_text("not a collected extension\n" * 40)
    return d


def test_collect_documents_dedup_and_filters(corpus):
    docs = prep.collect_documents([str(corpus)], (".py", ".md", ".txt"), 10**6)
    assert len(docs) == 2  # a.py (dup collapsed), b.md
    assert any("def add" in d for d in docs)


def test_end_to_end_pipeline_round_trip(corpus, tmp_path):
    out = tmp_path / "out"
    res = subprocess.run(
        [
            sys.executable, prep.__file__,
            "--roots", str(corpus),
            "--out-dir", str(out),
            "--vocab-size", "400",
            "--val-fraction", "0.5",
        ],
        capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stderr
    for name in ("train.bin", "val.bin", "tokenizer.json", "meta.pkl"):
        assert (out / name).exists()
    with open(out / "meta.pkl", "rb") as f:
        meta = pickle.load(f)
    assert meta["kind"] == "hf_bpe"
    assert meta["vocab_size"] <= 400

    from tokenizers import Tokenizer

    tok = Tokenizer.from_file(str(out / "tokenizer.json"))
    eot = tok.token_to_id(prep.EOT)
    train = np.fromfile(out / "train.bin", dtype=np.uint16)
    assert train.size > 0
    assert train.max() < meta["vocab_size"]
    assert train[-1] == eot  # every document ends in the sentinel
    # tokens decode back to text containing the source material
    text = tok.decode(train.tolist(), skip_special_tokens=True)
    assert "add" in text


def test_meta_fingerprint_detects_stale_bins(corpus, tmp_path):
    """meta.pkl records per-split token counts + tokenizer hash; TokenDataset
    refuses bins whose length disagrees (e.g. tracked tokenizer.json/meta.pkl
    updated by git while the untracked bins stayed behind)."""
    out = tmp_path / "out"
    res = subprocess.run(
        [
            sys.executable, prep.__file__,
            "--roots", str(corpus),
            "--out-dir", str(out),
            "--vocab-size", "400",
            "--val-fraction", "0.5",
        ],
        capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stderr
    with open(out / "meta.pkl", "rb") as f:
        meta = pickle.load(f)
    assert set(meta["split_tokens"]) == {"train", "val"}
    assert len(meta["tokenizer_sha256"]) == 64

    from midgpt_tpu.data.dataset import TokenDataset

    ds = TokenDataset(str(out))  # coherent set loads fine
    assert len(ds["train"]) == meta["split_tokens"]["train"]

    # simulate stale bins: truncate train.bin after meta was written
    tokens = np.fromfile(out / "train.bin", dtype=np.uint16)
    tokens[: tokens.size // 2].tofile(out / "train.bin")
    with pytest.raises(ValueError, match="prepare.py"):
        TokenDataset(str(out))
