"""Pluggable scheduler policies (sampling/scheduler.py): FCFS preserves
the PR 1 engine behavior (the extraction pin), the SLO policy implements
EDF admission / most-slack preemption / infeasible-deadline shedding, and
swapping policies compiles NOTHING — scheduling is host-side only."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.analysis.hlo_audit import CompileCounter
from midgpt_tpu.models.gpt import GPT, GPTConfig
from midgpt_tpu.sampling.engine import generate
from midgpt_tpu.sampling.scheduler import FCFSScheduler, SLOScheduler
from midgpt_tpu.sampling.serve import BackpressureError, Request, ServeEngine

CFG = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32)


@pytest.fixture(scope="module")
def params():
    return GPT.init(CFG, jax.random.PRNGKey(0))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _req(uid, deadline=None):
    return Request(uid, np.zeros(4, np.int32), 8, None, deadline)


@dataclasses.dataclass
class _StubSlot:
    admit_order: int
    request: Request


# ----------------------------------------------------------------------
# policy units (no device work)
# ----------------------------------------------------------------------


def test_fcfs_policy_decisions():
    s = FCFSScheduler()
    assert s.select_admit([_req(0), _req(1)], now=0.0) == 0
    assert s.select_admit([], now=0.0) is None
    slots = [_StubSlot(3, _req(3)), _StubSlot(7, _req(7)), _StubSlot(5, _req(5))]
    assert s.select_victim(_StubSlot(1, _req(1)), slots, now=0.0).admit_order == 7


def test_slo_policy_edf_admission():
    s = SLOScheduler()
    queue = [_req(0, deadline=9.0), _req(1, deadline=3.0), _req(2, None)]
    assert s.select_admit(queue, now=0.0) == 1  # earliest deadline first
    # deadline-less requests rank last; ties fall back to queue position
    assert s.select_admit([_req(0), _req(1)], now=0.0) == 0


def test_slo_policy_most_slack_victim():
    s = SLOScheduler()
    requester = _StubSlot(1, _req(1, deadline=2.0))
    tight = _StubSlot(4, _req(4, deadline=5.0))
    loose = _StubSlot(3, _req(3, deadline=50.0))
    assert s.select_victim(requester, [tight, loose], now=0.0) is loose
    # a deadline-less candidate has infinite slack: evicted before any
    # deadline-bearing one, youngest first among themselves
    free_a = _StubSlot(2, _req(2, None))
    free_b = _StubSlot(6, _req(6, None))
    assert s.select_victim(requester, [tight, free_a, free_b], now=0.0) is free_b


def test_slo_policy_sheds_infeasible_deadline(params):
    """A deadline closer than min_headroom_s sheds at submit with
    retryable=False — waiting cannot un-miss an SLO — while a comfortable
    deadline admits."""
    clock = FakeClock()
    eng = ServeEngine(
        CFG, params, max_slots=1, num_pages=17, cache_dtype=jnp.float32,
        scheduler=SLOScheduler(min_headroom_s=1.0), clock=clock,
    )
    with pytest.raises(BackpressureError) as ei:
        eng.submit(np.arange(4, dtype=np.int32), 4, ttl_s=0.5)
    assert not ei.value.retryable
    assert eng.shed == 1
    uid = eng.submit(np.arange(4, dtype=np.int32), 4, ttl_s=10.0)
    assert eng.run()[uid].status == "ok"


# ----------------------------------------------------------------------
# end-to-end: behavior preservation and the zero-new-compile pin
# ----------------------------------------------------------------------


def _mixed_trace(seed=0, lengths=(25, 34, 47), max_new=(9, 17, 17)):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, CFG.vocab_size, n).astype(np.int32), m)
        for n, m in zip(lengths, max_new)
    ]


def _run_engine(params, scheduler, trace, ttls=None):
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=25,
        prefill_chunk=16, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32, scheduler=scheduler,
    )
    uids = [
        eng.submit(p, m, ttl_s=None if ttls is None else ttls[i])
        for i, (p, m) in enumerate(trace)
    ]
    return eng, uids, eng.run()


def test_slo_policy_keeps_greedy_parity_and_compiles_nothing(params):
    """The tentpole pin: request streams are schedule-INDEPENDENT (greedy
    tokens depend only on the prompt), so the SLO policy must reproduce
    `generate` per request token-for-token — and because scheduling is
    pure host code, running a new policy after an FCFS warm run compiles
    ZERO programs (tests/test_recompile_pins.py methodology)."""
    trace = _mixed_trace()
    _run_engine(params, FCFSScheduler(), trace)  # warm the program set
    with CompileCounter() as cc:
        _, uids, done = _run_engine(
            params, SLOScheduler(), trace, ttls=(60.0, 1.0e4, None)
        )
    assert cc.count == 0, f"policy swap compiled {cc.count} program(s)"
    for (p, m), u in zip(trace, uids):
        ref = generate(CFG, params, jnp.asarray(p)[None], m, temperature=0.0)
        np.testing.assert_array_equal(
            done[u].tokens, np.asarray(ref[0]), err_msg=f"request {u}"
        )


@pytest.mark.slow  # heavy long-tail: full suite only, per the tier-1 870 s gate budget (CLAUDE.md)
def test_slo_policy_preempts_most_slack_slot_under_pressure(params):
    """On an oversubscribed pool the SLO engine evicts the younger slot
    with the MOST deadline slack: the urgent request streams through
    unpreempted while the relaxed one eats the recompute."""
    class Recording(SLOScheduler):
        def __init__(self):
            super().__init__()
            self.victim_uids = []

        def select_victim(self, requester, candidates, now):
            v = super().select_victim(requester, candidates, now)
            if v is not None:
                self.victim_uids.append(v.request.uid)
            return v

    clock = FakeClock()
    sched = Recording()
    eng = ServeEngine(
        CFG, params, max_slots=3, page_size=8, num_pages=10,
        prefill_chunk=16, decode_chunk=8, temperature=0.0,
        cache_dtype=jnp.float32, scheduler=sched, clock=clock,
    )
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, CFG.vocab_size, 8).astype(np.int32) for _ in range(3)]
    u_old = eng.submit(prompts[0], 30)
    u_urgent = eng.submit(prompts[1], 30, ttl_s=1e6)  # huge but finite TTL
    u_loose = eng.submit(prompts[2], 30)  # deadline-less: infinite slack
    done = eng.run()
    assert eng.preemptions >= 1, "pool was sized to force preemption"
    # the urgent (finite-deadline) request is never the chosen victim while
    # a deadline-less slot is on the table
    assert u_urgent not in sched.victim_uids
    assert u_loose in sched.victim_uids
    # every stream still exact (recompute preemption is parity-preserving)
    for u, p in ((u_old, prompts[0]), (u_urgent, prompts[1]), (u_loose, prompts[2])):
        ref = generate(CFG, params, jnp.asarray(p)[None], 30, temperature=0.0)
        np.testing.assert_array_equal(done[u].tokens, np.asarray(ref[0]))


def test_custom_scheduler_victim_contract_enforced(params):
    """A policy returning a victim outside the offered (strictly younger)
    candidate set is a contract violation — the engine refuses instead of
    breaking deadlock-freedom."""

    class Rogue(FCFSScheduler):
        def select_victim(self, requester, candidates, now):
            return requester  # never a candidate: candidates exclude it

    eng = ServeEngine(
        CFG, params, max_slots=2, page_size=8, num_pages=6,
        temperature=0.0, cache_dtype=jnp.float32, scheduler=Rogue(),
    )
    rng = np.random.default_rng(6)
    eng.submit(rng.integers(0, CFG.vocab_size, 8).astype(np.int32), 20)
    eng.submit(rng.integers(0, CFG.vocab_size, 8).astype(np.int32), 20)
    with pytest.raises(RuntimeError, match="non-candidate victim"):
        eng.run()
