"""Shared harness for the golden-loss regression fixture.

Runs a fully-seeded tiny fp32 training trajectory on the standard 8-device
virtual CPU mesh and returns the reported loss every `record_every` steps.
Both the fixture generator (tools/make_golden_fixture.py) and the regression
test (tests/test_golden_loss.py) call this one function, so the fixture can
never drift from what the test runs.

Why this exists (VERDICT r4 weak #6): the suite's only loss assertion was
`loss/final < 1.0` on a synthetic stream — a subtle numerics regression
(wrong RMSNorm eps, swapped adam beta, init-scale drift) passes that. This
pins the whole numeric chain — init, optimizer chain order, schedule, loss —
against a committed trajectory. The reference's only regression mechanism is
"training itself" (SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np

# Everything that defines the trajectory, in one place. Changing any of
# these invalidates the fixture (the test compares this dict against the
# one stored in the fixture and fails with a "regenerate" message).
GOLDEN_SPEC = {
    "seed": 0,
    "data_seed": 1337,
    "steps": 200,
    "record_every": 10,
    "batch_size": 8,
    "learning_rate": 1e-2,
    "warmup_steps": 20,
    "min_lr": 1e-3,
    "lr_decay_steps": 200,
    "beta2": 0.99,
    "weight_decay": 1e-4,
    "block_size": 64,
    "vocab_size": 64,
    "n_layer": 2,
    "n_head": 2,
    "n_embd": 64,
    "mesh": {"data": 2, "fsdp": 4, "sp": 1},
    "stream_tokens": 40000,
    "stream_period": 17,
    "stream_noise": 0.1,
}


def make_stream(tmpdir: str) -> str:
    """Deterministic learnable token stream: token[i] = i % period, 10%
    replaced with noise. Deterministic for a FIXED numpy version: the PCG64
    bit stream is guaranteed, but Generator method streams (random/integers)
    may change across numpy feature releases (NEP 19) — which is why the
    fixture records numpy's version and the test's failure message names it
    as a suspect."""
    spec = GOLDEN_SPEC
    rng = np.random.default_rng(0)
    n = spec["stream_tokens"]
    s = np.where(
        rng.random(n) < spec["stream_noise"],
        rng.integers(0, spec["vocab_size"], n),
        np.arange(n) % spec["stream_period"],
    ).astype(np.uint16)
    s[: n - 4000].tofile(f"{tmpdir}/train.bin")
    s[n - 4000 :].tofile(f"{tmpdir}/val.bin")
    return tmpdir


def run_trajectory(data_dir: str) -> list:
    """The fixture trajectory: reported train loss every record_every steps."""
    import jax

    from midgpt_tpu.config import ExperimentConfig, MeshConfig
    from midgpt_tpu.data.dataset import TokenDataset
    from midgpt_tpu.models.gpt import GPTConfig
    from midgpt_tpu.parallel.data import make_global_batch
    from midgpt_tpu.parallel.mesh import batch_spec, make_mesh
    from midgpt_tpu.training.train import init_state, make_train_step

    spec = GOLDEN_SPEC
    cfg = ExperimentConfig(
        rundir="",
        data_dir=data_dir,
        learning_rate=spec["learning_rate"],
        batch_size=spec["batch_size"],
        warmup_steps=spec["warmup_steps"],
        min_lr=spec["min_lr"],
        lr_decay_steps=spec["lr_decay_steps"],
        max_steps=spec["steps"],
        eval_interval=10**9,  # the runner drives its own loop; no evals
        beta2=spec["beta2"],
        weight_decay=spec["weight_decay"],
        param_dtype="float32",
        compute_dtype="float32",
        g_accum_iters=1,
        shard_model=True,
        mesh=MeshConfig(**spec["mesh"]),
        fsdp_min_size=0,
        seed=spec["seed"],
        data_seed=spec["data_seed"],
        model_config=GPTConfig(
            block_size=spec["block_size"],
            vocab_size=spec["vocab_size"],
            n_layer=spec["n_layer"],
            n_head=spec["n_head"],
            n_embd=spec["n_embd"],
        ),
    )
    mesh = make_mesh(cfg.mesh)
    params, opt_state, specs, optimizer = init_state(cfg, mesh)
    step, *_ = make_train_step(cfg, optimizer, mesh, specs)
    ds = TokenDataset(data_dir, seed=cfg.data_seed)
    base_key = jax.random.PRNGKey(cfg.seed)

    losses = []
    loss = None
    for itr in range(spec["steps"]):
        x, y = ds.batch("train", itr, spec["block_size"], spec["batch_size"], 1)
        xg = make_global_batch(x, mesh, batch_spec())
        yg = make_global_batch(y, mesh, batch_spec())
        params, opt_state, loss = step(
            params, opt_state, xg, yg, jax.random.fold_in(base_key, itr)
        )
        if (itr + 1) % spec["record_every"] == 0:
            losses.append(round(float(loss), 6))
    return losses
